"""Migration operator: re-dispatch a live request when its worker dies.

Reference parity: lib/llm/src/migration.rs:24 (Migration) + docs/
fault_tolerance/request_migration.md — when the response stream dies mid-
generation (worker crash, connection loss, no instances), rebuild the
PreprocessedRequest with the tokens accumulated so far appended to the
prompt, and send it to another worker, up to ``migration_limit`` times. The
new worker's prefix cache makes the re-prefill cheap; the client stream never
observes the failure.

Two budgets bound a pathological loop:

  * ``migration_limit`` — attempt count (the reference's knob);
  * ``max_reprefill_tokens`` — TOTAL prompt+carried tokens re-prefilled
    across all migrations of one stream. Attempt counts alone don't bound
    cost: a 100k-token prompt that dies late in generation re-prefills
    prompt+tail every time, so three "cheap" retries can cost more compute
    than the request itself. The token cap prices the retries in the unit
    that matters.

Covered failure classes (``MIGRATABLE``): transport disconnects, vanished
instances, connection errors, deadline/timeout aborts (the disagg pull
timeout surfaces here), and mid-disagg transfer failures
(``DisaggTransferError`` from a strict decode handler — it subclasses
ConnectionError, imported here only to label the metric reason).

Every migration emits a flight-recorder event and a
``dynamo_tpu_migration_*`` metric (runtime/metric_names.py ALL_MIGRATION).
"""

from __future__ import annotations

import asyncio
from typing import Any, AsyncIterator, List, Optional, Union

from dynamo_tpu import config
from dynamo_tpu.llm.protocols.common import (
    BackendOutput,
    FinishReason,
    PreprocessedRequest,
)
from dynamo_tpu.runtime import metric_names as mn
from dynamo_tpu.runtime.component import NoInstancesError
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.device_observe import FlightRecorder
from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.runtime.faults import note_activity
from dynamo_tpu.runtime.metrics_core import MetricsRegistry
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

try:
    from dynamo_tpu.runtime.network.tcp import StreamDisconnectedError
except ImportError:  # pragma: no cover

    class StreamDisconnectedError(ConnectionError):  # type: ignore[no-redef]
        pass


try:
    from dynamo_tpu.disagg.errors import DisaggTransferError
except ImportError:  # pragma: no cover

    class DisaggTransferError(ConnectionError):  # type: ignore[no-redef]
        pass


try:
    from dynamo_tpu.runtime.drain import WorkerDrainingError
except ImportError:  # pragma: no cover

    class WorkerDrainingError(ConnectionError):  # type: ignore[no-redef]
        pass


try:
    from dynamo_tpu.runtime.liveness import WorkerLostError
except ImportError:  # pragma: no cover

    class WorkerLostError(ConnectionError):  # type: ignore[no-redef]
        pass


# NOTE: asyncio.TimeoutError is a DISTINCT class from builtin TimeoutError
# until Python 3.11 — both must be listed. DisaggTransferError subclasses
# ConnectionError (already migratable); it is named for reason labeling.
MIGRATABLE = (
    StreamDisconnectedError,
    NoInstancesError,
    ConnectionError,
    TimeoutError,
    asyncio.TimeoutError,
)

# Default total re-prefill budget across all migrations of one stream.
DEFAULT_REPREFILL_CAP = config.MIGRATION_REPREFILL_CAP.get()


def _failure_reason(exc: BaseException) -> str:
    """Metric label for what killed the stream."""
    if isinstance(exc, WorkerDrainingError):
        # Planned churn (rolling restart / scale-down), not a fault: the
        # worker refused or handed back the stream while draining.
        return "drain"
    if isinstance(exc, WorkerLostError):
        # The crash plane declared the worker dead (missed load reports)
        # and aborted the stream proactively — faster than any transport
        # error would have surfaced.
        return "worker_lost"
    if isinstance(exc, DisaggTransferError):
        return "disagg"
    if isinstance(exc, NoInstancesError):
        return "no_instances"
    if isinstance(exc, (TimeoutError, asyncio.TimeoutError)):
        return "timeout"
    if isinstance(exc, ConnectionError):
        return "connection"
    return "other"


class MigrationMetrics:
    """Canonical migration families (runtime/metric_names.py
    ALL_MIGRATION). ``render`` plugs into SystemStatusServer's
    ``register_metrics`` seam."""

    def __init__(self) -> None:
        self.registry = MetricsRegistry()
        self.migrations = self.registry.counter(
            mn.MIGRATION_MIGRATIONS_TOTAL,
            "Live streams re-dispatched to another worker, by failure "
            "reason",
            ["reason"],
        )
        self.exhausted = self.registry.counter(
            mn.MIGRATION_EXHAUSTED_TOTAL,
            "Streams failed after exhausting the migration budget "
            "(attempt limit or re-prefill token cap) — each one reached "
            "the client as an error",
        )
        self.reprefill_tokens = self.registry.counter(
            mn.MIGRATION_REPREFILL_TOKENS_TOTAL,
            "Prompt+carried tokens re-prefilled by migrations (the cost "
            "the re-prefill cap bounds)",
        )

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics=openmetrics)


class Migration:
    def __init__(
        self,
        migration_limit: int = 3,
        *,
        max_reprefill_tokens: Optional[int] = DEFAULT_REPREFILL_CAP,
    ) -> None:
        self.migration_limit = migration_limit
        # None = uncapped (attempt count only — the pre-cap behavior).
        self.max_reprefill_tokens = max_reprefill_tokens
        self.metrics = MigrationMetrics()
        # Migration history for post-mortems (DYN005 owner "migration";
        # single writer: the frontend pipeline's event loop).
        self.flight = FlightRecorder("migration", capacity=256)

    def register_metrics(self, server: Any) -> None:
        server.register_metrics(self.metrics.render)
        server.register_flight(self.flight.name, self.flight.snapshot)

    async def generate(
        self, request: Any, context: Context, next: AsyncEngine
    ) -> AsyncIterator[Union[BackendOutput, dict]]:
        if isinstance(request, PreprocessedRequest):
            req = request
        else:
            req = PreprocessedRequest.from_dict(dict(request))
        generated: List[int] = []
        migrations = 0
        reprefilled = 0  # total tokens re-prefilled by migrations so far
        # Trajectory handoff_stall accounting: a re-dispatch's stall runs
        # from the failure to the first item the NEW worker streams.
        stall_from: Optional[float] = None
        stall_reason = ""

        while True:
            finished = False
            try:
                async for item in next.generate(_as_wire(request, req), context):
                    if stall_from is not None:
                        self._export_redispatch_span(
                            context, stall_from, stall_reason, migrations
                        )
                        stall_from = None
                    tokens = _tokens_of(item)
                    if tokens:
                        generated.extend(tokens)
                    yield item
                    if _finish_reason_of(item) is not None:
                        finished = True
                return
            except MIGRATABLE as exc:
                if finished or context.stopped:
                    return
                migrations += 1
                # The rebuilt request re-prefills its whole prompt plus
                # everything generated so far — charge it BEFORE
                # dispatching so the cap is a true bound, not a postmortem.
                next_reprefill = len(req.token_ids) + len(generated)
                reason = _failure_reason(exc)
                if migrations > self.migration_limit or (
                    self.max_reprefill_tokens is not None
                    and reprefilled + next_reprefill
                    > self.max_reprefill_tokens
                ):
                    over_cap = migrations <= self.migration_limit
                    self.metrics.exhausted.inc()
                    self.flight.record(
                        "exhausted", request=req.request_id, reason=reason,
                        migrations=migrations - 1,
                        reprefilled=reprefilled,
                        over=("reprefill_cap" if over_cap else "attempts"),
                    )
                    logger.error(
                        "request %s exceeded migration budget (%s; %d "
                        "attempts, %d tokens re-prefilled): %r",
                        req.request_id,
                        "re-prefill cap" if over_cap else "attempt limit",
                        migrations - 1, reprefilled, exc,
                    )
                    detail = (
                        f"{reprefilled} re-prefilled tokens (cap "
                        f"{self.max_reprefill_tokens})"
                        if over_cap
                        else f"{self.migration_limit} migrations"
                    )
                    # Typed terminal error: the frontend renders the kind
                    # as a structured SSE error event / JSON error_kind
                    # instead of a bare 500 (http/service.py taxonomy).
                    yield BackendOutput(
                        error=f"stream failed after {detail}: {exc}",
                        error_kind=reason,
                        finish_reason=FinishReason.ERROR,
                    )
                    return
                reprefilled += next_reprefill
                self.metrics.migrations.inc(reason=reason)
                self.metrics.reprefill_tokens.inc(next_reprefill)
                note_activity("migrations")
                self.flight.record(
                    "migrate", request=req.request_id, attempt=migrations,
                    reason=reason, carried=len(generated),
                    reprefill=next_reprefill,
                )
                logger.warning(
                    "migrating request %s (attempt %d/%d, %s) after %r "
                    "with %d tokens carried",
                    req.request_id, migrations, self.migration_limit,
                    reason, exc, len(generated),
                )
                if stall_from is None:
                    import time as _time

                    stall_from = _time.monotonic()
                    stall_reason = reason
                req = _carry_tokens(req, generated)
                generated = []  # now embedded in the prompt; don't carry twice
                request = req  # from now on send the rebuilt request

    def _export_redispatch_span(
        self, context: Context, start_mono: float, reason: str, attempt: int,
    ) -> None:
        """Trajectory handoff_stall span for one migration re-dispatch:
        stream death → first token from the new worker."""
        if not context.baggage.get("traceparent"):
            return
        try:
            from dynamo_tpu.utils.tracing import export_span

            export_span(
                "migration.redispatch", context, start_mono=start_mono,
                reason=reason, attempt=attempt,
            )
        except Exception:
            logger.debug("migration span export failed", exc_info=True)

    # Streams that end without any finish reason (worker vanished without an
    # exception) are NOT retried here: the transport layer is responsible for
    # surfacing disconnects as exceptions (tcp.py StreamDisconnectedError).


def _carry_tokens(req: PreprocessedRequest, generated: List[int]) -> PreprocessedRequest:
    """New request whose prompt embeds everything generated so far
    (ref: migration.rs retained-token re-dispatch)."""
    d = req.to_dict()
    d["token_ids"] = list(req.token_ids) + list(generated)
    new = PreprocessedRequest.from_dict(d)
    if new.stop.max_tokens is not None:
        new.stop.max_tokens = max(new.stop.max_tokens - len(generated), 1)
    if new.stop.min_tokens is not None:
        new.stop.min_tokens = max(new.stop.min_tokens - len(generated), 0)
    return new


def _as_wire(original: Any, req: PreprocessedRequest) -> Any:
    """Preserve the caller's representation (dict over the wire, object locally)."""
    return req.to_dict() if isinstance(original, dict) else req


def _tokens_of(item: Any) -> List[int]:
    if isinstance(item, dict):
        return item.get("token_ids") or []
    return getattr(item, "token_ids", None) or []


def _finish_reason_of(item: Any):
    if isinstance(item, dict):
        return item.get("finish_reason")
    return getattr(item, "finish_reason", None)
