"""Stream recording + replay.

Reference parity: lib/llm/src/recorder.rs:26 — tee request/response streams
to disk and replay them later. Invaluable for debugging disagg/migration
flows: capture a misbehaving stream in production, replay it into a test.

Format: JSONL, one event per line:
  {"kind": "request", "rid", "ts", "payload"}
  {"kind": "item",    "rid", "ts", "payload"}
  {"kind": "end",     "rid", "ts"}            (normal end)
  {"kind": "error",   "rid", "ts", "message"} (stream raised)
Payloads must be JSON-serializable (dataclasses with to_dict are handled).
Binary buffers — the KV wire payloads of a disagg transfer stream
(disagg/wire.py pack_array: bytes / memoryview fields) — are encoded as
``{"__b64__": "<base64>"}`` markers and restored bit-exact by
load_recording, so a captured transfer replays through unpack_reply and a
disagg transfer bug stays debuggable OFFLINE.
"""

from __future__ import annotations

import asyncio
import base64
import json
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Dict, List, Optional

from dynamo_tpu.runtime.context import Context
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_B64_KEY = "__b64__"


def _jsonable(obj: Any) -> Any:
    """Recursive JSON-safe encoding; bytes-like values (KV wire buffers)
    become base64 markers instead of json.dumps' lossy default=str."""
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return {_B64_KEY: base64.b64encode(bytes(obj)).decode("ascii")}
    if hasattr(obj, "to_dict"):
        return _jsonable(obj.to_dict())
    if hasattr(obj, "__dataclass_fields__"):
        import dataclasses

        return _jsonable(dataclasses.asdict(obj))
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    return obj


def _from_jsonable(obj: Any) -> Any:
    """Inverse of _jsonable's container walk: restore base64 markers."""
    if isinstance(obj, dict):
        if set(obj.keys()) == {_B64_KEY}:
            return base64.b64decode(obj[_B64_KEY])
        return {k: _from_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_from_jsonable(v) for v in obj]
    return obj


class StreamRecorder:
    """Pipeline operator: tees every request and response item to a JSONL
    file while passing them through untouched."""

    def __init__(self, path: str) -> None:
        self.path = path
        self.recorded_streams = 0
        self._lock = asyncio.Lock()

    async def _write(self, doc: Dict[str, Any]) -> None:
        line = json.dumps(doc, default=str) + "\n"
        async with self._lock:
            # Append synchronously: lines are small and interleaving-safe
            # under the lock; a failure disables recording, never the stream.
            try:
                with open(self.path, "a") as f:
                    f.write(line)
            except OSError:
                logger.exception("stream recorder write failed; disabling")
                self.path = ""

    async def generate(self, request: Any, context: Context, next: Any):
        if not self.path:
            async for item in next.generate(request, context):
                yield item
            return
        rid = context.id
        await self._write(
            {"kind": "request", "rid": rid, "ts": time.time(),
             "payload": _jsonable(request)}
        )
        self.recorded_streams += 1
        try:
            async for item in next.generate(request, context):
                await self._write(
                    {"kind": "item", "rid": rid, "ts": time.time(),
                     "payload": _jsonable(item)}
                )
                yield item
        except Exception as exc:
            await self._write(
                {"kind": "error", "rid": rid, "ts": time.time(),
                 "message": f"{type(exc).__name__}: {exc}"}
            )
            raise
        await self._write({"kind": "end", "rid": rid, "ts": time.time()})


@dataclass
class RecordedStream:
    request: Any
    items: List[Any] = field(default_factory=list)
    # seconds after the request each item arrived (replay pacing)
    offsets_s: List[float] = field(default_factory=list)
    error: Optional[str] = None
    rid: str = ""


def load_recording(path: str) -> List[RecordedStream]:
    """Parse a recorder JSONL file into per-request streams (wire order)."""
    streams: Dict[str, RecordedStream] = {}
    order: List[str] = []
    t0: Dict[str, float] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            doc = json.loads(line)
            rid = doc.get("rid", "")
            kind = doc.get("kind")
            if kind == "request":
                streams[rid] = RecordedStream(
                    request=_from_jsonable(doc.get("payload")), rid=rid
                )
                order.append(rid)
                t0[rid] = doc.get("ts", 0.0)
            elif kind == "item" and rid in streams:
                streams[rid].items.append(_from_jsonable(doc.get("payload")))
                streams[rid].offsets_s.append(
                    max(doc.get("ts", 0.0) - t0.get(rid, 0.0), 0.0)
                )
            elif kind == "error" and rid in streams:
                streams[rid].error = doc.get("message")
    return [streams[r] for r in order]


class ReplayEngine:
    """AsyncEngine that replays recorded streams.

    Requests are matched FIFO against the recording (the reference replays a
    capture in order); pass ``paced=True`` to reproduce original timing.
    """

    def __init__(self, recording: List[RecordedStream], *, paced: bool = False) -> None:
        self._streams = list(recording)
        self._next = 0
        self.paced = paced

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        if self._next >= len(self._streams):
            raise RuntimeError("replay exhausted: no more recorded streams")
        stream = self._streams[self._next]
        self._next += 1
        last = 0.0
        for item, off in zip(stream.items, stream.offsets_s or [0.0] * len(stream.items)):
            if self.paced and off > last:
                await asyncio.sleep(off - last)
                last = off
            if context.stopped:
                return
            yield item
        if stream.error:
            raise RuntimeError(f"recorded stream ended in error: {stream.error}")
