"""ModelDeploymentCard: metadata a worker publishes to the discovery plane.

Reference parity: lib/llm/src/model_card.rs:178 (ModelDeploymentCard) and
local_model/runtime_config.rs. The card is everything a frontend needs to
serve a model it has never seen: where the tokenizer/template live, context
window, KV block size, engine runtime capacity, migration budget.
"""

from __future__ import annotations

import os
import re
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

from dynamo_tpu import config


def slugify(name: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_.-]+", "-", name).strip("-").lower()


@dataclass
class RuntimeConfig:
    """Engine capacity info used by the router/planner
    (ref: local_model/runtime_config.rs)."""

    total_kv_blocks: int = 0
    kv_block_size: int = 64
    max_num_seqs: int = 256
    max_context_len: int = 4096
    dp_size: int = 1
    supports_disagg: bool = False


@dataclass
class ModelDeploymentCard:
    name: str
    model_type: str = "chat"  # chat | completion | embedding | multimodal | image
    model_path: Optional[str] = None  # local dir with tokenizer/config
    context_length: int = 4096
    kv_block_size: int = 64
    # DYN_TPU_MIGRATION_LIMIT, read at card creation: the card carries
    # the worker's migration budget to every frontend that serves it.
    migration_limit: int = field(
        default_factory=lambda: config.MIGRATION_LIMIT.get()
    )
    eos_token_ids: List[int] = field(default_factory=list)
    chat_template_source: Optional[str] = None  # inline template override
    # Reasoning-content marker style (parsers/reasoning.py KNOWN_MARKERS):
    # think | reasoning | seed | granite.
    reasoning_style: str = "think"
    # Tool-call dialect pin (parsers/incremental.py DIALECTS): json |
    # hermes | mistral | pythonic | harmony | dsml | xml. None =
    # auto-detect by opening marker — required for the marker-less
    # dialects (json, pythonic) to stream incrementally.
    tool_call_dialect: Optional[str] = None
    runtime_config: RuntimeConfig = field(default_factory=RuntimeConfig)
    user_data: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self) -> None:
        from dynamo_tpu.parsers.incremental import DIALECTS
        from dynamo_tpu.parsers.reasoning import KNOWN_MARKERS

        if self.reasoning_style not in KNOWN_MARKERS:
            raise ValueError(
                f"unknown reasoning_style {self.reasoning_style!r}; "
                f"known: {sorted(KNOWN_MARKERS)}"
            )
        if (
            self.tool_call_dialect is not None
            and self.tool_call_dialect not in DIALECTS
        ):
            raise ValueError(
                f"unknown tool_call_dialect {self.tool_call_dialect!r}; "
                f"known: {sorted(DIALECTS)}"
            )

    @property
    def slug(self) -> str:
        return slugify(self.name)

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ModelDeploymentCard":
        d = dict(d)
        d["runtime_config"] = RuntimeConfig(**(d.get("runtime_config") or {}))
        return cls(**d)

    @classmethod
    def from_model_dir(cls, name: str, model_dir: str, **overrides: Any) -> "ModelDeploymentCard":
        """Build a card from a local HF-style model directory
        (ref: local_model resolution, hub.rs — local path branch)."""
        import json

        context_length = 4096
        eos: List[int] = []
        cfg_path = os.path.join(model_dir, "config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                cfg = json.load(f)
            context_length = int(
                cfg.get("max_position_embeddings")
                or cfg.get("n_positions")
                or context_length
            )
            raw_eos = cfg.get("eos_token_id")
            if raw_eos is not None:
                eos = [raw_eos] if isinstance(raw_eos, int) else list(raw_eos)
        card = cls(
            name=name,
            model_path=model_dir,
            context_length=context_length,
            eos_token_ids=eos,
        )
        for k, v in overrides.items():
            setattr(card, k, v)
        return card
