"""Backend operator: incremental detokenization + stop conditions.

Reference parity: lib/llm/src/backend.rs (Backend::from_tokenizer :56 —
turns BackendOutput token streams into text deltas, applying stop-sequence
detection that needs text visibility the engine doesn't have).
"""

from __future__ import annotations

import logging
from typing import Any, AsyncIterator, Dict, List, Optional, Union

from dynamo_tpu.llm.protocols.common import (
    BackendOutput,
    FinishReason,
    PostprocessedOutput,
    PreprocessedRequest,
)
from dynamo_tpu.llm.tokenizer import DecodeStream, Tokenizer
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine

logger = logging.getLogger(__name__)


class Backend:
    """Pipeline operator placed between preprocessor and router."""

    def __init__(self, tokenizer: Tokenizer) -> None:
        self.tokenizer = tokenizer

    @classmethod
    def from_tokenizer(cls, tokenizer: Tokenizer) -> "Backend":
        return cls(tokenizer)

    async def generate(
        self, request: PreprocessedRequest, context: Context, next: AsyncEngine
    ) -> AsyncIterator[Union[PostprocessedOutput, dict]]:
        stop_strings: List[str] = list(request.stop.stop) if request.stop else []
        # A stop string may straddle text deltas; hold back a tail of
        # len(longest_stop)-1 chars until we know it can't complete a match.
        holdback = max((len(s) for s in stop_strings), default=0) - 1
        decode = DecodeStream(self.tokenizer)
        pending = ""  # decoded but held back
        cumulative = 0
        decoded_memo: Dict[int, str] = {}  # logprob token id → string

        async for item in next.generate(request, context):
            if isinstance(item, dict) and "annotation" in item:
                yield item
                continue
            out = item if isinstance(item, BackendOutput) else BackendOutput.from_dict(item)
            if out.error:
                yield PostprocessedOutput(
                    error=out.error,
                    error_kind=getattr(out, "error_kind", None),
                    finish_reason=FinishReason.ERROR,
                    cumulative_tokens=cumulative,
                )
                return
            if out.logprobs:
                # Fill each entry's token string here — the detokenizer is
                # the one pipeline stage that owns the tokenizer (the HTTP
                # layer renders OpenAI logprob objects from `decoded`).
                # Memoized per stream: top-N alternatives repeat the same
                # ids constantly (up to cap+1 decodes per generated token
                # otherwise).
                for step_entries in out.logprobs:
                    for tl in step_entries:
                        if tl.decoded is None:
                            s = decoded_memo.get(tl.token_id)
                            if s is None:
                                s = self.tokenizer.decode([tl.token_id])
                                decoded_memo[tl.token_id] = s
                            tl.decoded = s
            cumulative += len(out.token_ids)
            pending += decode.step(out.token_ids)
            if out.finish_reason is not None:
                pending += decode.flush()

            text_out, stop_hit = self._scan_stop(pending, stop_strings)
            if stop_hit:
                # Truncate at the stop string and end the stream.
                context.stop_generating(reason="stop-string")
                yield PostprocessedOutput(
                    text=text_out,
                    token_ids=out.token_ids,
                    finish_reason=FinishReason.STOP,
                    cumulative_tokens=cumulative,
                    logprobs=out.logprobs,
                )
                return

            if out.finish_reason is not None:
                yield PostprocessedOutput(
                    text=pending,
                    token_ids=out.token_ids,
                    finish_reason=out.finish_reason,
                    cumulative_tokens=cumulative,
                    logprobs=out.logprobs,
                )
                return

            emit = pending[: max(0, len(pending) - holdback)] if holdback > 0 else pending
            pending = pending[len(emit) :]
            if emit or out.token_ids:
                yield PostprocessedOutput(
                    text=emit,
                    token_ids=out.token_ids,
                    cumulative_tokens=cumulative,
                    logprobs=out.logprobs,
                )

        # Engine stream ended without a finish reason (e.g. cancelled).
        tail = pending + decode.flush()
        reason = (
            FinishReason.CANCELLED if context.stopped else FinishReason.ERROR
        )
        yield PostprocessedOutput(
            text=tail, finish_reason=reason, cumulative_tokens=cumulative
        )

    @staticmethod
    def _scan_stop(pending: str, stop_strings: List[str]):
        """Return (text_before_stop, hit?) scanning earliest stop match."""
        if not stop_strings:
            return pending, False
        earliest = -1
        for s in stop_strings:
            idx = pending.find(s)
            if idx != -1 and (earliest == -1 or idx < earliest):
                earliest = idx
        if earliest == -1:
            return pending, False
        return pending[:earliest], True
