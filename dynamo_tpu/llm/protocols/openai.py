"""OpenAI-compatible wire types: validation + response builders.

Reference parity: lib/async-openai (vendored request/response types),
lib/llm/src/protocols/openai/{validate.rs,nvext.rs} and the
chat_completions aggregator. The reference vendors a full typed API surface;
here requests stay as validated dicts (the frontend is schemaless JSON in →
JSON out) with typed accessors, and responses are built by constructor
functions guaranteeing OpenAI-shaped output.

The ``nvext`` extension namespace is honored (per-request annotations,
ignore_eos, greedy sampling) under the ``nvext`` key, matching nvext.rs.
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from dynamo_tpu.llm.protocols.common import (
    FinishReason,
    SamplingOptions,
    StopConditions,
)


class OpenAIError(Exception):
    """Maps to an OpenAI-style error JSON body with an HTTP status.

    ``kind`` carries the structured failure taxonomy (the PR 7
    classify_failure labels plus migration reasons) into the body as
    ``error_kind`` — a client distinguishing "worker link died" from
    "payload was garbage" retries differently."""

    def __init__(
        self, message: str, status: int = 400,
        err_type: str = "invalid_request_error",
        kind: Optional[str] = None,
    ) -> None:
        super().__init__(message)
        self.status = status
        self.err_type = err_type
        self.kind = kind

    def to_body(self) -> Dict[str, Any]:
        body: Dict[str, Any] = {
            "error": {
                "message": str(self),
                "type": self.err_type,
                "param": None,
                "code": None,
            }
        }
        if self.kind:
            body["error"]["error_kind"] = self.kind
        return body


def parse_n(req: Dict[str, Any]) -> int:
    """Validated 'n' (choice count) — the ONE source of truth for both the
    HTTP service gate and request preprocessing. None → 1; bools, non-ints
    and out-of-range values 400 (int('two') must never surface as a 500)."""
    raw = req.get("n", 1)
    if raw is None:
        return 1
    if isinstance(raw, bool) or not isinstance(raw, int) or not 1 <= raw <= 8:
        raise OpenAIError("'n' must be an integer in [1, 8]")
    return raw


def _require(cond: bool, message: str) -> None:
    if not cond:
        raise OpenAIError(message)


def _opt_number(req: Dict[str, Any], key: str, lo: float, hi: float) -> Optional[float]:
    value = req.get(key)
    if value is None:
        return None
    _require(isinstance(value, (int, float)) and not isinstance(value, bool), f"'{key}' must be a number")
    _require(lo <= value <= hi, f"'{key}' must be in [{lo}, {hi}]")
    return float(value)


@dataclass
class ParsedRequest:
    """Normalized view over a chat-completion or completion request."""

    kind: str  # "chat" | "completion"
    model: str
    messages: List[Dict[str, Any]] = field(default_factory=list)  # chat
    prompt: Optional[Any] = None  # completion: str | [str] | [int]
    stream: bool = False
    stream_usage: bool = False
    n: int = 1
    sampling: SamplingOptions = field(default_factory=SamplingOptions)
    stop: StopConditions = field(default_factory=StopConditions)
    tools: Optional[List[Dict[str, Any]]] = None
    tool_choice: Optional[Any] = None
    response_format: Optional[Dict[str, Any]] = None
    annotations: List[str] = field(default_factory=list)
    lora_name: Optional[str] = None
    raw: Dict[str, Any] = field(default_factory=dict)


_CHAT_ROLES = {"system", "user", "assistant", "tool", "developer"}


def parse_chat_request(req: Dict[str, Any]) -> ParsedRequest:
    """Validate /v1/chat/completions body (ref: validate.rs + openai.rs:865)."""
    _require(isinstance(req, dict), "request body must be a JSON object")
    model = req.get("model")
    _require(isinstance(model, str) and bool(model), "'model' is required")
    messages = req.get("messages")
    _require(isinstance(messages, list) and len(messages) > 0, "'messages' must be a non-empty array")
    for i, msg in enumerate(messages):
        _require(isinstance(msg, dict), f"messages[{i}] must be an object")
        role = msg.get("role")
        _require(role in _CHAT_ROLES, f"messages[{i}].role must be one of {sorted(_CHAT_ROLES)}")
        content = msg.get("content")
        if content is not None:
            _require(
                isinstance(content, (str, list)),
                f"messages[{i}].content must be a string or content-part array",
            )
    return _parse_shared(req, ParsedRequest(kind="chat", model=model, messages=messages, raw=req))


def parse_completion_request(req: Dict[str, Any]) -> ParsedRequest:
    """Validate /v1/completions body (ref: openai.rs:327)."""
    _require(isinstance(req, dict), "request body must be a JSON object")
    model = req.get("model")
    _require(isinstance(model, str) and bool(model), "'model' is required")
    prompt = req.get("prompt")
    _require(prompt is not None, "'prompt' is required")
    _require(
        isinstance(prompt, str)
        or (isinstance(prompt, list) and all(isinstance(x, (str, int)) for x in prompt)),
        "'prompt' must be a string, array of strings, or array of token ids",
    )
    return _parse_shared(req, ParsedRequest(kind="completion", model=model, prompt=prompt, raw=req))


def _parse_shared(req: Dict[str, Any], parsed: ParsedRequest) -> ParsedRequest:
    parsed.stream = bool(req.get("stream", False))
    stream_options = req.get("stream_options") or {}
    parsed.stream_usage = bool(stream_options.get("include_usage", False))
    parsed.n = parse_n(req)

    sampling = SamplingOptions(
        temperature=_opt_number(req, "temperature", 0.0, 2.0),
        top_p=_opt_number(req, "top_p", 0.0, 1.0),
        frequency_penalty=_opt_number(req, "frequency_penalty", -2.0, 2.0),
        presence_penalty=_opt_number(req, "presence_penalty", -2.0, 2.0),
        repetition_penalty=_opt_number(req, "repetition_penalty", 0.001, 10.0),
        min_p=_opt_number(req, "min_p", 0.0, 1.0),
        seed=req.get("seed"),
    )
    top_k = req.get("top_k")
    if top_k is not None:
        _require(isinstance(top_k, int) and top_k >= -1, "'top_k' must be an integer >= -1")
        sampling.top_k = top_k
    logit_bias = req.get("logit_bias")
    if logit_bias is not None:
        _require(
            isinstance(logit_bias, dict)
            and all(
                isinstance(k, (str, int)) and str(k).lstrip("-").isdigit()
                and isinstance(v, (int, float))
                for k, v in logit_bias.items()
            ),
            "'logit_bias' must map token ids to numbers",
        )
        _require(len(logit_bias) <= 300, "'logit_bias' supports at most 300 entries")
        sampling.logit_bias = {int(k): float(v) for k, v in logit_bias.items()}
    logprobs = req.get("logprobs")
    if parsed.kind == "chat":
        if logprobs:
            top_logprobs = req.get("top_logprobs", 0) or 0
            _require(
                isinstance(top_logprobs, int) and 0 <= top_logprobs <= 20,
                "'top_logprobs' must be in [0, 20]",
            )
            # 0 alternatives is valid: sampled-token logprob only (OpenAI
            # returns empty top_logprobs lists when none were requested).
            sampling.logprobs = top_logprobs
    elif logprobs is not None:
        _require(isinstance(logprobs, int) and 0 <= logprobs <= 20, "'logprobs' must be in [0, 20]")
        sampling.logprobs = logprobs
    parsed.sampling = sampling

    stop = req.get("stop")
    stop_list: List[str] = []
    if isinstance(stop, str):
        stop_list = [stop]
    elif isinstance(stop, list):
        _require(all(isinstance(s, str) for s in stop) and len(stop) <= 4, "'stop' must be up to 4 strings")
        stop_list = list(stop)
    elif stop is not None:
        raise OpenAIError("'stop' must be a string or array of strings")

    max_tokens = req.get("max_completion_tokens", req.get("max_tokens"))
    if max_tokens is not None:
        _require(isinstance(max_tokens, int) and max_tokens >= 1, "'max_tokens' must be a positive integer")

    nvext = req.get("nvext") or {}
    _require(isinstance(nvext, dict), "'nvext' must be an object")
    parsed.annotations = list(nvext.get("annotations", []) or [])
    ignore_eos = bool(nvext.get("ignore_eos", False))

    parsed.stop = StopConditions(
        max_tokens=max_tokens,
        stop=stop_list,
        stop_token_ids=list(req.get("stop_token_ids", []) or []),
        min_tokens=req.get("min_tokens"),
        ignore_eos=ignore_eos,
    )

    tools = req.get("tools")
    if tools is not None:
        _require(isinstance(tools, list), "'tools' must be an array")
        parsed.tools = tools
        parsed.tool_choice = req.get("tool_choice")
    rf = req.get("response_format")
    if rf is not None:
        _require(isinstance(rf, dict) and "type" in rf, "'response_format' must be an object with 'type'")
        parsed.response_format = rf

    # LoRA selection: model name "base:adapter" or explicit nvext field
    lora = nvext.get("lora_name")
    if isinstance(lora, str) and lora:
        parsed.lora_name = lora
    return parsed


# ---------------------------------------------------------------------------
# Response builders
# ---------------------------------------------------------------------------


def gen_id(prefix: str = "chatcmpl") -> str:
    return f"{prefix}-{uuid.uuid4().hex[:24]}"


def usage_block(prompt_tokens: int, completion_tokens: int) -> Dict[str, Any]:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


def chat_chunk(
    id: str,
    model: str,
    *,
    delta: Dict[str, Any],
    index: int = 0,
    finish_reason: Optional[str] = None,
    created: Optional[int] = None,
    usage: Optional[Dict[str, Any]] = None,
    logprobs: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    chunk: Dict[str, Any] = {
        "id": id,
        "object": "chat.completion.chunk",
        "created": created or int(time.time()),
        "model": model,
        "choices": [
            {
                "index": index,
                "delta": delta,
                "logprobs": logprobs,
                "finish_reason": finish_reason,
            }
        ],
    }
    if usage is not None:
        chunk["usage"] = usage
    return chunk


def chat_logprobs_block(entries) -> Dict[str, Any]:
    """OpenAI chat `choice.logprobs` from TokenLogprob step lists
    (entry 0 = sampled token, entries 1.. = top-N alternatives)."""

    def item(tl) -> Dict[str, Any]:
        s = tl.decoded if tl.decoded is not None else ""
        return {
            "token": s,
            "logprob": tl.logprob,
            "bytes": list(s.encode("utf-8")),
        }

    content = []
    for step in entries:
        head = item(step[0])
        head["top_logprobs"] = [item(tl) for tl in step[1:]]
        content.append(head)
    return {"content": content}


def completion_logprobs_block(entries, text_offset: int = 0) -> Dict[str, Any]:
    """Legacy text-completions `choice.logprobs` (tokens / token_logprobs /
    top_logprobs / text_offset arrays)."""
    tokens: List[str] = []
    token_logprobs: List[float] = []
    top: List[Dict[str, float]] = []
    offsets: List[int] = []
    off = text_offset
    for step in entries:
        s = step[0].decoded if step[0].decoded is not None else ""
        tokens.append(s)
        token_logprobs.append(step[0].logprob)
        top.append(
            {
                (tl.decoded if tl.decoded is not None else str(tl.token_id)): tl.logprob
                for tl in step[1:]
            }
            or None  # OpenAI uses null when no alternatives were requested
        )
        offsets.append(off)
        off += len(s)
    return {
        "tokens": tokens,
        "token_logprobs": token_logprobs,
        "top_logprobs": top,
        "text_offset": offsets,
    }


def completion_envelope(
    id: str,
    model: str,
    *,
    object_: str,  # "chat.completion" | "text_completion"
    choices: List[Dict[str, Any]],
    usage: Dict[str, Any],
    created: Optional[int] = None,
) -> Dict[str, Any]:
    """The unary response envelope — the ONE place its shape is defined
    (HTTP unary handlers pass 1..n pre-built choice entries)."""
    return {
        "id": id,
        "object": object_,
        "created": created or int(time.time()),
        "model": model,
        "choices": choices,
        "usage": usage,
    }


def chat_completion(
    id: str,
    model: str,
    *,
    content: Optional[str],
    finish_reason: str,
    usage: Dict[str, Any],
    role: str = "assistant",
    tool_calls: Optional[List[Dict[str, Any]]] = None,
    reasoning_content: Optional[str] = None,
    logprobs: Optional[Dict[str, Any]] = None,
    created: Optional[int] = None,
) -> Dict[str, Any]:
    message: Dict[str, Any] = {"role": role, "content": content}
    if tool_calls:
        message["tool_calls"] = tool_calls
    if reasoning_content:
        message["reasoning_content"] = reasoning_content
    return completion_envelope(
        id, model, object_="chat.completion", created=created,
        choices=[
            {
                "index": 0,
                "message": message,
                "logprobs": logprobs,
                "finish_reason": finish_reason,
            }
        ],
        usage=usage,
    )


def completion_chunk(
    id: str,
    model: str,
    *,
    text: str,
    index: int = 0,
    finish_reason: Optional[str] = None,
    created: Optional[int] = None,
    usage: Optional[Dict[str, Any]] = None,
    logprobs: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    chunk: Dict[str, Any] = {
        "id": id,
        "object": "text_completion",
        "created": created or int(time.time()),
        "model": model,
        "choices": [
            {"index": index, "text": text, "logprobs": logprobs, "finish_reason": finish_reason}
        ],
    }
    if usage is not None:
        chunk["usage"] = usage
    return chunk


def completion_response(
    id: str,
    model: str,
    *,
    text: str,
    finish_reason: str,
    usage: Dict[str, Any],
    created: Optional[int] = None,
) -> Dict[str, Any]:
    return completion_envelope(
        id, model, object_="text_completion", created=created,
        choices=[
            {"index": 0, "text": text, "logprobs": None, "finish_reason": finish_reason}
        ],
        usage=usage,
    )


def embedding_response(model: str, embeddings: List[List[float]], prompt_tokens: int) -> Dict[str, Any]:
    return {
        "object": "list",
        "data": [
            {"object": "embedding", "index": i, "embedding": e} for i, e in enumerate(embeddings)
        ],
        "model": model,
        "usage": {"prompt_tokens": prompt_tokens, "total_tokens": prompt_tokens},
    }


def model_list(models: List[Dict[str, Any]]) -> Dict[str, Any]:
    return {"object": "list", "data": models}


def model_entry(name: str, created: Optional[int] = None, owned_by: str = "dynamo_tpu") -> Dict[str, Any]:
    return {"id": name, "object": "model", "created": created or int(time.time()), "owned_by": owned_by}
