"""Wire protocols (ref: lib/llm/src/protocols)."""
