"""Internal wire protocols between pipeline stages.

Reference parity: lib/llm/src/protocols/common/llm_backend.rs
(PreprocessedRequest, BackendOutput, LLMEngineOutput) and common/timing.rs
(RequestPhase). These are the framework's *internal* types — the OpenAI wire
types live in protocols/openai.py; the preprocessor converts between them.

Everything serializes to plain dicts (msgpack-able) because these cross the
request plane between processes.
"""

from __future__ import annotations

import time
from dataclasses import asdict, dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional


class FinishReason(str, Enum):
    EOS = "eos"
    STOP = "stop"
    LENGTH = "length"
    CANCELLED = "cancelled"
    ERROR = "error"

    def to_openai(self) -> str:
        return {
            FinishReason.EOS: "stop",
            FinishReason.STOP: "stop",
            FinishReason.LENGTH: "length",
            FinishReason.CANCELLED: "stop",
            FinishReason.ERROR: "error",
        }[self]


@dataclass
class StopConditions:
    """(ref: llm_backend.rs StopConditions)"""

    max_tokens: Optional[int] = None
    stop: List[str] = field(default_factory=list)  # stop strings
    stop_token_ids: List[int] = field(default_factory=list)
    min_tokens: Optional[int] = None
    ignore_eos: bool = False


@dataclass
class SamplingOptions:
    """(ref: llm_backend.rs SamplingOptions)"""

    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    min_p: Optional[float] = None  # drop candidates below min_p × max-prob
    logit_bias: Optional[Dict[int, float]] = None  # token id → additive bias
    seed: Optional[int] = None
    logprobs: Optional[int] = None  # top-N logprobs to return, None = off


@dataclass
class DisaggregatedParams:
    """Bootstrap metadata carried from prefill worker to decode worker
    (ref: kv_router/prefill_router.rs:267–318, SGLang bootstrap rooms)."""

    worker_id: Optional[int] = None
    dp_rank: Optional[int] = None
    kv_transfer: Dict[str, Any] = field(default_factory=dict)  # engine-specific
    prefilled_tokens: Optional[int] = None


@dataclass
class PreprocessedRequest:
    """Tokenized, template-rendered request flowing router → worker
    (ref: llm_backend.rs PreprocessedRequest)."""

    token_ids: List[int]
    model: str = ""
    request_id: str = ""
    sampling: SamplingOptions = field(default_factory=SamplingOptions)
    stop: StopConditions = field(default_factory=StopConditions)
    eos_token_ids: List[int] = field(default_factory=list)
    annotations: List[str] = field(default_factory=list)
    lora_name: Optional[str] = None
    disaggregated_params: Optional[DisaggregatedParams] = None
    # Router hints
    estimated_prefix_hit_blocks: int = 0
    dp_rank: Optional[int] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PreprocessedRequest":
        d = dict(d)
        d["sampling"] = SamplingOptions(**d.get("sampling", {}) or {})
        d["stop"] = StopConditions(**d.get("stop", {}) or {})
        dp = d.get("disaggregated_params")
        d["disaggregated_params"] = DisaggregatedParams(**dp) if dp else None
        return cls(**d)


@dataclass
class TokenLogprob:
    token_id: int
    logprob: float
    decoded: Optional[str] = None


@dataclass
class BackendOutput:
    """One streamed step from an engine: new token ids + bookkeeping
    (ref: llm_backend.rs BackendOutput)."""

    token_ids: List[int] = field(default_factory=list)
    finish_reason: Optional[FinishReason] = None
    cumulative_tokens: Optional[int] = None
    logprobs: Optional[List[List[TokenLogprob]]] = None  # per new token, top-N
    disaggregated_params: Optional[DisaggregatedParams] = None
    error: Optional[str] = None
    # Structured failure taxonomy riding with ``error``: the PR 7
    # classify_failure labels (timeout | connection | decode | other) plus
    # the migration reasons (disagg | no_instances). The frontend maps it
    # to a typed HTTP status / terminal SSE error event instead of a bare
    # 500 (docs/design_docs/overload_control.md, error taxonomy section).
    error_kind: Optional[str] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d = asdict(self)
        if self.finish_reason is not None:
            d["finish_reason"] = self.finish_reason.value
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "BackendOutput":
        d = dict(d)
        fr = d.get("finish_reason")
        d["finish_reason"] = FinishReason(fr) if fr else None
        lps = d.get("logprobs")
        if lps:
            d["logprobs"] = [[TokenLogprob(**t) for t in step] for step in lps]
        dp = d.get("disaggregated_params")
        d["disaggregated_params"] = DisaggregatedParams(**dp) if dp else None
        return cls(**d)


@dataclass
class PostprocessedOutput:
    """Detokenized delta emitted by the Backend operator toward the frontend."""

    text: str = ""
    token_ids: List[int] = field(default_factory=list)
    finish_reason: Optional[FinishReason] = None
    cumulative_tokens: int = 0
    logprobs: Optional[List[List[TokenLogprob]]] = None
    error: Optional[str] = None
    error_kind: Optional[str] = None  # see BackendOutput.error_kind


class RequestPhase(str, Enum):
    """(ref: protocols/common/timing.rs)"""

    RECEIVED = "received"
    PREPROCESSED = "preprocessed"
    ROUTED = "routed"
    PREFILLING = "prefilling"
    FIRST_TOKEN = "first_token"
    DECODING = "decoding"
    COMPLETE = "complete"


@dataclass
class RequestTiming:
    phases: Dict[str, float] = field(default_factory=dict)

    def mark(self, phase: RequestPhase) -> None:
        self.phases.setdefault(phase.value, time.monotonic())

    def ttft(self) -> Optional[float]:
        t0 = self.phases.get(RequestPhase.RECEIVED.value)
        t1 = self.phases.get(RequestPhase.FIRST_TOKEN.value)
        return (t1 - t0) if (t0 is not None and t1 is not None) else None
