"""Dynamic model discovery: register_llm + ModelWatcher.

Reference parity: lib/bindings rust/lib.rs:232 (register_llm — publish a
ModelDeploymentCard to the discovery plane under the worker's lease) and
lib/llm/src/discovery/watcher.rs:57,112 (ModelWatcher — watch the models/
prefix; on add, assemble a routed pipeline and hand it to the frontend's
ModelManager; on delete, tear it down when the last instance goes).

The assembled chain matches entrypoint/input/common.rs:173:
    OpenAIPreprocessor → Backend → Migration → Client[KV-routed]
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, Optional

from dynamo_tpu.llm.backend import Backend
from dynamo_tpu.llm.entrypoint import resolve_chat_template, resolve_tokenizer
from dynamo_tpu.llm.migration import Migration
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.router import KvRouter, KvRouterConfig
from dynamo_tpu.runtime.component import Endpoint, RouterMode
from dynamo_tpu.runtime.discovery import MODELS_PREFIX, model_key
from dynamo_tpu.runtime.pipeline import build_pipeline
from dynamo_tpu.runtime.tasks import reap_task
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


async def register_llm(
    runtime: Any,
    card: ModelDeploymentCard,
    endpoint: Endpoint,
    instance_id: int,
    incarnation: int = 0,
) -> str:
    """Publish the model card for a served endpoint instance. Returns the
    discovery key. The card rides the runtime's serving lease, so it vanishes
    with the worker (liveness, ref: watcher.rs delete handling).

    ``incarnation`` (runtime/liveness.py process_incarnation) rides the doc
    so the frontend's liveness tracker fences the registration itself: a
    restarted worker re-registering under the same instance_id announces
    its fresh incarnation before its first load report arrives."""
    key = model_key(endpoint.namespace, card.slug, instance_id)
    doc = {
        "card": card.to_dict(),
        "endpoint": {
            "namespace": endpoint.namespace,
            "component": endpoint.component,
            "endpoint": endpoint.name,
        },
        "instance_id": instance_id,
        "incarnation": incarnation,
    }
    # put_leased remembers the doc: a control-plane outage that expires
    # the lease gets the card re-registered automatically on recovery.
    await runtime.put_leased(key, doc)
    logger.info("registered model %s at %s", card.name, key)
    return key


class ModelWatcher:
    """Feeds a ModelManager from the discovery plane."""

    def __init__(
        self,
        runtime: Any,
        model_manager: Any,
        *,
        router_mode: RouterMode = RouterMode.KV,
        kv_router_config: Optional[KvRouterConfig] = None,
        enable_disagg: bool = True,
        prefill_component: str = "prefill",
        encode_component: str = "encoder",
        disagg_threshold_tokens: int = 32,
        enable_busy_monitor: bool = True,
        enable_canary: bool = False,
        canary_interval_s: float = 5.0,
        canary_timeout_s: float = 10.0,
        enable_liveness: bool = True,
        liveness_config: Optional[Any] = None,  # runtime.liveness.LivenessConfig
    ) -> None:
        self._runtime = runtime
        self._manager = model_manager
        self.router_mode = router_mode
        self._kv_config = kv_router_config
        self.enable_disagg = enable_disagg
        self.prefill_component = prefill_component
        self.encode_component = encode_component
        self.disagg_threshold_tokens = disagg_threshold_tokens
        self.enable_busy_monitor = enable_busy_monitor
        self.enable_canary = enable_canary
        self.canary_interval_s = canary_interval_s
        self.canary_timeout_s = canary_timeout_s
        # Crash plane: missed-load-report dead-worker detection with the
        # drop_worker + stream-abort reconciliation (runtime/liveness.py).
        self.enable_liveness = enable_liveness
        self._liveness_config = liveness_config
        # model slug → state
        self._models: Dict[str, Dict[str, Any]] = {}
        self._task: Optional[asyncio.Task] = None
        self._watch = None
        self._ready = asyncio.Event()

    async def start(self) -> None:
        self._watch = self._runtime.discovery.watch(MODELS_PREFIX)
        for event in self._watch.drain_snapshot():
            await self._apply(event)
        self._ready.set()
        self._task = asyncio.get_running_loop().create_task(
            self._run(), name="model-watcher"
        )

    async def stop(self) -> None:
        if self._watch is not None:
            await self._watch.aclose()
        if self._task is not None:
            self._task.cancel()
            await reap_task(self._task, "model-watcher", logger)
        for slug in list(self._models):
            await self._remove_model(slug)

    async def wait_for_model(self, name: str, timeout: float = 10.0) -> None:
        async def poll() -> None:
            while self._manager.get(name) is None:
                await asyncio.sleep(0.05)

        await asyncio.wait_for(poll(), timeout)

    async def _run(self) -> None:
        async for event in self._watch:
            try:
                await self._apply(event)
            except Exception:
                logger.exception("model watch event failed")

    async def _apply(self, event) -> None:
        # key: models/{namespace}/{slug}/{instance_id}
        parts = event.key.split("/")
        if len(parts) != 4:
            return
        _, namespace, slug, iid_hex = parts
        from dynamo_tpu.runtime.discovery import EventKind

        if event.kind == EventKind.PUT and event.value is not None:
            await self._add_instance(slug, event.value)
        elif event.kind == EventKind.DELETE:
            await self._drop_instance(slug, iid_hex)

    async def _add_instance(self, slug: str, doc: Dict[str, Any]) -> None:
        state = self._models.get(slug)
        if state is not None:
            state["instances"].add(doc["instance_id"])
            if state.get("liveness") is not None and doc.get("incarnation"):
                # Registration is evidence of life AND of identity: seed
                # the fence/last-seen now so a warm-rejoining worker's old
                # incarnation is purged before its first load report.
                state["liveness"].observe_report(
                    doc["instance_id"], doc["incarnation"]
                )
            return
        card = ModelDeploymentCard.from_dict(doc["card"])
        ep_info = doc["endpoint"]
        endpoint = (
            self._runtime.namespace(ep_info["namespace"])
            .component(ep_info["component"])
            .endpoint(ep_info["endpoint"])
        )
        client = await endpoint.client(self.router_mode)
        router = None
        if self.router_mode == RouterMode.KV:
            router = KvRouter(
                self._runtime,
                ep_info["namespace"],
                ep_info["component"],
                block_size=card.kv_block_size,
                config=self._kv_config,
            )
            await router.start()
            router.attach(client)
        tokenizer = resolve_tokenizer(card)
        operators = [
            OpenAIPreprocessor(card, tokenizer, resolve_chat_template(card)),
        ]
        if card.model_type == "multimodal":
            # E/P/D staging: encode images via the encode component, then
            # splice placeholders + embeddings into the preprocessed request
            # (multimodal/handlers.py MultimodalPreprocessor, the
            # ECProcessor role). The encode worker registers at
            # <namespace>/<encode_component>/encode.
            from dynamo_tpu.multimodal import MultimodalPreprocessor

            mm_ns = ep_info["namespace"]

            async def encode_client():
                return await (
                    self._runtime.namespace(mm_ns)
                    .component(self.encode_component)
                    .endpoint("encode")
                    .client()
                )

            operators.append(MultimodalPreprocessor(encode_client))
        operators += [
            Backend(tokenizer),
            Migration(card.migration_limit),
        ]
        if self.enable_disagg:
            from dynamo_tpu.disagg import PrefillRouter

            ns = ep_info["namespace"]

            async def prefill_client():
                return await (
                    self._runtime.namespace(ns)
                    .component(self.prefill_component)
                    .endpoint("generate")
                    .client()
                )

            operators.append(
                PrefillRouter(
                    prefill_client, threshold_tokens=self.disagg_threshold_tokens
                )
            )
        pipeline = build_pipeline(operators, client)
        monitor = None
        liveness = None
        if self.enable_liveness:
            from dynamo_tpu import config as _cfg
            from dynamo_tpu.runtime.liveness import (
                LivenessConfig,
                LivenessTracker,
                WorkerLostError,
            )

            liveness = LivenessTracker(
                self._liveness_config
                or LivenessConfig(
                    interval_s=_cfg.LIVENESS_INTERVAL_S.get(),
                    suspect_after=_cfg.LIVENESS_SUSPECT_AFTER.get(),
                    dead_after=_cfg.LIVENESS_DEAD_AFTER.get(),
                )
            )
            client.enable_stream_aborts()

            def on_dead(worker_id: int, _inc: int, _router=router,
                        _client=client, _liveness=liveness) -> None:
                # The whole crash-recovery fan-out for an unplanned death:
                # (1) one drop_worker reconciliation (charges, link pairs,
                # breaker faults, radix entries), (2) routing eviction
                # ahead of the discovery lease expiring, (3) every
                # in-flight stream aborted into the migration ladder with
                # the typed worker_lost reason — all bounded by the
                # missed-report budget, none of it waiting on TCP.
                if _router is not None:
                    _router.drop_worker((worker_id, 0))
                _client.evict_instance(worker_id)
                aborted = _client.abort_instance(
                    worker_id,
                    WorkerLostError(
                        f"worker {worker_id:#x} declared dead (missed "
                        "load reports); re-dispatch with carried tokens"
                    ),
                )
                if aborted:
                    _liveness.note_streams_aborted(worker_id, aborted)

            def on_rejoin(worker_id: int, _inc: int, _router=router,
                          _client=client) -> None:
                # A rejoin: purge whatever state the old incarnation left
                # so the worker's reports and KV events rebuild from a
                # clean slate (its restored prefixes arrive via the
                # re-advertised snapshot) — and give its routing capacity
                # back. A RESTARTED worker re-PUTs its key (the watch
                # re-adds fresh transport), but a frozen-and-resumed one
                # (same incarnation, no new PUT) only comes back through
                # the revive; without it the eviction would be permanent.
                if _router is not None:
                    _router.drop_worker((worker_id, 0))
                _client.revive_instance(worker_id)

            liveness.add_dead_callback(on_dead)
            liveness.add_rejoin_callback(on_rejoin)
        if self.enable_busy_monitor or liveness is not None:
            from dynamo_tpu.http.worker_monitor import WorkerLoadMonitor

            monitor = WorkerLoadMonitor(
                self._runtime.event_plane, ep_info["namespace"],
                ep_info["component"], liveness=liveness,
            )
            await monitor.start()
        health = None
        if self.enable_canary:
            from dynamo_tpu.runtime.health import CanaryHealthChecker

            health = CanaryHealthChecker(
                client,
                interval_s=self.canary_interval_s,
                timeout_s=self.canary_timeout_s,
            )
            health.start()
        ns, comp = ep_info["namespace"], ep_info["component"]

        async def clear_kv() -> int:
            """Fan clear_kv_blocks out to every live worker instance
            (ref: clear_kv_blocks.rs)."""
            from dynamo_tpu.runtime.engine import collect

            ctl = await (
                self._runtime.namespace(ns).component(comp).endpoint("control").client()
            )
            cleared = 0
            try:
                for iid in list(ctl.instance_ids):
                    try:
                        out = await collect(ctl.direct({"op": "clear_kv_blocks"}, iid))
                        cleared += int(out[-1].get("cleared", 0)) if out else 0
                    except Exception:
                        logger.exception("clear_kv_blocks on %#x failed", iid)
            finally:
                await ctl.close()
            return cleared

        self._models[slug] = {
            "card": card,
            "client": client,
            "router": router,
            "monitor": monitor,
            "health": health,
            "liveness": liveness,
            "instances": {doc["instance_id"]},
        }
        if liveness is not None and doc.get("incarnation"):
            liveness.observe_report(doc["instance_id"], doc["incarnation"])
        self._manager.register(
            card.name, pipeline, card, monitor=monitor, health=health,
            admin={"clear_kv": clear_kv},
        )
        logger.info("model %s online (instance %x)", card.name, doc["instance_id"])

    async def _drop_instance(self, slug: str, iid_hex: str) -> None:
        state = self._models.get(slug)
        if state is None:
            return
        try:
            iid = int(iid_hex, 16)
        except ValueError:
            iid = None
        state["instances"].discard(iid)
        if state["router"] is not None and iid is not None:
            state["router"].remove_worker((iid, 0))
        if state.get("monitor") is not None and iid is not None:
            state["monitor"].drop_worker(iid)
        if state.get("liveness") is not None and iid is not None:
            # Discovery DELETE is the permanent departure: forget the
            # tracker entry (and its fence) so dead workers don't
            # accumulate across fleet turnover.
            state["liveness"].drop(iid)
        if not state["instances"]:
            await self._remove_model(slug)

    async def _remove_model(self, slug: str) -> None:
        state = self._models.pop(slug, None)
        if state is None:
            return
        self._manager.unregister(state["card"].name)
        if state.get("health") is not None:
            await state["health"].stop()
        if state.get("monitor") is not None:
            await state["monitor"].stop()
        if state["router"] is not None:
            await state["router"].stop()
        await state["client"].close()
        logger.info("model %s offline", state["card"].name)
