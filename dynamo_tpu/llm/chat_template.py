"""Chat-template rendering via jinja2.

Reference parity: lib/llm/src/preprocessor/prompt/template/oai.rs (minijinja
rendering of HF chat templates). Templates come from the model directory's
tokenizer_config.json (``chat_template``) or fall back to ChatML.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

import jinja2

# ChatML (Qwen-style) default — the most common open-model convention.
DEFAULT_CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "{{ '<|im_start|>' + message['role'] + '\n' + message['content'] + '<|im_end|>' + '\n' }}"
    "{% endfor %}"
    "{% if add_generation_prompt %}{{ '<|im_start|>assistant\n' }}{% endif %}"
)


class ChatTemplate:
    def __init__(self, template: str = DEFAULT_CHAT_TEMPLATE) -> None:
        self.source = template
        env = jinja2.Environment(
            loader=jinja2.BaseLoader(),
            trim_blocks=True,
            lstrip_blocks=True,
            # HF templates use .items() etc.; keep default but sandbox-free
            # since templates come from trusted local model dirs.
        )
        env.globals["raise_exception"] = _raise_exception
        env.filters["tojson"] = lambda value, **kw: json.dumps(value, **kw)
        self._template = env.from_string(template)

    @classmethod
    def from_model_dir(cls, model_dir: str) -> "ChatTemplate":
        path = os.path.join(model_dir, "tokenizer_config.json")
        if os.path.exists(path):
            try:
                with open(path) as f:
                    cfg = json.load(f)
                tpl = cfg.get("chat_template")
                if isinstance(tpl, list):
                    # Newer HF format: [{"name": "default", "template": ...}]
                    for entry in tpl:
                        if entry.get("name") == "default":
                            tpl = entry.get("template")
                            break
                    else:
                        tpl = tpl[0].get("template") if tpl else None
                if isinstance(tpl, str) and tpl:
                    return cls(tpl)
            except (OSError, json.JSONDecodeError):
                pass
        chat_path = os.path.join(model_dir, "chat_template.jinja")
        if os.path.exists(chat_path):
            with open(chat_path) as f:
                return cls(f.read())
        return cls()

    def render(
        self,
        messages: List[Dict[str, Any]],
        *,
        add_generation_prompt: bool = True,
        tools: Optional[List[Dict[str, Any]]] = None,
        bos_token: str = "",
        eos_token: str = "",
        **extra: Any,
    ) -> str:
        # Flatten OpenAI content-part arrays to text (multimodal parts are
        # handled upstream by the media preprocessor).
        normalized = []
        for msg in messages:
            msg = dict(msg)
            content = msg.get("content")
            if isinstance(content, list):
                msg["content"] = "".join(
                    part.get("text", "") for part in content if part.get("type") == "text"
                )
            elif content is None:
                msg["content"] = ""
            normalized.append(msg)
        return self._template.render(
            messages=normalized,
            add_generation_prompt=add_generation_prompt,
            tools=tools,
            bos_token=bos_token,
            eos_token=eos_token,
            **extra,
        )


def _raise_exception(message: str) -> None:
    raise jinja2.TemplateError(message)
