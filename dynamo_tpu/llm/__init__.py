"""LLM serving layer (ref: dynamo-llm crate, lib/llm)."""

from dynamo_tpu.llm.backend import Backend
from dynamo_tpu.llm.chat_template import ChatTemplate, DEFAULT_CHAT_TEMPLATE
from dynamo_tpu.llm.model_card import ModelDeploymentCard, RuntimeConfig, slugify
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.llm.protocols.common import (
    BackendOutput,
    DisaggregatedParams,
    FinishReason,
    PostprocessedOutput,
    PreprocessedRequest,
    RequestPhase,
    RequestTiming,
    SamplingOptions,
    StopConditions,
    TokenLogprob,
)
from dynamo_tpu.llm.protocols.openai import OpenAIError, parse_chat_request, parse_completion_request
from dynamo_tpu.llm.tokenizer import DecodeStream, HFTokenizer, Tokenizer, tiny_tokenizer

__all__ = [
    "Backend",
    "BackendOutput",
    "ChatTemplate",
    "DEFAULT_CHAT_TEMPLATE",
    "DecodeStream",
    "DisaggregatedParams",
    "FinishReason",
    "HFTokenizer",
    "ModelDeploymentCard",
    "OpenAIError",
    "OpenAIPreprocessor",
    "PostprocessedOutput",
    "PreprocessedRequest",
    "RequestPhase",
    "RequestTiming",
    "RuntimeConfig",
    "SamplingOptions",
    "StopConditions",
    "TokenLogprob",
    "Tokenizer",
    "parse_chat_request",
    "parse_completion_request",
    "slugify",
    "tiny_tokenizer",
]
