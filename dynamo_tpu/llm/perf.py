"""Logprob sensitivity analysis over recorded streams.

Reference parity: lib/llm/src/perf/logprobs.rs — given streams that carry
top-N logprobs, find the positions where the model was UNCERTAIN (top-2
candidates close in probability). Those are the positions where sampling
temperature, quantization, or a kernel change flips tokens — the first
thing to look at when two engine builds disagree on output.

Works on live BackendOutput streams or recordings from llm/recorder.py:

    streams = load_recording("capture.jsonl")
    analysis = analyze_logprob_sensitivity(streams)
    analysis.close_positions(threshold=0.1)   # near-ties
    analysis.close_fraction(threshold=0.1)    # how unstable was this run?
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class Candidate:
    token_id: int
    logprob: float
    decoded: Optional[str] = None


@dataclass
class PositionCloseness:
    """(ref: logprobs.rs PositionCloseness)"""

    stream_index: int  # which stream
    token_position: int  # position within the stream's token sequence
    logprob_difference: float  # top1 - top2 logprob
    probability_difference: float  # linear-space difference
    probability_remaining: float  # 1 - sum of candidate probabilities
    candidates: List[Candidate] = field(default_factory=list)


@dataclass
class SensitivityAnalysis:
    """(ref: logprobs.rs SensitivityAnalysis / ChoiceAnalysis)"""

    total_streams: int = 0
    positions: List[PositionCloseness] = field(default_factory=list)

    @property
    def positions_analyzed(self) -> int:
        return len(self.positions)

    def close_positions(self, threshold: float = 0.1) -> List[PositionCloseness]:
        """Positions whose top-2 probability gap is at most ``threshold``,
        most uncertain first (ref: get_close_positions_for_choice :352)."""
        out = [
            p for p in self.positions if p.probability_difference <= threshold
        ]
        out.sort(key=lambda p: p.probability_difference)
        return out

    def close_fraction(self, threshold: float = 0.1) -> float:
        """Share of analyzed positions that are near-ties
        (ref: close_position_percentage_for_choice :425)."""
        if not self.positions:
            return 0.0
        return len(self.close_positions(threshold)) / len(self.positions)

    def most_uncertain(self, n: int = 10) -> List[PositionCloseness]:
        return sorted(self.positions, key=lambda p: p.probability_difference)[:n]


def _positions_from_item(item: Any) -> List[List[Candidate]]:
    """Per-token candidate lists from one stream item (BackendOutput dict
    or object with a `logprobs` field: [positions][candidates]).
    Positions WITHOUT candidates stay as empty lists — alignment with the
    item's token indices must survive (compare_streams keys near-ties by
    (stream, token_position))."""
    lp = item.get("logprobs") if isinstance(item, dict) else getattr(
        item, "logprobs", None
    )
    if not lp:
        return []
    out = []
    for position in lp:
        cands = []
        for c in position or ():
            if isinstance(c, dict):
                cands.append(
                    Candidate(
                        token_id=int(c.get("token_id", -1)),
                        logprob=float(c.get("logprob", 0.0)),
                        decoded=c.get("decoded"),
                    )
                )
            else:
                cands.append(
                    Candidate(
                        token_id=int(getattr(c, "token_id", -1)),
                        logprob=float(getattr(c, "logprob", 0.0)),
                        decoded=getattr(c, "decoded", None),
                    )
                )
        out.append(cands)
    return out


def _item_token_count(item: Any) -> int:
    ids = item.get("token_ids") if isinstance(item, dict) else getattr(
        item, "token_ids", None
    )
    return len(ids) if ids else 0


def analyze_logprob_sensitivity(
    streams: Sequence[Any],
) -> SensitivityAnalysis:
    """``streams``: RecordedStream objects (recorder.py) or plain lists of
    stream items. Positions without at least 2 candidates are skipped —
    closeness needs an alternative (ref: analyze_logprob_sensitivity :270)."""
    analysis = SensitivityAnalysis(total_streams=len(streams))
    for si, stream in enumerate(streams):
        items = getattr(stream, "items", stream)
        tok_pos = 0
        for item in items:
            positions = _positions_from_item(item)
            # Token positions advance by the item's TOKEN count — an item
            # with tokens but partial/missing logprobs must not shift later
            # positions (compare_streams aligns by real token index).
            n_tokens = max(_item_token_count(item), len(positions))
            for i in range(len(positions)):
                cands = sorted(positions[i], key=lambda c: -c.logprob)
                if len(cands) >= 2:
                    p1 = math.exp(min(cands[0].logprob, 0.0))
                    p2 = math.exp(min(cands[1].logprob, 0.0))
                    mass = sum(
                        math.exp(min(c.logprob, 0.0)) for c in cands
                    )
                    analysis.positions.append(
                        PositionCloseness(
                            stream_index=si,
                            token_position=tok_pos + i,
                            logprob_difference=cands[0].logprob - cands[1].logprob,
                            probability_difference=p1 - p2,
                            probability_remaining=max(1.0 - mass, 0.0),
                            candidates=cands,
                        )
                    )
            tok_pos += n_tokens
    return analysis


def compare_streams(
    a: Sequence[Any], b: Sequence[Any], threshold: float = 0.1
) -> Dict[str, Any]:
    """Two captures of the same workload (e.g. before/after a kernel
    change): where do the chosen tokens diverge, and were those positions
    near-ties? A divergence at a near-tie is expected sampling noise; a
    divergence at a confident position is a correctness signal."""
    ana = analyze_logprob_sensitivity(a)
    close = {
        (p.stream_index, p.token_position)
        for p in ana.close_positions(threshold)
    }
    divergences = []
    for si, (sa, sb) in enumerate(zip(a, b)):
        ta = _token_seq(sa)
        tb = _token_seq(sb)
        for pos, (x, y) in enumerate(zip(ta, tb)):
            if x != y:
                divergences.append(
                    {
                        "stream": si,
                        "position": pos,
                        "a_token": x,
                        "b_token": y,
                        "near_tie": (si, pos) in close,
                    }
                )
    suspicious = [d for d in divergences if not d["near_tie"]]
    return {
        "divergences": divergences,
        "suspicious": suspicious,
        "total_compared": min(len(a), len(b)),
    }


def _token_seq(stream: Any) -> List[int]:
    items = getattr(stream, "items", stream)
    out: List[int] = []
    for item in items:
        ids = item.get("token_ids") if isinstance(item, dict) else getattr(
            item, "token_ids", None
        )
        out.extend(ids or ())
    return out
