"""Tokenizer abstraction + incremental detokenization.

Reference parity: lib/llm/src/tokenizers.rs (HF `tokenizers` wrapper with a
DecodeStream). Backed by the HuggingFace `tokenizers` runtime; tests use a
locally-trained tiny BPE (no network in this environment — models must be on
disk, matching the reference's local_model/hub.rs local-path flow).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Protocol, Sequence

_REPLACEMENT = "�"


class Tokenizer(Protocol):
    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]: ...
    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str: ...
    @property
    def vocab_size(self) -> int: ...
    @property
    def eos_token_ids(self) -> List[int]: ...
    @property
    def bos_token_id(self) -> Optional[int]: ...


class HFTokenizer:
    """Wraps a HuggingFace tokenizer.json (ref: tokenizers.rs)."""

    def __init__(self, tok, eos_token_ids: Optional[List[int]] = None, bos_token_id: Optional[int] = None) -> None:
        self._tok = tok
        self._eos = list(eos_token_ids or [])
        self._bos = bos_token_id

    @classmethod
    def from_file(cls, path: str) -> "HFTokenizer":
        from tokenizers import Tokenizer as _HfTok

        tok = _HfTok.from_file(path)
        eos, bos = _special_ids_from_config(os.path.dirname(path), tok)
        return cls(tok, eos_token_ids=eos, bos_token_id=bos)

    @classmethod
    def from_pretrained_dir(cls, model_dir: str) -> "HFTokenizer":
        path = os.path.join(model_dir, "tokenizer.json")
        if not os.path.exists(path):
            raise FileNotFoundError(f"no tokenizer.json under {model_dir}")
        return cls.from_file(path)

    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]:
        return self._tok.encode(text, add_special_tokens=add_special_tokens).ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special_tokens)

    def token_to_id(self, token: str) -> Optional[int]:
        return self._tok.token_to_id(token)

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()

    @property
    def eos_token_ids(self) -> List[int]:
        return self._eos

    @property
    def bos_token_id(self) -> Optional[int]:
        return self._bos


def _special_ids_from_config(model_dir: str, tok) -> tuple:
    """Pull eos/bos ids from config.json / generation_config.json /
    tokenizer_config.json when present (ref: model_card.rs special-token
    resolution)."""
    eos: List[int] = []
    bos: Optional[int] = None
    for name in ("generation_config.json", "config.json"):
        path = os.path.join(model_dir, name)
        if not os.path.exists(path):
            continue
        try:
            with open(path) as f:
                cfg = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        raw_eos = cfg.get("eos_token_id")
        if raw_eos is not None and not eos:
            eos = [raw_eos] if isinstance(raw_eos, int) else list(raw_eos)
        if bos is None and isinstance(cfg.get("bos_token_id"), int):
            bos = cfg["bos_token_id"]
    cfg_path = os.path.join(model_dir, "tokenizer_config.json")
    if not eos and os.path.exists(cfg_path):
        try:
            with open(cfg_path) as f:
                tcfg = json.load(f)
            eos_tok = tcfg.get("eos_token")
            if isinstance(eos_tok, dict):
                eos_tok = eos_tok.get("content")
            if isinstance(eos_tok, str):
                tid = tok.token_to_id(eos_tok)
                if tid is not None:
                    eos = [tid]
        except (OSError, json.JSONDecodeError):
            pass
    return eos, bos


class DecodeStream:
    """Incremental detokenizer: feed token ids, get printable text deltas.

    Handles multi-token unicode (holds back text ending in U+FFFD until the
    codepoint completes) and tokenizers whose decode needs left context
    (sentencepiece-style leading-space semantics). Algorithm matches the
    reference's tokenizers.rs DecodeStream / vLLM's incremental decode.
    """

    def __init__(self, tokenizer: Tokenizer, skip_special_tokens: bool = True) -> None:
        self._tok = tokenizer
        self._skip_special = skip_special_tokens
        self._ids: List[int] = []
        self._prefix_offset = 0
        self._read_offset = 0

    def step(self, token_ids: Sequence[int]) -> str:
        """Append new token ids; return newly-finalized text (may be '')."""
        self._ids.extend(token_ids)
        prefix_text = self._tok.decode(
            self._ids[self._prefix_offset : self._read_offset],
            skip_special_tokens=self._skip_special,
        )
        full_text = self._tok.decode(
            self._ids[self._prefix_offset :], skip_special_tokens=self._skip_special
        )
        if len(full_text) > len(prefix_text) and not full_text.endswith(_REPLACEMENT):
            delta = full_text[len(prefix_text) :]
            self._prefix_offset = self._read_offset
            self._read_offset = len(self._ids)
            return delta
        return ""

    @property
    def token_count(self) -> int:
        return len(self._ids)

    def flush(self) -> str:
        """Emit whatever is held back (end of stream)."""
        prefix_text = self._tok.decode(
            self._ids[self._prefix_offset : self._read_offset],
            skip_special_tokens=self._skip_special,
        )
        full_text = self._tok.decode(
            self._ids[self._prefix_offset :], skip_special_tokens=self._skip_special
        )
        delta = full_text[len(prefix_text) :]
        self._prefix_offset = len(self._ids)
        self._read_offset = len(self._ids)
        return delta.rstrip(_REPLACEMENT)


# ---------------------------------------------------------------------------
# Test tokenizer (trained in-process; no network)
# ---------------------------------------------------------------------------

_TINY_CACHE: Dict[int, HFTokenizer] = {}


def tiny_tokenizer(vocab_size: int = 512) -> HFTokenizer:
    """A small byte-level BPE trained on a synthetic corpus, for tests and
    the mock engine. Deterministic per vocab_size; cached per process."""
    if vocab_size in _TINY_CACHE:
        return _TINY_CACHE[vocab_size]
    from tokenizers import Tokenizer as _HfTok
    from tokenizers import decoders, models, pre_tokenizers, trainers

    tok = _HfTok(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=vocab_size,
        special_tokens=["<|endoftext|>", "<|im_start|>", "<|im_end|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        show_progress=False,
    )
    corpus = [
        "the quick brown fox jumps over the lazy dog",
        "hello world this is a test of the tokenizer",
        "paged attention continuous batching on tpu hardware",
        "0123456789 !@#$%^&*()",
        "streaming tokens one at a time over the wire",
    ] * 4
    tok.train_from_iterator(corpus, trainer=trainer)
    wrapped = HFTokenizer(
        tok,
        eos_token_ids=[tok.token_to_id("<|endoftext|>")],
        bos_token_id=None,
    )
    _TINY_CACHE[vocab_size] = wrapped
    return wrapped
