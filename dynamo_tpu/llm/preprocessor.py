"""OpenAIPreprocessor: OpenAI request → PreprocessedRequest (token ids).

Reference parity: lib/llm/src/preprocessor.rs:131 (OpenAIPreprocessor as a
pipeline Operator), preprocessor/prompt/template/oai.rs (templating),
annotations `formatted_prompt`/`token_ids` (preprocessor.rs:66–68).
"""

from __future__ import annotations

import logging
from typing import Any, AsyncIterator, Dict, Optional, Union

from dynamo_tpu.llm.chat_template import ChatTemplate
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.protocols.common import PreprocessedRequest
from dynamo_tpu.llm.protocols.openai import (
    OpenAIError,
    ParsedRequest,
    parse_chat_request,
    parse_completion_request,
)
from dynamo_tpu.llm.tokenizer import Tokenizer
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine

logger = logging.getLogger(__name__)

ANNOTATION_FORMATTED_PROMPT = "formatted_prompt"
ANNOTATION_TOKEN_IDS = "token_ids"


class OpenAIPreprocessor:
    """Pipeline operator: validates, templates, tokenizes, defaults sampling.

    Emits annotation events (dicts with an ``annotation`` key) ahead of engine
    output when requested via nvext.annotations, matching the reference's
    SSE-comment annotations.
    """

    def __init__(
        self,
        card: ModelDeploymentCard,
        tokenizer: Tokenizer,
        chat_template: Optional[ChatTemplate] = None,
    ) -> None:
        self.card = card
        self.tokenizer = tokenizer
        self.chat_template = chat_template or ChatTemplate()

    # -- request conversion ------------------------------------------------

    def preprocess(self, request: Union[Dict[str, Any], ParsedRequest]) -> PreprocessedRequest:
        parsed = self._parse(request)
        media_urls: list = []
        if parsed.kind == "chat":
            messages = parsed.messages
            if any(isinstance(m.get("content"), list) for m in messages):
                # Content-parts form: extract image URLs for the encode
                # stage (ref: preprocessor/media extraction); the template
                # renders the text-only rewrite.
                from dynamo_tpu.multimodal.handlers import extract_image_parts

                messages, media_urls = extract_image_parts(messages)
            prompt = self.chat_template.render(
                messages,
                add_generation_prompt=True,
                tools=parsed.tools,
            )
            token_ids = self.tokenizer.encode(prompt)
        else:
            prompt, token_ids = self._completion_prompt(parsed)

        max_context = self.card.context_length
        if len(token_ids) >= max_context:
            raise OpenAIError(
                f"prompt has {len(token_ids)} tokens which exceeds the model's "
                f"context length of {max_context}",
                status=400,
            )

        stop = parsed.stop
        if stop.max_tokens is None:
            stop.max_tokens = max_context - len(token_ids)
        else:
            stop.max_tokens = min(stop.max_tokens, max_context - len(token_ids))

        sampling = parsed.sampling
        if sampling.temperature is None:
            sampling.temperature = 1.0
        if sampling.top_p is None:
            sampling.top_p = 1.0

        pre = PreprocessedRequest(
            token_ids=token_ids,
            model=parsed.model,
            sampling=sampling,
            stop=stop,
            eos_token_ids=list(self.tokenizer.eos_token_ids or self.card.eos_token_ids),
            annotations=parsed.annotations,
            lora_name=parsed.lora_name,
        )
        if ANNOTATION_FORMATTED_PROMPT in parsed.annotations:
            pre.extra[ANNOTATION_FORMATTED_PROMPT] = prompt
        if media_urls:
            pre.extra["_mm_media"] = media_urls
        if isinstance(request, dict) and "_pinned_worker" in request:
            # Gateway pin (EPP header hint): survives preprocessing so the
            # request-plane KV picker can honor it (router.py attach).
            pre.extra["_pinned_worker"] = int(request["_pinned_worker"])
        return pre

    def _parse(self, request: Union[Dict[str, Any], ParsedRequest]) -> ParsedRequest:
        if isinstance(request, ParsedRequest):
            return request
        if "messages" in request:
            return parse_chat_request(request)
        return parse_completion_request(request)

    def _completion_prompt(self, parsed: ParsedRequest):
        prompt = parsed.prompt
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
            return None, list(prompt)  # pre-tokenized
        if isinstance(prompt, list):
            if len(prompt) != 1:
                raise OpenAIError("batched prompts are not supported on this endpoint; send one prompt per request")
            prompt = prompt[0]
        text = str(prompt)
        bos = self.tokenizer.bos_token_id
        ids = self.tokenizer.encode(text)
        if bos is not None and (not ids or ids[0] != bos):
            ids = [bos] + ids
        return text, ids

    # -- operator ----------------------------------------------------------

    async def generate(
        self, request: Any, context: Context, next: AsyncEngine
    ) -> AsyncIterator[Any]:
        pre = self.preprocess(request)
        pre.request_id = context.id
        from dynamo_tpu.runtime import lifecycle

        lifecycle.record(
            pre.request_id, "tokenized",
            context=context, n_tokens=len(pre.token_ids),
        )
        # Internal annotation consumed by the frontend for usage reporting
        # (never forwarded to clients).
        yield {"annotation": "_prompt_tokens", "value": len(pre.token_ids)}
        for annotation in pre.annotations:
            if annotation == ANNOTATION_FORMATTED_PROMPT and ANNOTATION_FORMATTED_PROMPT in pre.extra:
                yield {"annotation": ANNOTATION_FORMATTED_PROMPT, "value": pre.extra[ANNOTATION_FORMATTED_PROMPT]}
            elif annotation == ANNOTATION_TOKEN_IDS:
                yield {"annotation": ANNOTATION_TOKEN_IDS, "value": list(pre.token_ids)}
        async for item in next.generate(pre, context):
            yield item
