"""Pipeline assembly entrypoints.

Reference parity: lib/llm/src/entrypoint/input/common.rs:173
(build_routed_pipeline: SegmentSource → OpenAIPreprocessor → Backend →
Migration → Router) and entrypoint.rs EngineConfig. The local variant wires
an in-process engine; the routed variant (runtime/network + router tasks)
inserts Migration and a router client between Backend and the wire.
"""

from __future__ import annotations

from typing import Any, List, Optional

from dynamo_tpu.llm.backend import Backend
from dynamo_tpu.llm.chat_template import ChatTemplate
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.llm.tokenizer import HFTokenizer, Tokenizer
from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.runtime.pipeline import Operator, build_pipeline


def resolve_tokenizer(card: ModelDeploymentCard) -> Tokenizer:
    if card.model_path:
        return HFTokenizer.from_pretrained_dir(card.model_path)
    from dynamo_tpu.llm.tokenizer import tiny_tokenizer

    return tiny_tokenizer()


def resolve_chat_template(card: ModelDeploymentCard) -> ChatTemplate:
    if card.chat_template_source:
        return ChatTemplate(card.chat_template_source)
    if card.model_path:
        return ChatTemplate.from_model_dir(card.model_path)
    return ChatTemplate()


def build_local_pipeline(
    card: ModelDeploymentCard,
    engine: Any,
    *,
    tokenizer: Optional[Tokenizer] = None,
    extra_operators: Optional[List[Operator]] = None,
) -> AsyncEngine:
    """OpenAI dict request → preprocess → [extras] → detokenize → engine."""
    tokenizer = tokenizer or resolve_tokenizer(card)
    operators: List[Operator] = [
        OpenAIPreprocessor(card, tokenizer, resolve_chat_template(card)),
        Backend(tokenizer),
    ]
    operators.extend(extra_operators or [])
    return build_pipeline(operators, engine)
