"""Chained positional block hashing.

Reference parity: lib/tokens/src/{lib.rs,blocks.rs} — the reference chains
blake3 over (parent_hash, token_bytes); we chain xxh3_64 (available here,
similar speed class) over the same structure.

Only complete blocks are hashed: a sequence of 150 tokens with block_size 64
yields 2 hashes covering tokens [0,128). Partial tail blocks are not
routable/reusable (matches the reference block-granular semantics).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import xxhash

# Seed commits the hash space; mixed into the root so different deployments
# can salt their hash space (ref: KV event salts in kv_router/publisher.rs).
BLOCK_HASH_SEED = 0xD1A0_0000_0000_0001


def _hash_block(parent_hash: int, tokens: Sequence[int], extra_salt: int = 0) -> int:
    h = xxhash.xxh3_64(seed=(parent_hash ^ extra_salt) & 0xFFFF_FFFF_FFFF_FFFF)
    # Fixed-width little-endian encoding; tokens are < 2^32 for any real vocab.
    h.update(b"".join(int(t).to_bytes(4, "little", signed=False) for t in tokens))
    return h.intdigest()


def compute_block_hashes(
    tokens: Sequence[int],
    block_size: int,
    *,
    salt: int = 0,
    parent_hash: Optional[int] = None,
) -> List[int]:
    """Hashes for every *complete* block of ``tokens``.

    ``parent_hash`` allows incremental extension: pass the last hash of an
    already-hashed prefix and only the new tokens.
    """
    if block_size <= 0:
        raise ValueError("block_size must be positive")
    prev = parent_hash if parent_hash is not None else BLOCK_HASH_SEED
    out: List[int] = []
    for start in range(0, len(tokens) - block_size + 1, block_size):
        prev = _hash_block(prev, tokens[start : start + block_size], extra_salt=salt)
        out.append(prev)
    return out


def compute_block_hash_for_seq(
    tokens: Sequence[int], block_size: int, *, salt: int = 0
) -> List[int]:
    """Reference-named alias (kv_router.rs:50) for compute_block_hashes."""
    return compute_block_hashes(tokens, block_size, salt=salt)


def adapter_salt(lora_name: Optional[str]) -> int:
    """Hash-space salt for LoRA requests: K/V computed under an adapter are
    not interchangeable with base-model K/V (wk/wv deltas), so the block
    chain is salted per adapter — same prompt, different adapter, disjoint
    hashes (the role vLLM's extra_keys plays in its prefix cache)."""
    if not lora_name:
        return 0
    return xxhash.xxh3_64(lora_name.encode(), seed=0x10A).intdigest()
