"""Token-block hashing and radix structures (ref: lib/tokens, lib/kv-router).

Token sequences are chunked into fixed-size blocks; each block gets a
*chained positional hash* — the hash commits to every token before it, so a
block hash uniquely identifies a prefix of the sequence. Equal hashes ⇒ equal
prefixes (modulo 64-bit collisions), which is what makes KV-cache-aware
routing and prefix reuse work (ref: compute_block_hash_for_seq,
lib/tokens/src/blocks.rs; lib/llm/src/kv_router.rs:50–56).
"""

from dynamo_tpu.tokens.blocks import (
    BLOCK_HASH_SEED,
    compute_block_hash_for_seq,
    compute_block_hashes,
)
from dynamo_tpu.tokens.radix import OverlapScores, RadixTree

__all__ = [
    "BLOCK_HASH_SEED",
    "OverlapScores",
    "RadixTree",
    "compute_block_hash_for_seq",
    "compute_block_hashes",
]
