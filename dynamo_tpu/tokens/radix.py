"""Radix tree over chained block hashes → worker sets.

Reference parity: lib/kv-router/src/radix_tree.rs:73 (RadixTree),
protocols.rs (OverlapScores, WorkerId). Because block hashes are *chained*,
a child hash can only ever follow its unique parent hash, so the tree's edge
label is simply the child block hash and lookup is a walk from the root.

The tree answers: given a new request's block hashes, how many leading blocks
does each worker already hold in KV cache (OverlapScores)? Updates arrive as
KV events from workers: Stored(parent_hash, hashes), Removed(hashes), Clear.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

WorkerKey = Tuple[int, int]  # (worker_id, dp_rank)


@dataclass
class _Node:
    block_hash: int
    parent: Optional["_Node"]
    children: Dict[int, "_Node"] = field(default_factory=dict)
    workers: Set[WorkerKey] = field(default_factory=set)


@dataclass
class OverlapScores:
    """Per-worker count of leading blocks already cached."""

    scores: Dict[WorkerKey, int] = field(default_factory=dict)
    # Blocks matched by at least one worker (the frontier depth).
    matched_blocks: int = 0

    def best(self) -> Optional[Tuple[WorkerKey, int]]:
        if not self.scores:
            return None
        worker = max(self.scores, key=lambda w: self.scores[w])
        return worker, self.scores[worker]


class RadixTree:
    def __init__(self) -> None:
        self._root = _Node(block_hash=0, parent=None)
        # Global hash → node index: chained hashes are unique per prefix, so
        # each hash names exactly one node (ref: flat_hashmap.rs equivalence).
        self._nodes: Dict[int, _Node] = {}
        # Per-worker set of held hashes, for fast worker removal.
        self._worker_blocks: Dict[WorkerKey, Set[int]] = {}

    # -- stats -------------------------------------------------------------

    @property
    def num_blocks(self) -> int:
        return len(self._nodes)

    @property
    def workers(self) -> List[WorkerKey]:
        return sorted(self._worker_blocks)

    def worker_block_count(self, worker: WorkerKey) -> int:
        return len(self._worker_blocks.get(worker, ()))

    # -- updates -----------------------------------------------------------

    def store(
        self,
        worker: WorkerKey,
        block_hashes: Sequence[int],
        parent_hash: Optional[int] = None,
    ) -> None:
        """Worker now holds ``block_hashes`` (a chain, following parent_hash)."""
        if parent_hash is None:
            node = self._root
        else:
            node = self._nodes.get(parent_hash)
            if node is None:
                # Parent unknown (e.g. events replayed out of order): root the
                # chain at a detached node so lookups through the full chain
                # still work via the flat map.
                node = _Node(block_hash=parent_hash, parent=None)
                self._nodes[parent_hash] = node
        held = self._worker_blocks.setdefault(worker, set())
        for h in block_hashes:
            child = node.children.get(h)
            if child is None:
                child = self._nodes.get(h)
                if child is None:
                    child = _Node(block_hash=h, parent=node)
                    self._nodes[h] = child
                else:
                    child.parent = node
                node.children[h] = child
            child.workers.add(worker)
            held.add(h)
            node = child

    def remove(self, worker: WorkerKey, block_hashes: Iterable[int]) -> None:
        """Worker evicted these blocks."""
        held = self._worker_blocks.get(worker)
        for h in block_hashes:
            node = self._nodes.get(h)
            if node is not None:
                node.workers.discard(worker)
                self._maybe_prune(node)
            if held is not None:
                held.discard(h)

    def remove_worker(self, worker: WorkerKey) -> None:
        """Worker died / deregistered: drop all its blocks."""
        held = self._worker_blocks.pop(worker, set())
        for h in held:
            node = self._nodes.get(h)
            if node is not None:
                node.workers.discard(worker)
                self._maybe_prune(node)

    def clear_worker(self, worker: WorkerKey) -> None:
        """Worker flushed its KV cache (ref: clear_kv_blocks admin route)."""
        self.remove_worker(worker)
        self._worker_blocks[worker] = set()

    def _maybe_prune(self, node: _Node) -> None:
        # Prune leaf nodes nobody holds; walk up while the chain stays empty.
        while (
            node is not None
            and node is not self._root
            and not node.workers
            and not node.children
        ):
            parent = node.parent
            if parent is not None:
                parent.children.pop(node.block_hash, None)
            self._nodes.pop(node.block_hash, None)
            node = parent

    # -- lookup ------------------------------------------------------------

    def find_matches(self, block_hashes: Sequence[int]) -> OverlapScores:
        """Walk the chain from the root; score = leading blocks per worker.

        A worker's score counts contiguous blocks from position 0 — a hole
        ends its run (matching scheduler semantics: only a prefix can be
        skipped at prefill, ref: radix_tree.rs find_matches).
        """
        result = OverlapScores()
        node = self._root
        active: Set[WorkerKey] = set()
        depth = 0
        for h in block_hashes:
            child = node.children.get(h)
            if child is None:
                break
            depth += 1
            if depth == 1:
                active = set(child.workers)
            else:
                active &= child.workers
            if not active:
                # Workers holding a deeper block without this one can't use it
                # as prefix; stop at the last depth where someone held all.
                break
            for w in active:
                result.scores[w] = depth
            node = child
        result.matched_blocks = max(result.scores.values(), default=0)
        return result
