"""Adapter sources: where LoRA artifacts come from.

Reference parity: lib/llm/src/lora/source.rs (LoRASource trait with
LocalLoRASource / S3LoRASource). Zero-egress environment: only the local
source is functional; the remote source is a gated stub with the same
interface so deployments with egress can drop one in.
"""

from __future__ import annotations

import os
from typing import List, Protocol


class LoRASource(Protocol):
    def list_adapters(self) -> List[str]: ...
    def fetch(self, name: str, dest_dir: str) -> str:
        """Materialize adapter `name` under dest_dir; returns the local path."""
        ...


class LocalLoRASource:
    """Adapters laid out as ``root/<name>/adapter_config.json`` (+ weights)."""

    def __init__(self, root: str) -> None:
        self.root = root

    def list_adapters(self) -> List[str]:
        if not os.path.isdir(self.root):
            return []
        return sorted(
            d
            for d in os.listdir(self.root)
            if os.path.exists(os.path.join(self.root, d, "adapter_config.json"))
        )

    def fetch(self, name: str, dest_dir: str) -> str:
        path = os.path.join(self.root, name)
        if not os.path.exists(os.path.join(path, "adapter_config.json")):
            raise FileNotFoundError(f"no adapter '{name}' under {self.root}")
        # Local source: artifacts are already on disk — no copy needed.
        return path


class RemoteLoRASource:
    """Placeholder for object-store sources (ref: S3LoRASource). This
    environment has no egress; constructing one raises with guidance."""

    def __init__(self, uri: str) -> None:
        raise NotImplementedError(
            f"remote LoRA source {uri!r} requires network egress; "
            "mount the adapters locally and use LocalLoRASource"
        )
