"""Load PEFT-format LoRA adapters into stacked-layer JAX pytrees.

Reference parity: the reference hands adapter artifacts to vLLM and lets it
ingest PEFT checkpoints; here the engine is ours, so the mapping from
``base_model.model.model.layers.{i}.<module>.lora_{A,B}.weight`` to our
scan-stacked layout lives here. Per target module the adapter becomes
(A: [L, d_in, r], B: [L, r, d_out]) so ``lax.scan`` over layers consumes it
alongside the base weights; layers the adapter doesn't touch get zeros
(mathematically absent, shape-uniform for jit).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models.config import ModelConfig

# PEFT module name → (our param name, in_dim attr, out_dim fn)
_TARGET_MAP = {
    "q_proj": "wq",
    "k_proj": "wk",
    "v_proj": "wv",
    "o_proj": "wo",
    "gate_proj": "w_gate",
    "up_proj": "w_up",
    "down_proj": "w_down",
}


@dataclass
class LoRAAdapter:
    name: str
    rank: int
    scaling: float  # lora_alpha / r
    # our param name → (A [L, d_in, r], B [L, r, d_out])
    weights: Dict[str, Tuple[jnp.ndarray, jnp.ndarray]] = field(default_factory=dict)

    @property
    def targets(self) -> List[str]:
        return sorted(self.weights)


def _module_dims(config: ModelConfig, ours: str) -> Tuple[int, int]:
    d, ff = config.d_model, config.d_ff
    hd = config.head_dim_
    dims = {
        "wq": (d, config.n_heads * hd),
        "wk": (d, config.n_kv_heads * hd),
        "wv": (d, config.n_kv_heads * hd),
        "wo": (config.n_heads * hd, d),
        "w_gate": (d, ff),
        "w_up": (d, ff),
        "w_down": (ff, d),
    }
    return dims[ours]


def load_lora_adapter(
    adapter_dir: str, config: ModelConfig, *, name: Optional[str] = None
) -> LoRAAdapter:
    with open(os.path.join(adapter_dir, "adapter_config.json")) as f:
        acfg = json.load(f)
    rank = int(acfg.get("r", 8))
    alpha = float(acfg.get("lora_alpha", rank))
    adapter = LoRAAdapter(
        name=name or os.path.basename(adapter_dir.rstrip("/")),
        rank=rank,
        scaling=alpha / rank,
    )

    from safetensors import safe_open

    weights_path = os.path.join(adapter_dir, "adapter_model.safetensors")
    raw: Dict[str, np.ndarray] = {}
    with safe_open(weights_path, framework="numpy") as f:
        for key in f.keys():
            raw[key] = f.get_tensor(key)

    L = config.n_layers
    # group by target module
    per_target: Dict[str, Dict[int, Dict[str, np.ndarray]]] = {}
    for key, tensor in raw.items():
        # ...model.layers.{i}.self_attn.q_proj.lora_A.weight
        parts = key.split(".")
        try:
            li = parts.index("layers")
            layer = int(parts[li + 1])
        except (ValueError, IndexError):
            continue
        module = next((p for p in parts if p in _TARGET_MAP), None)
        ab = "A" if "lora_A" in key else "B" if "lora_B" in key else None
        if module is None or ab is None:
            continue
        per_target.setdefault(module, {}).setdefault(layer, {})[ab] = tensor

    for module, layers in per_target.items():
        ours = _TARGET_MAP[module]
        d_in, d_out = _module_dims(config, ours)
        A = np.zeros((L, d_in, rank), dtype=np.float32)
        B = np.zeros((L, rank, d_out), dtype=np.float32)
        for layer, ab in layers.items():
            if "A" in ab:
                A[layer] = ab["A"].T.astype(np.float32)  # PEFT stores [r, d_in]
            if "B" in ab:
                B[layer] = ab["B"].T.astype(np.float32)  # PEFT stores [d_out, r]
        adapter.weights[ours] = (
            jnp.asarray(A, dtype=config.dtype),
            jnp.asarray(B, dtype=config.dtype),
        )
    return adapter


def stack_adapters(
    adapters: List[LoRAAdapter], config: ModelConfig, targets: List[str]
) -> Dict[str, Tuple[jnp.ndarray, jnp.ndarray]]:
    """Stack N adapters (plus a zero 'no adapter' slot 0) per target:
    A: [N+1, L, d_in, r_max], B: [N+1, L, r_max, d_out]. Scaling is folded
    into B so the batched compute needs no per-adapter scalar."""
    L = config.n_layers
    r_max = max([a.rank for a in adapters], default=1)
    out: Dict[str, Tuple[jnp.ndarray, jnp.ndarray]] = {}
    for target in targets:
        d_in, d_out = _module_dims(config, target)
        A = np.zeros((len(adapters) + 1, L, d_in, r_max), dtype=np.float32)
        B = np.zeros((len(adapters) + 1, L, r_max, d_out), dtype=np.float32)
        for i, a in enumerate(adapters, start=1):
            if target not in a.weights:
                continue
            Aa, Ba = a.weights[target]
            A[i, :, :, : a.rank] = np.asarray(Aa, dtype=np.float32)
            B[i, :, : a.rank, :] = np.asarray(Ba, dtype=np.float32) * a.scaling
        out[target] = (
            jnp.asarray(A, dtype=config.dtype),
            jnp.asarray(B, dtype=config.dtype),
        )
    return out
