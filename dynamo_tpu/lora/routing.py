"""LoRA placement: rendezvous (HRW) hashing + routing table.

Reference parity: lib/llm/src/lora/routing/{hrw.rs,table.rs,mod.rs} —
RendezvousHasher.compute_score/rank_workers, LoraRoutingTable replica sets.
HRW gives stable, coordination-free placement: adding/removing a worker
only moves the adapters that hashed to it.
"""

from __future__ import annotations

import hashlib
import random
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

WorkerKey = Tuple[int, int]  # (worker_id, dp_rank)


class RendezvousHasher:
    """Highest-random-weight placement (ref: hrw.rs)."""

    @staticmethod
    def compute_score(lora_name: str, worker: WorkerKey) -> int:
        h = hashlib.blake2b(digest_size=8)
        h.update(lora_name.encode())
        h.update(f"{worker[0]:x}:{worker[1]}".encode())
        return int.from_bytes(h.digest(), "big")

    @classmethod
    def rank_workers(
        cls, lora_name: str, workers: Sequence[WorkerKey]
    ) -> List[WorkerKey]:
        return sorted(
            workers,
            key=lambda w: cls.compute_score(lora_name, w),
            reverse=True,
        )

    @classmethod
    def allocate(
        cls, lora_name: str, workers: Sequence[WorkerKey], n_replicas: int
    ) -> List[WorkerKey]:
        return cls.rank_workers(lora_name, workers)[: max(n_replicas, 1)]


class RandomAllocator:
    """(ref: mod.rs RandomAllocation) — baseline placement for comparison."""

    @classmethod
    def allocate(
        cls, lora_name: str, workers: Sequence[WorkerKey], n_replicas: int
    ) -> List[WorkerKey]:
        pool = list(workers)
        rng = random.Random(lora_name)  # deterministic per adapter
        rng.shuffle(pool)
        return pool[: max(n_replicas, 1)]


@dataclass
class LoraReplicaConfig:
    """(ref: table.rs LoraReplicaConfig)"""

    replicas: List[WorkerKey] = field(default_factory=list)
    n_desired: int = 1


class LoraRoutingTable:
    """adapter name → replica set; thread-safe (ref: table.rs)."""

    def __init__(self) -> None:
        self._table: Dict[str, LoraReplicaConfig] = {}
        self._lock = threading.Lock()

    def get_replica_set(self, lora_name: str) -> Optional[List[WorkerKey]]:
        with self._lock:
            cfg = self._table.get(lora_name)
            return list(cfg.replicas) if cfg else None

    def update_allocation(self, lora_name: str, config: LoraReplicaConfig) -> None:
        with self._lock:
            self._table[lora_name] = config

    def remove_lora(self, lora_name: str) -> Optional[LoraReplicaConfig]:
        with self._lock:
            return self._table.pop(lora_name, None)

    def list_loras(self) -> List[str]:
        with self._lock:
            return sorted(self._table)

    def __len__(self) -> int:
        with self._lock:
            return len(self._table)

    def clear(self) -> None:
        with self._lock:
            self._table.clear()

    def reallocate(
        self,
        workers: Sequence[WorkerKey],
        *,
        desired: Optional[Dict[str, int]] = None,
        allocator=RendezvousHasher,
    ) -> None:
        """Recompute every adapter's replica set over the live worker set
        (called on worker join/leave or when the load estimator changes the
        desired replica counts)."""
        with self._lock:
            for name, cfg in self._table.items():
                n = (desired or {}).get(name, cfg.n_desired)
                cfg.n_desired = n
                cfg.replicas = allocator.allocate(name, workers, n)
