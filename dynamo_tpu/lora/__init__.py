"""LoRA adapter serving: sources, cache, placement, batched TPU compute.

Reference parity: lib/llm/src/lora.rs — downloader/cache (adapter artifact
management), routing (RendezvousHasher HRW placement + LoraRoutingTable),
load_estimator (per-adapter demand → replica counts). The compute side is
TPU-native instead of punica-style CUDA kernels: adapters are stacked on a
leading axis and applied as batched einsums under jit (ops/lora.py), so one
compiled step serves a continuous batch mixing adapters.
"""

from dynamo_tpu.lora.cache import LoRACache
from dynamo_tpu.lora.load_estimator import LoadEstimator, LoadEstimatorConfig
from dynamo_tpu.lora.loader import LoRAAdapter, load_lora_adapter
from dynamo_tpu.lora.routing import (
    LoraRoutingTable,
    RendezvousHasher,
)
from dynamo_tpu.lora.source import LocalLoRASource, LoRASource

__all__ = [
    "LoRACache",
    "LoRAAdapter",
    "load_lora_adapter",
    "LoadEstimator",
    "LoadEstimatorConfig",
    "LoraRoutingTable",
    "RendezvousHasher",
    "LoRASource",
    "LocalLoRASource",
]
