"""Per-adapter load tracking → desired replica counts.

Reference parity: lib/llm/src/lora/load_estimator.rs (LoadEstimator —
increment/decrement on request start/end, bounded time series per adapter,
current-load snapshots feeding the allocator's replica decisions).
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Tuple


@dataclass
class LoadEstimatorConfig:
    """(ref: load_estimator.rs LoadEstimatorConfig)"""

    max_samples: int = 120  # bounded history per adapter
    sample_interval_s: float = 1.0
    # concurrency one replica handles before another is warranted
    per_replica_capacity: float = 4.0
    max_replicas: int = 8


@dataclass
class LoadSample:
    ts: float
    active: int


class LoadEstimator:
    def __init__(self, config: LoadEstimatorConfig = LoadEstimatorConfig()) -> None:
        self.config = config
        self._active: Dict[str, int] = {}
        self._series: Dict[str, List[LoadSample]] = {}
        self._lock = threading.Lock()

    # -- accounting (request lifecycle hooks) -------------------------------

    def increment(self, lora_name: str) -> None:
        with self._lock:
            self._active[lora_name] = self._active.get(lora_name, 0) + 1
            self._record_locked(lora_name)

    def decrement(self, lora_name: str) -> None:
        with self._lock:
            n = self._active.get(lora_name, 0)
            if n <= 1:
                self._active.pop(lora_name, None)
            else:
                self._active[lora_name] = n - 1
            self._record_locked(lora_name)

    def _record_locked(self, lora_name: str) -> None:
        series = self._series.setdefault(lora_name, [])
        series.append(LoadSample(time.monotonic(), self._active.get(lora_name, 0)))
        if len(series) > self.config.max_samples:
            del series[: len(series) - self.config.max_samples]

    # -- queries ------------------------------------------------------------

    def current_load(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._active)

    def time_series(self, lora_name: str) -> List[Tuple[float, int]]:
        with self._lock:
            return [(s.ts, s.active) for s in self._series.get(lora_name, [])]

    def peak_load(self, lora_name: str, window_s: float = 60.0) -> int:
        cutoff = time.monotonic() - window_s
        with self._lock:
            samples = self._series.get(lora_name, [])
            return max((s.active for s in samples if s.ts >= cutoff), default=0)

    def desired_replicas(self) -> Dict[str, int]:
        """Replica targets from recent peak concurrency per adapter."""
        out: Dict[str, int] = {}
        with self._lock:
            names = set(self._series)
        for name in names:
            peak = self.peak_load(name)
            n = math.ceil(peak / self.config.per_replica_capacity) if peak else 1
            out[name] = min(max(n, 1), self.config.max_replicas)
        return out
