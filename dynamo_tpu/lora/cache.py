"""LoRA adapter cache: fetch-once, LRU-evicted local materialization.

Reference parity: lib/llm/src/lora/cache.rs (LoRACache — bounded local cache
in front of a LoRASource, keyed by adapter name).
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional

from dynamo_tpu.lora.source import LoRASource
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class LoRACache:
    def __init__(
        self, source: LoRASource, *, cache_dir: str = "/tmp/dynamo_tpu_lora",
        max_adapters: int = 32,
    ) -> None:
        self.source = source
        self.cache_dir = cache_dir
        self.max_adapters = max_adapters
        self._paths: "collections.OrderedDict[str, str]" = collections.OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, name: str) -> str:
        """Local path for adapter ``name``, fetching on miss."""
        with self._lock:
            if name in self._paths:
                self.hits += 1
                self._paths.move_to_end(name)
                return self._paths[name]
        # Fetch outside the lock (may be slow for remote sources).
        path = self.source.fetch(name, self.cache_dir)
        with self._lock:
            self.misses += 1
            self._paths[name] = path
            self._paths.move_to_end(name)
            while len(self._paths) > self.max_adapters:
                evicted, _ = self._paths.popitem(last=False)
                logger.info("evicted LoRA adapter %s from cache", evicted)
        return path

    def contains(self, name: str) -> bool:
        with self._lock:
            return name in self._paths

    def list_cached(self) -> List[str]:
        with self._lock:
            return list(self._paths)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "cached": len(self._paths),
                "hits": self.hits,
                "misses": self.misses,
            }
