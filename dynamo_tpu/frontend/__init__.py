"""OpenAI HTTP frontend component (python -m dynamo_tpu.frontend).

Reference parity: components/src/dynamo/frontend/main.py — one process
running the OpenAI server + discovery watcher + preprocessor + router.
"""
