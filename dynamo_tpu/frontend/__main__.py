from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu import config
from dynamo_tpu.http.model_manager import ModelManager
from dynamo_tpu.http.service import HttpService
from dynamo_tpu.llm.discovery import ModelWatcher
from dynamo_tpu.router import KvRouterConfig
from dynamo_tpu.runtime.component import RouterMode
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.utils.logging import configure_logging


async def main() -> None:
    parser = argparse.ArgumentParser(
        "dynamo-tpu frontend",
        description="OpenAI-compatible HTTP server with dynamic model discovery",
    )
    parser.add_argument("--host", default=config.HTTP_HOST.get())
    parser.add_argument("--http-port", type=int, default=config.HTTP_PORT.get())
    parser.add_argument(
        "--router-mode",
        choices=["kv", "round-robin", "random"],
        default="kv",
        help="worker selection policy (ref: RouterMode, push_router.rs:76)",
    )
    parser.add_argument(
        "--kv-overlap-score-weight", type=float,
        default=config.ROUTER_OVERLAP_WEIGHT.get(),
    )
    parser.add_argument(
        "--router-temperature", type=float, default=config.ROUTER_TEMPERATURE.get()
    )
    parser.add_argument(
        "--enable-canary", action="store_true",
        help="active canary health checks per worker "
        "(ref: lib/runtime/src/health_check.rs)",
    )
    parser.add_argument("--canary-interval", type=float, default=5.0)
    parser.add_argument("--canary-timeout", type=float, default=10.0)
    parser.add_argument("--tls-cert", default=None,
                        help="PEM certificate chain (enables TLS with --tls-key)")
    parser.add_argument("--tls-key", default=None, help="PEM private key")
    args = parser.parse_args()

    configure_logging()
    runtime = DistributedRuntime.from_settings()
    # Trajectory plane: label this process's spans and collect the fleet's
    # shipped spans into the process-global store (the store auto-attaches
    # to the global tracer, so the frontend's own spans land there too).
    from dynamo_tpu.runtime.trajectory import TrajectoryCollector
    from dynamo_tpu.utils.tracing import set_service

    set_service("frontend")
    trajectory = TrajectoryCollector(
        runtime.event_plane, config.NAMESPACE.get()
    )
    await trajectory.start()
    manager = ModelManager()
    mode = {
        "kv": RouterMode.KV,
        "round-robin": RouterMode.ROUND_ROBIN,
        "random": RouterMode.RANDOM,
    }[args.router_mode]
    watcher = ModelWatcher(
        runtime,
        manager,
        router_mode=mode,
        kv_router_config=KvRouterConfig(
            overlap_score_weight=args.kv_overlap_score_weight,
            router_temperature=args.router_temperature,
        ),
        enable_canary=args.enable_canary,
        canary_interval_s=args.canary_interval,
        canary_timeout_s=args.canary_timeout,
    )
    await watcher.start()
    # Overload armor on by default: bounded EDF admission + (when an ITL
    # SLA is configured) the brownout state machine. Knobs:
    # DYN_TPU_OVERLOAD_* (docs/design_docs/overload_control.md).
    from dynamo_tpu.runtime.overload import OverloadController, config_from_env

    service = HttpService(
        manager, host=args.host, port=args.http_port,
        tls_cert=args.tls_cert, tls_key=args.tls_key,
        overload=OverloadController(config_from_env()),
    )
    port = await service.start()
    print(f"frontend listening on {args.host}:{port}", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await service.stop(grace_period=config.GRACE_PERIOD.get())
        await watcher.stop()
        await trajectory.stop()
        await runtime.shutdown(grace_period=config.GRACE_PERIOD.get())


if __name__ == "__main__":
    asyncio.run(main())
