"""Int8 KV-cache quantization (per-token-per-head dynamic scales).

The decode step is HBM-bound on two streams: weights and KV history. Int8
weights halve the first (ops/quant.py); this halves the second — and, just
as importantly on TPU, halves the decode kernel's per-page VMEM footprint,
which doubles the sequences one sequential grid step can serve
(ops/pallas/paged_attention.py batch_block 8 → 16 inside the ~16 MB scoped
VMEM budget).

Layout: a quantized pool is a dict
    {"q8": int8 [num_blocks, block_size, KH, D],
     "s":  float32 [num_blocks, KH, block_size]}
The scale array keeps block_size on the LANE axis so a kernel page-ref
slice ``s[0, h]`` is one dense lane vector — the dequant then rides the
existing score/prob multiplies (scores ×= s_k[t], probs ×= s_v[t]) instead
of touching the [bs, D] page itself.

Scales are per (token, head): absmax over head_dim / 127, computed at
write time (write_chunk_to_cache). This is the standard int8-KV recipe
(reference serves FP8-KV through its engines — e.g. vLLM's
kv_cache_dtype=fp8 path the recipes enable); per-token scaling keeps the
rounding error ~0.4% of each token's own magnitude, which parity tests
bound end-to-end.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple, Union

import jax.numpy as jnp

KVPool = Union[jnp.ndarray, Dict[str, jnp.ndarray]]


def is_quantized_pool(pool: Any) -> bool:
    return isinstance(pool, dict) and "q8" in pool


def quantize_kv_chunk(
    chunk: jnp.ndarray,  # [B, C, KH, D] float
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """→ (q8 [B, C, KH, D] int8, scales [B, C, KH] float32)."""
    xf = chunk.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)  # [B, C, KH]
    s = jnp.maximum(amax, 1e-8) / 127.0
    q8 = jnp.clip(jnp.round(xf / s[..., None]), -127, 127).astype(jnp.int8)
    return q8, s


def dequantize_pages(
    q8: jnp.ndarray,  # [..., bs, KH, D] int8 (gathered pages)
    s: jnp.ndarray,  # [..., KH, bs] float32 (gathered scales)
    dtype: Any = jnp.float32,
) -> jnp.ndarray:
    """Dense dequant for the XLA-oracle / export paths."""
    s_t = jnp.swapaxes(s, -1, -2)[..., None]  # [..., bs, KH, 1]
    return (q8.astype(jnp.float32) * s_t).astype(dtype)


def dequantize_pool(pool: KVPool, dtype: Any = jnp.bfloat16) -> jnp.ndarray:
    """Whole-pool dequant → [num_blocks, bs, KH, D] (checkpoint/export)."""
    if not is_quantized_pool(pool):
        return pool.astype(dtype) if pool.dtype != dtype else pool
    return dequantize_pages(pool["q8"], pool["s"], dtype)
