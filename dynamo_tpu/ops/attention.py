"""Attention over a paged KV cache.

The framework's equivalent of the CUDA paged-attention kernels inside the
reference's engines. One entrypoint `paged_attention` serves prefill, chunked
prefill, and decode uniformly: queries are a chunk of C tokens starting at
`start_pos` within each sequence; keys/values live in a block pool indexed by
per-sequence block tables.

Two implementations:
  - XLA path (here): gather pages → dense masked attention. Runs on any
    backend; the correctness oracle for the pallas kernel.
  - pallas TPU kernel (ops/pallas/paged_attention.py): streams pages
    HBM→VMEM with double buffering, flash-style online softmax; selected via
    `use_kernel=True` (engine enables it on TPU backends).
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)

NEG_INF = -1e30

_kernel_fn = None
_kernel_load_failed = False
_decode_kernel_fn = None
_decode_kernel_load_failed = False


def _load_kernel_attr(attr: str, cache: str, flag: str):
    """Resolve a pallas kernel once; on any failure fall back (caller uses
    the XLA path) with a loud warning instead of letting the engine
    crash-loop (round-1 failure mode: ModuleNotFoundError retried forever)."""
    g = globals()
    if g[cache] is not None or g[flag]:
        return g[cache]
    try:
        import dynamo_tpu.ops.pallas.paged_attention as mod

        g[cache] = getattr(mod, attr)
    except Exception:
        g[flag] = True
        logger.exception(
            "pallas kernel %s unavailable; falling back to the XLA gather "
            "path (expect much lower decode throughput)", attr,
        )
    return g[cache]


def _load_kernel():
    return _load_kernel_attr(
        "paged_attention_kernel", "_kernel_fn", "_kernel_load_failed"
    )


def _load_decode_kernel():
    return _load_kernel_attr(
        "paged_attention_decode_kernel",
        "_decode_kernel_fn",
        "_decode_kernel_load_failed",
    )


def paged_attention(
    q: jnp.ndarray,  # [B, C, n_heads, head_dim]
    k_cache: jnp.ndarray,  # [num_blocks, block_size, n_kv_heads, head_dim]
    v_cache: jnp.ndarray,  # [num_blocks, block_size, n_kv_heads, head_dim]
    block_tables: jnp.ndarray,  # [B, max_blocks] int32 (entries beyond seq = any)
    start_pos: jnp.ndarray,  # [B] int32 — tokens already in cache before chunk
    chunk_lens: jnp.ndarray,  # [B] int32 — valid query tokens in the chunk
    *,
    sm_scale: Optional[float] = None,
    use_kernel: bool = False,
    window: Any = 0,  # sliding window in tokens (int or traced scalar); 0 = full
    logit_cap: float = 0.0,  # cap·tanh(s/cap) score softcap; 0 = off
) -> jnp.ndarray:
    """Returns [B, C, n_heads, head_dim].

    The chunk's own K/V must already be written into the cache (the model
    writes the chunk before attending); causality is enforced by masking key
    position t to t <= start_pos + c for query offset c. ``window`` > 0
    additionally hides keys with t <= start_pos + c - window (Mistral-SWA /
    Gemma-2 alternating-layer sliding windows) — it may be a TRACED scalar
    so a lax.scan over layers can alternate windowed/full layers in one
    compiled body; ``logit_cap`` applies the Gemma-2 score softcap.
    """
    if use_kernel:
        if q.shape[1] == 1:
            # Decode: the batch-blocked kernel amortizes the sequential
            # grid's per-step overhead over 8 sequences per iteration.
            decode_kernel = _load_decode_kernel()
            if decode_kernel is not None:
                return decode_kernel(
                    q, k_cache, v_cache, block_tables, start_pos,
                    sm_scale=sm_scale, window=window, logit_cap=logit_cap,
                )
        kernel = _load_kernel()
        if kernel is not None:
            return kernel(
                q, k_cache, v_cache, block_tables, start_pos, chunk_lens,
                sm_scale=sm_scale, window=window, logit_cap=logit_cap,
            )
    return _paged_attention_xla(
        q, k_cache, v_cache, block_tables, start_pos, chunk_lens, window,
        sm_scale=sm_scale, logit_cap=logit_cap,
    )


@partial(jax.jit, static_argnames=("sm_scale", "logit_cap"))
def _paged_attention_xla(
    q, k_cache, v_cache, block_tables, start_pos, chunk_lens,
    window=0, *, sm_scale=None, logit_cap: float = 0.0,
):
    B, C, n_heads, head_dim = q.shape
    num_blocks, block_size, n_kv_heads, _ = k_cache.shape
    max_blocks = block_tables.shape[1]
    T = max_blocks * block_size
    q_per_kv = n_heads // n_kv_heads
    scale = sm_scale if sm_scale is not None else head_dim**-0.5

    # Gather pages: [B, max_blocks, block_size, KH, D] → [B, T, KH, D]
    k = k_cache[block_tables].reshape(B, T, n_kv_heads, head_dim)
    v = v_cache[block_tables].reshape(B, T, n_kv_heads, head_dim)

    # [B, C, KH, q_per_kv, D]
    qg = q.reshape(B, C, n_kv_heads, q_per_kv, head_dim).astype(jnp.float32)
    kf = k.astype(jnp.float32)
    scores = jnp.einsum("bcghd,btgd->bcght", qg, kf) * scale  # [B,C,KH,G,T]
    if logit_cap > 0.0:
        scores = logit_cap * jnp.tanh(scores / logit_cap)

    t_pos = jax.lax.broadcasted_iota(jnp.int32, (B, C, T), 2)
    c_pos = jax.lax.broadcasted_iota(jnp.int32, (B, C, T), 1)
    limit = start_pos[:, None, None] + c_pos  # key t visible iff t <= start+c
    mask = t_pos <= limit  # [B, C, T]
    w = jnp.asarray(window, jnp.int32)
    mask = mask & ((w <= 0) | (t_pos > limit - w))
    scores = jnp.where(mask[:, :, None, None, :], scores, NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bcght,btgd->bcghd", probs, v.astype(jnp.float32))
    return out.reshape(B, C, n_heads, head_dim).astype(q.dtype)


def write_chunk_to_cache(
    cache: jnp.ndarray,  # [num_blocks, block_size, KH, D]
    chunk: jnp.ndarray,  # [B, C, KH, D]
    block_tables: jnp.ndarray,  # [B, max_blocks]
    start_pos: jnp.ndarray,  # [B]
    chunk_lens: jnp.ndarray,  # [B]
) -> jnp.ndarray:
    """Scatter a chunk of K or V into its pages. Padding positions and
    positions beyond the block table's capacity (multi-step decode overshoot
    past a stop condition) are dropped (out-of-range index + mode='drop')."""
    B, C = chunk.shape[:2]
    num_blocks, block_size = cache.shape[:2]
    capacity = block_tables.shape[1] * block_size
    c_off = jax.lax.broadcasted_iota(jnp.int32, (B, C), 1)
    pos = start_pos[:, None] + c_off  # [B, C]
    valid = (c_off < chunk_lens[:, None]) & (pos < capacity)
    block_idx = jnp.take_along_axis(
        block_tables, jnp.clip(pos // block_size, 0, block_tables.shape[1] - 1), axis=1
    )
    block_idx = jnp.where(valid, block_idx, num_blocks)  # OOB → dropped
    slot = pos % block_size
    return cache.at[block_idx, slot].set(chunk, mode="drop")
