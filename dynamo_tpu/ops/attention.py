"""Attention over a paged KV cache.

The framework's equivalent of the CUDA paged-attention kernels inside the
reference's engines. One entrypoint `paged_attention` serves prefill, chunked
prefill, and decode uniformly: queries are a chunk of C tokens starting at
`start_pos` within each sequence; keys/values live in a block pool indexed by
per-sequence block tables.

Two implementations:
  - XLA path (here): gather pages → dense masked attention. Runs on any
    backend; the correctness oracle for the pallas kernel.
  - pallas TPU kernel (ops/pallas/paged_attention.py): streams pages
    HBM→VMEM with double buffering, flash-style online softmax; selected via
    `use_kernel=True` (engine enables it on TPU backends).
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp

from dynamo_tpu.runtime.device_observe import watched_jit

logger = logging.getLogger(__name__)

NEG_INF = -1e30

_kernel_fn = None
_kernel_load_failed = False
_decode_kernel_fn = None
_decode_kernel_load_failed = False


def _load_kernel_attr(attr: str, cache: str, flag: str):
    """Resolve a pallas kernel once; on any failure fall back (caller uses
    the XLA path) with a loud warning instead of letting the engine
    crash-loop (round-1 failure mode: ModuleNotFoundError retried forever)."""
    g = globals()
    if g[cache] is not None or g[flag]:
        return g[cache]
    try:
        import dynamo_tpu.ops.pallas.paged_attention as mod

        g[cache] = getattr(mod, attr)
    except Exception:
        g[flag] = True
        logger.exception(
            "pallas kernel %s unavailable; falling back to the XLA gather "
            "path (expect much lower decode throughput)", attr,
        )
    return g[cache]


def _load_kernel():
    return _load_kernel_attr(
        "paged_attention_kernel", "_kernel_fn", "_kernel_load_failed"
    )


def _load_decode_kernel():
    return _load_kernel_attr(
        "paged_attention_decode_kernel",
        "_decode_kernel_fn",
        "_decode_kernel_load_failed",
    )


def paged_attention(
    q: jnp.ndarray,  # [B, C, n_heads, head_dim]
    k_cache: jnp.ndarray,  # [num_blocks, block_size, n_kv_heads, head_dim]
    v_cache: jnp.ndarray,  # [num_blocks, block_size, n_kv_heads, head_dim]
    block_tables: jnp.ndarray,  # [B, max_blocks] int32 (entries beyond seq = any)
    start_pos: jnp.ndarray,  # [B] int32 — tokens already in cache before chunk
    chunk_lens: jnp.ndarray,  # [B] int32 — valid query tokens in the chunk
    *,
    sm_scale: Optional[float] = None,
    use_kernel: bool = False,
    window: Any = 0,  # sliding window in tokens (int or traced scalar); 0 = full
    logit_cap: float = 0.0,  # cap·tanh(s/cap) score softcap; 0 = off
) -> jnp.ndarray:
    """Returns [B, C, n_heads, head_dim].

    The chunk's own K/V must already be written into the cache (the model
    writes the chunk before attending); causality is enforced by masking key
    position t to t <= start_pos + c for query offset c. ``window`` > 0
    additionally hides keys with t <= start_pos + c - window (Mistral-SWA /
    Gemma-2 alternating-layer sliding windows) — it may be a TRACED scalar
    so a lax.scan over layers can alternate windowed/full layers in one
    compiled body; ``logit_cap`` applies the Gemma-2 score softcap.
    """
    if use_kernel:
        B, C, n_heads, _ = q.shape
        k_values = k_cache["q8"] if isinstance(k_cache, dict) else k_cache
        n_kv_heads = k_values.shape[2]
        G = n_heads // n_kv_heads
        if C <= 8 and C * G <= 64:
            # Decode (C=1) and short chunks (speculative verify, chunk
            # tails): the batch-blocked kernel amortizes the sequential
            # grid's per-step overhead over 8-16 sequences per iteration
            # (the generic (B, pages) grid runs B×P tiny steps — measured
            # 3.3× of an 8B verify dispatch before this route).
            decode_kernel = _load_decode_kernel()
            if decode_kernel is not None:
                return decode_kernel(
                    q, k_cache, v_cache, block_tables, start_pos,
                    sm_scale=sm_scale, window=window, logit_cap=logit_cap,
                )
        kernel = _load_kernel()
        if kernel is not None:
            return kernel(
                q, k_cache, v_cache, block_tables, start_pos, chunk_lens,
                sm_scale=sm_scale, window=window, logit_cap=logit_cap,
            )
    return _paged_attention_xla(
        q, k_cache, v_cache, block_tables, start_pos, chunk_lens, window,
        sm_scale=sm_scale, logit_cap=logit_cap,
    )


def _paged_attention_xla_impl(
    q, k_cache, v_cache, block_tables, start_pos, chunk_lens,
    window=0, *, sm_scale=None, logit_cap: float = 0.0,
):
    from dynamo_tpu.ops.kv_quant import dequantize_pages, is_quantized_pool

    def _gather(cache, B, T, n_kv_heads, head_dim):
        if is_quantized_pool(cache):
            pages = cache["q8"][block_tables]  # [B, P, bs, KH, D]
            scales = cache["s"][block_tables]  # [B, P, KH, bs]
            return dequantize_pages(pages, scales).reshape(
                B, T, n_kv_heads, head_dim
            )
        return cache[block_tables].reshape(B, T, n_kv_heads, head_dim)

    B, C, n_heads, head_dim = q.shape
    values = k_cache["q8"] if is_quantized_pool(k_cache) else k_cache
    num_blocks, block_size, n_kv_heads, _ = values.shape
    max_blocks = block_tables.shape[1]
    T = max_blocks * block_size
    q_per_kv = n_heads // n_kv_heads
    scale = sm_scale if sm_scale is not None else head_dim**-0.5

    # Gather pages: [B, max_blocks, block_size, KH, D] → [B, T, KH, D]
    k = _gather(k_cache, B, T, n_kv_heads, head_dim)
    v = _gather(v_cache, B, T, n_kv_heads, head_dim)

    # [B, C, KH, q_per_kv, D]
    qg = q.reshape(B, C, n_kv_heads, q_per_kv, head_dim).astype(jnp.float32)
    kf = k.astype(jnp.float32)
    scores = jnp.einsum("bcghd,btgd->bcght", qg, kf) * scale  # [B,C,KH,G,T]
    if logit_cap > 0.0:
        scores = logit_cap * jnp.tanh(scores / logit_cap)

    t_pos = jax.lax.broadcasted_iota(jnp.int32, (B, C, T), 2)
    c_pos = jax.lax.broadcasted_iota(jnp.int32, (B, C, T), 1)
    limit = start_pos[:, None, None] + c_pos  # key t visible iff t <= start+c
    mask = t_pos <= limit  # [B, C, T]
    w = jnp.asarray(window, jnp.int32)
    mask = mask & ((w <= 0) | (t_pos > limit - w))
    scores = jnp.where(mask[:, :, None, None, :], scores, NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bcght,btgd->bcghd", probs, v.astype(jnp.float32))
    return out.reshape(B, C, n_heads, head_dim).astype(q.dtype)


_paged_attention_xla = watched_jit(
    "ops.paged_attention_xla",
    partial(jax.jit, static_argnames=("sm_scale", "logit_cap"))(
        _paged_attention_xla_impl
    ),
)


def dense_chunk_attention(
    q: jnp.ndarray,  # [B, C, n_heads, head_dim]
    k: jnp.ndarray,  # [B, C, n_kv_heads, head_dim] — the chunk's OWN K
    v: jnp.ndarray,  # [B, C, n_kv_heads, head_dim]
    chunk_lens: jnp.ndarray,  # [B] int32 — valid tokens in the chunk
    *,
    sm_scale: Optional[float] = None,
    window: Any = 0,
    logit_cap: float = 0.0,
) -> jnp.ndarray:
    """First-chunk attention: the whole history IS the in-flight chunk, so
    attend densely over the registers instead of reading the pages just
    written — zero cache DMA. Returns [B, C, n_heads, head_dim].

    This is the fast path for fresh prefills (start_pos == 0, one chunk):
    at the bench shape it removes every per-layer paged read from the
    prefill program (the page DMAs dominated prefill time; the ISL=128
    chunk's dense scores are a [C, C] tile the MXU eats for free).
    Padding key columns (>= chunk_lens) are masked so valid rows are exact;
    padding ROWS produce garbage that callers already ignore (their cache
    writes are dropped and their logits never read)."""
    B, C, H, D = q.shape
    KH = k.shape[2]
    scale = sm_scale if sm_scale is not None else D**-0.5
    if KH != H:  # GQA: repeat kv heads into query-head groups
        rep = H // KH
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qf = q.astype(jnp.float32).transpose(0, 2, 1, 3)  # [B, H, C, D]
    kf = k.astype(jnp.float32).transpose(0, 2, 1, 3)
    vf = v.astype(jnp.float32).transpose(0, 2, 1, 3)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    if logit_cap:
        s = logit_cap * jnp.tanh(s / logit_cap)
    rows = jax.lax.broadcasted_iota(jnp.int32, (C, C), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (C, C), 1)
    mask = cols <= rows  # causal within the chunk
    win = jnp.asarray(window, jnp.int32)
    mask = mask & ((win <= 0) | (cols > rows - win))  # sliding window
    valid = cols[None] < chunk_lens[:, None, None]  # padding keys
    # -1e30, NOT -inf: a padding row whose window admits no valid key would
    # softmax to NaN, and the NEXT layer's p @ v turns 0-weight × NaN-value
    # into NaN for EVERY row (0 × NaN = NaN). With a finite sentinel the
    # empty row degrades to a uniform average — garbage but finite, and
    # garbage rows are never read (their cache writes drop, their logits
    # are never selected).
    s = jnp.where((mask[None] & valid)[:, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vf)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def write_chunk_to_cache(
    cache: jnp.ndarray,  # [num_blocks, block_size, KH, D]
    chunk: jnp.ndarray,  # [B, C, KH, D]
    block_tables: jnp.ndarray,  # [B, max_blocks]
    start_pos: jnp.ndarray,  # [B]
    chunk_lens: jnp.ndarray,  # [B]
) -> jnp.ndarray:
    """Scatter a chunk of K or V into its pages. Padding positions and
    positions beyond the block table's capacity (multi-step decode overshoot
    past a stop condition) are dropped (out-of-range index + mode='drop')."""
    from dynamo_tpu.ops.kv_quant import is_quantized_pool, quantize_kv_chunk

    B, C = chunk.shape[:2]
    quantized = is_quantized_pool(cache)
    values = cache["q8"] if quantized else cache
    num_blocks, block_size = values.shape[:2]
    capacity = block_tables.shape[1] * block_size
    c_off = jax.lax.broadcasted_iota(jnp.int32, (B, C), 1)
    pos = start_pos[:, None] + c_off  # [B, C]
    valid = (c_off < chunk_lens[:, None]) & (pos < capacity)
    block_idx = jnp.take_along_axis(
        block_tables, jnp.clip(pos // block_size, 0, block_tables.shape[1] - 1), axis=1
    )
    block_idx = jnp.where(valid, block_idx, num_blocks)  # OOB → dropped
    slot = pos % block_size
    if not quantized:
        return cache.at[block_idx, slot].set(chunk, mode="drop")
    q8, s = quantize_kv_chunk(chunk)  # [B, C, KH, D], [B, C, KH]
    # scales live [NB, KH, bs]: the two advanced indices surround the KH
    # slice, so the indexed result is [B, C, KH] — exactly s's shape.
    return {
        "q8": cache["q8"].at[block_idx, slot].set(q8, mode="drop"),
        "s": cache["s"].at[block_idx, :, slot].set(s, mode="drop"),
    }
