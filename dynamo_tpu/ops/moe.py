"""Mixture-of-Experts FFN: GShard-style einsum dispatch, expert-parallel.

TPU-native design (vs the reference's CUDA grouped-GEMM MoE engines, e.g.
the DeepSeek-R1/Qwen3-MoE recipes, recipes/deepseek-r1/README.md): the
classic dispatch/combine one-hot einsum formulation (GShard, Switch
Transformer) — static shapes, no host control flow, everything lands on the
MXU, and sharding the expert axis over the ``ep`` mesh axis makes XLA insert
the token all-to-alls automatically.

Shapes (S = B*C flattened tokens, E experts, K top-k, cap capacity):
  router_w   [d, E]
  we_gate/up [E, d, f]   we_down [E, f, d]   (sharded on axis 0 over ep)
  dispatch   [S, E, cap] one-hot; combine = dispatch × gate prob
Tokens beyond an expert's capacity are dropped (standard capacity-factor
semantics); callers size cap via capacity_factor ≥ 1.25 to make drops rare.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from dynamo_tpu.ops.quant import qeinsum


def moe_capacity(n_tokens: int, n_experts: int, top_k: int, capacity_factor: float) -> int:
    return max(int(math.ceil(n_tokens * top_k / n_experts * capacity_factor)), 1)


def moe_ffn(
    x: jnp.ndarray,  # [B, C, d]
    router_w: jnp.ndarray,  # [d, E]
    we_gate: jnp.ndarray,  # [E, d, f]
    we_up: jnp.ndarray,  # [E, d, f]
    we_down: jnp.ndarray,  # [E, f, d]
    *,
    top_k: int,
    capacity_factor: float = 2.0,
    norm_topk_prob: bool = True,
    capacity: Optional[int] = None,
) -> jnp.ndarray:
    """SwiGLU expert FFN with top-k routing. Returns [B, C, d]."""
    B, C, d = x.shape
    E = router_w.shape[-1]
    S = B * C
    cap = capacity if capacity is not None else moe_capacity(S, E, top_k, capacity_factor)
    xs = x.reshape(S, d)

    # -- routing -----------------------------------------------------------
    logits = (xs.astype(jnp.float32) @ router_w.astype(jnp.float32))  # [S, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_i = jax.lax.top_k(probs, top_k)  # [S, K]
    if norm_topk_prob:
        top_p = top_p / jnp.maximum(top_p.sum(-1, keepdims=True), 1e-9)

    # -- position-in-expert (GShard cumsum trick) --------------------------
    # For each (token, k) assignment, its slot index within the expert's
    # capacity buffer = number of earlier assignments to the same expert.
    # Walk k-major so a token's k=0 choice wins capacity ties.
    onehot = jax.nn.one_hot(top_i, E, dtype=jnp.int32)  # [S, K, E]
    flat = onehot.transpose(1, 0, 2).reshape(K_S := top_k * S, E)  # k-major
    pos = jnp.cumsum(flat, axis=0) - flat  # [K*S, E] slot per assignment
    pos = (pos * flat).sum(-1).reshape(top_k, S).T  # [S, K]
    keep = pos < cap

    combine = (
        top_p.astype(jnp.float32)[..., None, None]
        * jax.nn.one_hot(top_i, E, dtype=jnp.float32)[..., None]
        * jax.nn.one_hot(jnp.where(keep, pos, cap), cap + 1, dtype=jnp.float32)[
            ..., None, :
        ]
    ).sum(1)[..., :cap]  # [S, E, cap]
    dispatch = (combine > 0).astype(x.dtype)

    # -- expert compute (weights may be int8-quantized, ops/quant.py) ------
    expert_in = jnp.einsum("sec,sd->ecd", dispatch, xs)  # [E, cap, d]
    gate = jax.nn.silu(qeinsum("ecd,edf->ecf", expert_in, we_gate))
    up = qeinsum("ecd,edf->ecf", expert_in, we_up)
    out = qeinsum("ecf,efd->ecd", gate * up, we_down)  # [E, cap, d]

    y = jnp.einsum("sec,ecd->sd", combine.astype(x.dtype), out)
    return y.reshape(B, C, d)
