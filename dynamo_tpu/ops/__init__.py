"""TPU compute ops: attention over paged KV, rope, norms, sampling.

The hot ops the reference implements in CUDA (paged attention inside vLLM,
block_copy.cu in KVBM — SURVEY §2.1) are implemented here twice: a pure-XLA
reference path that runs anywhere (CPU tests, correctness oracle) and pallas
TPU kernels under ops/pallas/ selected automatically on TPU backends.
"""

from dynamo_tpu.ops.rope import apply_rope, rope_table
from dynamo_tpu.ops.attention import paged_attention
from dynamo_tpu.ops.sampling import sample_tokens

__all__ = ["apply_rope", "rope_table", "paged_attention", "sample_tokens"]
