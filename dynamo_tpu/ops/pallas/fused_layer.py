"""Fused-layer decode megakernel (pallas TPU).

ONE pallas program per decoder layer for the C=1 decode path: RMS-norm →
int8-streamed qkv (+fused RoPE) → paged attention (history pages + the
in-register current token) → int8-streamed o-proj → residual → RMS-norm →
int8-streamed gate/up/silu/mul/down → residual. Weights stay in HBM and
stream through VMEM tiles with manual double-buffered DMAs; KV pages stream
in per-(wave, page) steps whose first DMAs are issued during the qkv weight
stream, so page-issue latency hides under matmul compute.

History pages are driven by a DYNAMIC page loop (r6): the per-row block
tables and page counts live in SMEM (scalar-prefetch operands, available
before the body runs), each batch wave runs a ``fori_loop`` bounded by the
wave's maximum page count, and every DMA/compute step is gated per row on
its own scalar-prefetched count. Trace/compile size is therefore
independent of the table width — long contexts (4k+ tokens) compile the
same program as short ones — and short rows in a long-context batch skip
their dead pages entirely (no stream, no mask) instead of streaming-then-
masking up to the table capacity. Table widths are pow2-bucketed by the
engine (engines/tpu/engine.py::table_width_bucket), so XLA holds a handful
of programs per shape, one per bucket.

Why this exists (r5): the per-layer XLA decode structure leaves the chip at
~1/3 of its HBM roofline at the 8B shape — a device trace showed ~490
fusions + ~390 copies per step of inter-op glue, a DMA-issue-bound
standalone attention kernel (190µs/layer vs ~80µs of page bytes), and
weight matmuls at 663 GB/s that a pallas mixed int8 dot beats at 726 GB/s
(measured, `_prof_fused_ffn.py`). Fusing the whole layer removes the glue,
overlaps attention page fetches with weight streaming, and keeps the
residual in VMEM across phases.

Reference parity: plays the role of the fused decode kernels inside the
engines the reference orchestrates (vLLM/TRT-LLM fused attention+GEMM
paths); the reference repo itself carries no TPU equivalent.

Scope (v2): C=1 decode, dense FFN, no sliding window, no logit cap, no
qkv-bias, no qk-norm, no post-norms, no LoRA delta, int8 weights
({"q8","s"} per ops/quant.py), bf16 KV pools. Context length is NOT a
scope limit any more: the dynamic page loop serves any table width the
engine's block tables can describe (the former ``MAX_TABLE_PAGES = 16``
static-unroll ceiling — 256 tokens at block_size 16 — is gone). The XLA
path (models/llama.py::decoder_layer) remains the fallback for every
other configuration and stays the numerics oracle; parity is asserted in
interpret mode at 256/1k/4k-token contexts and ragged short+long batches
(tests/test_fused_layer.py, tests/test_zlongctx_fused.py).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _tiles_for(d: int, HD: int, KHD: int, F: int):
    """(TQ, TO, TF) weight-streaming tile widths for these dims."""
    return min(256, KHD), min(512, d), min(512, F)


def supports(config, *, lora: bool, quantized_weights: bool) -> bool:
    """Static eligibility of the megakernel for a model config. Every knob
    the kernel does NOT implement must be gated here — the kernel hardcodes
    SiLU and plain (non-unit-offset) RMSNorm — and every tiling constraint
    fused_decoder_layer asserts must hold, so an auto-enabled config can
    never crash at first decode instead of falling back."""
    c = config
    if not (
        quantized_weights
        and not lora
        and not any(int(w) != 0 for w in c.layer_windows())
        and not c.is_moe
        and not c.qkv_bias
        and not c.qk_norm
        and not c.post_norms
        and c.act_fn == "silu"
        and not c.rmsnorm_unit_offset
        and (c.attn_logit_softcap or 0.0) == 0.0
        and c.head_dim_ == 128
        and (c.n_heads % c.n_kv_heads) == 0
    ):
        return False
    d, D = c.d_model, c.head_dim_
    HD, KHD, F = c.n_heads * D, c.n_kv_heads * D, c.d_ff
    TQ, TO, TF = _tiles_for(d, HD, KHD, F)
    return bool(
        HD % TQ == 0 and KHD % TQ == 0 and TQ % D == 0
        and d % TO == 0 and F % TF == 0
    )


def _fused_layer_kernel(
    # SMEM operands (scalar-prefetch: available before the body runs, so
    # they drive every page DMA's index and the dynamic loop bounds)
    tables_ref,  # [B, P] int32
    start_ref,  # [B] int32
    pcount_ref,  # [B] int32 — history pages per row: ceil(start / BS)
    # VMEM operands
    x_ref,  # [B, d] bf16 residual stream
    cos_ref,  # [B, D] f32 rope table at each row's position
    sin_ref,  # [B, D] f32
    anorm_ref,  # [1, d] attn-norm weight
    mnorm_ref,  # [1, d] mlp-norm weight
    wqs_ref,  # [1, H*D] f32 — per-output-col int8 scales
    wks_ref,  # [1, KH*D]
    wvs_ref,  # [1, KH*D]
    wos_ref,  # [1, d]
    wgs_ref,  # [1, F]
    wus_ref,  # [1, F]
    wds_ref,  # [1, d]
    # ANY (HBM) operands
    wq_ref,  # [d, H*D] int8
    wk_ref,  # [d, KH*D]
    wv_ref,  # [d, KH*D]
    wo_ref,  # [H*D, d]
    wg_ref,  # [d, F]
    wu_ref,  # [d, F]
    wd_ref,  # [F, d]
    k_pool_ref,  # [NB, BS, KH, D] bf16 (HBM)
    v_pool_ref,
    # outputs (VMEM)
    xo_ref,  # [B, d]
    kn_ref,  # [B, KH, D] current-token K (post-rope)
    vn_ref,  # [B, KH, D]
    *,
    eps: float,
    sm_scale: float,
    B: int,
    d: int,
    H: int,
    KH: int,
    D: int,
    F: int,
    P: int,
    BS: int,
    TQ: int,
    TO: int,
    TF: int,
    BQ: int,
):
    G = H // KH
    HD = H * D
    KHD = KH * D
    HPT = TQ // D  # heads covered per qkv tile
    NQT = (HD + 2 * KHD) // TQ  # qkv col tiles (wq cols, then wk, then wv)
    NOT_ = d // TO
    NFT = F // TF
    NW = B // BQ  # attention waves
    half = D // 2

    def qkv_src(t):
        """(weight ref, scale ref, col offset, kind, head offset) for
        qkv col tile t of the concatenated [d, HD+2*KHD] projection."""
        off = t * TQ
        if off < HD:
            return wq_ref, wqs_ref, off, "q", off // D
        if off < HD + KHD:
            off -= HD
            return wk_ref, wks_ref, off, "k", off // D
        off -= HD + KHD
        return wv_ref, wvs_ref, off, "v", off // D

    def body(h_ref, attn4_ref, wsem):
        # ---- phase 0: attn norm (VPU) ----
        xf = x_ref[...].astype(jnp.float32)
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        h_ref[...] = (xf * jax.lax.rsqrt(var + eps)).astype(jnp.bfloat16) * (
            anorm_ref[...].astype(jnp.bfloat16)
        )

        def rope(v):  # [B, D] f32
            lo = v[:, :half]
            hi = v[:, half:]
            rot = jnp.concatenate([-hi, lo], axis=1)
            return v * cos_ref[...] + rot * sin_ref[...]

        # ---- phases 1+2 share the page-staging scratch: qkv streaming
        # issues wave 0's first page DMAs so their latency hides under
        # matmuls ----
        def qkv_and_attention(q4_ref, fl_m, fl_l, fl_acc, pages, psem):
            # THREE page-step slots: page pp+2 is issued while page pp is
            # being consumed, and lands in the slot that held page pp-1
            # (already consumed) — an issued DMA never targets a buffer
            # with pending reads, so no DMA/vector ordering assumption is
            # needed. Slots are indexed dynamically (pp % 3): the page loop
            # is a fori_loop over scalar-prefetched counts, not an unroll.
            def page_dma(slot, w, pp, j, which):
                pool = k_pool_ref if which == 0 else v_pool_ref
                page = tables_ref[w * BQ + j, pp]
                return pltpu.make_async_copy(
                    pool.at[page],
                    pages.at[slot, j, which],
                    psem.at[slot, j, which],
                )

            def row_needs(w, pp, j):
                """Does row j of wave w have history on page pp? The SAME
                SMEM-derived predicate gates issue (pp+2), wait (pp) and
                compute (pp), so conditional start/wait pairs always match
                — and a short row in a long-context wave does nothing at
                all for its dead pages (no stream, no mask)."""
                return pp < pcount_ref[w * BQ + j]

            def issue_page(w, pp):
                slot = pp % 3  # derived here so issue/wait can't desync
                for j in range(BQ):

                    @pl.when(row_needs(w, pp, j))
                    def _(j=j):
                        page_dma(slot, w, pp, j, 0).start()
                        page_dma(slot, w, pp, j, 1).start()

            def wait_page(w, pp, j):
                slot = pp % 3

                @pl.when(row_needs(w, pp, j))
                def _():
                    page_dma(slot, w, pp, j, 0).wait()
                    page_dma(slot, w, pp, j, 1).wait()

            # ---- phase 1: qkv weight streaming + fused RoPE ----
            def phase_qkv(wbuf):
                def w_dma(slot, t):
                    ref, _, off, _, _ = qkv_src(t)
                    return pltpu.make_async_copy(
                        ref.at[:, pl.ds(off, TQ)], wbuf.at[slot],
                        wsem.at[slot],
                    )

                w_dma(0, 0).start()
                issue_page(0, 0)
                if P > 1:
                    issue_page(0, 1)

                h = h_ref[...]
                for t in range(NQT):  # static: tile→(ref, head) per tile
                    slot = t % 2
                    if t + 1 < NQT:
                        w_dma((t + 1) % 2, t + 1).start()
                    w_dma(slot, t).wait()
                    _, sref, off, kind, h0 = qkv_src(t)
                    y = jax.lax.dot_general(
                        h, wbuf[slot], (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    ) * sref[0, pl.ds(off, TQ)][None, :]
                    for i in range(HPT):  # rope + scatter per covered head
                        col = y[:, i * D:(i + 1) * D]
                        hh = h0 + i
                        if kind == "q":
                            q4_ref[:, hh // G, hh % G, :] = rope(col)
                        elif kind == "k":
                            kn_ref[:, hh, :] = rope(col).astype(kn_ref.dtype)
                        else:
                            vn_ref[:, hh, :] = col.astype(vn_ref.dtype)

            pl.run_scoped(phase_qkv, wbuf=pltpu.VMEM((2, d, TQ), jnp.int8))

            # ---- phase 2: paged attention, page-granular flash pipeline.
            # DYNAMIC page loop per wave: the fori_loop trip count is the
            # wave's maximum scalar-prefetched page count, so the traced
            # program holds ONE page-step body per wave regardless of the
            # table width — trace/compile cost no longer scales with
            # context length (the old static unroll paid (B/BQ)·P bodies
            # and capped the table at 16 pages). Batch waves stay a static
            # unroll: NW = B/BQ is small and fixed by the batch shape, and
            # static j/kh indices keep the proven static-index style of
            # ops/pallas/paged_attention.py inside the loop body. ----
            def att_wave(w):
                npg = pcount_ref[w * BQ]
                for j in range(1, BQ):
                    npg = jnp.maximum(npg, pcount_ref[w * BQ + j])

                fl_m[...] = jnp.full_like(fl_m, NEG_INF)
                fl_l[...] = jnp.zeros_like(fl_l)
                fl_acc[...] = jnp.zeros_like(fl_acc)

                def page_step(pp, carry):
                    slot = pp % 3
                    issue_page(w, pp + 2)

                    for j in range(BQ):
                        b = w * BQ + j
                        start = start_ref[b]
                        wait_page(w, pp, j)

                        # Skip rows whose history ends before this page —
                        # the DMA was never issued (row_needs) and the
                        # flash state is untouched, so traffic+compute
                        # track sequence length, not table capacity.
                        @pl.when(row_needs(w, pp, j))
                        def _(j=j, b=b, start=start):
                            for kh in range(KH):
                                q = q4_ref[b, kh]  # [G, D]
                                kpg = pages[slot, j, 0, :, kh, :].astype(
                                    jnp.float32
                                )
                                vpg = pages[slot, j, 1, :, kh, :].astype(
                                    jnp.float32
                                )
                                s = jax.lax.dot_general(
                                    q, kpg, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32,
                                ) * sm_scale  # [G, BS]
                                t_idx = pp * BS + jax.lax.broadcasted_iota(
                                    jnp.int32, (G, BS), 1
                                )
                                s = jnp.where(t_idx < start, s, NEG_INF)
                                m = fl_m[j, kh]
                                m_new = jnp.maximum(
                                    m, jnp.max(s, -1, keepdims=True)
                                )
                                alpha = jnp.exp(m - m_new)
                                p_ = jnp.exp(s - m_new)
                                fl_l[j, kh] = fl_l[j, kh] * alpha + jnp.sum(
                                    p_, -1, keepdims=True
                                )
                                fl_acc[j, kh] = fl_acc[j, kh] * alpha + (
                                    jax.lax.dot_general(
                                        p_, vpg, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32,
                                    )
                                )
                                fl_m[j, kh] = m_new

                    return carry

                jax.lax.fori_loop(0, npg, page_step, 0)

                # Next wave's first pages start streaming while this wave
                # finalizes — the cross-wave analogue of hiding wave 0's
                # prologue under the qkv weight stream. Every DMA this
                # wave issued was waited inside the loop (matched
                # row_needs predicates), so slots 0/1 have no pending
                # traffic.
                if w + 1 < NW:
                    issue_page(w + 1, 0)
                    if P > 1:
                        issue_page(w + 1, 1)

                # wave finalize: current-token column + normalize + store
                for j in range(BQ):
                    b = w * BQ + j
                    for kh in range(KH):
                        q = q4_ref[b, kh]  # [G, D]
                        kcur = kn_ref[pl.ds(b, 1), kh, :].astype(
                            jnp.float32
                        )  # [1, D]
                        vcur = vn_ref[pl.ds(b, 1), kh, :].astype(
                            jnp.float32
                        )
                        s_c = jax.lax.dot_general(
                            q, kcur, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
                        ) * sm_scale  # [G, 1]
                        m = fl_m[j, kh]
                        m_new = jnp.maximum(m, s_c)
                        alpha = jnp.exp(m - m_new)
                        p_c = jnp.exp(s_c - m_new)
                        l = fl_l[j, kh] * alpha + p_c
                        acc = fl_acc[j, kh] * alpha + p_c * vcur
                        out = acc / jnp.maximum(l, 1e-30)
                        attn4_ref[pl.ds(b, 1), kh, :, :] = out.reshape(
                            1, G, D
                        ).astype(attn4_ref.dtype)

            for _w in range(NW):
                att_wave(_w)

        pl.run_scoped(
            qkv_and_attention,
            q4_ref=pltpu.VMEM((B, KH, G, D), jnp.float32),
            fl_m=pltpu.VMEM((BQ, KH, G, 1), jnp.float32),
            fl_l=pltpu.VMEM((BQ, KH, G, 1), jnp.float32),
            fl_acc=pltpu.VMEM((BQ, KH, G, D), jnp.float32),
            pages=pltpu.VMEM((3, BQ, 2, BS, KH, D), jnp.bfloat16),
            psem=pltpu.SemaphoreType.DMA((3, BQ, 2)),
        )

        # ---- phase 3: o-proj streaming + residual ----
        def phase_o(obuf):
            def o_dma(slot, t):
                return pltpu.make_async_copy(
                    wo_ref.at[:, pl.ds(t * TO, TO)], obuf.at[slot],
                    wsem.at[slot],
                )

            o_dma(0, 0).start()
            attn = attn4_ref[...].reshape(B, HD).astype(jnp.bfloat16)
            for t in range(NOT_):
                slot = t % 2
                if t + 1 < NOT_:
                    o_dma((t + 1) % 2, t + 1).start()
                o_dma(slot, t).wait()
                y = jax.lax.dot_general(
                    attn, obuf[slot], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * wos_ref[0, pl.ds(t * TO, TO)][None, :]
                xo_ref[:, pl.ds(t * TO, TO)] = (
                    x_ref[:, pl.ds(t * TO, TO)].astype(jnp.float32) + y
                ).astype(xo_ref.dtype)

        pl.run_scoped(phase_o, obuf=pltpu.VMEM((2, HD, TO), jnp.int8))

        # ---- phase 4: mlp norm ----
        x2 = xo_ref[...].astype(jnp.float32)
        var2 = jnp.mean(x2 * x2, axis=-1, keepdims=True)
        h_ref[...] = (x2 * jax.lax.rsqrt(var2 + eps)).astype(jnp.bfloat16) * (
            mnorm_ref[...].astype(jnp.bfloat16)
        )

        # ---- phases 5+6: gate/up then down (nested: gu activations stay
        # live while the gate/up weight buffers are freed) ----
        def phase_gu(wbuf, gu_ref):
            def gu_dma(slot, t, which):
                ref = wg_ref if which == 0 else wu_ref
                return pltpu.make_async_copy(
                    ref.at[:, pl.ds(t * TF, TF)], wbuf.at[slot, which],
                    wsem.at[slot * 2 + which],
                )

            gu_dma(0, 0, 0).start()
            gu_dma(0, 0, 1).start()
            h2 = h_ref[...]

            def gu_loop(t):
                slot = t % 2
                nxt = (t + 1) % 2

                if t + 1 < NFT:
                    gu_dma(nxt, t + 1, 0).start()
                    gu_dma(nxt, t + 1, 1).start()

                gu_dma(slot, t, 0).wait()
                gu_dma(slot, t, 1).wait()
                g = jax.lax.dot_general(
                    h2, wbuf[slot, 0], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * wgs_ref[0, pl.ds(t * TF, TF)][None, :]
                u = jax.lax.dot_general(
                    h2, wbuf[slot, 1], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * wus_ref[0, pl.ds(t * TF, TF)][None, :]
                gu_ref[:, pl.ds(t * TF, TF)] = (
                    g * jax.lax.logistic(g) * u
                ).astype(jnp.bfloat16)

            for _t in range(NFT):
                gu_loop(_t)

            def phase_down(dbuf, acc_ref):
                def d_dma(slot, t):
                    return pltpu.make_async_copy(
                        wd_ref.at[pl.ds(t * TF, TF), :], dbuf.at[slot],
                        wsem.at[4 + slot],
                    )

                d_dma(0, 0).start()
                acc_ref[...] = jnp.zeros_like(acc_ref)

                def d_loop(t):
                    slot = t % 2
                    nxt = (t + 1) % 2

                    if t + 1 < NFT:
                        d_dma(nxt, t + 1).start()

                    d_dma(slot, t).wait()
                    acc_ref[...] += jax.lax.dot_general(
                        gu_ref[:, pl.ds(t * TF, TF)], dbuf[slot],
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )

                for _t in range(NFT):
                    d_loop(_t)
                xo_ref[...] = (
                    xo_ref[...].astype(jnp.float32)
                    + acc_ref[...] * wds_ref[...]
                ).astype(xo_ref.dtype)

            pl.run_scoped(
                phase_down,
                dbuf=pltpu.VMEM((2, TF, d), jnp.int8),
                acc_ref=pltpu.VMEM((B, d), jnp.float32),
            )

        pl.run_scoped(
            phase_gu,
            wbuf=pltpu.VMEM((2, 2, d, TF), jnp.int8),
            gu_ref=pltpu.VMEM((B, F), jnp.bfloat16),
        )

    pl.run_scoped(
        body,
        h_ref=pltpu.VMEM((B, d), jnp.bfloat16),
        attn4_ref=pltpu.VMEM((B, KH, G, D), jnp.bfloat16),
        wsem=pltpu.SemaphoreType.DMA((6,)),
    )


def history_pcounts(
    start_pos: jnp.ndarray, block_size: int, table_width: int
) -> jnp.ndarray:
    """Per-row history page count for the decode megakernel's dynamic page
    loop, clamped to the table width so a row can never index past its
    table (the causal mask already hides any positions beyond it). Exposed
    so the per-step caller (models/llama.py forward_paged) derives it ONCE
    and shares it across all layers instead of recomputing per layer."""
    start32 = start_pos.astype(jnp.int32)
    return jnp.minimum((start32 + block_size - 1) // block_size, table_width)


def _fused_decoder_layer_impl(
    x: jnp.ndarray,  # [B, d] bf16 residual
    cos: jnp.ndarray,  # [B, D] f32
    sin: jnp.ndarray,  # [B, D] f32
    lp: Dict[str, Any],  # one layer's params (quantized tree)
    k_pool: jnp.ndarray,  # [NB, BS, KH, D] bf16
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, P] int32
    start_pos: jnp.ndarray,  # [B] int32
    *,
    eps: float,
    sm_scale: float,
    batch_block: int = 4,
    interpret: Optional[bool] = None,
    pcounts: Optional[jnp.ndarray] = None,  # [B] int32 (history_pcounts)
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Run one fused decoder layer. Returns (x_out [B, d], k_new [B, KH, D],
    v_new [B, KH, D]); the caller scatters k_new/v_new into the pools
    (ops/attention.write_chunk_to_cache) AFTER the call — the kernel
    attends to history pages plus the in-register current token. Rows
    whose history is shorter than the table width skip their dead pages
    via the scalar-prefetched per-row page counts (``pcounts``, derived
    per step via :func:`history_pcounts` when not supplied); the table
    width P may be anything (one compiled program per distinct P — callers
    should bucket widths, see engines/tpu/engine.py::table_width_bucket)."""
    if interpret is None:
        # CPU (tests, dryruns): Mosaic doesn't lower there — emulate.
        interpret = jax.default_backend() != "tpu"
    B, d = x.shape
    NB, BS, KH, D = k_pool.shape
    HD = lp["wq"]["q8"].shape[1]
    F = lp["w_gate"]["q8"].shape[1]
    H = HD // D
    P = block_tables.shape[1]
    BQ = batch_block
    assert B % BQ == 0, (B, BQ)

    KHD = KH * D
    TQ, TO, TF = _tiles_for(d, HD, KHD, F)  # same derivation supports() gates
    assert HD % TQ == 0 and KHD % TQ == 0 and TQ % D == 0, (HD, KHD, TQ)
    assert d % TO == 0 and F % TF == 0, (d, TO, F, TF)

    kernel = functools.partial(
        _fused_layer_kernel,
        eps=eps, sm_scale=sm_scale,
        B=B, d=d, H=H, KH=KH, D=D, F=F, P=P, BS=BS,
        TQ=TQ, TO=TO, TF=TF, BQ=BQ,
    )
    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)  # noqa: E731
    vmem = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)  # noqa: E731
    hbm = lambda: pl.BlockSpec(memory_space=pl.ANY)  # noqa: E731

    two_d = lambda a: a.reshape(1, -1)  # noqa: E731 — Mosaic wants >=2D

    start32 = start_pos.astype(jnp.int32)
    # Per-row history page count: the scalar-prefetch operand that bounds
    # the kernel's dynamic page loop and gates every page DMA per row.
    if pcounts is None:
        pcounts = history_pcounts(start32, BS, P)
    pcounts = pcounts.astype(jnp.int32)

    out = pl.pallas_call(
        kernel,
        in_specs=[smem(), smem(), smem()] + [vmem()] * 12 + [hbm()] * 9,
        out_specs=(vmem(), vmem(), vmem()),
        out_shape=(
            jax.ShapeDtypeStruct((B, d), x.dtype),
            jax.ShapeDtypeStruct((B, KH, D), x.dtype),
            jax.ShapeDtypeStruct((B, KH, D), x.dtype),
        ),
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        start32,
        pcounts,
        x, cos.astype(jnp.float32), sin.astype(jnp.float32),
        two_d(lp["attn_norm"]), two_d(lp["mlp_norm"]),
        two_d(lp["wq"]["s"]), two_d(lp["wk"]["s"]), two_d(lp["wv"]["s"]),
        two_d(lp["wo"]["s"]),
        two_d(lp["w_gate"]["s"]), two_d(lp["w_up"]["s"]),
        two_d(lp["w_down"]["s"]),
        lp["wq"]["q8"], lp["wk"]["q8"], lp["wv"]["q8"], lp["wo"]["q8"],
        lp["w_gate"]["q8"], lp["w_up"]["q8"], lp["w_down"]["q8"],
        k_pool, v_pool,
    )
    return out


# Jitted + watched program object (DYN001): the megakernel's signature
# count tracks (pow2 table-width bucket × variant) — exactly what the
# runner budgets via set_budget, and what a per-request width leak would
# blow through (the recompile-storm signal the runtime detector pages on).
from dynamo_tpu.runtime.device_observe import watched_jit  # noqa: E402

fused_decoder_layer = watched_jit(
    "pallas.fused_decoder_layer",
    functools.partial(
        jax.jit,
        static_argnames=("eps", "sm_scale", "batch_block", "interpret"),
    )(_fused_decoder_layer_impl),
)
