"""Fused-layer decode megakernel (pallas TPU).

ONE pallas program per decoder layer for the C=1 decode path: RMS-norm →
int8-streamed qkv (+fused RoPE) → paged attention (history pages + the
in-register current token) → int8-streamed o-proj → residual → RMS-norm →
int8-streamed gate/up/act/mul/down → residual. Weights stay in HBM and
stream through VMEM tiles with manual double-buffered DMAs; KV pages stream
in per-(wave, page) steps whose first DMAs are issued during the qkv weight
stream, so page-issue latency hides under matmul compute.

History pages are driven by a DYNAMIC page loop (r6): the per-row block
tables and page counts live in SMEM (scalar-prefetch operands, available
before the body runs), each batch wave runs a ``fori_loop`` bounded by the
wave's live page range, and every DMA/compute step is gated per row on its
own scalar-prefetched bounds. Trace/compile size is therefore independent
of the table width — long contexts (4k+ tokens) compile the same program
as short ones — and short rows in a long-context batch skip their dead
pages entirely (no stream, no mask) instead of streaming-then-masking up
to the table capacity. Table widths are pow2-bucketed by the engine
(engines/tpu/engine.py::table_width_bucket), so XLA holds a handful of
programs per shape, one per bucket.

Architecture epilogues (r11): the family knobs that used to force the
~1/3-roofline XLA fallback are now in-kernel, so Qwen3 and Gemma-2/3
decode on the fused path:

  - **qk-norm** — per-head RMSNorm on the q/k projection columns before
    RoPE (Qwen3/Gemma-3 order: norm → rope), a few VPU ops on vectors
    already live in registers plus two [1, D] norm-weight operands;
  - **attention logit softcap** — ``cap·tanh(s/cap)`` on scores before
    masking (Gemma-2), a static-float epilogue on both the page loop and
    the current-token column;
  - **post-norms** — Gemma-2/3's extra RMSNorms after the attention and
    FFN blocks; the o-proj phase accumulates into a [B, d] f32 scratch so
    the full row is normed before the residual add (the FFN side reuses
    the down-proj accumulator that already exists);
  - **sliding window** — each row's dynamic page loop STARTS at
    ``floor((pos−W)/BS)`` instead of page 0 (per-row SMEM page offsets,
    same predicate style as the page counts) and the boundary page is
    masked in-kernel, so a windowed row streams strictly fewer pages than
    full attention — a perf win, not just coverage. The window rides a
    TRACED scalar operand, so Gemma-3's 5:1 local/global layer mix shares
    ONE compiled program per width bucket;
  - **GeGLU / unit-offset RMSNorm / qkv-bias** — a static activation
    switch (tanh-gelu vs SiLU), ``(1 + w)`` norm weights, and per-column
    bias adds on the qkv tiles.

Why this exists (r5): the per-layer XLA decode structure leaves the chip at
~1/3 of its HBM roofline at the 8B shape — a device trace showed ~490
fusions + ~390 copies per step of inter-op glue, a DMA-issue-bound
standalone attention kernel (190µs/layer vs ~80µs of page bytes), and
weight matmuls at 663 GB/s that a pallas mixed int8 dot beats at 726 GB/s
(measured, `_prof_fused_ffn.py`). Fusing the whole layer removes the glue,
overlaps attention page fetches with weight streaming, and keeps the
residual in VMEM across phases.

Reference parity: plays the role of the fused decode kernels inside the
engines the reference orchestrates (vLLM/TRT-LLM fused attention+GEMM
paths serve Qwen3/Gemma natively); the reference repo itself carries no
TPU equivalent.

Scope (v3): C=1 decode, dense FFN, int8 weights ({"q8","s"} per
ops/quant.py), bf16 KV pools, head_dim a multiple of 128. Excluded (and
documented in supports_reason): MoE FFNs and LoRA deltas — both fall back
to the XLA path. The XLA path (models/llama.py::decoder_layer) remains the
fallback for every other configuration and stays the numerics oracle;
parity is asserted in interpret mode at 256/1k/4k-token contexts, ragged
short+long batches, and page-straddling window boundaries
(tests/test_fused_layer.py, tests/test_zlongctx_fused.py).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

_SUPPORTED_ACTS = ("silu", "gelu_tanh")


def _tiles_for(d: int, HD: int, KHD: int, F: int, D: int):
    """(TQ, TO, TF) weight-streaming tile widths for these dims, or None
    when no feasible split exists. Each tile is the LARGEST lane-aligned
    divisor under the VMEM cap: TQ covers whole heads (multiple of D) and
    must divide both the q and k/v projection widths so every qkv col tile
    lives entirely inside one of wq/wk/wv; TO/TF are multiples of the
    128-lane MXU width dividing d / d_ff (Gemma shapes like d=1152 or
    d_ff=6912 need 384 — the old min(512, ·) rule rejected them)."""

    def div_tile(n: int, cap: int, step: int) -> Optional[int]:
        t = (cap // step) * step
        while t >= step:
            if n % t == 0:
                return t
            t -= step
        return None

    tq = None
    t = (256 // D) * D if D else 0
    while t >= D > 0:
        if HD % t == 0 and KHD % t == 0:
            tq = t
            break
        t -= D
    to = div_tile(d, 512, 128)
    tf = div_tile(F, 512, 128)
    if tq is None or to is None or tf is None:
        return None
    return tq, to, tf


def supports_reason(
    config, *, lora: bool, quantized_weights: bool
) -> Optional[str]:
    """Why the megakernel can NOT serve this config (None = it can).

    Every knob the kernel does not implement must surface here — an
    auto-enabled config can never crash at first decode instead of
    falling back — and the docs' supports() matrix + the supports-matrix
    preset test render these exact strings. qk-norm, sliding windows,
    logit softcap, post-norms, unit-offset RMSNorm, qkv-bias and GeGLU
    are in-kernel epilogues since r11 and are deliberately absent."""
    c = config
    if not quantized_weights:
        return "weights not int8-quantized (the kernel streams int8 tiles)"
    if lora:
        return "LoRA adapters active (per-request delta einsums excluded)"
    if c.is_moe:
        return "MoE FFN (routed experts excluded; dense FFN only)"
    if c.act_fn not in _SUPPORTED_ACTS:
        return f"unsupported activation {c.act_fn!r} (silu/gelu_tanh only)"
    D = c.head_dim_
    if D <= 0 or D % 128 != 0:
        return f"head_dim {D} not a multiple of the 128-lane MXU width"
    if (c.n_heads % c.n_kv_heads) != 0:
        return "n_heads not a multiple of n_kv_heads (GQA grouping)"
    d, HD, KHD, F = c.d_model, c.n_heads * D, c.n_kv_heads * D, c.d_ff
    if _tiles_for(d, HD, KHD, F, D) is None:
        return (
            "no lane-aligned weight-streaming tile split for "
            f"(d={d}, HD={HD}, KHD={KHD}, d_ff={F})"
        )
    return None


def supports(config, *, lora: bool, quantized_weights: bool) -> bool:
    """Static eligibility of the megakernel for a model config — True when
    :func:`supports_reason` finds nothing to exclude."""
    return (
        supports_reason(config, lora=lora, quantized_weights=quantized_weights)
        is None
    )


def _fused_layer_kernel(
    *refs,
    eps: float,
    sm_scale: float,
    B: int,
    d: int,
    H: int,
    KH: int,
    D: int,
    F: int,
    P: int,
    BS: int,
    TQ: int,
    TO: int,
    TF: int,
    BQ: int,
    qk_norm: bool,
    qkv_bias: bool,
    post_norms: bool,
    act_fn: str,
    softcap: float,
    unit_offset: bool,
):
    # Positional refs vary with the static epilogue flags; parse in the
    # exact order _fused_decoder_layer_impl assembles them.
    it = iter(refs)
    # SMEM (scalar-prefetch: available before the body runs, so they drive
    # every page DMA's index and the dynamic loop bounds)
    tables_ref = next(it)  # [B, P] int32
    start_ref = next(it)  # [B] int32
    pcount_ref = next(it)  # [B] int32 — history pages: ceil(start / BS)
    wlo_ref = next(it)  # [B] int32 — first VISIBLE key index (window low)
    poff_ref = next(it)  # [B] int32 — first live page: wlo // BS
    # VMEM
    x_ref = next(it)  # [B, d] bf16 residual stream
    cos_ref = next(it)  # [B, D] f32 rope table at each row's position
    sin_ref = next(it)  # [B, D] f32
    anorm_ref = next(it)  # [1, d] attn-norm weight
    mnorm_ref = next(it)  # [1, d] mlp-norm weight
    qnorm_ref = knorm_ref = None
    if qk_norm:
        qnorm_ref = next(it)  # [1, D] per-head q-norm weight
        knorm_ref = next(it)  # [1, D]
    bq_ref = bk_ref = bv_ref = None
    if qkv_bias:
        bq_ref = next(it)  # [1, H*D]
        bk_ref = next(it)  # [1, KH*D]
        bv_ref = next(it)  # [1, KH*D]
    apost_ref = mpost_ref = None
    if post_norms:
        apost_ref = next(it)  # [1, d] post-attention norm weight
        mpost_ref = next(it)  # [1, d] post-FFN norm weight
    wqs_ref = next(it)  # [1, H*D] f32 — per-output-col int8 scales
    wks_ref = next(it)  # [1, KH*D]
    wvs_ref = next(it)  # [1, KH*D]
    wos_ref = next(it)  # [1, d]
    wgs_ref = next(it)  # [1, F]
    wus_ref = next(it)  # [1, F]
    wds_ref = next(it)  # [1, d]
    # ANY (HBM)
    wq_ref = next(it)  # [d, H*D] int8
    wk_ref = next(it)  # [d, KH*D]
    wv_ref = next(it)  # [d, KH*D]
    wo_ref = next(it)  # [H*D, d]
    wg_ref = next(it)  # [d, F]
    wu_ref = next(it)  # [d, F]
    wd_ref = next(it)  # [F, d]
    k_pool_ref = next(it)  # [NB, BS, KH, D] bf16 (HBM)
    v_pool_ref = next(it)
    # outputs (VMEM)
    xo_ref = next(it)  # [B, d]
    kn_ref = next(it)  # [B, KH, D] current-token K (post-rope)
    vn_ref = next(it)  # [B, KH, D]

    G = H // KH
    HD = H * D
    KHD = KH * D
    HPT = TQ // D  # heads covered per qkv tile
    NQT = (HD + 2 * KHD) // TQ  # qkv col tiles (wq cols, then wk, then wv)
    NOT_ = d // TO
    NFT = F // TF
    NW = B // BQ  # attention waves
    half = D // 2

    def w1(ref, dtype=jnp.float32):
        """Norm weight with the family's unit offset applied (Gemma stores
        w - 1; effective scale is 1 + w)."""
        w = ref[...].astype(dtype)
        return w + 1.0 if unit_offset else w

    def capped(s):
        """Gemma-2 attention logit softcap (static float; 0 = off)."""
        if softcap > 0.0:
            return softcap * jnp.tanh(s / softcap)
        return s

    def qkv_src(t):
        """(weight ref, scale ref, bias ref, col offset, kind, head offset)
        for qkv col tile t of the concatenated [d, HD+2*KHD] projection."""
        off = t * TQ
        if off < HD:
            return wq_ref, wqs_ref, bq_ref, off, "q", off // D
        if off < HD + KHD:
            off -= HD
            return wk_ref, wks_ref, bk_ref, off, "k", off // D
        off -= HD + KHD
        return wv_ref, wvs_ref, bv_ref, off, "v", off // D

    def body(h_ref, attn4_ref, wsem):
        # ---- phase 0: attn norm (VPU) ----
        xf = x_ref[...].astype(jnp.float32)
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        h_ref[...] = (xf * jax.lax.rsqrt(var + eps)).astype(jnp.bfloat16) * (
            w1(anorm_ref, jnp.bfloat16)
        )

        def rope(v):  # [B, D] f32
            lo = v[:, :half]
            hi = v[:, half:]
            rot = jnp.concatenate([-hi, lo], axis=1)
            return v * cos_ref[...] + rot * sin_ref[...]

        def head_norm(col, wref):
            """Qwen3/Gemma-3 per-head RMSNorm over head_dim, BEFORE RoPE
            (HF attention order: norm → rope). col: [B, D] f32."""
            hv = jnp.mean(col * col, axis=-1, keepdims=True)
            return col * jax.lax.rsqrt(hv + eps) * w1(wref)

        def wave_lo(w):
            """Wave's first live page (min over rows; 0 without windows)."""
            lo = poff_ref[w * BQ]
            for j in range(1, BQ):
                lo = jnp.minimum(lo, poff_ref[w * BQ + j])
            return lo

        # ---- phases 1+2 share the page-staging scratch: qkv streaming
        # issues wave 0's first page DMAs so their latency hides under
        # matmuls ----
        def qkv_and_attention(q4_ref, fl_m, fl_l, fl_acc, pages, psem):
            # THREE page-step slots: page pp+2 is issued while page pp is
            # being consumed, and lands in the slot that held page pp-1
            # (already consumed) — an issued DMA never targets a buffer
            # with pending reads, so no DMA/vector ordering assumption is
            # needed. Slots are indexed dynamically (pp % 3): the page loop
            # is a fori_loop over scalar-prefetched counts, not an unroll.
            def page_dma(slot, w, pp, j, which):
                pool = k_pool_ref if which == 0 else v_pool_ref
                page = tables_ref[w * BQ + j, pp]
                return pltpu.make_async_copy(
                    pool.at[page],
                    pages.at[slot, j, which],
                    psem.at[slot, j, which],
                )

            def row_needs(w, pp, j):
                """Is page pp LIVE for row j of wave w? Live = inside
                [poff, pcount): below pcount the row has history there,
                and at or past poff the page holds at least one key inside
                the sliding window. The SAME SMEM-derived predicate gates
                issue (pp+2), wait (pp) and compute (pp), so conditional
                start/wait pairs always match — and a short OR windowed
                row does nothing at all for its dead pages (no stream, no
                mask): windowed layers stream strictly fewer pages than
                full attention."""
                b = w * BQ + j
                return jnp.logical_and(
                    pp >= poff_ref[b], pp < pcount_ref[b]
                )

            def issue_page(w, pp):
                slot = pp % 3  # derived here so issue/wait can't desync
                for j in range(BQ):

                    @pl.when(row_needs(w, pp, j))
                    def _(j=j):
                        page_dma(slot, w, pp, j, 0).start()
                        page_dma(slot, w, pp, j, 1).start()

            def wait_page(w, pp, j):
                slot = pp % 3

                @pl.when(row_needs(w, pp, j))
                def _():
                    page_dma(slot, w, pp, j, 0).wait()
                    page_dma(slot, w, pp, j, 1).wait()

            # ---- phase 1: qkv weight streaming + fused RoPE ----
            def phase_qkv(wbuf):
                def w_dma(slot, t):
                    ref, _, _, off, _, _ = qkv_src(t)
                    return pltpu.make_async_copy(
                        ref.at[:, pl.ds(off, TQ)], wbuf.at[slot],
                        wsem.at[slot],
                    )

                w_dma(0, 0).start()
                lo0 = wave_lo(0)
                issue_page(0, lo0)
                if P > 1:
                    issue_page(0, lo0 + 1)

                h = h_ref[...]
                for t in range(NQT):  # static: tile→(ref, head) per tile
                    slot = t % 2
                    if t + 1 < NQT:
                        w_dma((t + 1) % 2, t + 1).start()
                    w_dma(slot, t).wait()
                    _, sref, bref, off, kind, h0 = qkv_src(t)
                    y = jax.lax.dot_general(
                        h, wbuf[slot], (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    ) * sref[0, pl.ds(off, TQ)][None, :]
                    if qkv_bias:
                        y = y + bref[0, pl.ds(off, TQ)][None, :]
                    for i in range(HPT):  # rope + scatter per covered head
                        col = y[:, i * D:(i + 1) * D]
                        hh = h0 + i
                        if kind == "q":
                            if qk_norm:
                                col = head_norm(col, qnorm_ref)
                            q4_ref[:, hh // G, hh % G, :] = rope(col)
                        elif kind == "k":
                            if qk_norm:
                                col = head_norm(col, knorm_ref)
                            kn_ref[:, hh, :] = rope(col).astype(kn_ref.dtype)
                        else:
                            vn_ref[:, hh, :] = col.astype(vn_ref.dtype)

            pl.run_scoped(phase_qkv, wbuf=pltpu.VMEM((2, d, TQ), jnp.int8))

            # ---- phase 2: paged attention, page-granular flash pipeline.
            # DYNAMIC page loop per wave: the fori_loop runs over the
            # wave's LIVE page range [min poff, max pcount) — scalar-
            # prefetched bounds, so the traced program holds ONE page-step
            # body per wave regardless of table width OR window value, and
            # a windowed wave starts at its first in-window page instead
            # of page 0. Batch waves stay a static unroll: NW = B/BQ is
            # small and fixed by the batch shape, and static j/kh indices
            # keep the proven static-index style of
            # ops/pallas/paged_attention.py inside the loop body. ----
            def att_wave(w):
                npg = pcount_ref[w * BQ]
                for j in range(1, BQ):
                    npg = jnp.maximum(npg, pcount_ref[w * BQ + j])
                lo = wave_lo(w)

                fl_m[...] = jnp.full_like(fl_m, NEG_INF)
                fl_l[...] = jnp.zeros_like(fl_l)
                fl_acc[...] = jnp.zeros_like(fl_acc)

                def page_step(pp, carry):
                    slot = pp % 3
                    issue_page(w, pp + 2)

                    for j in range(BQ):
                        b = w * BQ + j
                        start = start_ref[b]
                        wlo = wlo_ref[b]
                        wait_page(w, pp, j)

                        # Skip rows for whom this page is dead (history
                        # ends before it, or the sliding window starts
                        # after it) — the DMA was never issued (row_needs)
                        # and the flash state is untouched, so traffic +
                        # compute track the LIVE span, not table capacity.
                        @pl.when(row_needs(w, pp, j))
                        def _(j=j, b=b, start=start, wlo=wlo):
                            for kh in range(KH):
                                q = q4_ref[b, kh]  # [G, D]
                                kpg = pages[slot, j, 0, :, kh, :].astype(
                                    jnp.float32
                                )
                                vpg = pages[slot, j, 1, :, kh, :].astype(
                                    jnp.float32
                                )
                                s = capped(jax.lax.dot_general(
                                    q, kpg, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32,
                                ) * sm_scale)  # [G, BS]
                                t_idx = pp * BS + jax.lax.broadcasted_iota(
                                    jnp.int32, (G, BS), 1
                                )
                                # causal + window: visible history keys
                                # are t in [wlo, start) — wlo is 0 when
                                # the layer has no window, and masks the
                                # straddled boundary page when pos−W
                                # lands mid-page.
                                s = jnp.where(
                                    (t_idx < start) & (t_idx >= wlo),
                                    s, NEG_INF,
                                )
                                m = fl_m[j, kh]
                                m_new = jnp.maximum(
                                    m, jnp.max(s, -1, keepdims=True)
                                )
                                alpha = jnp.exp(m - m_new)
                                p_ = jnp.exp(s - m_new)
                                fl_l[j, kh] = fl_l[j, kh] * alpha + jnp.sum(
                                    p_, -1, keepdims=True
                                )
                                fl_acc[j, kh] = fl_acc[j, kh] * alpha + (
                                    jax.lax.dot_general(
                                        p_, vpg, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32,
                                    )
                                )
                                fl_m[j, kh] = m_new

                    return carry

                jax.lax.fori_loop(lo, npg, page_step, 0)

                # Next wave's first pages start streaming while this wave
                # finalizes — the cross-wave analogue of hiding wave 0's
                # prologue under the qkv weight stream. Every DMA this
                # wave issued was waited inside the loop (matched
                # row_needs predicates), so no slot has pending traffic.
                if w + 1 < NW:
                    nlo = wave_lo(w + 1)
                    issue_page(w + 1, nlo)
                    if P > 1:
                        issue_page(w + 1, nlo + 1)

                # wave finalize: current-token column + normalize + store.
                # The current token (t = start) is always inside the
                # window (W >= 1), so no extra mask here.
                for j in range(BQ):
                    b = w * BQ + j
                    for kh in range(KH):
                        q = q4_ref[b, kh]  # [G, D]
                        kcur = kn_ref[pl.ds(b, 1), kh, :].astype(
                            jnp.float32
                        )  # [1, D]
                        vcur = vn_ref[pl.ds(b, 1), kh, :].astype(
                            jnp.float32
                        )
                        s_c = capped(jax.lax.dot_general(
                            q, kcur, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
                        ) * sm_scale)  # [G, 1]
                        m = fl_m[j, kh]
                        m_new = jnp.maximum(m, s_c)
                        alpha = jnp.exp(m - m_new)
                        p_c = jnp.exp(s_c - m_new)
                        l = fl_l[j, kh] * alpha + p_c
                        acc = fl_acc[j, kh] * alpha + p_c * vcur
                        out = acc / jnp.maximum(l, 1e-30)
                        attn4_ref[pl.ds(b, 1), kh, :, :] = out.reshape(
                            1, G, D
                        ).astype(attn4_ref.dtype)

            for _w in range(NW):
                att_wave(_w)

        pl.run_scoped(
            qkv_and_attention,
            q4_ref=pltpu.VMEM((B, KH, G, D), jnp.float32),
            fl_m=pltpu.VMEM((BQ, KH, G, 1), jnp.float32),
            fl_l=pltpu.VMEM((BQ, KH, G, 1), jnp.float32),
            fl_acc=pltpu.VMEM((BQ, KH, G, D), jnp.float32),
            pages=pltpu.VMEM((3, BQ, 2, BS, KH, D), jnp.bfloat16),
            psem=pltpu.SemaphoreType.DMA((3, BQ, 2)),
        )

        # ---- phase 3: o-proj streaming + (post-norm →) residual.
        # Without post-norms each output tile folds straight into the
        # residual. WITH them (Gemma-2/3) the RMSNorm needs the FULL
        # projected row before the residual add, so tiles accumulate into
        # a [B, d] f32 scratch and the norm+residual run after the
        # stream. ----
        def phase_o(obuf, ao_ref):
            def o_dma(slot, t):
                return pltpu.make_async_copy(
                    wo_ref.at[:, pl.ds(t * TO, TO)], obuf.at[slot],
                    wsem.at[slot],
                )

            o_dma(0, 0).start()
            attn = attn4_ref[...].reshape(B, HD).astype(jnp.bfloat16)
            for t in range(NOT_):
                slot = t % 2
                if t + 1 < NOT_:
                    o_dma((t + 1) % 2, t + 1).start()
                o_dma(slot, t).wait()
                y = jax.lax.dot_general(
                    attn, obuf[slot], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * wos_ref[0, pl.ds(t * TO, TO)][None, :]
                if post_norms:
                    ao_ref[:, pl.ds(t * TO, TO)] = y
                else:
                    xo_ref[:, pl.ds(t * TO, TO)] = (
                        x_ref[:, pl.ds(t * TO, TO)].astype(jnp.float32) + y
                    ).astype(xo_ref.dtype)
            if post_norms:
                a = ao_ref[...]
                pv = jnp.mean(a * a, axis=-1, keepdims=True)
                normed = (a * jax.lax.rsqrt(pv + eps)).astype(
                    jnp.bfloat16
                ) * w1(apost_ref, jnp.bfloat16)
                xo_ref[...] = (
                    x_ref[...].astype(jnp.float32)
                    + normed.astype(jnp.float32)
                ).astype(xo_ref.dtype)

        if post_norms:
            pl.run_scoped(
                phase_o,
                obuf=pltpu.VMEM((2, HD, TO), jnp.int8),
                ao_ref=pltpu.VMEM((B, d), jnp.float32),
            )
        else:
            pl.run_scoped(
                lambda obuf: phase_o(obuf, None),
                obuf=pltpu.VMEM((2, HD, TO), jnp.int8),
            )

        # ---- phase 4: mlp norm ----
        x2 = xo_ref[...].astype(jnp.float32)
        var2 = jnp.mean(x2 * x2, axis=-1, keepdims=True)
        h_ref[...] = (x2 * jax.lax.rsqrt(var2 + eps)).astype(jnp.bfloat16) * (
            w1(mnorm_ref, jnp.bfloat16)
        )

        # ---- phases 5+6: gate/up then down (nested: gu activations stay
        # live while the gate/up weight buffers are freed) ----
        def phase_gu(wbuf, gu_ref):
            def gu_dma(slot, t, which):
                ref = wg_ref if which == 0 else wu_ref
                return pltpu.make_async_copy(
                    ref.at[:, pl.ds(t * TF, TF)], wbuf.at[slot, which],
                    wsem.at[slot * 2 + which],
                )

            gu_dma(0, 0, 0).start()
            gu_dma(0, 0, 1).start()
            h2 = h_ref[...]

            def gu_loop(t):
                slot = t % 2
                nxt = (t + 1) % 2

                if t + 1 < NFT:
                    gu_dma(nxt, t + 1, 0).start()
                    gu_dma(nxt, t + 1, 1).start()

                gu_dma(slot, t, 0).wait()
                gu_dma(slot, t, 1).wait()
                g = jax.lax.dot_general(
                    h2, wbuf[slot, 0], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * wgs_ref[0, pl.ds(t * TF, TF)][None, :]
                u = jax.lax.dot_general(
                    h2, wbuf[slot, 1], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * wus_ref[0, pl.ds(t * TF, TF)][None, :]
                if act_fn == "gelu_tanh":  # Gemma GeGLU
                    act = jax.nn.gelu(g, approximate=True)
                else:
                    act = g * jax.lax.logistic(g)
                gu_ref[:, pl.ds(t * TF, TF)] = (act * u).astype(jnp.bfloat16)

            for _t in range(NFT):
                gu_loop(_t)

            def phase_down(dbuf, acc_ref):
                def d_dma(slot, t):
                    return pltpu.make_async_copy(
                        wd_ref.at[pl.ds(t * TF, TF), :], dbuf.at[slot],
                        wsem.at[4 + slot],
                    )

                d_dma(0, 0).start()
                acc_ref[...] = jnp.zeros_like(acc_ref)

                def d_loop(t):
                    slot = t % 2
                    nxt = (t + 1) % 2

                    if t + 1 < NFT:
                        d_dma(nxt, t + 1).start()

                    d_dma(slot, t).wait()
                    acc_ref[...] += jax.lax.dot_general(
                        gu_ref[:, pl.ds(t * TF, TF)], dbuf[slot],
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )

                for _t in range(NFT):
                    d_loop(_t)
                mlp = acc_ref[...] * wds_ref[...]
                if post_norms:
                    pv = jnp.mean(mlp * mlp, axis=-1, keepdims=True)
                    mlp = (
                        (mlp * jax.lax.rsqrt(pv + eps)).astype(jnp.bfloat16)
                        * w1(mpost_ref, jnp.bfloat16)
                    ).astype(jnp.float32)
                xo_ref[...] = (
                    xo_ref[...].astype(jnp.float32) + mlp
                ).astype(xo_ref.dtype)

            pl.run_scoped(
                phase_down,
                dbuf=pltpu.VMEM((2, TF, d), jnp.int8),
                acc_ref=pltpu.VMEM((B, d), jnp.float32),
            )

        pl.run_scoped(
            phase_gu,
            wbuf=pltpu.VMEM((2, 2, d, TF), jnp.int8),
            gu_ref=pltpu.VMEM((B, F), jnp.bfloat16),
        )

    pl.run_scoped(
        body,
        h_ref=pltpu.VMEM((B, d), jnp.bfloat16),
        attn4_ref=pltpu.VMEM((B, KH, G, D), jnp.bfloat16),
        wsem=pltpu.SemaphoreType.DMA((6,)),
    )


def history_pcounts(
    start_pos: jnp.ndarray, block_size: int, table_width: int
) -> jnp.ndarray:
    """Per-row history page count for the decode megakernel's dynamic page
    loop, clamped to the table width so a row can never index past its
    table (the causal mask already hides any positions beyond it). Exposed
    so the per-step caller (models/llama.py forward_paged) derives it ONCE
    and shares it across all layers instead of recomputing per layer."""
    start32 = start_pos.astype(jnp.int32)
    return jnp.minimum((start32 + block_size - 1) // block_size, table_width)


def window_page_bounds(
    start_pos: jnp.ndarray, window, block_size: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(wlo, poff) for a sliding-window layer: ``wlo[b]`` is the first
    VISIBLE history key index (``max(0, pos − W + 1)``; 0 when the layer
    is full-attention) and ``poff[b] = wlo // BS`` its page — where each
    row's dynamic page loop STARTS, so a windowed row streams only pages
    holding in-window keys. The boundary page (``pos − W`` mid-page) is
    streamed and masked in-kernel via the same ``wlo``. ``window`` may be
    a TRACED scalar (0 = full) so one compiled program serves Gemma-3's
    local/global layer mix."""
    start32 = start_pos.astype(jnp.int32)
    w = jnp.asarray(window, jnp.int32)
    wlo = jnp.where(w > 0, jnp.maximum(start32 - w + 1, 0), 0)
    return wlo, wlo // block_size


def _fused_decoder_layer_impl(
    x: jnp.ndarray,  # [B, d] bf16 residual
    cos: jnp.ndarray,  # [B, D] f32 (already the layer's local/global table)
    sin: jnp.ndarray,  # [B, D] f32
    lp: Dict[str, Any],  # one layer's params (quantized tree)
    k_pool: jnp.ndarray,  # [NB, BS, KH, D] bf16
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, P] int32
    start_pos: jnp.ndarray,  # [B] int32
    *,
    eps: float,
    sm_scale: float,
    batch_block: int = 4,
    interpret: Optional[bool] = None,
    pcounts: Optional[jnp.ndarray] = None,  # [B] int32 (history_pcounts)
    window: Optional[jnp.ndarray] = None,  # scalar int32 (0/None = full)
    act_fn: str = "silu",
    unit_offset: bool = False,
    softcap: float = 0.0,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Run one fused decoder layer. Returns (x_out [B, d], k_new [B, KH, D],
    v_new [B, KH, D]); the caller scatters k_new/v_new into the pools
    (ops/attention.write_chunk_to_cache) AFTER the call — the kernel
    attends to history pages plus the in-register current token. Rows
    whose history is shorter than the table width skip their dead pages
    via the scalar-prefetched per-row page counts (``pcounts``, derived
    per step via :func:`history_pcounts` when not supplied); the table
    width P may be anything (one compiled program per distinct P — callers
    should bucket widths, see engines/tpu/engine.py::table_width_bucket).

    Epilogue knobs: ``window`` is a TRACED scalar (windowed and global
    layers of one model share a compiled program; per-row live page
    bounds are derived here via :func:`window_page_bounds` and ride the
    SMEM scalar-prefetch path like ``pcounts``); the presence of q/k
    norm weights, qkv biases and post-norm weights in ``lp`` selects the
    matching in-kernel epilogues; ``act_fn``/``unit_offset``/``softcap``
    are static switches (one compiled variant per model family, not per
    layer)."""
    if interpret is None:
        # CPU (tests, dryruns): Mosaic doesn't lower there — emulate.
        interpret = jax.default_backend() != "tpu"
    B, d = x.shape
    NB, BS, KH, D = k_pool.shape
    HD = lp["wq"]["q8"].shape[1]
    F = lp["w_gate"]["q8"].shape[1]
    H = HD // D
    P = block_tables.shape[1]
    BQ = batch_block
    assert B % BQ == 0, (B, BQ)

    KHD = KH * D
    tiles = _tiles_for(d, HD, KHD, F, D)  # same derivation supports() gates
    assert tiles is not None, (d, HD, KHD, F, D)
    TQ, TO, TF = tiles

    qk_norm = "q_norm" in lp
    qkv_bias = "bq" in lp
    post_norms = "attn_post_norm" in lp

    kernel = functools.partial(
        _fused_layer_kernel,
        eps=eps, sm_scale=sm_scale,
        B=B, d=d, H=H, KH=KH, D=D, F=F, P=P, BS=BS,
        TQ=TQ, TO=TO, TF=TF, BQ=BQ,
        qk_norm=qk_norm, qkv_bias=qkv_bias, post_norms=post_norms,
        act_fn=act_fn, softcap=float(softcap), unit_offset=unit_offset,
    )
    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)  # noqa: E731
    vmem = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)  # noqa: E731
    hbm = lambda: pl.BlockSpec(memory_space=pl.ANY)  # noqa: E731

    two_d = lambda a: a.reshape(1, -1)  # noqa: E731 — Mosaic wants >=2D

    start32 = start_pos.astype(jnp.int32)
    # Per-row history page count: the scalar-prefetch operand that bounds
    # the kernel's dynamic page loop and gates every page DMA per row.
    if pcounts is None:
        pcounts = history_pcounts(start32, BS, P)
    pcounts = pcounts.astype(jnp.int32)
    # Sliding-window live range: first visible key + its page, per row
    # (zeros when the layer has no window — the full-attention case).
    if window is None:
        wlo = jnp.zeros_like(start32)
        poff = jnp.zeros_like(start32)
    else:
        wlo, poff = window_page_bounds(start32, window, BS)

    extra_vmem = []
    if qk_norm:
        extra_vmem += [two_d(lp["q_norm"]), two_d(lp["k_norm"])]
    if qkv_bias:
        extra_vmem += [two_d(lp["bq"]), two_d(lp["bk"]), two_d(lp["bv"])]
    if post_norms:
        extra_vmem += [
            two_d(lp["attn_post_norm"]), two_d(lp["mlp_post_norm"]),
        ]

    out = pl.pallas_call(
        kernel,
        in_specs=(
            [smem()] * 5
            + [vmem()] * (12 + len(extra_vmem))
            + [hbm()] * 9
        ),
        out_specs=(vmem(), vmem(), vmem()),
        out_shape=(
            jax.ShapeDtypeStruct((B, d), x.dtype),
            jax.ShapeDtypeStruct((B, KH, D), x.dtype),
            jax.ShapeDtypeStruct((B, KH, D), x.dtype),
        ),
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        start32,
        pcounts,
        wlo.astype(jnp.int32),
        poff.astype(jnp.int32),
        x, cos.astype(jnp.float32), sin.astype(jnp.float32),
        two_d(lp["attn_norm"]), two_d(lp["mlp_norm"]),
        *extra_vmem,
        two_d(lp["wq"]["s"]), two_d(lp["wk"]["s"]), two_d(lp["wv"]["s"]),
        two_d(lp["wo"]["s"]),
        two_d(lp["w_gate"]["s"]), two_d(lp["w_up"]["s"]),
        two_d(lp["w_down"]["s"]),
        lp["wq"]["q8"], lp["wk"]["q8"], lp["wv"]["q8"], lp["wo"]["q8"],
        lp["w_gate"]["q8"], lp["w_up"]["q8"], lp["w_down"]["q8"],
        k_pool, v_pool,
    )
    return out


# Jitted + watched program object (DYN001): the megakernel's signature
# count tracks (pow2 table-width bucket × variant) — exactly what the
# runner budgets via set_budget, and what a per-request width leak would
# blow through (the recompile-storm signal the runtime detector pages on).
from dynamo_tpu.runtime.device_observe import watched_jit  # noqa: E402

fused_decoder_layer = watched_jit(
    "pallas.fused_decoder_layer",
    functools.partial(
        jax.jit,
        static_argnames=(
            "eps", "sm_scale", "batch_block", "interpret",
            "act_fn", "unit_offset", "softcap",
        ),
    )(_fused_decoder_layer_impl),
)
