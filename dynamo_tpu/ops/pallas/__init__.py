"""Pallas TPU kernels for the hot serving ops.

The TPU-native replacement for the CUDA kernels the reference ships in its
engines (and its one in-tree kernel, lib/llm/src/kernels/block_copy.cu):

  - paged_attention:  flash-style attention over the paged KV pool, pages
    streamed HBM->VMEM by the pallas pipeline via scalar-prefetched block
    tables (no dense gather materialized in HBM, unlike the XLA oracle path).
  - block_copy:       batched gather/scatter of KV blocks between the pool
    and staging buffers (disagg export/import, tier offload).
"""

from dynamo_tpu.ops.pallas.paged_attention import paged_attention_kernel

__all__ = ["paged_attention_kernel"]
