"""Pallas TPU paged-attention kernel.

The performance-critical op of the native engine: attention of a C-token
query chunk against a paged KV cache, serving decode (C=1), chunked prefill,
and full prefill uniformly (same contract as ops/attention.py's XLA oracle).

Reference parity: plays the role of the paged-attention CUDA kernels inside
the reference's engines (vLLM/TRT-LLM) that Dynamo orchestrates around; the
reference's own in-tree kernel is lib/llm/src/kernels/block_copy.cu (block
movement), covered here by ops/pallas/block_copy.py.

TPU-first design (not a CUDA translation):
  - The grid is (batch, page-group). The per-sequence block table is a
    scalar-prefetch operand; each grid step DMAs ``pages_per_step`` K/V
    pages selected by BlockSpec index_maps reading the table, so the pallas
    pipeline double-buffers the scattered HBM→VMEM page streams
    automatically — pages never materialize as a dense [B, T, KH, D] gather
    in HBM (the XLA oracle's O(padded-context) HBM-traffic problem).
  - Multiple pages per grid step matter on TPU: the grid is sequential, so
    per-iteration overhead × (B × P) dominated decode at large batch; the
    in-kernel concat builds one [S·bs, D] key block per head and runs ONE
    MXU dot per head per step instead of S skinny ones.
  - Each page DMA carries ALL kv heads (one [bs, KH, D] transfer — Mosaic
    wants the last two block dims full anyway); the small static KH loop is
    unrolled in the kernel body.
  - Flash-style online softmax: running max / normalizer / weighted
    accumulator live in VMEM scratch across the page-group axis (the
    innermost, sequentially-iterated grid dimension); the output block is
    written once on the last step.
  - Page groups wholly past a sequence's valid length skip all compute via
    pl.when; partially-valid groups are handled by the causal mask.
  - All dots run on the MXU in float32 via preferred_element_type; the cache
    stays bfloat16 in HBM.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _kernel(
    # scalar prefetch
    block_tables_ref,  # [B, P_pad] int32 (SMEM)
    start_pos_ref,  # [B] int32
    chunk_lens_ref,  # [B] int32
    window_ref,  # [1] int32 — sliding window (0 = full attention)
    # VMEM blocks: q, then S (k, v) page pairs — int8 caches interleave a
    # [1, KH, bs] scale ref after each page ref (k, ks, v, vs)
    q_ref,  # [1, KH, C*G, D] (host pre-transposed: rows are (c, g), c-major)
    *refs,  # pages..., o_ref, m, l, acc
    sm_scale: float,
    block_size: int,
    n_groups: int,
    pages_per_step: int,
    logit_cap: float = 0.0,
    quantized: bool = False,
):
    S = pages_per_step
    stride = 4 if quantized else 2
    kv_refs = refs[: stride * S]
    o_ref = refs[stride * S]
    m_ref, l_ref, acc_ref = refs[stride * S + 1 :]

    b = pl.program_id(0)
    p = pl.program_id(1)
    num_steps = pl.num_programs(1)

    KH = q_ref.shape[1]
    CG = q_ref.shape[2]
    G = n_groups
    W = S * block_size  # keys visited per grid step

    start = start_pos_ref[b]
    clen = chunk_lens_ref[b]

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Highest key position any valid query in this sequence can see is
    # start + clen - 1 (the chunk's own K/V are already in the cache).
    last_needed_page = jnp.maximum(start + clen - 1, 0) // block_size
    win = window_ref[0]
    # With a window, the EARLIEST key any query (offset 0) can see is
    # start - win + 1; earlier page groups skip entirely.
    first_needed_group = jnp.where(
        win > 0, jnp.maximum(start - win + 1, 0) // block_size // S, 0
    )

    @pl.when((p >= first_needed_group) & (p * S <= last_needed_page))
    def _compute():
        # Causal mask across the whole page group, shared by every head:
        # key position t visible to query offset c iff t <= start + c.
        # Rows are (c, g) pairs, c-major.
        c_idx = jax.lax.broadcasted_iota(jnp.int32, (CG, W), 0) // G
        t_idx = p * W + jax.lax.broadcasted_iota(jnp.int32, (CG, W), 1)
        visible = t_idx <= start + c_idx
        visible = visible & ((win <= 0) | (t_idx > start + c_idx - win))

        for h in range(KH):  # static unroll; KH is small (2-8)
            q = q_ref[0, h].astype(jnp.float32)  # [CG, D]
            st = stride
            k = jnp.concatenate(
                [kv_refs[st * s][0, :, h, :] for s in range(S)], axis=0
            ).astype(jnp.float32)  # [W, D]
            v = jnp.concatenate(
                [kv_refs[st * s + st // 2][0, :, h, :] for s in range(S)],
                axis=0,
            ).astype(jnp.float32)  # [W, D]
            if quantized:
                # Per-token scales ride the score/prob rows instead of
                # touching the [W, D] pages (ops/kv_quant.py layout).
                ks = jnp.concatenate(
                    [kv_refs[st * s + 1][0, h][None, :] for s in range(S)],
                    axis=1,
                )  # [1, W]
                vs = jnp.concatenate(
                    [kv_refs[st * s + 3][0, h][None, :] for s in range(S)],
                    axis=1,
                )  # [1, W]

            s_mat = (
                jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * sm_scale
            )  # [CG, W]
            if quantized:
                s_mat = s_mat * ks
            if logit_cap > 0.0:
                s_mat = logit_cap * jnp.tanh(s_mat / logit_cap)
            s_mat = jnp.where(visible, s_mat, NEG_INF)

            m_prev = m_ref[h]
            m_new = jnp.maximum(m_prev, jnp.max(s_mat, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            probs = jnp.exp(s_mat - m_new)
            l_ref[h] = l_ref[h] * alpha + jnp.sum(probs, axis=-1, keepdims=True)
            if quantized:
                probs = probs * vs
            acc_ref[h] = acc_ref[h] * alpha + jax.lax.dot_general(
                probs, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_ref[h] = m_new

    @pl.when(p == num_steps - 1)
    def _finalize():
        # Every query row sees at least key t=0 (0 <= start + c always), so
        # l is strictly positive for rows that matter.
        for h in range(KH):
            out = acc_ref[h] / jnp.maximum(l_ref[h], 1e-30)
            o_ref[0, h] = out.astype(o_ref.dtype)


def _decode_kernel(
    # scalar prefetch
    block_tables_ref,  # [B, P] int32 (SMEM)
    start_pos_ref,  # [B] int32
    window_ref,  # [1] int32 — sliding window (0 = full attention)
    # VMEM blocks: q [BQ, KH, C*G, D], then BQ (k, v) page pairs — int8
    # caches interleave a [1, KH, bs] scale ref after each page ref
    q_ref,
    *refs,  # pages..., o_ref, m, l, acc
    sm_scale: float,
    block_size: int,
    batch_block: int,
    n_groups: int,
    logit_cap: float = 0.0,
    quantized: bool = False,
):
    """Batch-blocked kernel for decode (C=1) and SHORT chunks (C ≤ 8, the
    speculative-verify shape): the grid is (B/BQ, pages) and each
    sequential grid step visits ONE page of BQ different sequences. The
    generic kernel's (B, pages) grid ran B×P tiny steps whose per-iteration
    overhead dominated (measured ~10µs/step ≫ the 0.5µs of compute);
    batch-blocking amortizes it BQ-fold while every page DMA stays a single
    contiguous [bs, KH, D] transfer. Int8 caches halve both the DMA bytes
    and the per-page VMEM, which doubles the default batch_block (8 → 16)
    inside the same scoped-VMEM budget.

    Query rows per (j, h) are (c, g) pairs, c-major; causality masks key t
    visible to row (c, g) iff t <= start_j + c (the chunk's own K/V are
    already in the cache, as in the generic kernel)."""
    BQ = batch_block
    stride = 4 if quantized else 2
    kv_refs = refs[: stride * BQ]
    o_ref = refs[stride * BQ]
    m_ref, l_ref, acc_ref = refs[stride * BQ + 1 :]

    bb = pl.program_id(0)
    p = pl.program_id(1)
    num_steps = pl.num_programs(1)
    KH = q_ref.shape[1]
    CG = q_ref.shape[2]
    G = n_groups
    C = CG // G

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    win = window_ref[0]
    for j in range(BQ):  # static unroll over the sequence block
        start = start_pos_ref[bb * BQ + j]
        # Highest key any row can see: start + C - 1 (last chunk row).
        last_needed_page = (start + C - 1) // block_size
        # With a sliding window, pages wholly before start-win+1 skip both
        # their compute AND never affect the causal/window mask.
        first_needed_page = jnp.where(
            win > 0, jnp.maximum(start - win + 1, 0) // block_size, 0
        )

        @pl.when((p >= first_needed_page) & (p <= last_needed_page))
        def _compute(j=j, start=start):
            if C == 1:
                # decode fast path: one shared [1, bs] mask row (broadcast)
                t_idx = p * block_size + jax.lax.broadcasted_iota(
                    jnp.int32, (1, block_size), 1
                )
                limit = start
            else:
                t_idx = p * block_size + jax.lax.broadcasted_iota(
                    jnp.int32, (CG, block_size), 1
                )
                c_idx = jax.lax.broadcasted_iota(
                    jnp.int32, (CG, block_size), 0
                ) // G
                limit = start + c_idx
            visible = t_idx <= limit
            visible = visible & ((win <= 0) | (t_idx > limit - win))
            for h in range(KH):
                q = q_ref[j, h].astype(jnp.float32)  # [CG, D]
                k = kv_refs[stride * j][0, :, h, :].astype(jnp.float32)
                v = kv_refs[stride * j + stride // 2][0, :, h, :].astype(
                    jnp.float32
                )
                s_mat = (
                    jax.lax.dot_general(
                        q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                    * sm_scale
                )  # [G, bs]
                if quantized:
                    s_mat = s_mat * kv_refs[stride * j + 1][0, h][None, :]
                if logit_cap > 0.0:
                    s_mat = logit_cap * jnp.tanh(s_mat / logit_cap)
                s_mat = jnp.where(visible, s_mat, NEG_INF)
                m_prev = m_ref[j, h]
                m_new = jnp.maximum(
                    m_prev, jnp.max(s_mat, axis=-1, keepdims=True)
                )
                alpha = jnp.exp(m_prev - m_new)
                probs = jnp.exp(s_mat - m_new)
                l_ref[j, h] = l_ref[j, h] * alpha + jnp.sum(
                    probs, axis=-1, keepdims=True
                )
                if quantized:
                    probs = probs * kv_refs[stride * j + 3][0, h][None, :]
                acc_ref[j, h] = acc_ref[j, h] * alpha + jax.lax.dot_general(
                    probs, v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                m_ref[j, h] = m_new

    @pl.when(p == num_steps - 1)
    def _finalize():
        for j in range(BQ):
            for h in range(KH):
                out = acc_ref[j, h] / jnp.maximum(l_ref[j, h], 1e-30)
                o_ref[j, h] = out.astype(o_ref.dtype)


def _paged_attention_decode_kernel_impl(
    q: jnp.ndarray,  # [B, 1, n_heads, head_dim]
    k_cache,  # [num_blocks, block_size, KH, D] — or {"q8", "s"} int8 pool
    v_cache,
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    start_pos: jnp.ndarray,  # [B] int32
    window=0,  # sliding window (int or traced scalar); 0 = full
    *,
    sm_scale: Optional[float] = None,
    interpret: bool = False,
    batch_block: Optional[int] = None,
    logit_cap: float = 0.0,
) -> jnp.ndarray:
    """Decode-path (C=1) batch-blocked kernel. Same contract as the XLA
    oracle at C=1; B is padded to a multiple of ``batch_block`` (padded
    rows read page 0 at position 0 — one valid key, discarded output).
    With a sliding ``window``, page-group steps wholly before the window
    skip their compute (long-context decode on windowed layers gets
    cheaper, the SWA point). Int8 pools (ops/kv_quant.py) stream half the
    bytes and default to batch_block 16."""
    from dynamo_tpu.ops.kv_quant import is_quantized_pool

    quantized = is_quantized_pool(k_cache)
    B, C, n_heads, head_dim = q.shape
    assert C <= 8, "batch-blocked kernel serves decode / short-chunk steps"
    k_values = k_cache["q8"] if quantized else k_cache
    _, block_size, n_kv_heads, _ = k_values.shape
    G = n_heads // n_kv_heads
    scale = sm_scale if sm_scale is not None else head_dim**-0.5
    if batch_block is None:
        from dynamo_tpu import config

        env_bq = config.DECODE_BQ.get()
        if env_bq > 0:
            batch_block = env_bq
        else:
            # Measured on v5e: BQ bounded by the ~16 MB scoped VMEM the
            # per-j double-buffered page pairs occupy; int8 pages are half
            # the size. DYN_TPU_DECODE_BQ overrides for shape tuning.
            batch_block = 16 if quantized else 8
    # C>1 multiplies the q block and all three scratches by C: shrink BQ
    # so the VMEM footprint stays at the C=1 budget.
    batch_block = max(1, batch_block // C)
    BQ = max(min(batch_block, B), 1)

    B_pad = ((B + BQ - 1) // BQ) * BQ
    if B_pad != B:
        q = jnp.pad(q, ((0, B_pad - B), (0, 0), (0, 0), (0, 0)))
        block_tables = jnp.pad(block_tables, ((0, B_pad - B), (0, 0)))
        start_pos = jnp.pad(start_pos, (0, B_pad - B))

    # [B, C, H, D] → [B, KH, C*G, D]; rows (c, g) c-major, as the kernel's
    # causal mask expects.
    q4 = (
        q.reshape(B_pad, C, n_kv_heads, G, head_dim)
        .transpose(0, 2, 1, 3, 4)
        .reshape(B_pad, n_kv_heads, C * G, head_dim)
    )
    CG = C * G
    P = block_tables.shape[1]
    win = jnp.asarray(window, jnp.int32).reshape(1)

    def q_map(bb, p, bt, sp, w):
        return (bb, 0, 0, 0)

    def kv_map_for(j):
        def kv_map(bb, p, bt, sp, w):
            return (bt[bb * BQ + j, p], 0, 0, 0)

        return kv_map

    def s_map_for(j):
        def s_map(bb, p, bt, sp, w):
            return (bt[bb * BQ + j, p], 0, 0)

        return s_map

    in_specs = [pl.BlockSpec((BQ, n_kv_heads, CG, head_dim), q_map)]
    kv_args = []
    for j in range(BQ):
        spec = pl.BlockSpec((1, block_size, n_kv_heads, head_dim), kv_map_for(j))
        if quantized:
            s_spec = pl.BlockSpec((1, n_kv_heads, block_size), s_map_for(j))
            in_specs.extend([spec, s_spec, spec, s_spec])
            kv_args.extend(
                [k_cache["q8"], k_cache["s"], v_cache["q8"], v_cache["s"]]
            )
        else:
            in_specs.extend([spec, spec])
            kv_args.extend([k_cache, v_cache])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B_pad // BQ, P),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((BQ, n_kv_heads, CG, head_dim), q_map),
        scratch_shapes=[
            pltpu.VMEM((BQ, n_kv_heads, CG, 1), jnp.float32),
            pltpu.VMEM((BQ, n_kv_heads, CG, 1), jnp.float32),
            pltpu.VMEM((BQ, n_kv_heads, CG, head_dim), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel, sm_scale=scale, block_size=block_size, batch_block=BQ,
        n_groups=G, logit_cap=logit_cap, quantized=quantized,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (B_pad, n_kv_heads, CG, head_dim), q.dtype
        ),
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        start_pos.astype(jnp.int32),
        win,
        q4,
        *kv_args,
    )
    out = (
        out[:B]
        .reshape(B, n_kv_heads, C, G, head_dim)
        .transpose(0, 2, 1, 3, 4)
    )
    return out.reshape(B, C, n_heads, head_dim)


def _paged_attention_kernel_impl(
    q: jnp.ndarray,  # [B, C, n_heads, head_dim]
    k_cache,  # [num_blocks, block_size, KH, D] — or {"q8", "s"} int8 pool
    v_cache,
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    start_pos: jnp.ndarray,  # [B] int32
    chunk_lens: jnp.ndarray,  # [B] int32
    window=0,  # sliding window (int or traced scalar); 0 = full
    *,
    sm_scale: Optional[float] = None,
    interpret: bool = False,
    # Measured on v5e: 1 page/step wins — Mosaic lowers the in-kernel concat
    # to VMEM copies that cost more than the per-iteration overhead saved.
    # The knob stays for future Mosaic versions / other topologies.
    pages_per_step: int = 1,
    logit_cap: float = 0.0,
) -> jnp.ndarray:
    """Returns [B, C, n_heads, head_dim]; same contract as the XLA oracle
    (ops/attention.py::_paged_attention_xla)."""
    from dynamo_tpu.ops.kv_quant import is_quantized_pool

    quantized = is_quantized_pool(k_cache)
    B, C, n_heads, head_dim = q.shape
    k_values = k_cache["q8"] if quantized else k_cache
    num_blocks, block_size, n_kv_heads, _ = k_values.shape
    P = block_tables.shape[1]
    G = n_heads // n_kv_heads
    scale = sm_scale if sm_scale is not None else head_dim**-0.5
    S = max(min(pages_per_step, P), 1)
    win = jnp.asarray(window, jnp.int32).reshape(1)

    # Pad the table width to a multiple of S; padded entries point at page 0
    # whose keys land beyond every sequence's causal limit (masked).
    P_pad = ((P + S - 1) // S) * S
    if P_pad != P:
        block_tables = jnp.pad(block_tables, ((0, 0), (0, P_pad - P)))

    # [B, C, H, D] -> [B, KH, C*G, D]: per-head row blocks, (c, g) c-major.
    # The transpose runs in XLA outside the kernel (fused, cheap) and lets
    # the kernel body index one head with zero in-kernel shape casts (Mosaic
    # rejects (C, G, D) -> (C*G, D) vector reshapes for C > 1).
    q5 = q.reshape(B, C, n_kv_heads, G, head_dim).transpose(0, 2, 1, 3, 4)
    q5 = q5.reshape(B, n_kv_heads, C * G, head_dim)

    def q_map(b, p, bt, sp, cl, w):
        return (b, 0, 0, 0)

    def kv_map_for(s):
        def kv_map(b, p, bt, sp, cl, w):
            return (bt[b, p * S + s], 0, 0, 0)

        return kv_map

    def s_map_for(s):
        def s_map(b, p, bt, sp, cl, w):
            return (bt[b, p * S + s], 0, 0)

        return s_map

    kv_spec = lambda s: pl.BlockSpec(  # noqa: E731
        (1, block_size, n_kv_heads, head_dim), kv_map_for(s)
    )
    in_specs = [pl.BlockSpec((1, n_kv_heads, C * G, head_dim), q_map)]
    kv_args = []
    for s in range(S):
        if quantized:
            sc_spec = pl.BlockSpec((1, n_kv_heads, block_size), s_map_for(s))
            in_specs.extend([kv_spec(s), sc_spec, kv_spec(s), sc_spec])
            kv_args.extend(
                [k_cache["q8"], k_cache["s"], v_cache["q8"], v_cache["s"]]
            )
        else:
            in_specs.extend([kv_spec(s), kv_spec(s)])
            kv_args.extend([k_cache, v_cache])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B, P_pad // S),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, n_kv_heads, C * G, head_dim), q_map),
        scratch_shapes=[
            pltpu.VMEM((n_kv_heads, C * G, 1), jnp.float32),
            pltpu.VMEM((n_kv_heads, C * G, 1), jnp.float32),
            pltpu.VMEM((n_kv_heads, C * G, head_dim), jnp.float32),
        ],
    )

    kernel = functools.partial(
        _kernel, sm_scale=scale, block_size=block_size, n_groups=G,
        pages_per_step=S, logit_cap=logit_cap, quantized=quantized,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (B, n_kv_heads, C * G, head_dim), q.dtype
        ),
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        start_pos.astype(jnp.int32),
        chunk_lens.astype(jnp.int32),
        win,
        q5,
        *kv_args,
    )
    out = out.reshape(B, n_kv_heads, C, G, head_dim).transpose(0, 2, 1, 3, 4)
    return out.reshape(B, C, n_heads, head_dim)


# Jitted + watched program objects (DYN001): decorator jits are invisible
# to /debug/compiles; wrapping the jitted impls here gives the pallas
# attention plane compile telemetry and a storm budget keyed on the pow2
# table-width buckets the runner dispatches.
from dynamo_tpu.runtime.device_observe import watched_jit  # noqa: E402

paged_attention_decode_kernel = watched_jit(
    "pallas.paged_attention_decode",
    functools.partial(
        jax.jit,
        static_argnames=("sm_scale", "interpret", "batch_block", "logit_cap"),
    )(_paged_attention_decode_kernel_impl),
)

paged_attention_kernel = watched_jit(
    "pallas.paged_attention",
    functools.partial(
        jax.jit,
        static_argnames=("sm_scale", "interpret", "pages_per_step", "logit_cap"),
    )(_paged_attention_kernel_impl),
)
