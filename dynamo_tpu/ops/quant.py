"""Int8 weight-only quantization ops (TPU-native).

Per-output-channel symmetric int8: each weight is stored as
``{"q8": int8 tensor, "s": float32 scale}`` where the scale is the absmax
over the *contracted* (input) axes divided by 127, kept with ``keepdims`` so
the pair shards under exactly the original weight's logical axes (the
contracted axis collapses to size 1 → trivially replicable).

The matmul itself stays on the MXU in the activation dtype: the int8
weight is upcast in-register (XLA fuses the convert into the dot's operand
read) and the per-output-channel scale multiplies the *result* — exact up to
weight rounding. The win is HBM: weight bytes halve vs bf16, which is the
whole game for bandwidth-bound decode, and an 8B-class model fits a single
16 GB v5e chip with room for KV.

Reference parity: the reference serves FP8/NVFP4 checkpoints through its
engines (ref: recipes/llama-3-70b/README.md:7-11 FP8 shapes,
docs/performance/tuning.md:50-57 NVFP4 capacity table); int8 weight-only
with XLA-fused dequant is the TPU-idiomatic equivalent deployment lever.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Union

import jax.numpy as jnp
import numpy as np

QTensor = Dict[str, jnp.ndarray]  # {"q8": int8, "s": float32 keepdims}
MaybeQ = Union[jnp.ndarray, QTensor]


def quantize_q8(w: Any, contract_axes: Sequence[int]) -> QTensor:
    """Symmetric per-output-channel int8 over the given contracted axes.

    Numpy input → numpy output (host-side quantization: checkpoint loading
    quantizes per-layer on the host so full-precision weights never touch
    HBM); jax input → jax output on the input's device.
    """
    if isinstance(w, np.ndarray):
        wf = np.asarray(w, dtype=np.float32)
        amax = np.max(np.abs(wf), axis=tuple(contract_axes), keepdims=True)
        s = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
        q = np.clip(np.rint(wf / s), -127, 127).astype(np.int8)
        return {"q8": q, "s": s}
    wf = jnp.asarray(w, dtype=jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=tuple(contract_axes), keepdims=True)
    s = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wf / s), -127, 127).astype(jnp.int8)
    return {"q8": q, "s": s.astype(jnp.float32)}


def is_q8(w: Any) -> bool:
    return isinstance(w, dict) and "q8" in w


def dequantize(w: MaybeQ, dtype: Any = jnp.float32) -> jnp.ndarray:
    if not is_q8(w):
        return jnp.asarray(w, dtype=dtype)
    return (w["q8"].astype(jnp.float32) * w["s"]).astype(dtype)


def qeinsum(spec: str, x: jnp.ndarray, w: MaybeQ) -> jnp.ndarray:
    """``jnp.einsum(spec, x, w)`` where ``w`` may be int8-quantized.

    Quantized path: einsum on the raw int8 codes upcast to ``x.dtype``
    (fused by XLA into the dot), then multiply by the per-output-channel
    scale broadcast into the output layout.
    """
    if not is_q8(w):
        return jnp.einsum(spec, x, w)
    lhs, out = spec.split("->")
    w_labels = lhs.split(",")[1]
    q, s = w["q8"], w["s"]
    contracted = [lbl for lbl in w_labels if lbl not in out]
    kept = [lbl for lbl in w_labels if lbl in out]
    # Scale broadcasting relies on w's kept labels appearing in the output
    # in the same relative order (true for every weight layout here).
    assert kept == [lbl for lbl in out if lbl in w_labels], (
        f"qeinsum: weight output labels reordered in {spec!r}"
    )
    y = jnp.einsum(spec, x, q.astype(x.dtype))
    # Build the scale's output-aligned shape: kept w dims, 1 elsewhere.
    sizes = {lbl: q.shape[i] for i, lbl in enumerate(w_labels)}
    s_kept = jnp.squeeze(
        s, axis=tuple(i for i, lbl in enumerate(w_labels) if lbl in contracted)
    )
    s_out = s_kept.reshape([sizes[lbl] if lbl in kept else 1 for lbl in out])
    return (y.astype(jnp.float32) * s_out).astype(y.dtype)


def embed_lookup(embed: MaybeQ, tokens: jnp.ndarray, dtype: Any) -> jnp.ndarray:
    """Embedding-table row gather; rows dequantized by their per-row scale."""
    if not is_q8(embed):
        return embed[tokens]
    rows = embed["q8"][tokens].astype(jnp.float32)  # [..., d]
    return (rows * embed["s"][tokens]).astype(dtype)  # s[tokens]: [..., 1]


def lm_head(x: jnp.ndarray, w: MaybeQ, *, tied: bool) -> jnp.ndarray:
    """Project hidden states to vocab logits.

    ``tied``: w is the embedding table [V, d] (scale per vocab row [V, 1]);
    otherwise w is lm_head [d, V] (scale [1, V]). Returns float32 logits
    with x's leading dims.
    """
    if not is_q8(w):
        h = w.T if tied else w
        return (x @ h).astype(jnp.float32)
    q, s = w["q8"], w["s"]
    if tied:
        y = x @ q.astype(x.dtype).T  # [..., V]
        return y.astype(jnp.float32) * s[:, 0]
    y = x @ q.astype(x.dtype)
    return y.astype(jnp.float32) * s[0]
