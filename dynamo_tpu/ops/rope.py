"""Rotary position embeddings (non-interleaved / HF "rotate_half" layout)."""

from __future__ import annotations

import jax.numpy as jnp


def rope_table(positions: jnp.ndarray, head_dim: int, theta: float,
               scale: float = 1.0):
    """cos/sin tables for integer positions.

    positions: [...], returns (cos, sin) each [..., head_dim].
    ``scale`` > 1 is HF linear rope_scaling (positions divided by factor —
    Gemma-3's global-rope long-context stretch).
    """
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = (
        positions.astype(jnp.float32)[..., None] / scale
    ) * freqs  # [..., half]
    cos = jnp.cos(angles)
    sin = jnp.sin(angles)
    # rotate_half layout: duplicate for both halves
    return (
        jnp.concatenate([cos, cos], axis=-1),
        jnp.concatenate([sin, sin], axis=-1),
    )


def _rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x: [..., n_heads, head_dim]; cos/sin: [..., head_dim] (broadcast over heads)."""
    cos = cos[..., None, :]
    sin = sin[..., None, :]
    out = x.astype(jnp.float32) * cos + _rotate_half(x.astype(jnp.float32)) * sin
    return out.astype(x.dtype)
