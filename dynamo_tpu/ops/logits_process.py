"""Jittable logits processors (penalties, bias, bans) for the native engine.

Reference parity: the reference exposes pluggable logits processing to
engines via its Python bindings (`dynamo.logits_processing` — see reference
lib/bindings/python, `src/dynamo/logits_processing`) and relies on the
engine's sampler (vLLM) for presence/frequency/repetition penalties and
logit bias. Here the engine IS native, so the processors are part of the
fused sampling step.

TPU-first design notes:
  - All processors are batched and gated by per-sequence parameters, so ONE
    compiled program serves a heterogeneous continuous batch: sequences
    that didn't ask for a processor carry neutral parameters (rep=1,
    pres=freq=0, empty bias) that make the transform an identity for their
    row. No per-request recompilation, no dynamic shapes.
  - Token bookkeeping ([B, V] output counts + prompt-membership mask) lives
    on device and is updated inside the decode scan; the engine only pays
    for it when some active request actually uses a penalty (the engine
    compiles a separate program variant, see engines/tpu/engine.py).
  - `logit_bias` is a fixed number of (token, bias) slots per row
    (MAX_BIAS_SLOTS), applied with a dropped-out-of-bounds scatter — static
    shapes, no host round trip. Banned tokens are just bias slots with
    BAN_BIAS.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.runtime.device_observe import watched_jit

# Matches the OpenAI API contract (300 logit_bias entries max), so the
# protocol-level validation and the engine capacity agree exactly.
MAX_BIAS_SLOTS = 300
BAN_BIAS = -1e9  # effectively -inf but safe in fp32 arithmetic


class ProcParams(NamedTuple):
    """Per-sequence processor parameters ([B]-shaped unless noted)."""

    rep: jnp.ndarray  # repetition penalty; 1.0 = off
    pres: jnp.ndarray  # presence penalty; 0.0 = off
    freq: jnp.ndarray  # frequency penalty; 0.0 = off
    bias_ids: jnp.ndarray  # [B, MAX_BIAS_SLOTS] int32; -1 = empty slot
    bias_vals: jnp.ndarray  # [B, MAX_BIAS_SLOTS] float32


class ProcState(NamedTuple):
    """Per-sequence device bookkeeping for penalties."""

    out_counts: jnp.ndarray  # [B, V] int32 — generated-token counts
    prompt_mask: jnp.ndarray  # [B, V] bool — token appears in the prompt


def neutral_params(batch: int) -> ProcParams:
    return ProcParams(
        rep=jnp.ones((batch,), jnp.float32),
        pres=jnp.zeros((batch,), jnp.float32),
        freq=jnp.zeros((batch,), jnp.float32),
        bias_ids=jnp.full((batch, MAX_BIAS_SLOTS), -1, jnp.int32),
        bias_vals=jnp.zeros((batch, MAX_BIAS_SLOTS), jnp.float32),
    )


def init_state(batch: int, vocab: int) -> ProcState:
    return ProcState(
        out_counts=jnp.zeros((batch, vocab), jnp.int32),
        prompt_mask=jnp.zeros((batch, vocab), jnp.bool_),
    )


def apply(
    logits: jnp.ndarray,  # [B, V] float
    params: ProcParams,
    state: Optional[ProcState],
) -> jnp.ndarray:
    """Apply penalties then bias. Neutral params → identity per row."""
    logits = logits.astype(jnp.float32)
    if state is not None:
        counts = state.out_counts.astype(jnp.float32)
        seen = (state.out_counts > 0) | state.prompt_mask
        # Repetition penalty (HF semantics: prompt ∪ output tokens).
        rp = params.rep[:, None]
        logits = jnp.where(
            seen,
            jnp.where(logits > 0, logits / rp, logits * rp),
            logits,
        )
        # OpenAI-style additive penalties (output tokens only).
        logits = logits - params.freq[:, None] * counts
        logits = logits - params.pres[:, None] * (state.out_counts > 0)
    return _add_bias(logits, params)


def apply_prompt_only(
    logits: jnp.ndarray,  # [B, V] float
    prompt_mask: jnp.ndarray,  # [B, V] bool
    params: ProcParams,
) -> jnp.ndarray:
    """Prefill-time variant: at the first sampled token no output tokens
    exist yet, so presence/frequency penalties are identically zero — only
    the repetition penalty (over the prompt) and the bias apply."""
    logits = logits.astype(jnp.float32)
    rp = params.rep[:, None]
    logits = jnp.where(
        prompt_mask,
        jnp.where(logits > 0, logits / rp, logits * rp),
        logits,
    )
    return _add_bias(logits, params)


def _add_bias(logits: jnp.ndarray, params: ProcParams) -> jnp.ndarray:
    # Sparse per-row logit bias; -1 slots fall outside [0, V) and are
    # dropped by the scatter.
    B = logits.shape[0]
    rows = jnp.arange(B, dtype=jnp.int32)[:, None]
    safe_vals = jnp.where(params.bias_ids >= 0, params.bias_vals, 0.0)
    return logits.at[rows, params.bias_ids].add(
        safe_vals, mode="drop", indices_are_sorted=False
    )


def record_tokens(
    state: ProcState,
    tokens: jnp.ndarray,  # [B] int32 just-sampled tokens
    active: jnp.ndarray,  # [B] int/bool — 1 where the row really generated
) -> ProcState:
    """Count one generated token per active row (inside the decode scan)."""
    B = tokens.shape[0]
    rows = jnp.arange(B, dtype=jnp.int32)
    counts = state.out_counts.at[rows, tokens].add(
        active.astype(jnp.int32), mode="drop"
    )
    return state._replace(out_counts=counts)


def _reset_row_impl(state: ProcState, slot: jnp.ndarray, hot: jnp.ndarray,
                    counts_row: jnp.ndarray):
    counts = state.out_counts.at[slot].set(counts_row)
    mask = state.prompt_mask.at[slot].set(hot)
    return ProcState(out_counts=counts, prompt_mask=mask)


_reset_row = watched_jit(
    "ops.proc_reset_row",
    functools.partial(jax.jit, donate_argnums=(0,))(_reset_row_impl),
)


def prompt_hot(tokens, vocab: int) -> np.ndarray:
    """[V] bool membership mask for a token list (ids clamped to vocab)."""
    hot = np.zeros((vocab,), dtype=np.bool_)
    toks = np.asarray(tokens, dtype=np.int64)
    toks = toks[(toks >= 0) & (toks < vocab)]
    hot[toks] = True
    return hot


def reset_slot(
    state: ProcState, slot: int, prompt_tokens, generated_tokens=()
) -> ProcState:
    """Host-side: initialize a slot's bookkeeping at admission.

    ``generated_tokens`` restores output-token counts for preempted
    sequences being re-admitted (recompute keeps their generation history —
    presence/frequency penalties must keep applying to it)."""
    vocab = state.prompt_mask.shape[1]
    hot = prompt_hot(prompt_tokens, vocab)
    gen = np.asarray(generated_tokens, dtype=np.int64)
    gen = gen[(gen >= 0) & (gen < vocab)]
    counts = np.bincount(gen, minlength=vocab).astype(np.int32)
    return _reset_row(state, jnp.int32(slot), jnp.asarray(hot), jnp.asarray(counts))


def _count_one_impl(state: ProcState, slot: jnp.ndarray, token: jnp.ndarray):
    counts = state.out_counts.at[slot, token].add(1, mode="drop")
    return state._replace(out_counts=counts)


_count_one = watched_jit(
    "ops.proc_count_one",
    functools.partial(jax.jit, donate_argnums=(0,))(_count_one_impl),
)


def count_token(state: ProcState, slot: int, token: int) -> ProcState:
    """Host-side: count a single generated token (the prefill-sampled one)."""
    return _count_one(state, jnp.int32(slot), jnp.int32(token))


def pack_bias(logit_bias, vocab: int):
    """OpenAI `logit_bias` dict → fixed (ids, vals) slot arrays (numpy).

    Entries beyond MAX_BIAS_SLOTS are dropped, most-extreme-bias first kept
    (bans and strong steering survive truncation).
    """
    ids = np.full((MAX_BIAS_SLOTS,), -1, dtype=np.int32)
    vals = np.zeros((MAX_BIAS_SLOTS,), dtype=np.float32)
    if not logit_bias:
        return ids, vals
    items = []
    for k, v in logit_bias.items():
        t = int(k)
        if 0 <= t < vocab:
            b = float(v)
            # OpenAI semantics: ±100 means ban/force; map to BAN_BIAS scale.
            if b <= -100.0:
                b = BAN_BIAS
            elif b >= 100.0:
                b = -BAN_BIAS
            items.append((t, b))
    items.sort(key=lambda tv: -abs(tv[1]))
    for i, (t, b) in enumerate(items[:MAX_BIAS_SLOTS]):
        ids[i] = t
        vals[i] = b
    return ids, vals
