"""Batched token sampling under jit.

Greedy / temperature / top-k / top-p with per-sequence parameters so one
compiled decode step serves a continuous batch of heterogeneous requests
(the reference delegates this to vLLM's sampler; here it is part of the
engine's fused decode step).

TPU note: a full-vocab argsort per step dominated decode time (~tens of ms
for 150k vocabs), so filtering happens inside the top-`SAMPLE_WIDTH` logits
via `lax.top_k` (O(V log W)). top-p truncates at SAMPLE_WIDTH candidates —
the standard accelerator-side approximation; requests asking for
top_k > SAMPLE_WIDTH are clamped.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30
SAMPLE_WIDTH = 64  # candidates considered by top-k/top-p filtering


def fold_row_keys(
    rng: jax.Array,  # single base PRNG key
    salts: jnp.ndarray,  # [B] int — per-sequence salt (admission order)
    positions: jnp.ndarray,  # [B] int — index of the token being sampled
) -> jax.Array:
    """Per-row sampling keys: fold (sequence salt, token index) into the
    engine's base key. This makes the sampling noise for a given token a
    pure function of (engine seed, sequence, position) — independent of
    dispatch count or batch composition — which is what lets the pipelined
    decode path (engines/tpu/engine.py) speculatively dispatch burst N+1
    before burst N's stop conditions are known, and lets preemption-by-
    recompute regenerate an identical continuation."""
    def one(s, p):
        return jax.random.fold_in(jax.random.fold_in(rng, s), p)

    return jax.vmap(one)(
        salts.astype(jnp.uint32), positions.astype(jnp.uint32)
    )


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] float
    rng: jax.Array,  # single PRNG key (ignored when row_keys given)
    temperature: jnp.ndarray,  # [B] float; <=0 means greedy
    top_k: jnp.ndarray,  # [B] int; <=0 means off
    top_p: jnp.ndarray,  # [B] float; >=1 means off
    min_p: jnp.ndarray = None,  # [B] float; <=0/None means off
    row_keys: jax.Array = None,  # [B] per-row keys (fold_row_keys)
) -> jnp.ndarray:
    """Returns sampled token ids [B]. Fully vectorized, static shapes.

    With ``row_keys``, each row draws its gumbel noise from its own key so
    the sample depends only on that row's (key, logits, params) — batch
    layout and the other rows' state cannot perturb it."""
    B, V = logits.shape
    W = min(SAMPLE_WIDTH, V)

    # Top-k FIRST, on the raw (bf16) logits: per-row division by a positive
    # temperature preserves order, so the candidate set is identical — and
    # skipping the full-vocab f32 materialization saves two [B, V] HBM
    # passes per step (the sampler was ~35% of decode-step time at B=256).
    if jax.default_backend() == "tpu":
        # approx_max_k maps onto the TPU's segmented-reduce hardware path;
        # exact top_k lowers to a full sort network (measurably slower at
        # 150k vocab). recall_target keeps it effectively exact for the
        # head of the distribution that sampling actually uses.
        raw_top, top_idx = jax.lax.approx_max_k(logits, W, recall_target=0.99)
        order = jnp.argsort(-raw_top, axis=-1)  # approx op is unsorted
        raw_top = jnp.take_along_axis(raw_top, order, axis=-1)
        top_idx = jnp.take_along_axis(top_idx, order, axis=-1)
    else:
        raw_top, top_idx = jax.lax.top_k(logits, W)  # [B, W] descending

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    top_logits = raw_top.astype(jnp.float32) / temp  # [B, W] — cheap in W

    ranks = jax.lax.broadcasted_iota(jnp.int32, (B, W), 1)
    k = jnp.where(top_k > 0, jnp.minimum(top_k, W), W)[:, None]
    keep_k = ranks < k

    probs = jax.nn.softmax(top_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Keep tokens while the cumulative mass *before* them is < top_p
    # (always keeps the first token).
    keep_p = (cum - probs) < jnp.clip(top_p, 0.0, 1.0)[:, None]

    keep = keep_k & keep_p
    if min_p is not None:
        # min-p: drop candidates with prob < min_p × max-prob. probs is
        # descending, so column 0 is the max. Neutral at min_p <= 0.
        keep_mp = probs >= jnp.clip(min_p, 0.0, 1.0)[:, None] * probs[:, :1]
        keep = keep & keep_mp
    masked = jnp.where(keep, top_logits, NEG_INF)
    if row_keys is not None:
        gumbel = jax.vmap(
            lambda k: jax.random.gumbel(k, (W,), dtype=jnp.float32)
        )(row_keys)
    else:
        gumbel = jax.random.gumbel(rng, (B, W), dtype=jnp.float32)
    choice_rank = jnp.argmax(masked + gumbel, axis=-1)  # [B]
    sampled = jnp.take_along_axis(top_idx, choice_rank[:, None], axis=-1)[:, 0]

    greedy = top_idx[:, 0]  # top-1 of the scaled logits == argmax of logits
    return jnp.where(temperature <= 0.0, greedy, sampled)


def compute_logprobs(
    logits: jnp.ndarray,  # [B, V]
    token_ids: jnp.ndarray,  # [B]
) -> jnp.ndarray:
    """Log-probability of the chosen tokens (for logprobs=N support)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(logp, token_ids[:, None], axis=-1)[:, 0]


def top_logprobs(
    logits: jnp.ndarray,  # [B, V]
    n: int,
) -> tuple:
    """Top-n (logprob, token_id) per row for OpenAI top_logprobs support.
    Returns ([B, n] float32 logprobs, [B, n] int32 ids), descending."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    vals, ids = jax.lax.top_k(logp, n)
    return vals, ids.astype(jnp.int32)


def spec_verify_sample(
    logits: jnp.ndarray,  # [B, C, V] — position i decides token i+1
    proposals: jnp.ndarray,  # [B, C-1] int32 draft tokens (one-hot draft q)
    prop_len: jnp.ndarray,  # [B] int32 — valid proposal count per row
    rng: jax.Array,
    temperature: jnp.ndarray,  # [B]; <=0 greedy
    top_k: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B]
):
    """Speculative verify with REJECTION SAMPLING (Leviathan/Chen): exact
    target-distribution sampling for sampled requests, greedy verify as the
    temperature<=0 special case — one program serves mixed ticks.

    The prompt-lookup draft is deterministic (one-hot q), so acceptance of
    proposal x at a position with filtered target distribution p is
    u < p(x), and a rejection replaces it with a sample from p with x
    zeroed and renormalized — exactly max(p − q, 0) normalized. Filtering
    (temperature/top-k/top-p inside the top-W candidates) matches
    sample_tokens, so spec and non-spec paths draw from the same target.

    Returns (emitted [B, C] int32, counts [B] int32): row b's first
    counts[b] entries are the accepted prefix plus the final corrected (or
    bonus) token.
    """
    B, C, V = logits.shape
    W = min(SAMPLE_WIDTH, V)
    N = B * C
    flat = logits.reshape(N, V)

    if jax.default_backend() == "tpu":
        raw_top, top_idx = jax.lax.approx_max_k(flat, W, recall_target=0.99)
        order = jnp.argsort(-raw_top, axis=-1)
        raw_top = jnp.take_along_axis(raw_top, order, axis=-1)
        top_idx = jnp.take_along_axis(top_idx, order, axis=-1)
    else:
        raw_top, top_idx = jax.lax.top_k(flat, W)

    rep = lambda a: jnp.repeat(a, C, axis=0)  # noqa: E731 — [B] → [N]
    temp = jnp.maximum(rep(temperature), 1e-6)[:, None]
    top_logits = raw_top.astype(jnp.float32) / temp

    ranks = jax.lax.broadcasted_iota(jnp.int32, (N, W), 1)
    k = jnp.where(rep(top_k) > 0, jnp.minimum(rep(top_k), W), W)[:, None]
    keep_k = ranks < k
    probs = jax.nn.softmax(top_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_p = (cum - probs) < jnp.clip(rep(top_p), 0.0, 1.0)[:, None]
    keep = keep_k & keep_p
    masked = jnp.where(keep, top_logits, NEG_INF)

    # draft token per position: proposals shifted onto logit positions
    prop_pos = jnp.concatenate(
        [proposals, jnp.zeros((B, 1), jnp.int32)], axis=1
    ).reshape(N)  # position i's draft (garbage past prop_len, masked later)
    match = top_idx == prop_pos[:, None]  # [N, W]
    pr = jax.nn.softmax(masked, axis=-1)  # renormalized filtered target
    p_prop = jnp.sum(jnp.where(match & keep, pr, 0.0), axis=-1)  # [N]

    rng_u, rng_g = jax.random.split(rng)
    u = jax.random.uniform(rng_u, (N,), dtype=jnp.float32)
    gumbel = jax.random.gumbel(rng_g, (N, W), dtype=jnp.float32)

    greedy = rep(temperature) <= 0.0
    argmax_tok = top_idx[:, 0]
    accept = jnp.where(greedy, prop_pos == argmax_tok, u < p_prop)

    # plain sample (bonus position) + rejection sample (proposal excluded)
    choice = jnp.argmax(masked + gumbel, axis=-1)
    sample = jnp.take_along_axis(top_idx, choice[:, None], axis=-1)[:, 0]
    masked_excl = jnp.where(match, NEG_INF, masked)
    choice_r = jnp.argmax(masked_excl + gumbel, axis=-1)
    resample = jnp.take_along_axis(top_idx, choice_r[:, None], axis=-1)[:, 0]
    sample = jnp.where(greedy, argmax_tok, sample)
    resample = jnp.where(greedy, argmax_tok, resample)

    accept = accept.reshape(B, C)
    sample = sample.reshape(B, C)
    resample = resample.reshape(B, C)

    pl_ = jnp.maximum(prop_len, 0)[:, None]  # [B, 1]
    pos = jax.lax.broadcasted_iota(jnp.int32, (B, C), 1)
    acc_run = jnp.cumprod(
        jnp.where(pos < pl_, accept, False).astype(jnp.int32), axis=1
    )
    n_acc = jnp.sum(acc_run, axis=1)  # [B] accepted proposal count

    gather1 = lambda a, i: jnp.take_along_axis(  # noqa: E731
        a, i[:, None], axis=1
    )[:, 0]
    rejected = n_acc < pl_[:, 0]
    final = jnp.where(
        rejected, gather1(resample, n_acc), gather1(sample, n_acc)
    )

    props_padded = jnp.concatenate(
        [proposals, jnp.zeros((B, 1), jnp.int32)], axis=1
    )
    emitted = jnp.where(
        pos < n_acc[:, None],
        props_padded,
        jnp.where(pos == n_acc[:, None], final[:, None], 0),
    )
    counts = n_acc + 1
    return emitted, counts
