"""Batched token sampling under jit.

Greedy / temperature / top-k / top-p with per-sequence parameters so one
compiled decode step serves a continuous batch of heterogeneous requests
(the reference delegates this to vLLM's sampler; here it is part of the
engine's fused decode step).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] float
    rng: jax.Array,  # single PRNG key
    temperature: jnp.ndarray,  # [B] float; <=0 means greedy
    top_k: jnp.ndarray,  # [B] int; <=0 means off
    top_p: jnp.ndarray,  # [B] float; >=1 means off
) -> jnp.ndarray:
    """Returns sampled token ids [B]. Fully vectorized, no data-dependent
    shapes: filters are applied as masks over the sorted vocab."""
    B, V = logits.shape
    logits = logits.astype(jnp.float32)

    greedy = jnp.argmax(logits, axis=-1)

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / temp

    # Sort once (descending); apply top-k and top-p masks in sorted space.
    sort_idx = jnp.argsort(-scaled, axis=-1)  # [B, V]
    sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)

    ranks = jax.lax.broadcasted_iota(jnp.int32, (B, V), 1)
    k = jnp.where(top_k > 0, top_k, V)[:, None]
    keep_k = ranks < k

    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Keep tokens while the cumulative mass *before* them is < top_p
    # (always keeps the first token).
    keep_p = (cum - probs) < jnp.clip(top_p, 0.0, 1.0)[:, None]

    keep = keep_k & keep_p
    masked = jnp.where(keep, sorted_logits, NEG_INF)
    gumbel = jax.random.gumbel(rng, (B, V), dtype=jnp.float32)
    choice_rank = jnp.argmax(masked + gumbel, axis=-1)  # [B]
    sampled = jnp.take_along_axis(sort_idx, choice_rank[:, None], axis=-1)[:, 0]

    return jnp.where(temperature <= 0.0, greedy, sampled)


def compute_logprobs(
    logits: jnp.ndarray,  # [B, V]
    token_ids: jnp.ndarray,  # [B]
) -> jnp.ndarray:
    """Log-probability of the chosen tokens (for logprobs=N support)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(logp, token_ids[:, None], axis=-1)[:, 0]
