"""Batched multi-LoRA application under jit.

The CUDA world does this with punica/SGMV kernels (grouped GEMM over
per-request adapters); the TPU-native formulation is a gather + two batched
einsums, which XLA fuses and tiles onto the MXU: every sequence in the
continuous batch carries an adapter index (0 = no adapter, zero weights),
adapters live stacked on a leading axis, and one compiled step serves any
mix of adapters. Scaling (alpha/r) is pre-folded into B at stack time
(lora/loader.py), so the hot path is exactly two einsums per target.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax.numpy as jnp

# per-layer stacked adapter weights for one target module:
#   A: [N_adapters+1, d_in, r],  B: [N_adapters+1, r, d_out]
LoraLayer = Dict[str, Tuple[jnp.ndarray, jnp.ndarray]]


def apply_lora(
    x: jnp.ndarray,  # [B, C, d_in] (normed layer input / attn output)
    ab: Tuple[jnp.ndarray, jnp.ndarray],
    adapter_ids: jnp.ndarray,  # [B] int32, 0 = none
) -> jnp.ndarray:
    """x @ A[ids] @ B[ids] — the low-rank delta, [B, C, d_out]."""
    A, B = ab
    Ax = jnp.einsum("bcd,bdr->bcr", x, A[adapter_ids])
    return jnp.einsum("bcr,brh->bch", Ax, B[adapter_ids])


def lora_delta(
    lora: Optional[LoraLayer],
    target: str,
    x: jnp.ndarray,
    adapter_ids: Optional[jnp.ndarray],
) -> jnp.ndarray:
    """Delta for ``target`` or 0.0 when the adapter set doesn't touch it
    (compiles away entirely when lora is None/empty)."""
    if not lora or target not in lora or adapter_ids is None:
        return jnp.zeros((), dtype=x.dtype)
    return apply_lora(x, lora[target], adapter_ids)
