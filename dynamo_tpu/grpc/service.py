"""KServe v2 gRPC inference service.

Reference parity: lib/llm/src/grpc/service/kserve.rs (request/response
mapping, unary rejects streaming=true :356, temperature/max_tokens
defaulting :367-371, streaming demux note :407) and tensor.rs (BYTES
raw-contents codec :402). The text-generation convention matches the
reference (and Triton's TensorRT-LLM frontends):

  inputs:  text_input (BYTES [1]) — the prompt
           streaming (BOOL [1], optional) — only legal on ModelStreamInfer
  request parameters: temperature, max_tokens, top_p, top_k, seed,
           stop_words, ignore_eos (InferParameter map)
  outputs: text_output (BYTES [1]) — generated text (delta when streaming)
           finish_reason (BYTES [1]) — set on the final response
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

import grpc

from dynamo_tpu.grpc import kserve_v2_pb2 as pb
from dynamo_tpu.llm.protocols.common import PostprocessedOutput
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

SERVICE_NAME = "inference.GRPCInferenceService"


# -- tensor codec -----------------------------------------------------------


def _bytes_tensor(name: str, values: List[bytes]) -> "pb.ModelInferResponse.InferOutputTensor":
    t = pb.ModelInferResponse.InferOutputTensor(
        name=name, datatype="BYTES", shape=[len(values)]
    )
    t.contents.bytes_contents.extend(values)
    return t


def _decode_raw_bytes(raw: bytes) -> List[bytes]:
    """KServe raw_input_contents codec for BYTES tensors: each element is a
    4-byte little-endian length followed by the payload (tensor.rs :402)."""
    out: List[bytes] = []
    off = 0
    while off + 4 <= len(raw):
        (n,) = struct.unpack_from("<I", raw, off)
        off += 4
        out.append(raw[off : off + n])
        off += n
    return out


def _param_value(p: "pb.InferParameter") -> Any:
    kind = p.WhichOneof("parameter_choice")
    return getattr(p, kind) if kind else None


def _input_tensor_values(
    request: "pb.ModelInferRequest", name: str
) -> Optional[List[Any]]:
    for i, tensor in enumerate(request.inputs):
        if tensor.name != name:
            continue
        c = tensor.contents
        for field in ("bytes_contents", "bool_contents", "int_contents",
                      "int64_contents", "fp32_contents", "fp64_contents",
                      "uint_contents", "uint64_contents"):
            vals = list(getattr(c, field))
            if vals:
                return vals
        # contents empty: the tensor may ride raw_input_contents (positional)
        if i < len(request.raw_input_contents):
            raw = request.raw_input_contents[i]
            if tensor.datatype == "BYTES":
                return _decode_raw_bytes(raw)
            if tensor.datatype == "BOOL":
                return [b != 0 for b in raw]
        return None
    return None


def request_to_openai(request: "pb.ModelInferRequest") -> Tuple[Dict[str, Any], bool]:
    """ModelInferRequest → (OpenAI completion dict, streaming flag)."""
    text_vals = _input_tensor_values(request, "text_input")
    if not text_vals:
        raise ValueError("missing required input tensor 'text_input'")
    prompt = text_vals[0]
    if isinstance(prompt, bytes):
        prompt = prompt.decode("utf-8", errors="replace")
    stream_vals = _input_tensor_values(request, "streaming")
    streaming = bool(stream_vals[0]) if stream_vals else False

    body: Dict[str, Any] = {
        "model": request.model_name,
        "prompt": prompt,
        "stream": streaming,
    }
    if request.id:
        body["request_id"] = request.id
    params = {k: _param_value(v) for k, v in request.parameters.items()}
    for key in ("temperature", "top_p", "frequency_penalty", "presence_penalty"):
        if key in params:
            body[key] = float(params[key])
    for key in ("max_tokens", "top_k", "seed", "min_tokens"):
        if key in params:
            body[key] = int(params[key])
    if "ignore_eos" in params:
        body["ignore_eos"] = bool(params["ignore_eos"])
    if "stop_words" in params and params["stop_words"]:
        body["stop"] = str(params["stop_words"]).split(",")
    return body, streaming


def response_from(
    model: str, request_id: str, text: str, finish_reason: Optional[str]
) -> "pb.ModelInferResponse":
    resp = pb.ModelInferResponse(model_name=model, id=request_id)
    resp.outputs.append(_bytes_tensor("text_output", [text.encode()]))
    if finish_reason is not None:
        resp.outputs.append(_bytes_tensor("finish_reason", [finish_reason.encode()]))
    return resp


# -- service ----------------------------------------------------------------


class KserveGrpcService:
    """The gRPC frontend server; shares a ModelManager with the HTTP one."""

    def __init__(self, model_manager: Any, *, host: str = "0.0.0.0", port: int = 8787) -> None:
        self.models = model_manager
        self.host = host
        self.port = port
        self._server: Optional[grpc.aio.Server] = None

    # -- handlers ----------------------------------------------------------

    async def _server_live(self, request, context) -> "pb.ServerLiveResponse":
        return pb.ServerLiveResponse(live=True)

    async def _server_ready(self, request, context) -> "pb.ServerReadyResponse":
        return pb.ServerReadyResponse(ready=len(self.models) > 0)

    async def _model_ready(self, request, context) -> "pb.ModelReadyResponse":
        return pb.ModelReadyResponse(ready=self.models.get(request.name) is not None)

    async def _server_metadata(self, request, context) -> "pb.ServerMetadataResponse":
        from dynamo_tpu._version import __version__

        return pb.ServerMetadataResponse(
            name="dynamo_tpu", version=__version__, extensions=[]
        )

    async def _model_metadata(self, request, context) -> "pb.ModelMetadataResponse":
        entry = self.models.get(request.name)
        if entry is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND, f"model '{request.name}' not found"
            )
        resp = pb.ModelMetadataResponse(
            name=entry.name, versions=["1"], platform="dynamo_tpu"
        )
        TM = pb.ModelMetadataResponse.TensorMetadata
        resp.inputs.append(TM(name="text_input", datatype="BYTES", shape=[1]))
        resp.inputs.append(TM(name="streaming", datatype="BOOL", shape=[1]))
        resp.outputs.append(TM(name="text_output", datatype="BYTES", shape=[1]))
        resp.outputs.append(TM(name="finish_reason", datatype="BYTES", shape=[1]))
        return resp

    async def _generate(
        self, body: Dict[str, Any], entry: Any, ctx: Context
    ) -> AsyncIterator[PostprocessedOutput]:
        async for item in entry.engine.generate(body, ctx):
            if isinstance(item, dict):
                continue  # annotations are HTTP/SSE concerns
            yield item

    async def _model_infer(self, request, context) -> "pb.ModelInferResponse":
        try:
            body, streaming = request_to_openai(request)
        except ValueError as exc:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
        if streaming:
            # (ref: kserve.rs :356) unary infer cannot stream
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "streaming=true requires ModelStreamInfer",
            )
        entry = self.models.get(request.model_name)
        if entry is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"model '{request.model_name}' not found",
            )
        ctx = Context(baggage={"model": request.model_name})
        parts: List[str] = []
        finish: Optional[str] = None
        try:
            async for out in self._generate(body, entry, ctx):
                if out.error:
                    await context.abort(grpc.StatusCode.INTERNAL, out.error)
                parts.append(out.text)
                if out.finish_reason is not None:
                    finish = out.finish_reason.value
        except asyncio.CancelledError:
            ctx.kill()
            raise
        return response_from(request.model_name, request.id, "".join(parts), finish)

    async def _model_stream_infer(self, request_iterator, context):
        """Bidi stream: requests are served sequentially, each producing a
        stream of delta responses (ref: kserve.rs ModelStreamInfer; errors
        travel in-band via error_message per the protocol)."""
        async for request in request_iterator:
            entry = self.models.get(request.model_name)
            if entry is None:
                yield pb.ModelStreamInferResponse(
                    error_message=f"model '{request.model_name}' not found"
                )
                continue
            try:
                body, _streaming = request_to_openai(request)
            except ValueError as exc:
                yield pb.ModelStreamInferResponse(error_message=str(exc))
                continue
            body["stream"] = True
            ctx = Context(baggage={"model": request.model_name})
            try:
                async for out in self._generate(body, entry, ctx):
                    if out.error:
                        yield pb.ModelStreamInferResponse(error_message=out.error)
                        break
                    finish = (
                        out.finish_reason.value
                        if out.finish_reason is not None
                        else None
                    )
                    yield pb.ModelStreamInferResponse(
                        infer_response=response_from(
                            request.model_name, request.id, out.text, finish
                        )
                    )
            except asyncio.CancelledError:
                ctx.kill()
                raise

    # -- lifecycle ---------------------------------------------------------

    def _handlers(self) -> grpc.GenericRpcHandler:
        def unary(fn, req_cls, _resp_cls):
            return grpc.unary_unary_rpc_method_handler(
                fn,
                request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )

        handlers = {
            "ServerLive": unary(self._server_live, pb.ServerLiveRequest, pb.ServerLiveResponse),
            "ServerReady": unary(self._server_ready, pb.ServerReadyRequest, pb.ServerReadyResponse),
            "ModelReady": unary(self._model_ready, pb.ModelReadyRequest, pb.ModelReadyResponse),
            "ServerMetadata": unary(self._server_metadata, pb.ServerMetadataRequest, pb.ServerMetadataResponse),
            "ModelMetadata": unary(self._model_metadata, pb.ModelMetadataRequest, pb.ModelMetadataResponse),
            "ModelInfer": unary(self._model_infer, pb.ModelInferRequest, pb.ModelInferResponse),
            "ModelStreamInfer": grpc.stream_stream_rpc_method_handler(
                self._model_stream_infer,
                request_deserializer=pb.ModelInferRequest.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            ),
        }
        return grpc.method_handlers_generic_handler(SERVICE_NAME, handlers)

    async def start(self) -> int:
        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((self._handlers(),))
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        await self._server.start()
        logger.info("gRPC KServe frontend listening on %s:%d", self.host, self.port)
        return self.port

    async def stop(self, grace_period: float = 30.0) -> None:
        if self._server is not None:
            await self._server.stop(grace_period)
            self._server = None
