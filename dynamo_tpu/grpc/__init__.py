"""gRPC KServe (Open Inference Protocol v2) frontend.

Reference parity: lib/llm/src/grpc/service/kserve.rs — the second frontend
class next to HTTP: ServerLive/ServerReady/ModelReady/ModelMetadata,
ModelInfer (unary) and ModelStreamInfer (streaming) speaking the public
KServe v2 protocol, backed by the same ModelManager pipelines the HTTP
frontend serves.

The protobuf gencode (kserve_v2_pb2.py) is committed; regenerate with:
    protoc --python_out=dynamo_tpu/grpc -I dynamo_tpu/grpc/protos \
        dynamo_tpu/grpc/protos/kserve_v2.proto
"""

from dynamo_tpu.grpc.service import KserveGrpcService

__all__ = ["KserveGrpcService"]
