"""gRPC KServe frontend entrypoint.

Mirrors the HTTP frontend (frontend/__main__.py) but serves the KServe v2
protocol (ref: the reference's `dynamo-run` http+grpc listener split,
lib/llm/src/grpc/service/kserve.rs). Models arrive via the same discovery
watcher; one process can serve both frontends off one ModelManager.
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu import config
from dynamo_tpu.grpc.service import KserveGrpcService
from dynamo_tpu.http.model_manager import ModelManager
from dynamo_tpu.llm.discovery import ModelWatcher
from dynamo_tpu.router import KvRouterConfig
from dynamo_tpu.runtime.component import RouterMode
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.utils.logging import configure_logging


async def main() -> None:
    parser = argparse.ArgumentParser("dynamo-tpu grpc frontend (KServe v2)")
    parser.add_argument("--host", default=config.HTTP_HOST.get())
    parser.add_argument("--grpc-port", type=int, default=8787)
    parser.add_argument(
        "--router-mode", choices=["kv", "round-robin", "random"], default="kv"
    )
    args = parser.parse_args()

    configure_logging()
    runtime = DistributedRuntime.from_settings()
    manager = ModelManager()
    mode = {
        "kv": RouterMode.KV,
        "round-robin": RouterMode.ROUND_ROBIN,
        "random": RouterMode.RANDOM,
    }[args.router_mode]
    watcher = ModelWatcher(
        runtime, manager, router_mode=mode, kv_router_config=KvRouterConfig()
    )
    await watcher.start()
    service = KserveGrpcService(manager, host=args.host, port=args.grpc_port)
    port = await service.start()
    print(f"grpc frontend listening on {args.host}:{port}", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await service.stop(grace_period=config.GRACE_PERIOD.get())
        await watcher.stop()
        await runtime.shutdown(grace_period=config.GRACE_PERIOD.get())


if __name__ == "__main__":
    asyncio.run(main())
