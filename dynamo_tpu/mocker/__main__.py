from __future__ import annotations

import argparse
import asyncio
import random

from dynamo_tpu import config
from dynamo_tpu.engines.mock.engine import MockEngine, MockEngineArgs
from dynamo_tpu.llm.discovery import register_llm
from dynamo_tpu.llm.model_card import ModelDeploymentCard, RuntimeConfig
from dynamo_tpu.router import KvEventPublisher, LoadPublisher
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.utils.logging import configure_logging


async def serve_mocker(args) -> None:
    runtime = DistributedRuntime.from_settings()
    # Crash plane: the registration + every load report carry this
    # process's incarnation, and --instance-id pins a stable identity so
    # a SIGKILLed-and-restarted mocker rejoins as the SAME worker under a
    # fresh incarnation (the chaos soak's restart contract).
    from dynamo_tpu.runtime.liveness import process_incarnation

    incarnation = process_incarnation()
    served = []
    cleanup = []
    for rank in range(args.num_workers):
        instance_id = (
            args.instance_id + rank if args.instance_id
            else random.getrandbits(63)
        )
        kv_pub = KvEventPublisher(
            runtime.event_plane, args.namespace, args.component, instance_id
        )
        engine = MockEngine(
            MockEngineArgs(
                block_size=args.block_size,
                num_kv_blocks=args.num_kv_blocks,
                max_num_seqs=args.max_num_seqs,
                speedup_ratio=args.speedup_ratio,
                dp_size=1,
            ),
            on_kv_event=kv_pub.on_kv_event,
        )
        kv_pub.set_snapshot_fn(engine.kv.committed_view)
        load_pub = LoadPublisher(
            runtime.event_plane, args.namespace, args.component, instance_id,
            lambda e=engine: {
                "active_seqs": len(e._running),
                "waiting": len(e._waiting),
                "free_blocks": e.kv.free_blocks,
                "total_blocks": e.args.num_kv_blocks,
            },
            total_blocks=args.num_kv_blocks,
        )
        card = ModelDeploymentCard(
            name=args.model_name,
            context_length=args.max_model_len,
            kv_block_size=args.block_size,
            runtime_config=RuntimeConfig(
                total_kv_blocks=args.num_kv_blocks,
                kv_block_size=args.block_size,
                max_num_seqs=args.max_num_seqs,
                max_context_len=args.max_model_len,
            ),
        )
        endpoint = (
            runtime.namespace(args.namespace)
            .component(args.component)
            .endpoint(args.endpoint)
        )
        served.append(
            await endpoint.serve_endpoint(
                engine.generate, instance_id=instance_id,
                metadata={"incarnation": incarnation},
            )
        )
        await register_llm(
            runtime, card, endpoint, instance_id, incarnation=incarnation
        )
        load_pub.start()
        await engine.start()
        cleanup.extend([load_pub.close, kv_pub.close, engine.stop])
        print(
            f"mocker serving {args.model_name} instance {instance_id:#x}", flush=True
        )
    try:
        await asyncio.Event().wait()
    finally:
        for s in served:
            await s.shutdown(grace_period=5)
        for fn in cleanup:
            await fn()
        await runtime.shutdown(grace_period=5)


def main() -> None:
    parser = argparse.ArgumentParser("dynamo-tpu mocker worker")
    parser.add_argument("--model-name", default="mock-model")
    parser.add_argument("--namespace", default=config.NAMESPACE.get())
    parser.add_argument("--component", default="backend")
    parser.add_argument("--endpoint", default="generate")
    parser.add_argument("--num-workers", type=int, default=1,
                        help="mock engine instances in this process")
    parser.add_argument("--instance-id", type=lambda s: int(s, 0), default=0,
                        help="stable worker identity (rank offsets for "
                        "--num-workers > 1; 0 = random). A restarted "
                        "mocker under the same id rejoins as the same "
                        "worker with a fresh incarnation (crash plane)")
    parser.add_argument(
        "--block-size", type=int, default=config.KV_BLOCK_SIZE.get()
    )
    parser.add_argument("--num-kv-blocks", type=int, default=1024)
    parser.add_argument("--max-num-seqs", type=int, default=32)
    parser.add_argument("--max-model-len", type=int, default=4096)
    parser.add_argument("--speedup-ratio", type=float, default=1.0)
    args = parser.parse_args()
    configure_logging()
    asyncio.run(serve_mocker(args))


if __name__ == "__main__":
    main()
