"""Mock-engine worker component (python -m dynamo_tpu.mocker).

Reference parity: components/src/dynamo/mocker (CLI over the Rust mocker
engine, lib/mocker) — a deterministic fake worker so router/disagg/planner
e2e runs need no accelerator (SURVEY §4 'centerpiece').
"""
