"""Llama-family decoder: pure-JAX, scan-over-layers, paged KV cache.

TPU-first design notes (vs the reference's torch engines):
  - functional params pytree; layers stacked on a leading axis and consumed
    by `lax.scan` — one traced layer body regardless of depth (fast compile,
    XLA pipelines the per-layer HBM traffic).
  - one `forward_paged` serves prefill, chunked prefill and decode: a chunk
    of C tokens per sequence starting at `start_pos`, K/V written into the
    block pool first, then attention over the pages (ops/attention.py).
  - logical-axis annotations (parallel/sharding.py) drive tp/dp/sp layout;
    XLA inserts the collectives.

Covers Llama-2/3, Qwen2/2.5 (qkv_bias, tied embeddings), Mistral via
ModelConfig knobs.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.ops.attention import (
    dense_chunk_attention,
    paged_attention,
    write_chunk_to_cache,
)
from dynamo_tpu.ops.lora import lora_delta
from dynamo_tpu.ops.moe import moe_ffn
from dynamo_tpu.ops.quant import embed_lookup, lm_head as q_lm_head, qeinsum
from dynamo_tpu.ops.rope import apply_rope, rope_table

Params = Dict[str, Any]


# ---------------------------------------------------------------------------
# Parameter initialization / logical sharding axes
# ---------------------------------------------------------------------------


def init_params(config: ModelConfig, key: jax.Array) -> Params:
    """Random-init params (He-style scaled normal), layers stacked on axis 0."""
    c = config
    hd = c.head_dim_
    L = c.n_layers
    keys = jax.random.split(key, 12)

    def norm(k, shape, scale):
        return (jax.random.normal(k, shape, dtype=jnp.float32) * scale).astype(c.dtype)

    d, ff, H, KH = c.d_model, c.d_ff, c.n_heads, c.n_kv_heads
    s_d = d**-0.5
    s_ff = ff**-0.5
    # Unit-offset norms (Gemma) store w-1 → effective weight 1+w; ones()
    # here means effective 2.0 for them, fine for random init.
    norm_fill = 0.0 if c.rmsnorm_unit_offset else 1.0
    layers: Params = {
        "attn_norm": jnp.full((L, d), norm_fill, dtype=c.dtype),
        "wq": norm(keys[0], (L, d, H * hd), s_d),
        "wk": norm(keys[1], (L, d, KH * hd), s_d),
        "wv": norm(keys[2], (L, d, KH * hd), s_d),
        "wo": norm(keys[3], (L, H * hd, d), (H * hd) ** -0.5),
        "mlp_norm": jnp.full((L, d), norm_fill, dtype=c.dtype),
    }
    if c.post_norms:
        layers["attn_post_norm"] = jnp.full((L, d), norm_fill, dtype=c.dtype)
        layers["mlp_post_norm"] = jnp.full((L, d), norm_fill, dtype=c.dtype)
    if c.is_moe:
        E, eff = c.n_experts, c.moe_d_ff_
        s_eff = eff**-0.5
        layers["router_w"] = norm(keys[9], (L, d, E), s_d)
        layers["we_gate"] = norm(keys[4], (L, E, d, eff), s_d)
        layers["we_up"] = norm(keys[5], (L, E, d, eff), s_d)
        layers["we_down"] = norm(keys[6], (L, E, eff, d), s_eff)
    else:
        layers["w_gate"] = norm(keys[4], (L, d, ff), s_d)
        layers["w_up"] = norm(keys[5], (L, d, ff), s_d)
        layers["w_down"] = norm(keys[6], (L, ff, d), s_ff)
    if c.qkv_bias:
        layers["bq"] = jnp.zeros((L, H * hd), dtype=c.dtype)
        layers["bk"] = jnp.zeros((L, KH * hd), dtype=c.dtype)
        layers["bv"] = jnp.zeros((L, KH * hd), dtype=c.dtype)
    if c.qk_norm:
        layers["q_norm"] = jnp.ones((L, hd), dtype=c.dtype)
        layers["k_norm"] = jnp.ones((L, hd), dtype=c.dtype)
    params: Params = {
        "embed": norm(keys[7], (c.vocab_size, d), 1.0),
        "layers": layers,
        "final_norm": jnp.full((d,), norm_fill, dtype=c.dtype),
    }
    if not c.tie_word_embeddings:
        params["lm_head"] = norm(keys[8], (d, c.vocab_size), s_d)
    return params


def param_logical_axes(config: ModelConfig) -> Params:
    """Logical axis names per param (see parallel/sharding.py rules)."""
    layers = {
        "attn_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv_heads"),
        "wv": ("layers", "embed", "kv_heads"),
        "wo": ("layers", "heads", "embed"),
        "mlp_norm": ("layers", "embed"),
    }
    if config.post_norms:
        layers["attn_post_norm"] = ("layers", "embed")
        layers["mlp_post_norm"] = ("layers", "embed")
    if config.is_moe:
        layers["router_w"] = ("layers", "embed", None)
        layers["we_gate"] = ("layers", "experts", "embed", "ffn")
        layers["we_up"] = ("layers", "experts", "embed", "ffn")
        layers["we_down"] = ("layers", "experts", "ffn", "embed")
    else:
        layers["w_gate"] = ("layers", "embed", "ffn")
        layers["w_up"] = ("layers", "embed", "ffn")
        layers["w_down"] = ("layers", "ffn", "embed")
    if config.qkv_bias:
        layers["bq"] = ("layers", "heads")
        layers["bk"] = ("layers", "kv_heads")
        layers["bv"] = ("layers", "kv_heads")
    if config.qk_norm:
        layers["q_norm"] = ("layers", "head_dim")
        layers["k_norm"] = ("layers", "head_dim")
    axes: Params = {
        "embed": ("vocab", "embed"),
        "layers": layers,
        "final_norm": ("embed",),
    }
    if not config.tie_word_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def kv_cache_shape(
    config: ModelConfig, num_blocks: int, block_size: int
) -> Tuple[int, ...]:
    return (config.n_layers, num_blocks, block_size, config.n_kv_heads, config.head_dim_)


def init_kv_cache(
    config: ModelConfig, num_blocks: int, block_size: int, *,
    layered: bool = False, kv_dtype: Optional[str] = None,
):
    """Zeroed K/V pools. ``layered=False``: one stacked [L, NB, BS, KH, D]
    array each (checkpoint/transfer-friendly). ``layered=True``: L-tuples of
    4D arrays — the serving layout. The layered form is what the hot path
    wants: the stacked form forces the layer-scan to rematerialize the FULL
    cache as scan ys every step (~2× cache size of HBM traffic per decode
    step, measured 22.2 → 15.2 ms/step at the bench shape when switched),
    while per-layer carries update in place.

    ``kv_dtype="int8"`` (layered only): each layer's pool is a quantized
    {"q8", "s"} dict (ops/kv_quant.py) — half the history-read bytes and
    half the decode kernel's page VMEM."""
    if kv_dtype == "int8":
        if not layered:
            raise ValueError("int8 KV cache requires the layered layout")
        shape = kv_cache_shape(config, num_blocks, block_size)[1:]
        s_shape = (num_blocks, config.n_kv_heads, block_size)

        def one():
            return {
                "q8": jnp.zeros(shape, dtype=jnp.int8),
                # zero scales: zero pages dequantize to exact zeros
                "s": jnp.zeros(s_shape, dtype=jnp.float32),
            }

        k = tuple(one() for _ in range(config.n_layers))
        v = tuple(one() for _ in range(config.n_layers))
        return k, v
    if layered:
        shape = kv_cache_shape(config, num_blocks, block_size)[1:]
        k = tuple(jnp.zeros(shape, dtype=config.dtype) for _ in range(config.n_layers))
        v = tuple(jnp.zeros(shape, dtype=config.dtype) for _ in range(config.n_layers))
        return k, v
    shape = kv_cache_shape(config, num_blocks, block_size)
    return jnp.zeros(shape, dtype=config.dtype), jnp.zeros(shape, dtype=config.dtype)


def kv_cache_logical_axes() -> Tuple[str, ...]:
    return ("layers", "kv_blocks", None, "kv_heads", "head_dim")


def kv_cache_layered_axes() -> Tuple[str, ...]:
    """Logical axes of ONE layer's pool in the layered layout."""
    return ("kv_blocks", None, "kv_heads", "head_dim")


def is_layered_cache(cache) -> bool:
    return isinstance(cache, (tuple, list))


def stack_kv_cache(k_layers, v_layers) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Layered → stacked (for checkpoint/export interop). Copies."""
    return jnp.stack(tuple(k_layers)), jnp.stack(tuple(v_layers))


def unstack_kv_cache(k_cache: jnp.ndarray, v_cache: jnp.ndarray):
    """Stacked → layered. Copies (per-layer slices become separate buffers)."""
    L = k_cache.shape[0]
    return (
        tuple(k_cache[l] for l in range(L)),
        tuple(v_cache[l] for l in range(L)),
    )


def unstack_layer_params(layers, n_layers: int):
    """Stacked [L, ...] per-leaf layer params → list of per-layer trees:
    the serving layout, paired with the layered KV cache. With stacked
    params the per-layer ``a[l]`` slices inside the unrolled decode loop
    force XLA to re-lay-out the kv-projection weights EVERY STEP (the
    stacked array's layout puts the layer dim minor; a device trace at the
    8B shape showed 4 s8-relayout fusions costing ~0.7 ms/step). Separate
    per-layer buffers are born in their matmul-preferred layout, so the
    loop body references them directly. A list (not tuple) so the axes
    tree mirrors it without tripping param_shardings' tuple is_leaf.

    Conversion runs leaf-by-leaf as a DONATED jit split so peak extra HBM
    is bounded by one stacked leaf (~1.9 GB at 8B) instead of the whole
    weight tree, and dispatch count is one per leaf rather than
    n_layers × n_leaves eager slices."""
    splits: Dict[Tuple[Any, ...], Any] = {}

    def split_leaf(a):
        from dynamo_tpu.runtime.device_observe import watched_jit

        a = jnp.asarray(a)
        key = (a.shape, a.dtype)
        if key not in splits:
            # One watch name for every leaf-shaped split program: the
            # signature count legitimately tracks distinct leaf shapes, so
            # the site is unbudgeted (load-time only, never a hot path).
            splits[key] = watched_jit(
                "llama.unstack_layer_split",
                jax.jit(
                    lambda x: tuple(x[l] for l in range(n_layers)),
                    donate_argnums=(0,),
                ),
            )
        return splits[key](a)

    per_leaf = jax.tree.map(split_leaf, layers)
    return [
        jax.tree.map(
            lambda t: t[l], per_leaf,
            is_leaf=lambda x: isinstance(x, tuple) and not isinstance(x, dict),
        )
        for l in range(n_layers)
    ]


def unstack_layer_axes(layer_axes, n_layers: int):
    """Logical-axes tree matching unstack_layer_params: the leading
    "layers" axis is stripped from every leaf tuple."""
    one = jax.tree.map(
        lambda t: t[1:], layer_axes, is_leaf=lambda x: isinstance(x, tuple)
    )
    return [one for _ in range(n_layers)]


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _rms_norm(
    x: jnp.ndarray, w: jnp.ndarray, eps: float, unit_offset: bool = False
) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype)
    # Gemma stores norm weights as (w - 1); effective scale is 1 + w.
    return normed * (1.0 + w) if unit_offset else normed * w


def _act(x: jnp.ndarray, act_fn: str) -> jnp.ndarray:
    if act_fn == "gelu_tanh":
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.silu(x)


def decoder_layer(
    c: ModelConfig,
    lp: Params,  # one layer's params (axis 0 stripped)
    ll: Dict[str, Any],  # one layer's stacked LoRA arrays ({} = none)
    win: jnp.ndarray,  # scalar int32 sliding window (0 = full)
    x: jnp.ndarray,  # [B, C, d]
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    k_c: jnp.ndarray,  # [num_blocks, block_size, KH, D] — this layer's pool
    v_c: jnp.ndarray,
    block_tables: jnp.ndarray,
    start_pos: jnp.ndarray,
    chunk_lens: jnp.ndarray,
    *,
    use_kernel: bool,
    adapter_ids: Optional[jnp.ndarray],
    first_chunk: bool = False,
    cos_loc: Optional[jnp.ndarray] = None,  # Gemma-3 local-rope table
    sin_loc: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One decoder layer (attention + FFN, all family knobs). Shared by the
    scan-over-layers forward and the pipeline-parallel stage executor
    (parallel/pipeline.py), so every architecture behavior lives in exactly
    one place.

    ``first_chunk`` (static): every row's history is the in-flight chunk
    itself (start_pos == 0, fresh prefill) — attend densely over the
    registers (ops/attention.dense_chunk_attention) instead of reading the
    pages just written; the cache is still written for the decode that
    follows. Removes ALL per-layer page DMA from fresh-prefill programs."""
    B, C = x.shape[:2]
    hd = c.head_dim_
    uo = c.rmsnorm_unit_offset
    sm_scale = c.query_scale**-0.5 if c.query_scale is not None else hd**-0.5
    cap = float(c.attn_logit_softcap or 0.0)

    h = _rms_norm(x, lp["attn_norm"], c.rms_norm_eps, uo)
    q = qeinsum("bcd,dh->bch", h, lp["wq"]) + lora_delta(ll, "wq", h, adapter_ids)
    k = qeinsum("bcd,dh->bch", h, lp["wk"]) + lora_delta(ll, "wk", h, adapter_ids)
    v = qeinsum("bcd,dh->bch", h, lp["wv"]) + lora_delta(ll, "wv", h, adapter_ids)
    if c.qkv_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    q = q.reshape(B, C, c.n_heads, hd)
    k = k.reshape(B, C, c.n_kv_heads, hd)
    v = v.reshape(B, C, c.n_kv_heads, hd)
    if c.qk_norm:
        # Qwen3/Gemma-3: per-head RMSNorm over head_dim on q and k, BEFORE
        # RoPE (HF attention order: norm → rope). Gemma-family norms store
        # (w - 1), hence the unit offset.
        q = _rms_norm(q, lp["q_norm"], c.rms_norm_eps, uo)
        k = _rms_norm(k, lp["k_norm"], c.rms_norm_eps, uo)
    if cos_loc is not None:
        # Gemma-3 dual-frequency RoPE: windowed (local) layers rotate with
        # the local-base table; global layers with the (possibly
        # position-scaled) global table. ``win`` is a traced scalar.
        sel = (win > 0)
        cos = jnp.where(sel, cos_loc, cos)
        sin = jnp.where(sel, sin_loc, sin)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    k_c = write_chunk_to_cache(k_c, k, block_tables, start_pos, chunk_lens)
    v_c = write_chunk_to_cache(v_c, v, block_tables, start_pos, chunk_lens)

    if first_chunk:
        attn = dense_chunk_attention(
            q, k, v, chunk_lens, sm_scale=sm_scale, window=win,
            logit_cap=cap,
        ).reshape(B, C, -1)
    else:
        attn = paged_attention(
            q, k_c, v_c, block_tables, start_pos, chunk_lens,
            use_kernel=use_kernel, sm_scale=sm_scale, window=win,
            logit_cap=cap,
        ).reshape(B, C, -1)
    attn_out = qeinsum("bch,hd->bcd", attn, lp["wo"]) + lora_delta(
        ll, "wo", attn, adapter_ids
    )
    if c.post_norms:
        attn_out = _rms_norm(attn_out, lp["attn_post_norm"], c.rms_norm_eps, uo)
    x = x + attn_out

    h = _rms_norm(x, lp["mlp_norm"], c.rms_norm_eps, uo)
    if c.is_moe:
        mlp_out = moe_ffn(
            h, lp["router_w"], lp["we_gate"], lp["we_up"], lp["we_down"],
            top_k=c.n_experts_per_tok,
            capacity_factor=c.moe_capacity_factor,
            norm_topk_prob=c.norm_topk_prob,
        )
    else:
        gate = _act(
            qeinsum("bcd,df->bcf", h, lp["w_gate"])
            + lora_delta(ll, "w_gate", h, adapter_ids),
            c.act_fn,
        )
        up = qeinsum("bcd,df->bcf", h, lp["w_up"]) + lora_delta(
            ll, "w_up", h, adapter_ids
        )
        gu = gate * up
        mlp_out = qeinsum("bcf,fd->bcd", gu, lp["w_down"]) + lora_delta(
            ll, "w_down", gu, adapter_ids
        )
    if c.post_norms:
        mlp_out = _rms_norm(mlp_out, lp["mlp_post_norm"], c.rms_norm_eps, uo)
    x = x + mlp_out
    return x, k_c, v_c


def embed_tokens(
    params: Params,
    config: ModelConfig,
    tokens: jnp.ndarray,
    mm_embeds: Optional[jnp.ndarray] = None,
    mm_slot: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Token (+ multimodal splice) embeddings with family scaling."""
    c = config
    x = embed_lookup(params["embed"], tokens, c.dtype)
    if c.embed_scale:  # Gemma: embeddings scaled by sqrt(d_model)
        x = x * jnp.asarray(c.d_model**0.5, dtype=c.dtype)
    if mm_embeds is not None and mm_slot is not None:
        rows = mm_embeds[jnp.clip(mm_slot, 0, mm_embeds.shape[0] - 1)]
        x = jnp.where((mm_slot >= 0)[..., None], rows.astype(x.dtype), x)
    return x


def lm_head_logits(
    params: Params, config: ModelConfig, x: jnp.ndarray
) -> jnp.ndarray:
    """Final norm → vocab projection → final softcap. x: [..., d]."""
    c = config
    x = _rms_norm(x, params["final_norm"], c.rms_norm_eps, c.rmsnorm_unit_offset)
    head = params["embed"] if c.tie_word_embeddings else params["lm_head"]
    logits = q_lm_head(x, head, tied=c.tie_word_embeddings)
    if c.final_logit_softcap:
        fcap = float(c.final_logit_softcap)
        logits = fcap * jnp.tanh(logits / fcap)
    return logits


def forward_paged(
    params: Params,
    config: ModelConfig,
    tokens: jnp.ndarray,  # [B, C] int32
    start_pos: jnp.ndarray,  # [B] int32
    chunk_lens: jnp.ndarray,  # [B] int32
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    k_cache: jnp.ndarray,  # [L, num_blocks, block_size, KH, D]
    v_cache: jnp.ndarray,
    *,
    use_kernel: bool = False,
    lora: Optional[Dict[str, Any]] = None,  # target → (A [L,N,d,r], B [L,N,r,h])
    adapter_ids: Optional[jnp.ndarray] = None,  # [B] int32, 0 = no adapter
    mm_embeds: Optional[jnp.ndarray] = None,  # [M, d] image patch embeddings
    mm_slot: Optional[jnp.ndarray] = None,  # [B, C] int32 row into mm_embeds, -1=text
    all_logits: bool = False,  # True → logits for EVERY position [B, C, V]
    first_chunk: bool = False,  # static: fresh prefill, dense in-chunk attention
    use_megakernel: bool = False,  # C=1: fused-layer pallas decode path
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One forward step over a chunk. Returns (last_logits [B, V], k_cache,
    v_cache). K/V for the chunk are scattered into the pools before attending,
    so the same function implements prefill (large C), chunked prefill
    (start_pos > 0), and decode (C = 1).

    Multi-LoRA: ``lora`` carries layer-major stacked adapters (ops/lora.py);
    each sequence's ``adapter_ids`` entry selects its adapter per einsum —
    one compiled program for any adapter mix (punica-role, TPU-style)."""
    c = config
    B, C = tokens.shape
    hd = c.head_dim_

    x = embed_tokens(params, c, tokens, mm_embeds, mm_slot)  # [B, C, d]

    pos = start_pos[:, None] + jax.lax.broadcasted_iota(jnp.int32, (B, C), 1)
    cos, sin = rope_table(
        pos, hd, c.rope_theta, scale=c.rope_scaling_factor or 1.0
    )  # [B, C, hd]
    cos_loc = sin_loc = None
    if c.rope_local_theta is not None:
        # Gemma-3: local (windowed) layers rotate at the local base freq,
        # UNscaled (HF applies rope_scaling only to the global rope).
        cos_loc, sin_loc = rope_table(pos, hd, c.rope_local_theta)

    if is_layered_cache(k_cache):
        # Serving layout: Python-unrolled layers over per-layer 4D pools.
        # Static layer indices let XLA update every pool in place (step-scan
        # carry / donated buffer). The stacked form below rematerializes the
        # FULL cache as scan ys every call (~2× cache size of HBM traffic) —
        # measured 22.2 → 15.2 ms/step at the bench shape when switched.
        # HLO grows ~L× but is traced once; compile stays cached.
        win_list = c.layer_windows()
        layered_params = isinstance(params["layers"], (tuple, list))

        if (
            use_megakernel
            and C == 1
            and layered_params
            and not lora
        ):
            # Fused-layer decode megakernel (ops/pallas/fused_layer.py):
            # one pallas program per layer; the current token's K/V come
            # back as outputs and are scattered AFTER (the kernel attends
            # history pages + the in-register token). Family epilogues
            # (qk-norm, softcap, post-norms, GeGLU, unit-offset norms,
            # qkv-bias, sliding windows) run IN-KERNEL: the per-layer
            # window rides a traced scalar operand (windowed and global
            # layers share one compiled program) and the per-layer rope
            # table is selected HERE (Gemma-3 dual-frequency: local table
            # on windowed layers, unscaled).
            from dynamo_tpu.ops.attention import write_chunk_to_cache
            from dynamo_tpu.ops.pallas.fused_layer import (
                fused_decoder_layer,
            )

            sm = (
                c.query_scale**-0.5
                if c.query_scale is not None
                else c.head_dim_**-0.5
            )
            x2 = x[:, 0]
            cos1, sin1 = cos[:, 0], sin[:, 0]
            cosl1 = cos_loc[:, 0] if cos_loc is not None else None
            sinl1 = sin_loc[:, 0] if sin_loc is not None else None
            # Per-row history page counts (the kernel's scalar-prefetch
            # loop bound): one derivation per STEP, shared by every layer,
            # instead of recomputing from start_pos inside each layer call.
            from dynamo_tpu.ops.pallas.fused_layer import history_pcounts

            pcounts = history_pcounts(
                start_pos, k_cache[0].shape[1], block_tables.shape[1]
            )
            any_window = any(int(w) != 0 for w in win_list)
            k_out, v_out = [], []
            for l in range(c.n_layers):
                win_l = int(win_list[l])
                local = cosl1 is not None and win_l > 0
                x2, k_n, v_n = fused_decoder_layer(
                    x2,
                    cosl1 if local else cos1,
                    sinl1 if local else sin1,
                    params["layers"][l],
                    k_cache[l], v_cache[l], block_tables, start_pos,
                    eps=c.rms_norm_eps, sm_scale=sm, pcounts=pcounts,
                    # Traced operand (not static) whenever ANY layer is
                    # windowed, so the model's layers share one compiled
                    # program per width bucket; window-free models omit
                    # the operand entirely (identical trace to r6).
                    window=(
                        jnp.asarray(win_l, jnp.int32) if any_window else None
                    ),
                    act_fn=c.act_fn,
                    unit_offset=c.rmsnorm_unit_offset,
                    softcap=float(c.attn_logit_softcap or 0.0),
                )
                k_out.append(
                    write_chunk_to_cache(
                        k_cache[l], k_n[:, None], block_tables,
                        start_pos, chunk_lens,
                    )
                )
                v_out.append(
                    write_chunk_to_cache(
                        v_cache[l], v_n[:, None], block_tables,
                        start_pos, chunk_lens,
                    )
                )
            x = x2[:, None]
            k_cache, v_cache = tuple(k_out), tuple(v_out)
            if all_logits:
                return lm_head_logits(params, c, x), k_cache, v_cache
            return (
                lm_head_logits(params, c, x[:, 0]), k_cache, v_cache
            )
        k_out, v_out = [], []
        for l in range(c.n_layers):
            if layered_params:
                lp_l = params["layers"][l]
            else:
                lp_l = jax.tree.map(lambda a, _l=l: a[_l], params["layers"])
            ll_l = jax.tree.map(lambda a, _l=l: a[_l], lora) if lora else {}
            x, k_l, v_l = decoder_layer(
                c, lp_l, ll_l, jnp.asarray(win_list[l], jnp.int32), x, cos, sin,
                k_cache[l], v_cache[l], block_tables, start_pos, chunk_lens,
                use_kernel=use_kernel, adapter_ids=adapter_ids,
                first_chunk=first_chunk, cos_loc=cos_loc, sin_loc=sin_loc,
            )
            k_out.append(k_l)
            v_out.append(v_l)
        k_cache, v_cache = tuple(k_out), tuple(v_out)
    else:
        # Per-layer sliding windows (0 = full) ride the scan xs so one traced
        # body serves Gemma-2's alternating local/global layers.
        windows = jnp.asarray(c.layer_windows(), dtype=jnp.int32)

        def layer_fn(carry, xs):
            x = carry
            lp, k_c, v_c, ll, win = xs
            x, k_c, v_c = decoder_layer(
                c, lp, ll, win, x, cos, sin, k_c, v_c,
                block_tables, start_pos, chunk_lens,
                use_kernel=use_kernel, adapter_ids=adapter_ids,
                first_chunk=first_chunk, cos_loc=cos_loc, sin_loc=sin_loc,
            )
            return x, (k_c, v_c)

        x, (k_cache, v_cache) = jax.lax.scan(
            layer_fn, x, (params["layers"], k_cache, v_cache, lora or {}, windows)
        )

    if all_logits:
        # Every position's logits (speculative verify reads them all).
        return lm_head_logits(params, c, x), k_cache, v_cache
    # Only the last valid position's logits are needed (sampling).
    last_idx = jnp.clip(chunk_lens - 1, 0, C - 1)
    x_last = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)[:, 0]  # [B, d]
    return lm_head_logits(params, c, x_last), k_cache, v_cache


def encode(
    params: Params,
    config: ModelConfig,
    tokens: jnp.ndarray,  # [B, T] int32 (right-padded)
    lengths: jnp.ndarray,  # [B] int32 valid lengths
) -> jnp.ndarray:
    """Mean-pooled final hidden states [B, d] — the embedding-model forward
    (bidirectional is unnecessary for decoder-embedding models; pooling over
    the causal states matches the common last/mean-pool recipes)."""
    c = config
    B, T = tokens.shape
    hd = c.head_dim_
    uo = c.rmsnorm_unit_offset
    sm_scale = c.query_scale**-0.5 if c.query_scale is not None else hd**-0.5
    cap = float(c.attn_logit_softcap or 0.0)
    windows = jnp.asarray(c.layer_windows(), dtype=jnp.int32)
    x = embed_lookup(params["embed"], tokens, c.dtype)
    if c.embed_scale:
        x = x * jnp.asarray(c.d_model**0.5, dtype=c.dtype)
    pos = jax.lax.broadcasted_iota(jnp.int32, (B, T), 1)
    cos, sin = rope_table(
        pos, hd, c.rope_theta, scale=c.rope_scaling_factor or 1.0
    )
    cos_loc = sin_loc = None
    if c.rope_local_theta is not None:
        cos_loc, sin_loc = rope_table(pos, hd, c.rope_local_theta)

    def layer_fn(carry, xs):
        x = carry
        lp, win = xs
        h = _rms_norm(x, lp["attn_norm"], c.rms_norm_eps, uo)
        q = qeinsum("btd,dh->bth", h, lp["wq"])
        k = qeinsum("btd,dh->bth", h, lp["wk"])
        v = qeinsum("btd,dh->bth", h, lp["wv"])
        if c.qkv_bias:
            q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
        q = q.reshape(B, T, c.n_heads, hd)
        k = k.reshape(B, T, c.n_kv_heads, hd)
        if c.qk_norm:  # Qwen3/Gemma-3: per-head RMSNorm before RoPE
            q = _rms_norm(q, lp["q_norm"], c.rms_norm_eps, uo)
            k = _rms_norm(k, lp["k_norm"], c.rms_norm_eps, uo)
        lcos, lsin = cos, sin
        if cos_loc is not None:  # Gemma-3 dual-frequency rope
            sel = (win > 0)
            lcos = jnp.where(sel, cos_loc, cos)
            lsin = jnp.where(sel, sin_loc, sin)
        q = apply_rope(q, lcos, lsin)
        k = apply_rope(k, lcos, lsin)
        v = v.reshape(B, T, c.n_kv_heads, hd)
        G = c.q_per_kv
        qf = q.astype(jnp.float32).transpose(0, 2, 1, 3)
        kf = jnp.repeat(k.astype(jnp.float32).transpose(0, 2, 1, 3), G, axis=1)
        vf = jnp.repeat(v.astype(jnp.float32).transpose(0, 2, 1, 3), G, axis=1)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * sm_scale
        if cap > 0.0:
            s = cap * jnp.tanh(s / cap)
        t_q = jax.lax.broadcasted_iota(jnp.int32, (T, T), 0)
        t_k = jax.lax.broadcasted_iota(jnp.int32, (T, T), 1)
        causal = (t_q >= t_k) & ((win <= 0) | (t_k > t_q - win))
        valid = t_k[None] < lengths[:, None, None]  # padded keys masked
        s = jnp.where(causal[None, None] & valid[:, None], s, -1e30)
        attn = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), vf)
        attn = attn.transpose(0, 2, 1, 3).reshape(B, T, -1).astype(x.dtype)
        attn_out = qeinsum("bth,hd->btd", attn, lp["wo"])
        if c.post_norms:
            attn_out = _rms_norm(attn_out, lp["attn_post_norm"], c.rms_norm_eps, uo)
        x = x + attn_out
        h = _rms_norm(x, lp["mlp_norm"], c.rms_norm_eps, uo)
        if c.is_moe:
            mlp_out = moe_ffn(
                h, lp["router_w"], lp["we_gate"], lp["we_up"], lp["we_down"],
                top_k=c.n_experts_per_tok,
                capacity_factor=c.moe_capacity_factor,
                norm_topk_prob=c.norm_topk_prob,
            )
        else:
            gate = _act(qeinsum("btd,df->btf", h, lp["w_gate"]), c.act_fn)
            up = qeinsum("btd,df->btf", h, lp["w_up"])
            mlp_out = qeinsum("btf,fd->btd", gate * up, lp["w_down"])
        if c.post_norms:
            mlp_out = _rms_norm(mlp_out, lp["mlp_post_norm"], c.rms_norm_eps, uo)
        x = x + mlp_out
        return x, None

    x, _ = jax.lax.scan(layer_fn, x, (params["layers"], windows))
    x = _rms_norm(x, params["final_norm"], c.rms_norm_eps, uo).astype(jnp.float32)
    mask = (jax.lax.broadcasted_iota(jnp.int32, (B, T), 1) < lengths[:, None])
    pooled = (x * mask[..., None]).sum(1) / jnp.maximum(
        lengths[:, None].astype(jnp.float32), 1.0
    )
    return pooled


def decode_multi(
    params: Params,
    config: ModelConfig,
    tokens: jnp.ndarray,  # [B] int32 — current input token per slot
    start_pos: jnp.ndarray,  # [B] int32
    active: jnp.ndarray,  # [B] int32 0/1
    block_tables: jnp.ndarray,  # [B, max_blocks]
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    rng: jax.Array,
    temperature: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B]
    *,
    num_steps: int,
    use_kernel: bool = False,
    use_megakernel: bool = False,
    lora: Optional[Dict[str, Any]] = None,
    adapter_ids: Optional[jnp.ndarray] = None,
    want_logprobs: bool = True,
    min_p: Optional[jnp.ndarray] = None,  # [B]
    proc_params: Optional[Any] = None,  # logits_process.ProcParams
    proc_state: Optional[Any] = None,  # logits_process.ProcState
    num_top_logprobs: int = 0,  # >0 → also return top-N alternatives/step
    salts: Optional[jnp.ndarray] = None,  # [B] per-sequence sampling salt
    want_carry: bool = False,  # also return the device-resident carry
) -> Tuple[jnp.ndarray, ...]:
    """``num_steps`` fused decode iterations in ONE dispatch (lax.scan over
    single-token forward+sample steps). Minimizes host↔device round trips —
    the decisive factor on TPU where dispatch latency dwarfs a small model's
    step compute. Host-side stop conditions are applied afterwards at
    num_steps granularity (overshoot tokens are discarded; their KV writes
    beyond the table capacity are dropped by write_chunk_to_cache).

    When ``proc_params``/``proc_state`` are given (ops/logits_process.py),
    penalties/bias are applied before sampling and generated-token counts
    are carried through the scan.

    RNG: with ``salts`` the per-step sampling key for row b is derived from
    (rng, salts[b], position-of-sampled-token) — see
    ops/sampling.fold_row_keys. Noise then depends only on (seed, sequence,
    token index), never on dispatch order, which is the determinism
    contract the pipelined decode scheduler relies on. Without salts the
    legacy per-dispatch split keys are used (profiling scripts).

    Returns (tokens [B, num_steps], logprobs [B, num_steps], k_cache,
    v_cache[, proc_state][, carry_tokens [B], carry_pos [B]]). With
    ``num_top_logprobs`` = N > 0 the tuple gains (top_vals
    [B, num_steps, N], top_ids [B, num_steps, N]) right after the logprobs
    entry — the per-step top-N alternatives that back the OpenAI
    ``top_logprobs`` surface. With ``want_carry`` the final carry (last
    sampled token and advanced position per row) comes last — device
    arrays the runner feeds straight into the next burst without a host
    round trip.
    """
    from dynamo_tpu.ops import logits_process as lp
    from dynamo_tpu.ops.sampling import (
        compute_logprobs,
        fold_row_keys,
        sample_tokens,
        top_logprobs as top_logprobs_op,
    )

    def one(carry, step_rng):
        if proc_state is not None:
            toks, pos, k_c, v_c, st = carry
        else:
            toks, pos, k_c, v_c = carry
            st = None
        logits, k_c, v_c = forward_paged(
            params, config, toks[:, None], pos, active, block_tables, k_c, v_c,
            use_kernel=use_kernel, use_megakernel=use_megakernel, lora=lora,
            adapter_ids=adapter_ids,
        )
        if proc_params is not None:
            logits = lp.apply(logits, proc_params, st)
        if salts is not None:
            # The sampled token's index is pos + 1 (pos counts the tokens
            # before the current input token; the input occupies index pos)
            # — the same index the prefill program folds for the first
            # generated token, so preemption-by-recompute redraws
            # identical noise.
            row_keys = fold_row_keys(rng, salts, pos + 1)
            nxt = sample_tokens(
                logits, None, temperature, top_k, top_p, min_p,
                row_keys=row_keys,
            )
        else:
            nxt = sample_tokens(
                logits, step_rng, temperature, top_k, top_p, min_p
            )
        nxt = jnp.where(active > 0, nxt, toks)
        if want_logprobs:
            logp = compute_logprobs(logits, nxt)
        else:
            # Full-vocab log-softmax each step is pure waste when no active
            # request asked for logprobs (the common case).
            logp = jnp.zeros_like(nxt, dtype=jnp.float32)
        ys = (nxt, logp)
        if num_top_logprobs > 0:
            tv, ti = top_logprobs_op(logits, num_top_logprobs)
            ys = ys + (tv, ti)
        if st is not None:
            st = lp.record_tokens(st, nxt, active)
        pos = pos + active
        if st is not None:
            return (nxt, pos, k_c, v_c, st), ys
        return (nxt, pos, k_c, v_c), ys

    xs = None if salts is not None else jax.random.split(rng, num_steps)
    if proc_state is not None:
        (fin_toks, fin_pos, k_cache, v_cache, proc_state), ys = jax.lax.scan(
            one, (tokens, start_pos, k_cache, v_cache, proc_state), xs,
            length=num_steps,
        )
    else:
        (fin_toks, fin_pos, k_cache, v_cache), ys = jax.lax.scan(
            one, (tokens, start_pos, k_cache, v_cache), xs, length=num_steps
        )
    toks, logps = ys[0], ys[1]
    out: Tuple[jnp.ndarray, ...] = (toks.T, logps.T)
    if num_top_logprobs > 0:
        # scan stacks on axis 0 (steps) → [B, S, N]
        out = out + (ys[2].swapaxes(0, 1), ys[3].swapaxes(0, 1))
    out = out + (k_cache, v_cache)
    if proc_state is not None:
        out = out + (proc_state,)
    if want_carry:
        out = out + (fin_toks, fin_pos)
    return out
