"""Checkpoint quantization: fp params pytree → int8 weight-only pytree.

Walks the llama-family param tree (models/llama.py layout) and replaces each
matmul weight with the ``{"q8", "s"}`` pair from ops/quant.py; norms, biases
and the (tiny, precision-sensitive) MoE router stay in the original dtype.
The logical-axes tree is transformed in lockstep so parallel/sharding.py
rules apply unchanged — the scale inherits the weight's axes with the
contracted axis mapped to None (size 1 after keepdims).

Reference parity: quantized-checkpoint serving (ref:
recipes/llama-3-70b/README.md:7-11 FP8, docs/performance/tuning.md:50-57).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.ops.quant import is_q8, quantize_q8

# weight name → contracted axis (in the STACKED [L, ...] layout for layer
# weights; top-level weights as stored).
_LAYER_CONTRACT = {
    "wq": 1, "wk": 1, "wv": 1, "wo": 1,
    "w_gate": 1, "w_up": 1, "w_down": 1,
    "we_gate": 2, "we_up": 2, "we_down": 2,
}
_TOP_CONTRACT = {"embed": 1, "lm_head": 0}


def is_quantized(params: Any) -> bool:
    return any(
        is_q8(leaf)
        for leaf in jax.tree.leaves(params, is_leaf=is_q8)
        if isinstance(leaf, dict)
    )


def quantize_params(
    params: Dict[str, Any], param_axes: Optional[Dict[str, Any]] = None
) -> Tuple[Dict[str, Any], Optional[Dict[str, Any]]]:
    """Quantize a llama-family param tree (+ its logical-axes tree).

    Returns ``(qparams, qaxes)``; ``qaxes`` is None when ``param_axes`` is.
    Idempotent: already-quantized leaves pass through.
    """
    qparams: Dict[str, Any] = {}
    qaxes: Optional[Dict[str, Any]] = {} if param_axes is not None else None

    def put(dst, dst_axes, name, w, axes, contract):
        if contract is None:
            dst[name] = w
            if dst_axes is not None:
                dst_axes[name] = axes
            return
        # Idempotent: a pre-quantized leaf (e.g. loaded from the int8 weight
        # cache) passes through but still gets the {"q8","s"} axes pair.
        dst[name] = w if is_q8(w) else quantize_q8(w, (contract,))
        if dst_axes is not None:
            dst_axes[name] = {
                "q8": axes,
                "s": tuple(
                    None if i == contract else ax for i, ax in enumerate(axes)
                ),
            }

    for name, w in params.items():
        axes = param_axes[name] if param_axes is not None else None
        if name == "layers" and isinstance(w, dict):
            qlayers: Dict[str, Any] = {}
            qlaxes: Optional[Dict[str, Any]] = {} if qaxes is not None else None
            for lname, lw in w.items():
                put(
                    qlayers, qlaxes, lname, lw,
                    axes[lname] if axes is not None else None,
                    _LAYER_CONTRACT.get(lname),
                )
            qparams[name] = qlayers
            if qaxes is not None:
                qaxes[name] = qlaxes
        else:
            put(qparams, qaxes, name, w, axes, _TOP_CONTRACT.get(name))
    return qparams, qaxes


def init_quantized_params(config: ModelConfig, seed: int = 0) -> Dict[str, Any]:
    """Random-init DIRECTLY in int8 — no full-precision tree ever exists.

    For benchmarks/tests on random weights (weights don't affect
    throughput): int8 codes are drawn uniform in [-127, 127] host-side
    (orders of magnitude faster than fp normal init on one CPU core) and
    the per-channel scale is set so the dequantized std matches
    models/llama.py init_params' He-style scaling (uniform int8 std ≈ 73.3).
    Norms/biases/router stay fp as in quantize_params.
    """
    c = config
    rng = np.random.default_rng(seed)
    hd = c.head_dim_
    L, d, ff, H, KH = c.n_layers, c.d_model, c.d_ff, c.n_heads, c.n_kv_heads
    _INT8_STD = 73.3

    def q(shape, target_std, contract_axis):
        codes = rng.integers(-127, 128, size=shape, dtype=np.int8)
        s_shape = tuple(1 if i == contract_axis else n for i, n in enumerate(shape))
        scale = np.full(s_shape, target_std / _INT8_STD, dtype=np.float32)
        return {"q8": jnp.asarray(codes), "s": jnp.asarray(scale)}

    def fp(shape, fill=1.0):
        return jnp.full(shape, fill, dtype=c.dtype)

    norm_fill = 0.0 if c.rmsnorm_unit_offset else 1.0
    layers: Dict[str, Any] = {
        "attn_norm": fp((L, d), norm_fill),
        "wq": q((L, d, H * hd), d**-0.5, 1),
        "wk": q((L, d, KH * hd), d**-0.5, 1),
        "wv": q((L, d, KH * hd), d**-0.5, 1),
        "wo": q((L, H * hd, d), (H * hd) ** -0.5, 1),
        "mlp_norm": fp((L, d), norm_fill),
    }
    if c.post_norms:
        layers["attn_post_norm"] = fp((L, d), norm_fill)
        layers["mlp_post_norm"] = fp((L, d), norm_fill)
    if c.is_moe:
        E, eff = c.n_experts, c.moe_d_ff_
        layers["router_w"] = jnp.asarray(
            rng.normal(0, d**-0.5, size=(L, d, E)).astype(np.float32)
        ).astype(c.dtype)
        layers["we_gate"] = q((L, E, d, eff), d**-0.5, 2)
        layers["we_up"] = q((L, E, d, eff), d**-0.5, 2)
        layers["we_down"] = q((L, E, eff, d), eff**-0.5, 2)
    else:
        layers["w_gate"] = q((L, d, ff), d**-0.5, 1)
        layers["w_up"] = q((L, d, ff), d**-0.5, 1)
        layers["w_down"] = q((L, ff, d), ff**-0.5, 1)
    if c.qkv_bias:
        layers["bq"] = fp((L, H * hd), 0.0)
        layers["bk"] = fp((L, KH * hd), 0.0)
        layers["bv"] = fp((L, KH * hd), 0.0)
    if c.qk_norm:
        layers["q_norm"] = fp((L, hd), 1.0)
        layers["k_norm"] = fp((L, hd), 1.0)
    params: Dict[str, Any] = {
        "embed": q((c.vocab_size, d), 1.0, 1),
        "layers": layers,
        "final_norm": fp((d,), norm_fill),
    }
    if not c.tie_word_embeddings:
        params["lm_head"] = q((d, c.vocab_size), d**-0.5, 0)
    return params
