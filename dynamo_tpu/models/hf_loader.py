"""Load HuggingFace safetensors checkpoints into the functional param tree.

Reference parity: the reference's LocalModel/hub resolution
(lib/llm/src/local_model/, model_card.rs:178) hands weights to the engine;
here the engine is ours so we map HF names → our stacked-layer pytree.
Zero-egress environment: only local directories are supported; remote hub
fetch is a gated stub.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List

import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models.config import ModelConfig

_HF_LAYER_MAP = {
    # our name -> (hf suffix, transpose)
    "attn_norm": ("input_layernorm.weight", False),
    "wq": ("self_attn.q_proj.weight", True),
    "wk": ("self_attn.k_proj.weight", True),
    "wv": ("self_attn.v_proj.weight", True),
    "wo": ("self_attn.o_proj.weight", True),
    "bq": ("self_attn.q_proj.bias", False),
    "bk": ("self_attn.k_proj.bias", False),
    "bv": ("self_attn.v_proj.bias", False),
    "mlp_norm": ("post_attention_layernorm.weight", False),
    "w_gate": ("mlp.gate_proj.weight", True),
    "w_up": ("mlp.up_proj.weight", True),
    "w_down": ("mlp.down_proj.weight", True),
}


def _open_safetensors(model_dir: str):
    """Yield (name, numpy array) for every tensor in the checkpoint."""
    from safetensors import safe_open  # lazy: not needed for random-init paths

    index_path = os.path.join(model_dir, "model.safetensors.index.json")
    if os.path.exists(index_path):
        with open(index_path) as f:
            index = json.load(f)
        files = sorted(set(index["weight_map"].values()))
    else:
        files = [
            f for f in sorted(os.listdir(model_dir)) if f.endswith(".safetensors")
        ]
    for fname in files:
        with safe_open(os.path.join(model_dir, fname), framework="numpy") as f:
            for name in f.keys():
                yield name, f.get_tensor(name)


def load_hf_checkpoint(model_dir: str, config: ModelConfig) -> Dict[str, Any]:
    """Build the param pytree from a local HF model directory."""
    c = config
    raw: Dict[str, np.ndarray] = {}
    for name, tensor in _open_safetensors(model_dir):
        raw[name] = tensor

    def get(name: str) -> np.ndarray:
        for prefix in ("model.", ""):
            if prefix + name in raw:
                return raw[prefix + name]
        raise KeyError(f"missing tensor {name!r} in {model_dir}")

    def to_jnp(a: np.ndarray, transpose: bool) -> jnp.ndarray:
        if a.dtype == np.uint16:  # bf16 stored raw
            a = a.view(np.uint16)
            out = jnp.asarray(a).view(jnp.bfloat16)
        else:
            out = jnp.asarray(a)
        if transpose:
            out = out.T
        return out.astype(c.dtype)

    layer_names = list(_HF_LAYER_MAP)
    if not c.qkv_bias:
        layer_names = [n for n in layer_names if not n.startswith("b")]
    layers: Dict[str, List[jnp.ndarray]] = {n: [] for n in layer_names}
    for i in range(c.n_layers):
        for ours, (suffix, transpose) in _HF_LAYER_MAP.items():
            if ours not in layers:
                continue
            layers[ours].append(to_jnp(get(f"layers.{i}.{suffix}"), transpose))

    params: Dict[str, Any] = {
        "embed": to_jnp(get("embed_tokens.weight"), False),
        "layers": {n: jnp.stack(v) for n, v in layers.items()},
        "final_norm": to_jnp(get("norm.weight"), False),
    }
    if not c.tie_word_embeddings:
        params["lm_head"] = to_jnp(raw["lm_head.weight"], True)
    return params
