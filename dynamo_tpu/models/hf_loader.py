"""Load HuggingFace safetensors checkpoints into the functional param tree.

Reference parity: the reference's LocalModel/hub resolution
(lib/llm/src/local_model/, model_card.rs:178) hands weights to the engine;
here the engine is ours so we map HF names → our stacked-layer pytree.
Zero-egress environment: only local directories are supported; remote hub
fetch is a gated stub.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List

import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models.config import ModelConfig

_HF_LAYER_MAP = {
    # our name -> (hf suffix, transpose)
    "attn_norm": ("input_layernorm.weight", False),
    "wq": ("self_attn.q_proj.weight", True),
    "wk": ("self_attn.k_proj.weight", True),
    "wv": ("self_attn.v_proj.weight", True),
    "wo": ("self_attn.o_proj.weight", True),
    "bq": ("self_attn.q_proj.bias", False),
    "bk": ("self_attn.k_proj.bias", False),
    "bv": ("self_attn.v_proj.bias", False),
    "mlp_norm": ("post_attention_layernorm.weight", False),
    "w_gate": ("mlp.gate_proj.weight", True),
    "w_up": ("mlp.up_proj.weight", True),
    "w_down": ("mlp.down_proj.weight", True),
}


def _open_safetensors(model_dir: str):
    """Yield (name, numpy array) for every tensor in the checkpoint."""
    reader = _SafetensorsReader(model_dir)
    for name in reader.names():
        yield name, reader.get(name)


class _SafetensorsReader:
    """Lazy per-tensor access across a (possibly sharded) checkpoint.

    Tensors are fetched on demand so peak host memory during load is one
    tensor, not the whole checkpoint (load_hf_checkpoint walks layer by
    layer and devices-put or quantizes each before touching the next)."""

    def __init__(self, model_dir: str) -> None:
        from safetensors import safe_open  # lazy: unused by random-init paths

        self._open = safe_open
        self._dir = model_dir
        self._by_name: Dict[str, str] = {}  # tensor name → file path
        self._handles: Dict[str, Any] = {}
        index_path = os.path.join(model_dir, "model.safetensors.index.json")
        if os.path.exists(index_path):
            with open(index_path) as f:
                index = json.load(f)
            files = sorted(set(index["weight_map"].values()))
        else:
            files = [
                f for f in sorted(os.listdir(model_dir))
                if f.endswith(".safetensors")
            ]
        for fname in files:
            path = os.path.join(model_dir, fname)
            self._handles[fname] = self._open(path, framework="numpy")
            for name in self._handles[fname].keys():
                self._by_name[name] = fname

    def names(self):
        return list(self._by_name)

    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    def get(self, name: str) -> np.ndarray:
        return self._handles[self._by_name[name]].get_tensor(name)


def load_hf_checkpoint(
    model_dir: str, config: ModelConfig, *, quantization: str | None = None
) -> Dict[str, Any]:
    """Build the param pytree from a local HF model directory.

    ``quantization="int8"``: each matmul weight is quantized PER LAYER on
    the host (numpy) before stacking/device-put, so full-precision weights
    never reach HBM — peak device memory is the int8 tree, peak host memory
    one fp32 layer tensor. Per-layer quantization is bit-identical to
    quantizing the stacked tensor (scales never span the layer axis).
    """
    from dynamo_tpu.models.quantize import _LAYER_CONTRACT, _TOP_CONTRACT
    from dynamo_tpu.ops.quant import quantize_q8

    if quantization not in (None, "int8"):
        raise ValueError(f"unsupported quantization {quantization!r}")
    c = config
    raw = _SafetensorsReader(model_dir)

    def get_first(*names: str) -> np.ndarray:
        for n in names:
            for prefix in ("model.", ""):
                if prefix + n in raw:
                    return raw.get(prefix + n)
        raise KeyError(f"none of {names!r} in {model_dir}")

    def get(name: str) -> np.ndarray:
        return get_first(name)

    def to_np(a: np.ndarray, transpose: bool) -> np.ndarray:
        if a.dtype == np.uint16:  # bf16 stored raw
            import ml_dtypes

            a = a.view(ml_dtypes.bfloat16)
        if transpose:
            a = a.T
        return a

    def to_jnp(a: np.ndarray, transpose: bool) -> jnp.ndarray:
        return jnp.asarray(to_np(a, transpose)).astype(c.dtype)

    layer_map = dict(_HF_LAYER_MAP)
    if c.post_norms:
        # Gemma-2 norm naming: post_attention_layernorm is a true POST-attn
        # norm (llama reuses that name for the pre-MLP norm).
        layer_map["attn_post_norm"] = ("post_attention_layernorm.weight", False)
        layer_map["mlp_norm"] = ("pre_feedforward_layernorm.weight", False)
        layer_map["mlp_post_norm"] = ("post_feedforward_layernorm.weight", False)
    if c.qk_norm:
        layer_map["q_norm"] = ("self_attn.q_norm.weight", False)
        layer_map["k_norm"] = ("self_attn.k_norm.weight", False)
    layer_names = list(layer_map)
    if not c.qkv_bias:
        layer_names = [n for n in layer_names if not n.startswith("b")]
    if c.is_moe:
        # Expert FFNs replace the dense MLP (mapped separately below).
        layer_names = [
            n for n in layer_names if n not in ("w_gate", "w_up", "w_down")
        ]
        layer_names += ["router_w", "we_gate", "we_up", "we_down"]
        if any("shared_expert" in n for n in raw.names()):
            # Qwen1.5/Qwen2-MoE carry a shared expert the routed forward
            # (ops/moe.py) does not model; loading would silently drop
            # those weights and serve wrong logits.
            raise ValueError(
                "checkpoint has shared-expert tensors (Qwen1.5/Qwen2-MoE "
                "layout); shared experts are not supported — serve a "
                "routed-experts-only family (Mixtral layout)"
            )
    layers: Dict[str, List[Any]] = {n: [] for n in layer_names}

    def moe_layer(i: int) -> Dict[str, Any]:
        """Map one MoE layer: Mixtral (block_sparse_moe.gate +
        experts.{e}.w1/w3/w2) or Qwen-MoE (mlp.gate + experts.{e}.
        gate_proj/up_proj/down_proj) naming (ref: the reference serves
        these checkpoints through its engines — recipes/deepseek-r1/
        README.md:9-12 headlines MoE; HF layouts are the public contract).

        Experts are processed ONE AT A TIME (quantized or cast before the
        next is touched): a Mixtral-8x7B layer's experts are ~1.4 B params
        — materializing them all in fp32 would be ~5.6 GB of host RAM per
        layer."""
        L = f"layers.{i}"
        router = to_np(
            get_first(
                f"{L}.block_sparse_moe.gate.weight", f"{L}.mlp.gate.weight"
            ),
            True,
        )  # [d, E]
        out: Dict[str, Any] = {"router_w": jnp.asarray(router).astype(c.dtype)}
        hf_names = {
            "we_gate": ("w1", "gate_proj"),
            "we_up": ("w3", "up_proj"),
            "we_down": ("w2", "down_proj"),
        }
        for ours, (mixtral, qwen) in hf_names.items():
            experts = []
            for e in range(c.n_experts):
                a = to_np(
                    get_first(
                        f"{L}.block_sparse_moe.experts.{e}.{mixtral}.weight",
                        f"{L}.mlp.experts.{e}.{qwen}.weight",
                    ),
                    True,
                )  # gate/up: [d, eff]; down: [eff, d]
                if quantization:
                    # per-expert quantization == stacked quantization: the
                    # contract axis (we_*: stacked axis 2 → per-layer 1 →
                    # per-expert 0) never spans the expert axis.
                    experts.append(quantize_q8(np.asarray(a), (0,)))
                else:
                    # narrow to the serving dtype per expert — stacking
                    # fp32 first would peak at ~4× the layer's final bytes
                    experts.append(np.asarray(jnp.asarray(a).astype(c.dtype)))
            if quantization:
                out[ours] = {
                    "q8": np.stack([x["q8"] for x in experts]),
                    "s": np.stack([x["s"] for x in experts]),
                }
            else:
                out[ours] = jnp.asarray(np.stack(experts)).astype(c.dtype)
        return out

    for i in range(c.n_layers):
        if c.is_moe:
            for ours, arr in moe_layer(i).items():
                layers[ours].append(arr)
        for ours, (suffix, transpose) in layer_map.items():
            if ours not in layers:
                continue
            a = to_np(get(f"layers.{i}.{suffix}"), transpose)
            if quantization and ours in _LAYER_CONTRACT:
                # stacked contract axis minus the leading L axis
                layers[ours].append(
                    quantize_q8(np.asarray(a), (_LAYER_CONTRACT[ours] - 1,))
                )
            else:
                layers[ours].append(jnp.asarray(a).astype(c.dtype))

    def stack(name: str, leaves: List[Any]) -> Any:
        if leaves and isinstance(leaves[0], dict):
            return {
                "q8": jnp.asarray(np.stack([l["q8"] for l in leaves])),
                "s": jnp.asarray(np.stack([l["s"] for l in leaves])),
            }
        return jnp.stack(leaves)

    def top(name: str, a: np.ndarray, transpose: bool) -> Any:
        if quantization and name in _TOP_CONTRACT:
            q = quantize_q8(
                np.asarray(to_np(a, transpose)), (_TOP_CONTRACT[name],)
            )
            return {"q8": jnp.asarray(q["q8"]), "s": jnp.asarray(q["s"])}
        return to_jnp(a, transpose)

    params: Dict[str, Any] = {
        "embed": top("embed", get("embed_tokens.weight"), False),
        "layers": {n: stack(n, v) for n, v in layers.items()},
        "final_norm": to_jnp(get("norm.weight"), False),
    }
    if not c.tie_word_embeddings:
        params["lm_head"] = top("lm_head", get("lm_head.weight"), True)
    return params
