"""Model zoo: TPU-native (pure-JAX, scan-over-layers, paged-KV) LLMs.

Where the reference adapts external engines (vLLM/SGLang/TRT-LLM) per model
family, this framework ships its own jit-compiled model implementations. The
llama module covers the dense decoder family (Llama-2/3, Qwen2/2.5, Mistral —
differing only in config: GQA ratio, rope theta, qkv bias, tied embeddings).
"""

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models import llama

__all__ = ["ModelConfig", "llama"]
