"""Model architecture config.

One dataclass describes the dense decoder family; `from_hf_config` ingests a
HuggingFace `config.json` (llama / qwen2 / mistral architectures), which is
what the reference's ModelDeploymentCard resolves from the hub
(ref: lib/llm/src/model_card.rs:178, local_model/).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax.numpy as jnp


@dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    head_dim: Optional[int] = None  # defaults to d_model // n_heads
    d_ff: int = 14336
    rms_norm_eps: float = 1e-5
    rope_theta: float = 500000.0
    max_position_embeddings: int = 8192
    qkv_bias: bool = False  # Qwen2-style
    # Qwen3-style per-head RMSNorm on q and k (over head_dim, before RoPE).
    qk_norm: bool = False
    tie_word_embeddings: bool = False
    # MoE knobs (0 experts = dense). Covers Mixtral/Qwen-MoE/DeepSeek-lite
    # shapes: every layer's FFN becomes top-k routed experts (ops/moe.py).
    n_experts: int = 0
    n_experts_per_tok: int = 2
    moe_d_ff: Optional[int] = None  # expert hidden dim (default: d_ff)
    norm_topk_prob: bool = True
    moe_capacity_factor: float = 2.0
    eos_token_ids: List[int] = field(default_factory=list)
    bos_token_id: Optional[int] = None
    dtype: Any = jnp.bfloat16
    name: str = "llama"
    # Gemma-family knobs (defaults = llama semantics):
    act_fn: str = "silu"  # "silu" | "gelu_tanh"
    rmsnorm_unit_offset: bool = False  # weight stored as (w - 1), apply 1+w
    post_norms: bool = False  # extra norms AFTER attention and FFN blocks
    embed_scale: bool = False  # multiply embeddings by sqrt(d_model)
    attn_logit_softcap: Optional[float] = None  # cap·tanh(s/cap) on scores
    final_logit_softcap: Optional[float] = None  # same on lm_head logits
    query_scale: Optional[float] = None  # q·scale⁻⁰·⁵ (query_pre_attn_scalar)
    # Sliding-window attention: window size in tokens (None = full) applied
    # to layers where ``layer_idx % sliding_window_every == 0`` (1 = all
    # layers, Mistral-style; 2 = alternating, Gemma-2-style).
    sliding_window: Optional[int] = None
    sliding_window_every: int = 1
    # HF-style pattern (Gemma-3): layer i is WINDOWED unless
    # (i + 1) % sliding_window_pattern == 0 (i.e. every pattern-th layer is
    # global — the 5:1 local/global layout). Takes precedence over
    # sliding_window_every when set.
    sliding_window_pattern: Optional[int] = None
    # Authoritative per-layer window list (overrides every pattern knob):
    # ingested verbatim from an HF ``layer_types`` list, so aperiodic
    # layouts are honored exactly.
    layer_window_overrides: Optional[List[int]] = None
    # Gemma-3 dual-frequency RoPE: LOCAL (windowed) layers use this theta;
    # global layers use rope_theta (optionally linearly position-scaled by
    # rope_scaling_factor, the HF rope_scaling={linear, factor} dialect).
    rope_local_theta: Optional[float] = None
    rope_scaling_factor: Optional[float] = None

    @property
    def head_dim_(self) -> int:
        return self.head_dim if self.head_dim is not None else self.d_model // self.n_heads

    @property
    def q_per_kv(self) -> int:
        return self.n_heads // self.n_kv_heads

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0

    @property
    def moe_d_ff_(self) -> int:
        return self.moe_d_ff if self.moe_d_ff is not None else self.d_ff

    def layer_windows(self) -> List[int]:
        """Per-layer attention window (0 = unlimited)."""
        if self.layer_window_overrides is not None:
            assert len(self.layer_window_overrides) == self.n_layers
            return list(self.layer_window_overrides)
        if not self.sliding_window:
            return [0] * self.n_layers
        if self.sliding_window_pattern:
            p = self.sliding_window_pattern
            return [
                self.sliding_window if (i + 1) % p != 0 else 0
                for i in range(self.n_layers)
            ]
        return [
            self.sliding_window if i % max(self.sliding_window_every, 1) == 0 else 0
            for i in range(self.n_layers)
        ]

    @classmethod
    def from_hf_config(cls, cfg: Dict[str, Any], name: str = "") -> "ModelConfig":
        archs = cfg.get("architectures") or [""]
        arch = archs[0].lower()
        eos = cfg.get("eos_token_id")
        if eos is None:
            eos_ids: List[int] = []
        elif isinstance(eos, list):
            eos_ids = [int(e) for e in eos]
        else:
            eos_ids = [int(eos)]
        # MoE fields across HF dialects: Mixtral (num_local_experts),
        # Qwen-MoE (num_experts + moe_intermediate_size + norm_topk_prob)
        n_experts = cfg.get("num_local_experts") or cfg.get("num_experts") or 0
        model_type = str(cfg.get("model_type", ""))
        # Gemma-family: unit-offset norms, GeGLU, scaled/tied embeddings.
        # Gemma-2 ADDS post-norms, softcaps and 1:1 local/global layers;
        # Gemma-3 swaps softcaps for qk-norm, 5:1 local/global layers and
        # dual-frequency RoPE (implemented since r5).
        gemma = "gemma" in arch or "gemma" in model_type
        gemma2 = "gemma2" in arch or model_type == "gemma2"
        # Gemma-3 (text): gemma-2 layout + qk-norm, 5:1 local/global layers
        # (sliding_window_pattern / layer_types), dual-frequency RoPE
        # (rope_local_base_freq on windowed layers), softcaps removed.
        gemma3 = "gemma3" in arch or "gemma3" in model_type
        swp = cfg.get("sliding_window_pattern") or cfg.get(
            "_sliding_window_pattern"
        )
        # (gated: a vestigial sliding_window behind use_sliding_window=false
        # must not re-enter through the layer_types path either)
        _gated_window = (
            cfg.get("sliding_window")
            if cfg.get("use_sliding_window", True)
            else None
        )
        window_overrides = None
        if cfg.get("layer_types") and _gated_window:
            # layer_types is the authoritative per-layer layout — honor it
            # VERBATIM (aperiodic lists included) instead of inferring a
            # period from it.
            window_overrides = [
                int(_gated_window) if t == "sliding_attention" else 0
                for t in cfg["layer_types"]
            ]
        if gemma3 and not swp and window_overrides is None:
            # A gemma-3 config carrying neither field would silently fall
            # through to every-layer-windowed — the garbage-logits mode the
            # old refusal existed to prevent.
            raise ValueError(
                "gemma-3 config carries neither sliding_window_pattern nor "
                "layer_types; cannot determine the local/global layer layout"
            )
        rope_scaling = cfg.get("rope_scaling") or {}
        rope_factor = (
            float(rope_scaling.get("factor"))
            if rope_scaling.get("rope_type", rope_scaling.get("type")) == "linear"
            and rope_scaling.get("factor")
            else None
        )
        # Some configs (Qwen2 dialect) carry a vestigial sliding_window with
        # an explicit use_sliding_window=false gate — honor the gate.
        sliding = (
            cfg.get("sliding_window")
            if cfg.get("use_sliding_window", True)
            else None
        )
        return cls(
            vocab_size=cfg["vocab_size"],
            d_model=cfg["hidden_size"],
            n_layers=cfg["num_hidden_layers"],
            n_heads=cfg["num_attention_heads"],
            n_kv_heads=cfg.get("num_key_value_heads", cfg["num_attention_heads"]),
            head_dim=cfg.get("head_dim"),
            d_ff=cfg["intermediate_size"],
            n_experts=int(n_experts),
            n_experts_per_tok=int(cfg.get("num_experts_per_tok", 2)),
            moe_d_ff=cfg.get("moe_intermediate_size"),
            norm_topk_prob=bool(cfg.get("norm_topk_prob", True)),
            rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
            rope_theta=cfg.get("rope_theta", 10000.0),
            max_position_embeddings=cfg.get("max_position_embeddings", 8192),
            qkv_bias="qwen2" in arch and "qwen3" not in arch,
            qk_norm="qwen3" in arch or model_type == "qwen3" or gemma3,
            tie_word_embeddings=cfg.get("tie_word_embeddings", gemma),
            eos_token_ids=eos_ids,
            bos_token_id=cfg.get("bos_token_id"),
            name=name or cfg.get("model_type", "llama"),
            # Gemma-2 (ref: the HF Gemma2 config dialect)
            # Prefer the modern 'hidden_activation' key ('or', not a dict
            # default: real Gemma-1 hub configs carry an explicit
            # hidden_activation: null beside hidden_act). HF forces tanh-gelu
            # for the gemma family regardless of hidden_act, so plain 'gelu'
            # and an unset gemma config both resolve to gelu_tanh.
            act_fn=(
                "gelu_tanh"
                if (
                    (cfg.get("hidden_activation") or cfg.get("hidden_act"))
                    in ("gelu_pytorch_tanh", "gelu_tanh", "gelu")
                    or (
                        gemma
                        and not cfg.get("hidden_activation")
                        and not cfg.get("hidden_act")
                    )
                )
                else "silu"
            ),
            rmsnorm_unit_offset=gemma,
            post_norms=gemma2 or gemma3,
            embed_scale=gemma,
            attn_logit_softcap=cfg.get("attn_logit_softcapping"),
            final_logit_softcap=cfg.get("final_logit_softcapping"),
            query_scale=cfg.get("query_pre_attn_scalar"),
            sliding_window=int(sliding) if sliding else None,
            sliding_window_every=2 if gemma2 else 1,
            sliding_window_pattern=(
                int(swp) if (gemma3 and swp and window_overrides is None)
                else None
            ),
            layer_window_overrides=window_overrides,
            rope_local_theta=(
                float(cfg.get("rope_local_base_freq", 10000.0))
                if gemma3 else None
            ),
            rope_scaling_factor=rope_factor,
        )

    @classmethod
    def from_model_dir(cls, path: str) -> "ModelConfig":
        with open(os.path.join(path, "config.json")) as f:
            return cls.from_hf_config(json.load(f), name=os.path.basename(path.rstrip("/")))


# Handy known shapes for tests/benchmarks (no downloads in this environment).
def tiny_config(**overrides) -> ModelConfig:
    base = dict(
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=256,
        max_position_embeddings=512,
        eos_token_ids=[2],
        dtype=jnp.float32,
        name="tiny-llama",
    )
    base.update(overrides)
    return ModelConfig(**base)


def tiny_moe_config(**overrides) -> ModelConfig:
    base = dict(
        n_experts=4,
        n_experts_per_tok=2,
        moe_d_ff=128,
        name="tiny-moe",
    )
    base.update(overrides)
    return tiny_config(**base)


def mixtral_8x7b_config() -> ModelConfig:
    """Mixtral-8x7B shape (BASELINE MoE class; ref: recipes/ MoE configs)."""
    return ModelConfig(
        vocab_size=32000,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14336,
        n_experts=8,
        n_experts_per_tok=2,
        rope_theta=1000000.0,
        max_position_embeddings=32768,
        eos_token_ids=[2],
        name="mixtral-8x7b",
    )


def qwen2_500m_config() -> ModelConfig:
    """Qwen2.5-0.5B shape (SURVEY §7 stage 5 first real model)."""
    return ModelConfig(
        vocab_size=151936,
        d_model=896,
        n_layers=24,
        n_heads=14,
        n_kv_heads=2,
        d_ff=4864,
        rope_theta=1000000.0,
        max_position_embeddings=32768,
        qkv_bias=True,
        tie_word_embeddings=True,
        eos_token_ids=[151645],
        name="qwen2.5-0.5b",
    )


def llama3_8b_config() -> ModelConfig:
    return ModelConfig(
        vocab_size=128256,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14336,
        rope_theta=500000.0,
        max_position_embeddings=8192,
        eos_token_ids=[128001, 128009],
        name="llama-3-8b",
    )


def qwen3_8b_config() -> ModelConfig:
    """Qwen3-8B shape (HF Qwen/Qwen3-8B config.json values): qk-norm,
    no qkv bias, head_dim 128 — the architecture family of the reference's
    only hard in-tree perf anchor (aiconfigurator Qwen3-32B,
    docs/performance/aiconfigurator.md:55-59)."""
    return ModelConfig(
        vocab_size=151936,
        d_model=4096,
        n_layers=36,
        n_heads=32,
        n_kv_heads=8,
        head_dim=128,
        d_ff=12288,
        rms_norm_eps=1e-6,
        rope_theta=1000000.0,
        max_position_embeddings=40960,
        qk_norm=True,
        eos_token_ids=[151645],
        name="qwen3-8b",
    )


def llama3_3b_config() -> ModelConfig:
    """Llama-3.2-3B shape (HF meta-llama/Llama-3.2-3B config.json values).
    The largest dense shape whose bf16 AND int8 forms both fit one 16 GB
    chip — the apples-to-apples proof shape for weight-only quantization."""
    return ModelConfig(
        vocab_size=128256,
        d_model=3072,
        n_layers=28,
        n_heads=24,
        n_kv_heads=8,
        head_dim=128,
        d_ff=8192,
        rope_theta=500000.0,
        max_position_embeddings=8192,
        tie_word_embeddings=True,
        eos_token_ids=[128001, 128009],
        name="llama-3.2-3b",
    )


def llama3_70b_config() -> ModelConfig:
    return ModelConfig(
        vocab_size=128256,
        d_model=8192,
        n_layers=80,
        n_heads=64,
        n_kv_heads=8,
        d_ff=28672,
        rope_theta=500000.0,
        max_position_embeddings=8192,
        eos_token_ids=[128001, 128009],
        name="llama-3-70b",
    )


def gemma3_1b_config() -> ModelConfig:
    """Gemma-3-1B text shape (HF google/gemma-3-1b-it config.json values):
    5:1 local/global layers, dual-frequency RoPE, qk-norm."""
    return ModelConfig(
        vocab_size=262144,
        d_model=1152,
        n_layers=26,
        n_heads=4,
        n_kv_heads=1,
        head_dim=256,
        d_ff=6912,
        rms_norm_eps=1e-6,
        rope_theta=1000000.0,
        rope_local_theta=10000.0,
        max_position_embeddings=32768,
        qk_norm=True,
        tie_word_embeddings=True,
        act_fn="gelu_tanh",
        rmsnorm_unit_offset=True,
        post_norms=True,
        embed_scale=True,
        query_scale=256,
        sliding_window=512,
        sliding_window_pattern=6,
        eos_token_ids=[1, 106],
        name="gemma-3-1b",
    )


def all_presets() -> Dict[str, "ModelConfig"]:
    """Every named preset, keyed by its ``name``. The megakernel
    supports-matrix test iterates THIS registry (a new preset is
    automatically checked against the fused path's supports() gate or
    the documented-exclusion table — it can never silently drift to the
    slow decode path), and bench.py's BENCH_MODEL knob resolves from the
    same names."""
    presets = [
        tiny_config(), tiny_moe_config(), mixtral_8x7b_config(),
        qwen2_500m_config(), llama3_8b_config(), llama3_3b_config(),
        llama3_70b_config(), qwen3_8b_config(), gemma3_1b_config(),
        gemma2_2b_config(),
    ]
    return {c.name: c for c in presets}


def gemma2_2b_config() -> ModelConfig:
    """Gemma-2-2B shape (HF google/gemma-2-2b config.json values)."""
    return ModelConfig(
        vocab_size=256000,
        d_model=2304,
        n_layers=26,
        n_heads=8,
        n_kv_heads=4,
        head_dim=256,
        d_ff=9216,
        rms_norm_eps=1e-6,
        rope_theta=10000.0,
        max_position_embeddings=8192,
        tie_word_embeddings=True,
        eos_token_ids=[1, 107],
        name="gemma-2-2b",
        act_fn="gelu_tanh",
        rmsnorm_unit_offset=True,
        post_norms=True,
        embed_scale=True,
        attn_logit_softcap=50.0,
        final_logit_softcap=30.0,
        query_scale=256.0,
        sliding_window=4096,
        sliding_window_every=2,
    )
