"""Model architecture config.

One dataclass describes the dense decoder family; `from_hf_config` ingests a
HuggingFace `config.json` (llama / qwen2 / mistral architectures), which is
what the reference's ModelDeploymentCard resolves from the hub
(ref: lib/llm/src/model_card.rs:178, local_model/).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax.numpy as jnp


@dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    head_dim: Optional[int] = None  # defaults to d_model // n_heads
    d_ff: int = 14336
    rms_norm_eps: float = 1e-5
    rope_theta: float = 500000.0
    max_position_embeddings: int = 8192
    qkv_bias: bool = False  # Qwen2-style
    tie_word_embeddings: bool = False
    # MoE knobs (0 experts = dense). Covers Mixtral/Qwen-MoE/DeepSeek-lite
    # shapes: every layer's FFN becomes top-k routed experts (ops/moe.py).
    n_experts: int = 0
    n_experts_per_tok: int = 2
    moe_d_ff: Optional[int] = None  # expert hidden dim (default: d_ff)
    norm_topk_prob: bool = True
    moe_capacity_factor: float = 2.0
    eos_token_ids: List[int] = field(default_factory=list)
    bos_token_id: Optional[int] = None
    dtype: Any = jnp.bfloat16
    name: str = "llama"

    @property
    def head_dim_(self) -> int:
        return self.head_dim if self.head_dim is not None else self.d_model // self.n_heads

    @property
    def q_per_kv(self) -> int:
        return self.n_heads // self.n_kv_heads

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0

    @property
    def moe_d_ff_(self) -> int:
        return self.moe_d_ff if self.moe_d_ff is not None else self.d_ff

    @classmethod
    def from_hf_config(cls, cfg: Dict[str, Any], name: str = "") -> "ModelConfig":
        archs = cfg.get("architectures") or [""]
        arch = archs[0].lower()
        eos = cfg.get("eos_token_id")
        if eos is None:
            eos_ids: List[int] = []
        elif isinstance(eos, list):
            eos_ids = [int(e) for e in eos]
        else:
            eos_ids = [int(eos)]
        # MoE fields across HF dialects: Mixtral (num_local_experts),
        # Qwen-MoE (num_experts + moe_intermediate_size + norm_topk_prob)
        n_experts = cfg.get("num_local_experts") or cfg.get("num_experts") or 0
        return cls(
            vocab_size=cfg["vocab_size"],
            d_model=cfg["hidden_size"],
            n_layers=cfg["num_hidden_layers"],
            n_heads=cfg["num_attention_heads"],
            n_kv_heads=cfg.get("num_key_value_heads", cfg["num_attention_heads"]),
            head_dim=cfg.get("head_dim"),
            d_ff=cfg["intermediate_size"],
            n_experts=int(n_experts),
            n_experts_per_tok=int(cfg.get("num_experts_per_tok", 2)),
            moe_d_ff=cfg.get("moe_intermediate_size"),
            norm_topk_prob=bool(cfg.get("norm_topk_prob", True)),
            rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
            rope_theta=cfg.get("rope_theta", 10000.0),
            max_position_embeddings=cfg.get("max_position_embeddings", 8192),
            qkv_bias="qwen2" in arch,
            tie_word_embeddings=cfg.get("tie_word_embeddings", False),
            eos_token_ids=eos_ids,
            bos_token_id=cfg.get("bos_token_id"),
            name=name or cfg.get("model_type", "llama"),
        )

    @classmethod
    def from_model_dir(cls, path: str) -> "ModelConfig":
        with open(os.path.join(path, "config.json")) as f:
            return cls.from_hf_config(json.load(f), name=os.path.basename(path.rstrip("/")))


# Handy known shapes for tests/benchmarks (no downloads in this environment).
def tiny_config(**overrides) -> ModelConfig:
    base = dict(
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=256,
        max_position_embeddings=512,
        eos_token_ids=[2],
        dtype=jnp.float32,
        name="tiny-llama",
    )
    base.update(overrides)
    return ModelConfig(**base)


def tiny_moe_config(**overrides) -> ModelConfig:
    base = dict(
        n_experts=4,
        n_experts_per_tok=2,
        moe_d_ff=128,
        name="tiny-moe",
    )
    base.update(overrides)
    return tiny_config(**base)


def mixtral_8x7b_config() -> ModelConfig:
    """Mixtral-8x7B shape (BASELINE MoE class; ref: recipes/ MoE configs)."""
    return ModelConfig(
        vocab_size=32000,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14336,
        n_experts=8,
        n_experts_per_tok=2,
        rope_theta=1000000.0,
        max_position_embeddings=32768,
        eos_token_ids=[2],
        name="mixtral-8x7b",
    )


def qwen2_500m_config() -> ModelConfig:
    """Qwen2.5-0.5B shape (SURVEY §7 stage 5 first real model)."""
    return ModelConfig(
        vocab_size=151936,
        d_model=896,
        n_layers=24,
        n_heads=14,
        n_kv_heads=2,
        d_ff=4864,
        rope_theta=1000000.0,
        max_position_embeddings=32768,
        qkv_bias=True,
        tie_word_embeddings=True,
        eos_token_ids=[151645],
        name="qwen2.5-0.5b",
    )


def llama3_8b_config() -> ModelConfig:
    return ModelConfig(
        vocab_size=128256,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14336,
        rope_theta=500000.0,
        max_position_embeddings=8192,
        eos_token_ids=[128001, 128009],
        name="llama-3-8b",
    )


def llama3_70b_config() -> ModelConfig:
    return ModelConfig(
        vocab_size=128256,
        d_model=8192,
        n_layers=80,
        n_heads=64,
        n_kv_heads=8,
        d_ff=28672,
        rope_theta=500000.0,
        max_position_embeddings=8192,
        eos_token_ids=[128001, 128009],
        name="llama-3-70b",
    )
