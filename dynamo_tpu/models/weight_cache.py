"""Sharded-weight cache for fast worker restart (GMS role).

Reference parity: the GPU Memory Service + chrek role
(lib/gpu_memory_service/README.md:1-60, deploy/chrek/) — the reference
keeps weights resident OUTSIDE the worker so a crashed worker remaps
instead of reloading. Two tiers here:

  1. **Shared-memory tier** (``SHM_CACHE_DIR``, tmpfs): the engine-ready
     pytree as raw mmap-able .npy leaves in RAM. The pages belong to the
     kernel page cache, not the worker — a SIGKILLed worker's replacement
     mmaps the same physical pages with zero copies and zero disk I/O.
     This is the GMS ownership model, TPU-style: on TPU the weights' device
     residency dies with the process (the runtime frees HBM), so what can
     survive — and what is expensive — is the host-side ingest
     (safetensors walk, name mapping, transposes, casts, quantization).
  2. **Disk tier** (``DEFAULT_CACHE_DIR``): same format, survives reboot.

A respawned worker mmaps straight into device transfer — no safetensors
walk, no per-tensor transform, no requantization.

Cache key = (checkpoint dir identity, config fingerprint), so a changed
checkpoint or config never serves stale weights.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DEFAULT_CACHE_DIR = os.path.expanduser("~/.cache/dynamo_tpu/weights")
# tmpfs weight residency (GMS role): RAM-backed, survives worker death.
# None (tier disabled) when the host has no tmpfs mount — a disk-backed
# "shm" directory would just duplicate the disk tier.
SHM_CACHE_DIR = (
    "/dev/shm/dynamo_tpu/weights" if os.path.isdir("/dev/shm") else None
)


def _fingerprint(model_dir: str, config: ModelConfig) -> str:
    h = hashlib.blake2b(digest_size=16)
    h.update(os.path.abspath(model_dir).encode())
    try:
        for name in sorted(os.listdir(model_dir)):
            if name.endswith((".safetensors", ".json")):
                st = os.stat(os.path.join(model_dir, name))
                h.update(f"{name}:{st.st_size}:{int(st.st_mtime)}".encode())
    except OSError:
        pass
    cfg = {k: str(v) for k, v in sorted(vars(config).items())}
    h.update(json.dumps(cfg, sort_keys=True).encode())
    return h.hexdigest()


def _flatten(tree: Any, prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}."))
    else:
        out[prefix[:-1]] = tree
    return out


def _unflatten(flat: Dict[str, Any]) -> Dict[str, Any]:
    tree: Dict[str, Any] = {}
    for key, value in flat.items():
        parts = key.split(".")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return tree


def save_params(cache_dir: str, key: str, params: Any) -> str:
    """Persist a param pytree as raw .npy leaves + manifest. Returns path."""
    import shutil

    root = os.path.join(cache_dir, key)
    tmp = root + ".tmp"
    try:
        os.makedirs(tmp, exist_ok=True)
        manifest: Dict[str, Any] = {"leaves": {}}
        for name, leaf in _flatten(params).items():
            arr = np.asarray(leaf)
            dtype = str(arr.dtype)
            if dtype == "bfloat16":  # raw bytes; np.save handles ml_dtypes,
                arr = arr.view(np.uint16)  # raw u16 keeps loads dependency-lean
            fname = name.replace("/", "_") + ".npy"
            np.save(os.path.join(tmp, fname), arr, allow_pickle=False)
            manifest["leaves"][name] = {"file": fname, "dtype": dtype,
                                        "shape": list(np.asarray(leaf).shape)}
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump(manifest, f)
    except BaseException:
        # A half-written tmp dir must not linger — on the tmpfs tier it
        # would pin RAM until reboot (and retry on every restart).
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    # Atomic publish: a crashed writer never leaves a half cache.
    if os.path.exists(root):
        shutil.rmtree(root)
    os.replace(tmp, root)
    logger.info("weight cache written: %s (%d leaves)", root, len(manifest["leaves"]))
    return root


def load_params(cache_dir: str, key: str) -> Optional[Dict[str, Any]]:
    """mmap-load a cached pytree; None if absent/corrupt."""
    root = os.path.join(cache_dir, key)
    manifest_path = os.path.join(root, "manifest.json")
    if not os.path.exists(manifest_path):
        return None
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
        flat: Dict[str, Any] = {}
        for name, meta in manifest["leaves"].items():
            arr = np.load(os.path.join(root, meta["file"]), mmap_mode="r",
                          allow_pickle=False)
            if meta["dtype"] == "bfloat16":
                import ml_dtypes

                arr = arr.view(ml_dtypes.bfloat16)
            flat[name] = jax.numpy.asarray(arr)
        return _unflatten(flat)
    except (OSError, KeyError, ValueError) as exc:
        logger.warning("weight cache %s unreadable (%s); ignoring", root, exc)
        return None


def load_checkpoint_cached(
    model_dir: str,
    config: ModelConfig,
    *,
    cache_dir: str = DEFAULT_CACHE_DIR,
    quantization: str | None = None,
    shm_dir: str | None = SHM_CACHE_DIR,
) -> Tuple[Dict[str, Any], bool]:
    """HF checkpoint → engine pytree, through the restart caches.

    Lookup order: shared-memory tier (RAM pages surviving worker death —
    the GMS role) → disk tier → full HF ingest. Misses repopulate every
    tier above them. Quantized loads cache the QUANTIZED tree under a
    distinct key — restarts skip requantization and the cache holds int8
    (half the bytes). Returns (params, was_cache_hit)."""
    key = _fingerprint(model_dir, config) + (f"-{quantization}" if quantization else "")
    if shm_dir:
        cached = load_params(shm_dir, key)
        if cached is not None:
            logger.info("weight SHM hit for %s (RAM-resident, GMS role)", model_dir)
            return cached, True
    cached = load_params(cache_dir, key)
    if cached is not None:
        logger.info("weight cache hit for %s", model_dir)
        if shm_dir:
            _try_save(shm_dir, key, cached)
        return cached, True
    from dynamo_tpu.models.hf_loader import load_hf_checkpoint

    params = load_hf_checkpoint(model_dir, config, quantization=quantization)
    _try_save(cache_dir, key, params)
    if shm_dir:
        _try_save(shm_dir, key, params)
    return params, False


def _try_save(cache_dir: str, key: str, params: Any) -> None:
    try:
        save_params(cache_dir, key, params)
    except OSError:
        logger.exception(
            "weight cache write to %s failed; serving uncached", cache_dir
        )


def _dir_bytes(root: Optional[str]) -> Dict[str, int]:
    """{"bytes", "entries"} for one cache tier directory (0s when absent)."""
    total = 0
    entries = 0
    if root and os.path.isdir(root):
        for name in os.listdir(root):
            path = os.path.join(root, name)
            if not os.path.isdir(path):
                continue
            entries += 1
            for dirpath, _dirs, files in os.walk(path):
                for fname in files:
                    try:
                        total += os.stat(os.path.join(dirpath, fname)).st_size
                    except OSError:
                        pass
    return {"bytes": total, "entries": entries}


def cache_usage(
    *,
    cache_dir: str = DEFAULT_CACHE_DIR,
    shm_dir: Optional[str] = SHM_CACHE_DIR,
) -> Dict[str, Dict[str, int]]:
    """Host-side weight-cache tier usage for GET /debug/memory. The shm
    tier is RAM the kernel page cache holds on the worker's behalf (GMS
    role) — invisible to device memory_stats but very much part of the
    process's memory story on a shared host."""
    return {"shm": _dir_bytes(shm_dir), "disk": _dir_bytes(cache_dir)}
