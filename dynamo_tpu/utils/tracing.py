"""Distributed tracing: W3C traceparent propagation + span export.

Reference parity: lib/runtime/src/logging.rs:72-97 (traceparent parse /
propagate so distributed request flows correlate across frontend → router →
worker) and the OTel span layer the reference hangs off tracing-subscriber.
Dependency-free by design (no otel SDK in the image): spans are recorded to
an in-process ring + optional JSONL file (``DYN_TPU_TRACE_FILE``), one JSON
object per span — the OTLP-friendly shape an exporter can ship later.

Propagation rides Context baggage (runtime/context.py), which the request
plane already serializes: the HTTP/gRPC frontends extract ``traceparent``
into baggage; every hop's spans join the same trace; workers see the parent
span id of the frontend span that dispatched to them.
"""

from __future__ import annotations

import json
import os
import re
import secrets
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional

from dynamo_tpu import config

TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)

TRACE_FILE = config.env_str(
    "DYN_TPU_TRACE_FILE", "",
    "Append finished spans as JSONL to this path ('' disables file export)",
)


@dataclass
class TraceContext:
    trace_id: str  # 32 hex
    span_id: str  # 16 hex — the CURRENT span (parent of children)
    sampled: bool = True

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{'01' if self.sampled else '00'}"


def parse_traceparent(header: Optional[str]) -> Optional[TraceContext]:
    """(ref: logging.rs:72 parse_traceparent)"""
    if not header:
        return None
    m = TRACEPARENT_RE.match(header.strip().lower())
    if not m:
        return None
    _, trace_id, span_id, flags = m.groups()
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return TraceContext(trace_id, span_id, sampled=flags != "00")


def new_trace_context() -> TraceContext:
    return TraceContext(secrets.token_hex(16), secrets.token_hex(8))


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_span_id: Optional[str]
    start_s: float
    end_s: float = 0.0
    attributes: Dict[str, Any] = field(default_factory=dict)
    events: List[Dict[str, Any]] = field(default_factory=list)
    status: str = "ok"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "start_unix_s": round(self.start_s, 6),
            "duration_ms": round((self.end_s - self.start_s) * 1000, 3),
            "attributes": self.attributes,
            "events": self.events,
            "status": self.status,
        }


class Tracer:
    """Process-wide span recorder (ring buffer + optional JSONL file)."""

    def __init__(self, *, max_spans: int = 2048, path: Optional[str] = None) -> None:
        self._ring: Deque[Span] = deque(maxlen=max_spans)
        self._lock = threading.Lock()
        self._path = path if path is not None else (TRACE_FILE.get() or None)

    def export(self, span: Span) -> None:
        with self._lock:
            self._ring.append(span)
            if self._path:
                try:
                    with open(self._path, "a") as f:
                        f.write(json.dumps(span.to_dict()) + "\n")
                except OSError:
                    self._path = None  # disable after first failure

    def finished_spans(self) -> List[Span]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    @contextmanager
    def span(
        self,
        name: str,
        context: Any = None,  # runtime Context (baggage carrier) or None
        **attributes: Any,
    ):
        """Start a child span of the context's trace (creating a fresh trace
        when none is active) and advance the context's traceparent so
        downstream hops parent under this span."""
        parent = None
        if context is not None:
            parent = parse_traceparent(context.baggage.get("traceparent"))
        if parent is None:
            parent = new_trace_context()
            parent_span_id: Optional[str] = None
        else:
            parent_span_id = parent.span_id
        span = Span(
            name=name,
            trace_id=parent.trace_id,
            span_id=secrets.token_hex(8),
            parent_span_id=parent_span_id,
            start_s=time.time(),
            attributes=dict(attributes),
        )
        if context is not None:
            context.baggage["traceparent"] = TraceContext(
                span.trace_id, span.span_id, parent.sampled
            ).to_traceparent()
        try:
            yield span
        except BaseException as exc:
            span.status = f"error: {type(exc).__name__}"
            raise
        finally:
            span.end_s = time.time()
            self.export(span)


_GLOBAL: Optional[Tracer] = None


def global_tracer() -> Tracer:
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = Tracer()
    return _GLOBAL


def span(name: str, context: Any = None, **attributes: Any):
    """Convenience: a span on the process-global tracer."""
    return global_tracer().span(name, context, **attributes)
