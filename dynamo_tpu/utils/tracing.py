"""Distributed tracing: W3C traceparent propagation + span export.

Reference parity: lib/runtime/src/logging.rs:72-97 (traceparent parse /
propagate so distributed request flows correlate across frontend → router →
worker) and the OTel span layer the reference hangs off tracing-subscriber.
Dependency-free by design (no otel SDK in the image): spans are recorded to
an in-process ring + optional JSONL file (``DYN_TPU_TRACE_FILE``), one JSON
object per span — the OTLP-friendly shape an exporter can ship later.

Propagation rides Context baggage (runtime/context.py), which the request
plane already serializes: the HTTP/gRPC frontends extract ``traceparent``
into baggage; every hop's spans join the same trace; workers see the parent
span id of the frontend span that dispatched to them.
"""

from __future__ import annotations

import json
import os
import re
import secrets
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional

from dynamo_tpu import config

TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)

# Declared in the canonical registry (config.py).
TRACE_FILE = config.TRACE_FILE
OTLP_ENDPOINT = config.OTLP_ENDPOINT
OTLP_SERVICE = config.OTLP_SERVICE


@dataclass
class TraceContext:
    trace_id: str  # 32 hex
    span_id: str  # 16 hex — the CURRENT span (parent of children)
    sampled: bool = True

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{'01' if self.sampled else '00'}"


def parse_traceparent(header: Optional[str]) -> Optional[TraceContext]:
    """(ref: logging.rs:72 parse_traceparent)"""
    if not header:
        return None
    m = TRACEPARENT_RE.match(header.strip().lower())
    if not m:
        return None
    _, trace_id, span_id, flags = m.groups()
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return TraceContext(trace_id, span_id, sampled=flags != "00")


def new_trace_context() -> TraceContext:
    return TraceContext(secrets.token_hex(16), secrets.token_hex(8))


# -- process/service identity -------------------------------------------------
# Every exported span is stamped with the emitting process's label so the
# frontend's trajectory stitcher (runtime/trajectory.py) knows which spans
# share a clock domain — durations from one proc are comparable, wall
# clocks across procs are NOT (the liveness.py local-clock-only rule).
# Worker/frontend mains set an explicit label; the pid default keeps
# distinct processes distinguishable even unlabeled.

_SERVICE: Optional[str] = None


def set_service(name: str) -> None:
    """Name this process for span attribution (e.g. ``worker-0x1a2b``)."""
    global _SERVICE
    _SERVICE = name


def service_label() -> str:
    return _SERVICE or f"proc-{os.getpid()}"


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_span_id: Optional[str]
    start_s: float
    end_s: float = 0.0
    attributes: Dict[str, Any] = field(default_factory=dict)
    events: List[Dict[str, Any]] = field(default_factory=list)
    status: str = "ok"
    # Clock-domain tag (service_label() at export) + local-monotonic start
    # anchor: the trajectory stitcher uses proc to decide which spans share
    # a clock and start_mono_s for exact same-process offsets.
    proc: Optional[str] = None
    start_mono_s: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "proc": self.proc,
            "start_unix_s": round(self.start_s, 6),
            "start_mono_s": (
                round(self.start_mono_s, 6)
                if self.start_mono_s is not None else None
            ),
            "duration_ms": round((self.end_s - self.start_s) * 1000, 3),
            "attributes": self.attributes,
            "events": self.events,
            "status": self.status,
        }


def _otlp_value(v: Any) -> Dict[str, Any]:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def _otlp_span(s: Span) -> Dict[str, Any]:
    """One span in OTLP/HTTP JSON encoding (hex ids per the OTLP JSON
    mapping). Ref: lib/runtime/src/logging.rs:72-97 ships the reference's
    spans to a collector via the otel exporter; this is the wire-format
    equivalent without an SDK dependency."""
    out: Dict[str, Any] = {
        "traceId": s.trace_id,
        "spanId": s.span_id,
        "name": s.name,
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(int(s.start_s * 1e9)),
        "endTimeUnixNano": str(int(s.end_s * 1e9)),
        "attributes": [
            {"key": k, "value": _otlp_value(v)}
            for k, v in s.attributes.items()
        ],
        "status": (
            {"code": 1}
            if s.status == "ok"
            else {"code": 2, "message": s.status}
        ),
    }
    if s.parent_span_id:
        out["parentSpanId"] = s.parent_span_id
    if s.events:
        out["events"] = [
            {
                "name": e.get("name", "event"),
                "timeUnixNano": str(int(e.get("time_s", s.start_s) * 1e9)),
            }
            for e in s.events
        ]
    return out


class OtlpHttpExporter:
    """Minimal OTLP/HTTP JSON trace exporter (no otel SDK in the image).

    Spans are queued by the tracer's export() and shipped in batches from
    one daemon thread — span-producing paths never block on the network.
    Failures drop the batch after a bounded retry (telemetry must never
    take down serving)."""

    def __init__(
        self,
        endpoint: str,
        *,
        service_name: str = "dynamo-tpu",
        flush_interval_s: float = 2.0,
        max_batch: int = 256,
        max_queue: int = 8192,
    ) -> None:
        self.endpoint = endpoint
        self.service_name = service_name
        self.flush_interval_s = flush_interval_s
        self.max_batch = max_batch
        self._queue: Deque[Span] = deque(maxlen=max_queue)
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self.sent = 0
        self.dropped = 0
        self._thread = threading.Thread(
            target=self._run, name="otlp-exporter", daemon=True
        )
        self._thread.start()

    def offer(self, span: Span) -> None:
        """Enqueue a span; when the bounded queue is full the OLDEST span is
        evicted (deque maxlen semantics) and counted as dropped."""
        with self._lock:
            if len(self._queue) == self._queue.maxlen:
                self.dropped += 1
            self._queue.append(span)
            # Decide the wake inside the lock: the post-append length is
            # only stable here, and a racy read could miss the batch edge.
            wake = len(self._queue) >= self.max_batch
        if wake:
            self._wake.set()

    def _drain(self) -> List[Span]:
        with self._lock:
            batch = list(self._queue)[: self.max_batch]
            for _ in batch:
                self._queue.popleft()
        return batch

    def _post(self, batch: List[Span]) -> None:
        import urllib.request

        body = json.dumps(
            {
                "resourceSpans": [
                    {
                        "resource": {
                            "attributes": [
                                {
                                    "key": "service.name",
                                    "value": {"stringValue": self.service_name},
                                }
                            ]
                        },
                        "scopeSpans": [
                            {
                                "scope": {"name": "dynamo_tpu"},
                                "spans": [_otlp_span(s) for s in batch],
                            }
                        ],
                    }
                ]
            }
        ).encode()
        req = urllib.request.Request(
            self.endpoint, data=body,
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=5.0):
            pass

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.flush_interval_s)
            self._wake.clear()
            self.flush_once()

    def flush_once(self) -> None:
        while True:
            batch = self._drain()
            if not batch:
                return
            try:
                self._post(batch)
                # Accounted HERE (not inside _post) so success/drop
                # bookkeeping is transport-independent.
                self.sent += len(batch)
            except Exception:
                self.dropped += len(batch)
                return

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=2.0)
        self.flush_once()


class Tracer:
    """Process-wide span recorder: ring buffer + optional JSONL file +
    optional OTLP/HTTP wire exporter (DYN_TPU_OTLP_ENDPOINT).

    ``otlp=False`` disables the wire exporter even when the env endpoint
    is set — micro-benchmarks and tests that pump synthetic spans through
    a private tracer must never ship them to a real collector."""

    def __init__(
        self, *, max_spans: int = 2048, path: Optional[str] = None,
        otlp: Any = None,
    ) -> None:
        self._ring: Deque[Span] = deque(maxlen=max_spans)
        self._lock = threading.Lock()
        self._path = path if path is not None else (TRACE_FILE.get() or None)
        if otlp is None and OTLP_ENDPOINT.get():
            otlp = OtlpHttpExporter(
                OTLP_ENDPOINT.get(), service_name=OTLP_SERVICE.get()
            )
        self.otlp = otlp or None
        # Finished-span taps (the trajectory shipper/store subscribe here).
        # A listener must never take down a span-producing path.
        self._listeners: List[Callable[[Span], None]] = []

    def add_listener(self, fn: Callable[[Span], None]) -> None:
        """``fn(span)`` on every export — used by the trajectory plane to
        ship finished spans frontend-ward (runtime/trajectory.py)."""
        self._listeners.append(fn)

    def remove_listener(self, fn: Callable[[Span], None]) -> None:
        if fn in self._listeners:
            self._listeners.remove(fn)

    def export(self, span: Span) -> None:
        if span.proc is None:
            span.proc = service_label()
        with self._lock:
            self._ring.append(span)
            if self._path:
                try:
                    with open(self._path, "a") as f:
                        f.write(json.dumps(span.to_dict()) + "\n")
                except OSError:
                    self._path = None  # disable after first failure
        if self.otlp is not None:
            self.otlp.offer(span)
        for fn in self._listeners:
            try:
                fn(span)
            except Exception:
                import logging

                logging.getLogger(__name__).debug(
                    "span listener failed", exc_info=True
                )

    def finished_spans(self) -> List[Span]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    @contextmanager
    def span(
        self,
        name: str,
        context: Any = None,  # runtime Context (baggage carrier) or None
        **attributes: Any,
    ):
        """Start a child span of the context's trace (creating a fresh trace
        when none is active) and advance the context's traceparent so
        downstream hops parent under this span. On exit the PREVIOUS
        traceparent is restored: a closed span's later siblings must parent
        under the same parent, not chain under the closed leaf (a remote
        hop parented under a tiny finished decision span would be clamped
        into its bounds by the trajectory stitcher)."""
        parent = None
        prev_traceparent: Optional[str] = None
        if context is not None:
            prev_traceparent = context.baggage.get("traceparent")
            parent = parse_traceparent(prev_traceparent)
        if parent is None:
            parent = new_trace_context()
            parent_span_id: Optional[str] = None
        else:
            parent_span_id = parent.span_id
        span = Span(
            name=name,
            trace_id=parent.trace_id,
            span_id=secrets.token_hex(8),
            parent_span_id=parent_span_id,
            start_s=time.time(),
            attributes=dict(attributes),
        )
        # Monotonic anchor for the duration: an NTP step between start and
        # end must not produce negative (or inflated) span durations. The
        # wall-clock start_s stays as the export timestamp; end_s is derived
        # as start + monotonic elapsed so duration_ms is always honest.
        # time.monotonic (not perf_counter) so start_mono_s is directly
        # comparable with the engine/lifecycle monotonic stamps.
        start_mono = time.monotonic()
        span.start_mono_s = start_mono
        if context is not None:
            context.baggage["traceparent"] = TraceContext(
                span.trace_id, span.span_id, parent.sampled
            ).to_traceparent()
        try:
            yield span
        except BaseException as exc:
            span.status = f"error: {type(exc).__name__}"
            raise
        finally:
            span.end_s = span.start_s + (time.monotonic() - start_mono)
            if context is not None:
                if prev_traceparent is None:
                    context.baggage.pop("traceparent", None)
                else:
                    context.baggage["traceparent"] = prev_traceparent
            self.export(span)


_GLOBAL: Optional[Tracer] = None


def global_tracer() -> Tracer:
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = Tracer()
    return _GLOBAL


def span(name: str, context: Any = None, **attributes: Any):
    """Convenience: a span on the process-global tracer."""
    return global_tracer().span(name, context, **attributes)


def export_span(
    name: str,
    context: Any = None,
    *,
    start_mono: float,
    end_mono: Optional[float] = None,
    tracer: Optional[Tracer] = None,
    proc: Optional[str] = None,
    status: str = "ok",
    events: Optional[List[Dict[str, Any]]] = None,
    **attributes: Any,
) -> Span:
    """Export a RETROSPECTIVE span from monotonic timestamps.

    Hot paths (the engine's per-request phases, the drain handoff stall)
    stamp ``time.monotonic()`` boundaries as they pass and build the span
    object once, at stream end — a live context manager per phase would put
    span bookkeeping inside the decode loop. Parents under the context's
    CURRENT traceparent without advancing it (these are leaves), and
    anchors the wall-clock start as ``now_wall - (now_mono - start_mono)``
    so the duration stays monotonic-honest."""
    parent = None
    if context is not None:
        baggage = getattr(context, "baggage", None)
        if isinstance(baggage, dict):
            parent = parse_traceparent(baggage.get("traceparent"))
    if parent is None:
        parent = new_trace_context()
        parent_span_id: Optional[str] = None
    else:
        parent_span_id = parent.span_id
    now_mono = time.monotonic()
    if end_mono is None:
        end_mono = now_mono
    start_s = time.time() - (now_mono - start_mono)
    sp = Span(
        name=name,
        trace_id=parent.trace_id,
        span_id=secrets.token_hex(8),
        parent_span_id=parent_span_id,
        start_s=start_s,
        end_s=start_s + max(end_mono - start_mono, 0.0),
        attributes={k: v for k, v in attributes.items() if v is not None},
        events=list(events or ()),
        status=status,
        proc=proc,
        start_mono_s=start_mono,
    )
    (tracer or global_tracer()).export(sp)
    return sp
