"""Structured logging setup.

Reference parity: lib/runtime/src/logging.rs (DYN_LOG level control, JSONL
mode, request-id propagation). OTel export is out of scope in this
environment; the JSONL format carries trace fields so an external collector
can ingest it.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Optional

from dynamo_tpu import config

_LEVELS = {
    "trace": logging.DEBUG,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}

_configured = False


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": round(time.time(), 6),
            "level": record.levelname.lower(),
            "target": record.name,
            "message": record.getMessage(),
        }
        # Lazy: utils.logging is the first import of half the tree, and
        # the layer DAG bans foundation -> runtime at module level
        # (ImportLayeringConfig.lazy_obligations pins this seam).
        from dynamo_tpu.runtime.context import current_context

        ctx = current_context()
        if ctx is not None:
            entry["request_id"] = ctx.id
        if record.exc_info and record.exc_info[0] is not None:
            entry["exception"] = self.formatException(record.exc_info)
        return json.dumps(entry, separators=(",", ":"))


class TextFormatter(logging.Formatter):
    def __init__(self) -> None:
        super().__init__(
            fmt="%(asctime)s %(levelname)-5s %(name)s: %(message)s",
            datefmt="%H:%M:%S",
        )


def configure_logging(level: Optional[str] = None, json_mode: Optional[bool] = None) -> None:
    global _configured
    level = level or config.LOG_LEVEL.get()
    json_mode = json_mode if json_mode is not None else config.LOG_JSON.get()
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(JsonFormatter() if json_mode else TextFormatter())
    root = logging.getLogger("dynamo_tpu")
    root.handlers.clear()
    root.addHandler(handler)
    root.setLevel(_LEVELS.get(str(level).lower(), logging.INFO))
    root.propagate = False
    _configured = True


def get_logger(name: str) -> logging.Logger:
    if not _configured:
        configure_logging()
    return logging.getLogger(name if name.startswith("dynamo_tpu") else f"dynamo_tpu.{name}")
