"""Shared utilities."""
