"""Multi-host topology: one logical worker spanning several processes.

Reference parity: the reference's DP leader/non-leader worker pattern
(components/src/dynamo/vllm/main.py:67-78 — rank 0 serves the endpoint,
other ranks join collectives only) and its distributed KVBM leader/worker
split (lib/llm/src/block_manager/distributed/{leader,worker}.rs roles).

TPU-style: `jax.distributed.initialize` joins the processes into one JAX
runtime; every process sees the GLOBAL device set, `make_mesh` lays a mesh
over all of it, and jit executes SPMD — XLA inserts ICI/DCN collectives.
The leader (process_index 0) runs the engine scheduler and serves the
endpoint; followers run `engines/tpu/spmd.follow(...)`, executing the same
device programs in lockstep (driven by the leader's op broadcast, see
runtime/network/spmd_channel.py).

Environment contract (mirrors the usual TPU pod env):
  DYN_TPU_COORDINATOR   host:port of process 0's jax.distributed service
  DYN_TPU_NUM_PROCESSES world size
  DYN_TPU_PROCESS_ID    this process's rank
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax

from dynamo_tpu import config
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass(frozen=True)
class HostTopology:
    """What this process is within the logical worker."""

    process_index: int
    num_processes: int
    coordinator: Optional[str] = None

    @property
    def is_leader(self) -> bool:
        return self.process_index == 0

    @property
    def is_multihost(self) -> bool:
        return self.num_processes > 1


def multihost_config_from_env() -> Optional[dict]:
    """Read the multihost env contract; None when not configured."""
    coord = config.COORDINATOR.get()
    if not coord:
        return None
    return {
        "coordinator_address": coord,
        "num_processes": config.NUM_PROCESSES.get(),
        "process_id": config.PROCESS_ID.get(),
    }


def init_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> HostTopology:
    """Join (or skip joining) the multi-process JAX runtime.

    With no explicit args, reads the env contract; with neither, returns a
    single-process topology without touching jax.distributed (the common
    single-host path stays zero-cost). Must run before any JAX computation
    creates a backend.
    """
    if coordinator_address is None:
        cfg = multihost_config_from_env()
        if cfg is None:
            return HostTopology(process_index=0, num_processes=1)
        coordinator_address = cfg["coordinator_address"]
        num_processes = cfg["num_processes"]
        process_id = cfg["process_id"]
    if num_processes is None or num_processes <= 1:
        return HostTopology(process_index=0, num_processes=1)
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id or 0,
    )
    topo = HostTopology(
        process_index=jax.process_index(),
        num_processes=jax.process_count(),
        coordinator=coordinator_address,
    )
    logger.info(
        "multihost: process %d/%d (leader=%s), %d global / %d local devices",
        topo.process_index, topo.num_processes, topo.is_leader,
        len(jax.devices()), len(jax.local_devices()),
    )
    return topo


def spmd_port(coordinator_address: str) -> int:
    """Default op-broadcast port: coordinator port + 1 (one logical worker
    per coordinator, so the offset can't collide within a worker group)."""
    return int(coordinator_address.rsplit(":", 1)[1]) + 1
