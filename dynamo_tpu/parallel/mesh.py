"""Device mesh construction.

The reference treats intra-model parallelism as an engine concern configured
by flags (`tensor_parallel_size` forwarded to vLLM — SURVEY §2.4); here the
engine is ours, so the mesh is a first-class object. A `MeshConfig` names the
degree of each axis; `make_mesh` lays devices out so that tp (the
latency-critical axis, all-reduce per layer) occupies the innermost,
highest-bandwidth ICI neighbors.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


class AxisNames:
    DP = "dp"
    TP = "tp"
    SP = "sp"
    EP = "ep"
    PP = "pp"

    ALL = (DP, PP, SP, EP, TP)


@dataclass(frozen=True)
class MeshConfig:
    """Degrees for each parallel axis. Product must divide available devices.

    Mirrors the reference's engine-parallelism knobs (vllm/args.py
    tensor_parallel_size etc.) as one declarative object.
    """

    dp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1

    @property
    def total(self) -> int:
        return self.dp * self.tp * self.sp * self.ep * self.pp

    def axis_sizes(self) -> Tuple[int, ...]:
        return (self.dp, self.pp, self.sp, self.ep, self.tp)

    @classmethod
    def for_devices(cls, n: int, *, tp: Optional[int] = None) -> "MeshConfig":
        """Default layout: everything tensor-parallel (single-replica engine)."""
        return cls(tp=tp if tp is not None else n)


def make_mesh(
    config: MeshConfig, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build a Mesh with axes (dp, pp, sp, ep, tp), tp innermost.

    Innermost placement gives tp the tightest ICI neighborhood on real TPU
    topologies (jax.devices() orders by torus coordinates).
    """
    devices = list(devices if devices is not None else jax.devices())
    if config.total > len(devices):
        raise ValueError(
            f"mesh needs {config.total} devices, only {len(devices)} available"
        )
    devices = devices[: config.total]
    arr = np.array(devices).reshape(config.axis_sizes())
    return Mesh(arr, AxisNames.ALL)


def local_mesh() -> Mesh:
    """Single-device mesh (all axes size 1) — process-local/test mode."""
    return make_mesh(MeshConfig())
