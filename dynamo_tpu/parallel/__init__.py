"""Parallelism layer: device meshes, sharding rules, collectives.

TPU-first design: scaling is expressed as a `jax.sharding.Mesh` with named
axes plus `NamedSharding` annotations on params/caches/activations; XLA
inserts the collectives (psum/all-gather/reduce-scatter) that the reference
delegates to NCCL inside its engines (SURVEY §2.4, §2.5).

Axes:
  dp — data parallel (batch replicas inside one engine step)
  tp — tensor parallel (attention heads / MLP hidden)
  sp — sequence parallel (long-context prefill: shard the sequence axis)
  ep — expert parallel (MoE experts)
  pp — pipeline parallel (layer stages; engine-level, round 2+)
"""

from dynamo_tpu.parallel.mesh import (
    AxisNames,
    MeshConfig,
    make_mesh,
    local_mesh,
)
from dynamo_tpu.parallel.multihost import (
    HostTopology,
    init_multihost,
    multihost_config_from_env,
)
from dynamo_tpu.parallel.sharding import (
    ShardingRules,
    logical_to_physical,
    param_shardings,
    shard_params,
)

__all__ = [
    "AxisNames",
    "HostTopology",
    "MeshConfig",
    "init_multihost",
    "make_mesh",
    "local_mesh",
    "multihost_config_from_env",
    "ShardingRules",
    "logical_to_physical",
    "param_shardings",
    "shard_params",
]
