"""Logical-axis sharding rules.

Params and caches are annotated with *logical* axis names ("embed", "heads",
"ffn", "kv_blocks", ...); `ShardingRules` maps logical → mesh axes. This is
the flax `logical_axis_rules` idea kept dependency-free: one table controls
how every tensor in the model shards, so changing the parallel layout never
touches model code.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.parallel.mesh import AxisNames

MeshAxes = Union[None, str, Tuple[str, ...]]


def shard_map_unchecked(f, mesh, in_specs, out_specs):
    """shard_map with replication checking off, across jax versions: the
    public `jax.shard_map` (with `check_vma`) landed after 0.4.x, where
    the API lives in jax.experimental with the `check_rep` spelling —
    callers (ring attention, pipeline parallel) use this shim so one tree
    serves both jaxlibs."""
    try:
        sm = jax.shard_map  # jax >= 0.6 public API
        kw = {"check_vma": False}
    except AttributeError:
        from jax.experimental.shard_map import shard_map as sm

        kw = {"check_rep": False}
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


@dataclass(frozen=True)
class ShardingRules:
    """logical axis name → mesh axis (or None = replicate)."""

    rules: Dict[str, MeshAxes] = field(
        default_factory=lambda: {
            # weights
            "vocab": AxisNames.TP,  # embedding / lm_head vocab shard
            "embed": None,  # d_model replicated
            "heads": AxisNames.TP,  # attention heads
            "kv_heads": AxisNames.TP,
            "head_dim": None,
            "ffn": AxisNames.TP,  # MLP hidden
            "experts": AxisNames.EP,
            "layers": None,  # stacked-layer leading axis (pp later)
            # activations
            "batch": AxisNames.DP,
            "seq": AxisNames.SP,
            # paged KV cache
            "kv_blocks": None,  # block pool is per-replica
        }
    )

    def spec(self, *logical: Optional[str]) -> P:
        return P(*(self.rules.get(ax) if ax else None for ax in logical))

    def sharding(self, mesh: Mesh, *logical: Optional[str]) -> NamedSharding:
        return NamedSharding(mesh, self.spec(*logical))


def logical_to_physical(
    rules: ShardingRules, mesh: Mesh, logical_axes: Tuple[Optional[str], ...]
) -> NamedSharding:
    return rules.sharding(mesh, *logical_axes)


def param_shardings(param_axes, rules: ShardingRules, mesh: Mesh):
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings."""
    return jax.tree.map(
        lambda axes: rules.sharding(mesh, *axes),
        param_axes,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def shard_params(params, param_axes, rules: ShardingRules, mesh: Mesh):
    """device_put a param pytree onto the mesh per the rules."""
    shardings = param_shardings(param_axes, rules, mesh)
    return jax.tree.map(jax.device_put, params, shardings)
