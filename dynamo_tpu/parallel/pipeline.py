"""Pipeline-parallel (pp) stage execution for the paged decoder.

Reference parity: the reference treats PP as engine passthrough
(SURVEY §2.4 — vLLM/TRT-LLM run pipeline stages over NCCL). TPU-first
design: the layer stack (and each layer's KV pool) shards over the ``pp``
mesh axis; a GPipe-style schedule runs under ``shard_map`` with
``lax.ppermute`` moving activations stage→stage over ICI. The batch splits
into PP microbatches so stages overlap once the pipeline fills
(T = M + PP - 1 ticks, M = PP microbatches).

Bubble math: utilization = M / (M + PP - 1) = 50%+ at M = PP; serving fills
the pipe continuously so steady-state decode approaches 100%. Fill/drain
ticks compute on garbage activations whose cache writes are suppressed by
zeroed chunk_lens (write_chunk_to_cache drops everything) and whose
outputs are never collected.

Every architecture behavior comes from models/llama.py::decoder_layer —
the same body the single-stage scan uses — so tp×pp composition and all
family knobs (windows, softcaps, post-norms, int8 weights) hold here too.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dynamo_tpu.parallel.sharding import shard_map_unchecked

from dynamo_tpu.models.config import ModelConfig


def forward_paged_pp(
    params: Dict[str, Any],
    config: ModelConfig,
    tokens: jnp.ndarray,  # [B, C] int32
    start_pos: jnp.ndarray,  # [B]
    chunk_lens: jnp.ndarray,  # [B]
    block_tables: jnp.ndarray,  # [B, P]
    k_cache: jnp.ndarray,  # [L, NB, BS, KH, D] (sharded on L over pp)
    v_cache: jnp.ndarray,
    mesh: Mesh,
    *,
    axis: str = "pp",
    use_kernel: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pipeline-parallel forward over the ``axis`` mesh dimension.

    Same contract as models/llama.py::forward_paged (last-position logits +
    updated caches); B must divide by the pp degree (the microbatch count).
    """
    from dynamo_tpu.models import llama

    c = config
    PP = mesh.shape[axis]
    B, C = tokens.shape
    if isinstance(params.get("layers"), (tuple, list)):
        raise ValueError(
            "forward_paged_pp requires STACKED layer params ([L, ...] per "
            "leaf, sliced over the pp axis); got the layered serving layout "
            "— construct the runner with layered_cache=False for pipeline "
            "parallelism"
        )
    assert c.n_layers % PP == 0, "n_layers must divide by pp degree"
    assert B % PP == 0, "batch must divide into pp microbatches"
    M = PP  # microbatch count = stages (the classic GPipe fill)
    mb = B // M
    T = M + PP - 1

    x = llama.embed_tokens(params, c, tokens)  # [B, C, d] (replicated)
    x_mb = x.reshape(M, mb, C, -1)
    sp_mb = start_pos.reshape(M, mb)
    cl_mb = chunk_lens.reshape(M, mb)
    bt_mb = block_tables.reshape(M, mb, -1)
    windows = jnp.asarray(c.layer_windows(), dtype=jnp.int32)

    layer_specs = jax.tree.map(lambda _: P(axis), params["layers"])

    def stage_fn(local_layers, local_windows, k_c, v_c, x_mb, sp_mb, cl_mb, bt_mb):
        r = jax.lax.axis_index(axis)

        def run_local_stack(x_in, sp, cl, bt, k_c, v_c):
            pos = sp[:, None] + jax.lax.broadcasted_iota(
                jnp.int32, (mb, C), 1
            )
            from dynamo_tpu.ops.rope import rope_table

            cos, sin = rope_table(
                pos, c.head_dim_, c.rope_theta,
                scale=c.rope_scaling_factor or 1.0,
            )
            cos_loc = sin_loc = None
            if c.rope_local_theta is not None:
                cos_loc, sin_loc = rope_table(
                    pos, c.head_dim_, c.rope_local_theta
                )

            def layer_fn(carry, xs):
                x = carry
                lp, k_l, v_l, win = xs
                x, k_l, v_l = llama.decoder_layer(
                    c, lp, {}, win, x, cos, sin, k_l, v_l, bt, sp, cl,
                    use_kernel=use_kernel, adapter_ids=None,
                    cos_loc=cos_loc, sin_loc=sin_loc,
                )
                return x, (k_l, v_l)

            x_out, (k_c, v_c) = jax.lax.scan(
                layer_fn, x_in, (local_layers, k_c, v_c, local_windows)
            )
            return x_out, k_c, v_c

        def tick(carry, t):
            act, k_c, v_c, out = carry
            m = t - r  # the microbatch this stage works on at tick t
            valid = (m >= 0) & (m < M)
            mc = jnp.clip(m, 0, M - 1)
            # Stage 0 ingests a fresh microbatch; later stages consume what
            # the previous stage permuted over last tick.
            x_in = jnp.where(r == 0, x_mb[mc], act)
            sp = sp_mb[mc]
            cl = jnp.where(valid, cl_mb[mc], 0)  # garbage ticks write nothing
            bt = bt_mb[mc]
            x_out, k_c, v_c = run_local_stack(x_in, sp, cl, bt, k_c, v_c)
            # Last stage owns the finished microbatch.
            out = jnp.where(
                valid & (r == PP - 1), out.at[mc].set(x_out), out
            )
            act = jax.lax.ppermute(
                x_out, axis, [(i, (i + 1) % PP) for i in range(PP)]
            )
            return (act, k_c, v_c, out), None

        init = (
            jnp.zeros((mb, C, x_mb.shape[-1]), x_mb.dtype),
            k_c,
            v_c,
            jnp.zeros_like(x_mb),
        )
        (_, k_c, v_c, out), _ = jax.lax.scan(
            tick, init, jnp.arange(T, dtype=jnp.int32)
        )
        # Replicate the collected activations (only the last stage holds
        # real values).
        out = jax.lax.psum(
            jnp.where(r == PP - 1, out, jnp.zeros_like(out)), axis
        )
        return out, k_c, v_c

    replicated = P()
    out, k_cache, v_cache = shard_map_unchecked(
        stage_fn,
        mesh,
        (
            layer_specs,  # layer stack sharded over pp
            P(axis),  # per-layer windows
            P(axis),  # k_cache on layers
            P(axis),  # v_cache
            replicated, replicated, replicated, replicated,
        ),
        (replicated, P(axis), P(axis)),
    )(params["layers"], windows, k_cache, v_cache, x_mb, sp_mb, cl_mb, bt_mb)

    x = out.reshape(B, C, -1)
    last_idx = jnp.clip(chunk_lens - 1, 0, C - 1)
    x_last = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)[:, 0]
    logits = llama.lm_head_logits(params, c, x_last)
    return logits, k_cache, v_cache
