"""Ring attention: sequence-parallel exact attention for long context.

The long-context strategy the SURVEY calls first-class: shard the sequence
over the ``sp`` mesh axis, keep each device's Q resident, and rotate K/V
shards around the ring with ``ppermute`` while accumulating flash-style
online softmax — exact attention over sequences far beyond one device's
memory, with communication overlapped against compute by XLA.

This is the TPU-native counterpart of the reference's long-context serving
(context parallelism in its engines): collectives over ICI neighbors
(ppermute = ring), no all-gather of the full sequence, O(T/n) activation
memory per device.

Public pattern: ring attention (Liu et al.) / the scaling-book sharding
recipe; implementation here is original, built on shard_map + ppermute.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _flash_block(q, k, v, mask, m, l, acc, scale):
    """One online-softmax accumulation step.

    q [B,H,Tq,D], k/v [B,H,Tk,D], mask [Tq,Tk] bool, carries m/l [B,H,Tq,1],
    acc [B,H,Tq,D] (all float32)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    s = jnp.where(mask[None, None], s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, acc_new


def ring_attention(
    q: jnp.ndarray,  # [B, T, H, D] — T sharded over `axis` under shard_map
    k: jnp.ndarray,  # [B, T, KH, D]
    v: jnp.ndarray,  # [B, T, KH, D]
    *,
    axis: str,
    causal: bool = True,
    sm_scale: Optional[float] = None,
) -> jnp.ndarray:
    """Per-shard body (call under shard_map; see make_ring_attention).

    Each rank holds a T/n slice; K/V slices rotate n times around the ring.
    GQA: KH may divide H; K/V heads are broadcast over the query groups.
    """
    B, T_blk, H, D = q.shape
    KH = k.shape[2]
    G = H // KH
    scale = sm_scale if sm_scale is not None else D**-0.5
    idx = jax.lax.axis_index(axis)
    n = jax.lax.psum(1, axis)

    # [B, H, T, D] layout for the inner compute
    qf = q.astype(jnp.float32).transpose(0, 2, 1, 3)
    if G > 1:
        expand = lambda x: jnp.repeat(  # noqa: E731
            x.astype(jnp.float32).transpose(0, 2, 1, 3), G, axis=1
        )
    else:
        expand = lambda x: x.astype(jnp.float32).transpose(0, 2, 1, 3)  # noqa: E731

    q_pos = idx * T_blk + jax.lax.broadcasted_iota(jnp.int32, (T_blk, T_blk), 0)

    perm = [(j, (j + 1) % n) for j in range(n)]  # ring: j → j+1

    def body(i, carry):
        k_c, v_c, m, l, acc = carry
        # The K/V block currently held started at rank (idx - i) mod n.
        src = jax.lax.rem(idx - i + n, n)
        k_pos = src * T_blk + jax.lax.broadcasted_iota(jnp.int32, (T_blk, T_blk), 1)
        mask = (q_pos >= k_pos) if causal else jnp.ones_like(q_pos, dtype=bool)
        m, l, acc = _flash_block(qf, expand(k_c), expand(v_c), mask, m, l, acc, scale)
        # Rotate for the next step (the final rotation is harmless and keeps
        # the loop body uniform; XLA overlaps it with the epilogue).
        k_c = jax.lax.ppermute(k_c, axis, perm)
        v_c = jax.lax.ppermute(v_c, axis, perm)
        return k_c, v_c, m, l, acc

    m0 = jnp.full((B, H, T_blk, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, T_blk, 1), jnp.float32)
    acc0 = jnp.zeros((B, H, T_blk, D), jnp.float32)
    _, _, m, l, acc = jax.lax.fori_loop(0, n, body, (k, v, m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-30)  # causal ⇒ every query sees itself
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, T_blk, H, D]


def make_ring_attention(mesh: Mesh, axis: str = "sp", *, causal: bool = True):
    """Jitted [B, T, H, D] ring attention with T sharded over ``axis``."""
    spec = P(None, axis, None, None)
    from dynamo_tpu.parallel.sharding import shard_map_unchecked
    from dynamo_tpu.runtime.device_observe import watched_jit

    fn = shard_map_unchecked(
        functools.partial(ring_attention, axis=axis, causal=causal),
        mesh,
        (spec, spec, spec),
        spec,
    )
    return watched_jit("parallel.ring_attention", jax.jit(fn))
