"""Parser-plane observability: ALL_PARSER metric families + the
``parser`` flight ring.

One process-global ``ParserPlane`` (the frontend's event loop is the
single writer — every ``ToolCallJail`` lives inside an SSE handler on
that loop, DYN005 owner "parser"). The jail reports commits, completed
calls, argument-delta volume, degradation-ladder activations, lossy
``__raw__`` argument wraps (the ``tool_calling._normalize`` counter the
SLO plane reads), parser exceptions (each one is a terminal typed SSE
error frame downstream), and the peak jailed-buffer size.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from dynamo_tpu.runtime import metric_names as mn
from dynamo_tpu.runtime.device_observe import FlightRecorder
from dynamo_tpu.runtime.faults import note_activity
from dynamo_tpu.runtime.metrics_core import MetricsRegistry


class ParserMetrics:
    """Canonical parser families (runtime/metric_names.py ALL_PARSER) on
    a private registry; ``render`` plugs into the system server's / the
    frontend's ``/metrics`` surface like every other subsystem."""

    def __init__(self) -> None:
        self.registry = MetricsRegistry()
        self.tool_calls = self.registry.counter(
            mn.PARSER_TOOL_CALLS_TOTAL,
            "Tool calls fully streamed (CallStart..CallEnd), by dialect",
            ["dialect"],
        )
        self.args_delta_chars = self.registry.counter(
            mn.PARSER_ARGS_DELTA_CHARS_TOTAL,
            "Argument-delta characters emitted mid-generation, by dialect "
            "(the incremental jail's reason to exist: nonzero here means "
            "argument bytes reached clients before the call closed)",
            ["dialect"],
        )
        self.degraded_calls = self.registry.counter(
            mn.PARSER_DEGRADED_CALLS_TOTAL,
            "Degradation-ladder activations, by dialect and reason "
            "(truncated | bad_nesting | drift | buffer_cap | ...): the "
            "malformed call was sealed / returned to content — never a "
            "dropped stream",
            ["dialect", "reason"],
        )
        self.degraded_args = self.registry.counter(
            mn.PARSER_DEGRADED_ARGS_TOTAL,
            "Calls whose argument string was unparseable and shipped as a "
            "lossy {\"__raw__\": ...} wrap (tool_calling._normalize and "
            "its streaming twin) — clients see degraded=true",
            ["dialect"],
        )
        self.exceptions = self.registry.counter(
            mn.PARSER_EXCEPTIONS_TOTAL,
            "Parser BUGS (not malformed model output): each one surfaced "
            "as a terminal typed SSE error frame "
            "(error_kind=tool_call_parse)",
        )
        self.streams = self.registry.counter(
            mn.PARSER_STREAMS_TOTAL,
            "Tool-enabled streams through the jail, by outcome "
            "(clean | degraded | error)",
            ["outcome"],
        )
        self.buffered_peak = self.registry.gauge(
            mn.PARSER_JAIL_BUFFERED_PEAK_CHARS,
            "Peak jailed-buffer size (chars) across streams — bounded by "
            "the jail's buffer cap by construction",
        )

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics=openmetrics)


class ParserPlane:
    """Process-global parser observability. Threading contract: mutating
    notes run on the frontend's event loop (single-writer flight ring,
    DYN005 owner "parser"); render/snapshot may run anywhere."""

    def __init__(self) -> None:
        self.flight = FlightRecorder("parser", capacity=1024)
        self.metrics = ParserMetrics()
        self.peak_buffered = 0
        # Lifetime counters (bench legs + /debug snapshots read these;
        # the metric families are their scrapeable form).
        self.calls = 0
        self.degrades: Dict[str, int] = {}
        self.exceptions = 0
        self.streams: Dict[str, int] = {}
        self.metrics.registry.on_render(self._refresh)

    def _refresh(self) -> None:
        self.metrics.buffered_peak.set(self.peak_buffered)

    # -- jail reporting ----------------------------------------------------

    def note_commit(self, dialect: str) -> None:
        self.flight.record("jail_commit", dialect=dialect)

    def note_call(self, dialect: str, name: str) -> None:
        self.calls += 1
        self.metrics.tool_calls.inc(dialect=dialect)
        self.flight.record("call", dialect=dialect, name=name)

    def note_args_chars(self, dialect: str, n: int) -> None:
        self.metrics.args_delta_chars.inc(n, dialect=dialect)

    def note_degrade(self, dialect: str, reason: str) -> None:
        self.degrades[reason] = self.degrades.get(reason, 0) + 1
        self.metrics.degraded_calls.inc(dialect=dialect, reason=reason)
        self.flight.record("degrade", dialect=dialect, reason=reason)
        note_activity("parser_degraded")

    def note_degraded_args(self, dialect: str) -> None:
        self.metrics.degraded_args.inc(dialect=dialect)

    def note_exception(self, dialect: str) -> None:
        self.exceptions += 1
        self.metrics.exceptions.inc()
        self.flight.record("exception", dialect=dialect)
        note_activity("parser_exceptions")

    def note_stream(self, outcome: str) -> None:
        self.streams[outcome] = self.streams.get(outcome, 0) + 1
        self.metrics.streams.inc(outcome=outcome)

    def note_buffered(self, chars: int) -> None:
        if chars > self.peak_buffered:
            self.peak_buffered = chars

    # -- surfaces ----------------------------------------------------------

    def register_metrics(self, server: Any) -> None:
        server.register_metrics(self.metrics.render)
        server.register_flight(self.flight.name, self.flight.snapshot)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "calls": self.calls,
            "degrades": dict(self.degrades),
            "exceptions": self.exceptions,
            "streams": dict(self.streams),
            "peak_buffered_chars": self.peak_buffered,
        }


_PLANE: Optional[ParserPlane] = None
_PLANE_LOCK = threading.Lock()


def parser_plane() -> ParserPlane:
    """The process-global plane (created on first use)."""
    global _PLANE
    if _PLANE is None:
        with _PLANE_LOCK:
            if _PLANE is None:
                _PLANE = ParserPlane()
    return _PLANE
