"""Streaming tool-call jail: hold back content that is becoming a tool call.

Reference parity: lib/llm/src/protocols/openai/chat_completions/jail.rs —
when a streamed response starts emitting a tool-call dialect, the raw
marker text must NOT reach the client as content deltas; it is jailed
until the stream ends, parsed, and delivered as OpenAI `tool_calls`
deltas with finish_reason "tool_calls".

The jail is marker-driven: the opening tokens of every supported dialect
(parsers/tool_calling.py) trigger it, and a suffix that might be a
partially-received marker is held back one delta (the same holdback scheme
the reasoning parser uses for tags straddling delta boundaries).
"""

from __future__ import annotations

from typing import List, Tuple

# Opening markers of the tool-call dialects (tool_calling.py):
# hermes/xml share <tool_call>; mistral, harmony (gpt-oss channels), DSML.
TOOL_MARKERS: Tuple[str, ...] = (
    "<tool_call>",
    "[TOOL_CALLS]",
    "<|channel|>",
    "<｜DSML｜",
)


class ToolCallJail:
    """Feed content deltas; get back what is safe to stream as content.
    Once a full opening marker appears, everything from the marker onward
    is jailed until ``flush()``."""

    def __init__(self) -> None:
        self._buf = ""
        self._jailed = False

    @property
    def jailed(self) -> bool:
        return self._jailed

    def feed(self, delta: str) -> str:
        if self._jailed:
            self._buf += delta
            return ""
        text = self._buf + delta
        self._buf = ""
        # Earliest full marker jails the rest of the stream.
        idx, _marker = _find_first(text, TOOL_MARKERS)
        if idx != -1:
            self._jailed = True
            self._buf = text[idx:]
            return text[:idx]
        # Hold back the longest suffix that is a prefix of any marker.
        max_n = min(max(len(m) for m in TOOL_MARKERS) - 1, len(text))
        for n in range(max_n, 0, -1):
            tail = text[-n:]
            if any(m.startswith(tail) for m in TOOL_MARKERS):
                self._buf = tail
                return text[:-n]
        return text

    def flush(self) -> Tuple[str, str]:
        """End of stream → (releasable_content, jailed_text). Exactly one
        of the two is non-empty (or both empty)."""
        buf, self._buf = self._buf, ""
        if self._jailed:
            return "", buf
        return buf, ""


def _find_first(text: str, markers) -> Tuple[int, str]:
    best, best_m = -1, ""
    for m in markers:
        i = text.find(m)
        if i != -1 and (best == -1 or i < best):
            best, best_m = i, m
    return best, best_m


def tool_call_stream_deltas(calls: List) -> List[dict]:
    """OpenAI streaming `tool_calls` delta entries (indexed) from parsed
    ToolCall objects (tool_calling.py)."""
    out = []
    for i, call in enumerate(calls):
        entry = call.to_openai()
        entry["index"] = i
        out.append(entry)
    return out
