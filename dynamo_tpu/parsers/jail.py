"""Streaming tool-call jail: incremental, dialect-aware, never drops.

Reference parity: the reference's ~1.2k-LoC incremental jail
(lib/llm/src/protocols/openai/chat_completions/jail.rs). The old jail
buffered a tool call from first marker to ``flush()`` at stream end —
time-to-first-tool-call-byte was O(call length) and a malformed call had
no degradation path. This jail is the orchestrator over the per-dialect
streaming machines in parsers/incremental.py:

  * DETECT — content streams through; a suffix that might be a partial
    opening marker is held back one delta (parsers/holdback.py, the same
    scheme the reasoning parser uses). A complete marker commits the
    matching dialect machine.
  * STREAM — the machine emits ``CallStart`` as soon as the call name is
    parseable, ``ArgsDelta`` raw argument text as the model generates it
    (partial-JSON for json/hermes/mistral/harmony, element-wise for
    pythonic/dsml/xml), ``CallEnd`` when the call closes. A machine that
    finishes its construct hands trailing text back to DETECT, so two
    back-to-back calls with content between them stream naturally.
  * Degradation ladder — malformed input (truncated JSON, bad nesting,
    dialect drift mid-call, buffer-cap overflow) NEVER kills the stream:
    a call that already emitted deltas is sealed with ``CallEnd(error=
    reason)``; un-emitted jailed text degrades to content deltas; the
    buffer-cap rung additionally stops jailing for the rest of the
    stream (PASSTHROUGH). A parser exception anywhere (a BUG, not bad
    input — exercised deterministically via the ``parser.jail.feed``
    fault seam) is wrapped in ``ToolCallParseError`` so the HTTP layer
    ships a terminal typed SSE error frame (``error_kind=
    tool_call_parse``).
  * Bounded memory — the jail degrades when a machine's unresolved raw
    tail exceeds ``buffer_cap``: a dialect that never closes cannot grow
    host memory without limit.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from dynamo_tpu import config
from dynamo_tpu.parsers.holdback import find_first, holdback_split
from dynamo_tpu.parsers.incremental import (
    AUTO_MARKERS,
    PINNED,
    ArgsDelta,
    CallEnd,
    CallStart,
    ContentDelta,
    ToolCallParseError,
    _JailCtx,
    _MachineDegrade,
)
from dynamo_tpu.runtime.fault_names import PARSER_JAIL_FEED
from dynamo_tpu.runtime.faults import fault_point

# Default unresolved-buffer cap (chars). Generous for real calls (most
# argument payloads stream out incrementally and never sit in the
# buffer) yet small enough that a marker bomb cannot balloon host RSS.
DEFAULT_BUFFER_CAP = config.TOOL_JAIL_CAP_CHARS.get()

_DETECT, _STREAM, _PASSTHROUGH = 0, 1, 2


class ToolCallJail:
    """Feed content deltas; get back typed streaming events
    (parsers/incremental.py ContentDelta / CallStart / ArgsDelta /
    CallEnd). Call ``finish()`` exactly once at stream end."""

    def __init__(
        self,
        dialect: Optional[str] = None,
        *,
        buffer_cap: int = DEFAULT_BUFFER_CAP,
        call_id_factory: Optional[Callable[[], str]] = None,
        plane=None,
    ) -> None:
        if dialect is not None and dialect not in PINNED:
            raise ValueError(
                f"unknown tool-call dialect {dialect!r}; "
                f"known: {sorted(PINNED)}"
            )
        self.dialect = dialect
        self.buffer_cap = int(buffer_cap)
        self._ctx = _JailCtx(call_id_factory)
        if plane is None:
            from dynamo_tpu.parsers.observe import parser_plane

            plane = parser_plane()
        self._plane = plane
        self._mode = _DETECT
        self._machine = None
        self._last_dialect: Optional[str] = None
        self._buf = ""  # DETECT holdback buffer
        self._finished = False
        if dialect is None:
            self._markers = tuple(m for m, _mk in AUTO_MARKERS)
            self._factories = dict(AUTO_MARKERS)
        else:
            markers, factory = PINNED[dialect]
            self._markers = markers
            self._factories = {m: factory for m in markers}
        # Stream-level accounting (the SSE assembler reads these).
        self.calls_started = 0
        self.calls_done = 0
        self.open_calls: set = set()
        self.degrade_reasons: List[str] = []
        self.args_chars = 0

    # -- public surface ----------------------------------------------------

    @property
    def jailed(self) -> bool:
        """True while a dialect machine holds the stream."""
        return self._mode == _STREAM

    def outcome(self) -> str:
        """clean | degraded — one word per stream for ALL_PARSER's
        streams counter (the error outcome is recorded by the HTTP layer
        when a ToolCallParseError reaches it)."""
        return "degraded" if self.degrade_reasons else "clean"

    def feed(self, delta: str) -> List[object]:
        """Process one content delta → events. Malformed input degrades
        (typed ladder); only a parser BUG raises, and it raises
        ``ToolCallParseError``."""
        return self._guard(self._feed_inner, delta)

    def finish(self) -> List[object]:
        """End of stream: close the active machine (sealing a truncated
        call / degrading its un-emitted text) and release any held-back
        detection suffix as content."""
        return self._guard(self._finish_inner)

    # -- internals ---------------------------------------------------------

    def _guard(self, fn, *args) -> List[object]:
        try:
            fault_point(PARSER_JAIL_FEED)
            events = fn(*args)
        except _MachineDegrade as exc:
            events = list(exc.events)
            events.extend(self._ladder(exc.reason))
        except ToolCallParseError:
            raise
        except Exception as exc:
            self._plane.note_exception(self._machine_dialect())
            # The stream is NOT lost: the HTTP layer maps this to a
            # terminal typed SSE error frame (error_kind=tool_call_parse).
            raise ToolCallParseError(
                f"tool-call parser failed: {type(exc).__name__}: {exc}"
            ) from exc
        self._account(events)
        return events

    def _machine_dialect(self) -> str:
        if self._machine is not None:
            return self._machine.dialect
        return self._last_dialect or self.dialect or "auto"

    def _feed_inner(self, delta: str) -> List[object]:
        events: List[object] = []
        text = delta
        while True:
            if self._mode == _PASSTHROUGH:
                if text:
                    events.append(ContentDelta(text))
                break
            if self._mode == _DETECT:
                text = self._buf + text
                self._buf = ""
                idx, marker = find_first(text, self._markers)
                if idx == -1:
                    emit, self._buf = holdback_split(text, self._markers)
                    if emit:
                        events.append(ContentDelta(emit))
                    break
                if text[:idx]:
                    events.append(ContentDelta(text[:idx]))
                self._machine = self._factories[marker](self._ctx)
                self._plane.note_commit(self._machine.dialect)
                self._mode = _STREAM
                text = text[idx:]
                continue
            # _STREAM
            try:
                evs = self._machine.feed(text)
            except _MachineDegrade as exc:
                events.extend(exc.events)
                events.extend(self._ladder(exc.reason))
                break
            events.extend(evs)
            buffered = self._machine.raw_len() + len(self._buf)
            self._plane.note_buffered(buffered)
            if buffered > self.buffer_cap:
                events.extend(self._ladder("buffer_cap"))
                break
            if self._machine.done:
                text = self._machine.trailing
                self._last_dialect = self._machine.dialect
                self._machine = None
                self._mode = _DETECT
                if text:
                    continue
                break
            break
        return events

    def _finish_inner(self) -> List[object]:
        if self._finished:
            return []
        self._finished = True
        events: List[object] = []
        if self._machine is not None:
            self._last_dialect = self._machine.dialect
            try:
                events.extend(self._machine.finish())
            except _MachineDegrade as exc:
                events.extend(exc.events)
                events.extend(self._ladder(exc.reason))
            self._machine = None
        if self._buf:
            # Held-back partial marker that never completed: released
            # verbatim (the old jail's false-alarm flush).
            events.append(ContentDelta(self._buf))
            self._buf = ""
        return events

    def _ladder(self, reason: str) -> List[object]:
        """The typed degradation ladder: seal the open call (its deltas
        already reached the client), return un-emitted jailed text to
        content, and — on buffer-cap overflow — stop jailing entirely."""
        events: List[object] = []
        m = self._machine
        dialect = self._machine_dialect()
        if m is not None:
            if m.open_index is not None:
                # The sealing CallEnd carries the reason; _account counts
                # it (every CallEnd.error is exactly one ladder rung).
                events.append(
                    CallEnd(m.open_index, error=reason, degraded=True)
                )
            else:
                self.degrade_reasons.append(reason)
                self._plane.note_degrade(dialect, reason)
            # Exact-replay guard: the raw tail degrades to content ONLY
            # while the machine emitted nothing (after an emission the
            # tail can overlap already-delivered call text — replaying
            # it would duplicate the call on the wire as content).
            pending = "" if m.emitted_any else m.raw_text()
            if pending:
                events.append(ContentDelta(pending))
        else:
            self.degrade_reasons.append(reason)
            self._plane.note_degrade(dialect, reason)
        if m is not None:
            self._last_dialect = m.dialect
        self._machine = None
        self._mode = _PASSTHROUGH if reason == "buffer_cap" else _DETECT
        return events

    def _account(self, events: List[object]) -> None:
        dialect = self._machine_dialect()
        for ev in events:
            if isinstance(ev, CallStart):
                self.calls_started += 1
                self.open_calls.add(ev.index)
                self._plane.note_call(dialect, ev.name)
            elif isinstance(ev, ArgsDelta):
                self.args_chars += len(ev.text)
                self._plane.note_args_chars(dialect, len(ev.text))
            elif isinstance(ev, CallEnd):
                self.open_calls.discard(ev.index)
                self.calls_done += 1
                if ev.error is not None:
                    # A sealed malformed call (ladder rung 1) — whether
                    # sealed by the ladder, a machine's mid-stream seal
                    # (harmony payload ending mid-JSON), or truncation
                    # at finish().
                    self.degrade_reasons.append(ev.error)
                    self._plane.note_degrade(dialect, ev.error)
                elif ev.degraded:
                    self._plane.note_degraded_args(dialect)
