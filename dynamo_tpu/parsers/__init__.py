"""Tool-call and reasoning-content parsers.

Reference parity: lib/parsers (SURVEY §2.1 dynamo-parsers row) — tool-call
dialects (JSON / hermes-XML / mistral / pythonic / harmony / dsml,
src/tool_calling/) and reasoning extraction (<think> family,
src/reasoning/). One-shot parsers are pure functions over text; streaming
runs through small state machines — the reasoning splitter and the
incremental tool-call jail (parsers/jail.py + parsers/incremental.py),
which emits OpenAI ``tool_calls`` argument deltas while the model is
still generating the call.
"""

from dynamo_tpu.parsers.incremental import (
    DIALECTS,
    ArgsDelta,
    CallEnd,
    CallStart,
    ContentDelta,
    ToolCallParseError,
)
from dynamo_tpu.parsers.jail import ToolCallJail
from dynamo_tpu.parsers.reasoning import ReasoningParser, split_reasoning
from dynamo_tpu.parsers.tool_calling import ToolCall, detect_and_parse_tool_calls

__all__ = [
    "ArgsDelta",
    "CallEnd",
    "CallStart",
    "ContentDelta",
    "DIALECTS",
    "ReasoningParser",
    "split_reasoning",
    "ToolCall",
    "ToolCallJail",
    "ToolCallParseError",
    "detect_and_parse_tool_calls",
]
