"""Tool-call and reasoning-content parsers.

Reference parity: lib/parsers (SURVEY §2.1 dynamo-parsers row) — tool-call
dialects (JSON / hermes-XML / mistral / pythonic, src/tool_calling/) and
reasoning extraction (<think> family, src/reasoning/). Parsers are pure
functions over text plus small streaming state machines so the frontend can
rewrite SSE deltas (the reference's chat_completions "jail").
"""

from dynamo_tpu.parsers.reasoning import ReasoningParser, split_reasoning
from dynamo_tpu.parsers.tool_calling import ToolCall, detect_and_parse_tool_calls

__all__ = [
    "ReasoningParser",
    "split_reasoning",
    "ToolCall",
    "detect_and_parse_tool_calls",
]
