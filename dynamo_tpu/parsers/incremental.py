"""Incremental tool-call parsing: per-dialect streaming state machines.

Reference parity: the reference's ~1.2k-LoC incremental jail
(lib/llm/src/protocols/openai/chat_completions/jail.rs + lib/parsers
streaming modes) — once a dialect's opening marker commits, the parser
emits OpenAI ``tool_calls`` ARGUMENT DELTAS as the model generates them
instead of buffering the whole call to stream end:

  * json / hermes / mistral / harmony — partial-JSON streaming: the call
    name is emitted as soon as its string literal completes, and the raw
    text of the ``arguments`` object streams through as argument deltas
    while the model is still generating it;
  * pythonic / dsml / xml — element-wise streaming: each completed
    keyword argument / ``<parameter>`` element appends one JSON fragment
    to the arguments string (the fragments concatenate to a valid JSON
    object, closed when the call's structure closes).

Event model (what the jail in parsers/jail.py returns to the SSE
assembler): ``ContentDelta`` (safe to stream as content), ``CallStart``
(index + name + call id — the first ``tool_calls`` delta), ``ArgsDelta``
(raw argument text for one call), ``CallEnd`` (the call closed; carries
``error`` when the degradation ladder sealed it and ``degraded`` when
the arguments needed a lossy ``__raw__`` wrap).

Malformed input NEVER raises out of a machine as a plain exception:
structured failures raise ``_MachineDegrade(reason)`` which the jail
turns into the typed degradation ladder (seal emitted calls, return
un-emitted jailed text to content). Anything else escaping ``feed`` is
a parser BUG and is wrapped by the jail into ``ToolCallParseError`` so
the HTTP layer can ship a terminal typed SSE error frame
(``error_kind=tool_call_parse``) instead of dropping the stream.

Machines never buffer without bound: every machine tracks the raw text
it has consumed since the last emitted event (``raw_len``), and the jail
degrades the stream when that exceeds its buffer cap — a dialect that
never closes cannot grow host memory without limit.
"""

from __future__ import annotations

import ast
import json
import re
import uuid
from dataclasses import dataclass
from typing import Callable, List, Optional

from dynamo_tpu.parsers.holdback import find_first, holdback_split

_WS = " \t\r\n"
_SCALAR_END = frozenset(" \t\r\n,}]")
_NAME_RE = re.compile(r"^[\w.-]+$")

# Every dialect a jail can be pinned to (None = auto-detect by marker).
DIALECTS = (
    "json", "hermes", "mistral", "pythonic", "harmony", "dsml", "xml",
)


# ---------------------------------------------------------------------------
# Events
# ---------------------------------------------------------------------------


@dataclass
class ContentDelta:
    """Text that is safe to stream to the client as content."""

    text: str


@dataclass
class CallStart:
    """A tool call's name parsed — the first ``tool_calls`` delta."""

    index: int
    name: str
    call_id: str


@dataclass
class ArgsDelta:
    """Raw argument text for call ``index`` (concatenates to JSON)."""

    index: int
    text: str


@dataclass
class CallEnd:
    """Call ``index`` closed. ``error`` set when the degradation ladder
    sealed a malformed/truncated call; ``degraded`` when the arguments
    needed a lossy wrap (``__raw__``) or the seal was lossy."""

    index: int
    error: Optional[str] = None
    degraded: bool = False


class ToolCallParseError(RuntimeError):
    """A parser BUG (not malformed model output): surfaces as a terminal
    typed SSE error frame (``error_kind=tool_call_parse``) — never a
    dropped stream."""


class _MachineDegrade(Exception):
    """Structured malformed-input failure. The jail catches this and runs
    the degradation ladder; ``events`` carries whatever the machine had
    already emitted in the feed that raised."""

    def __init__(self, reason: str) -> None:
        super().__init__(reason)
        self.reason = reason
        self.events: List[object] = []


class _JailCtx:
    """Shared per-stream identity: global call indices + call ids (call
    index keeps counting across back-to-back jailed segments)."""

    def __init__(
        self, call_id_factory: Optional[Callable[[], str]] = None
    ) -> None:
        self._next = 0
        self._mk_id = call_id_factory or (
            lambda: f"call-{uuid.uuid4().hex[:24]}"
        )

    def alloc_index(self) -> int:
        i = self._next
        self._next = i + 1
        return i

    def new_call_id(self) -> str:
        return self._mk_id()


# ---------------------------------------------------------------------------
# Incremental JSON consumers (shared by json / hermes / mistral / harmony)
# ---------------------------------------------------------------------------


class _JsonString:
    """One JSON string literal, opening quote already consumed.
    ``feed`` returns consumed chars; ``raw`` excludes both quotes."""

    def __init__(self) -> None:
        self.raw: List[str] = []
        self.done = False
        self._esc = False

    def feed(self, text: str) -> int:
        i, n = 0, len(text)
        start = 0
        while i < n:
            c = text[i]
            i += 1
            if self._esc:
                self._esc = False
            elif c == "\\":
                self._esc = True
            elif c == '"':
                self.done = True
                self.raw.append(text[start:i - 1])
                return i
        self.raw.append(text[start:i])
        return i

    def value(self) -> str:
        raw = "".join(self.raw)
        try:
            return json.loads('"' + raw + '"')
        except json.JSONDecodeError:
            return raw


class _JsonValue:
    """One JSON value of any kind, consumed incrementally. ``kind`` is
    set at the first non-ws char (object | array | string | scalar);
    scalar values stop BEFORE their terminator (",", "}", "]", ws).
    ``sink(span, kind)`` receives every consumed value char (used to
    stream object arguments raw); ``keep`` retains raw for decoding.
    Mismatched brackets raise ``_MachineDegrade("bad_nesting")``."""

    def __init__(self, sink=None, keep: bool = False) -> None:
        self.kind: Optional[str] = None
        self.done = False
        self.keep = keep
        self.sink = sink
        self.raw: List[str] = []
        self._stack: List[str] = []
        self._in_str = False
        self._esc = False

    def feed(self, text: str) -> int:
        i, n = 0, len(text)
        v0 = 0 if self.kind is not None else None
        while i < n and not self.done:
            c = text[i]
            if self.kind is None:
                if c in _WS:
                    i += 1
                    continue
                v0 = i
                if c == "{" or c == "[":
                    self.kind = "object" if c == "{" else "array"
                    self._stack.append(c)
                elif c == '"':
                    self.kind = "string"
                    self._in_str = True
                else:
                    self.kind = "scalar"
                i += 1
                continue
            if self.kind == "scalar":
                if c in _SCALAR_END:
                    self.done = True
                    break
                i += 1
                continue
            if self.kind == "string":
                i += 1
                if self._esc:
                    self._esc = False
                elif c == "\\":
                    self._esc = True
                elif c == '"':
                    self.done = True
                continue
            # object | array
            i += 1
            if self._in_str:
                if self._esc:
                    self._esc = False
                elif c == "\\":
                    self._esc = True
                elif c == '"':
                    self._in_str = False
            elif c == '"':
                self._in_str = True
            elif c == "{" or c == "[":
                self._stack.append(c)
            elif c == "}" or c == "]":
                opener = self._stack.pop() if self._stack else None
                if opener is None or (c == "}") != (opener == "{"):
                    raise _MachineDegrade("bad_nesting")
                if not self._stack:
                    self.done = True
        if v0 is not None and i > v0:
            span = text[v0:i]
            if self.keep:
                self.raw.append(span)
            if self.sink is not None:
                self.sink(span, self.kind)
        return i

    def raw_text(self) -> str:
        return "".join(self.raw)

    def decode_string(self) -> str:
        raw = self.raw_text()
        if raw.startswith('"'):
            raw = raw[1:]
        if raw.endswith('"') and not raw.endswith('\\"'):
            raw = raw[:-1]
        try:
            return json.loads('"' + raw + '"')
        except json.JSONDecodeError:
            return raw


class _ArgsValue:
    """The ``arguments`` value of a call, streamed per the OpenAI wire
    contract: an OBJECT value streams its raw text as argument deltas
    while it is still being generated; a string value is decoded at its
    close (emitted verbatim when it parses as a JSON object, wrapped as
    ``{"__raw__": ...}`` + degraded when it doesn't — the streaming twin
    of tool_calling._normalize); arrays and scalars buffer and emit one
    ``{"value": ...}`` wrap at completion.

    ``string_embedded_json=False`` (harmony payloads) switches the
    string rule: there a top-level string IS the argument value
    (``{"value": s}``, matching the one-shot harmony parser), not an
    embedded-JSON arguments string."""

    def __init__(
        self, emit: Callable[[str], None],
        string_embedded_json: bool = True,
    ) -> None:
        self._emit = emit
        self._string_embedded_json = string_embedded_json
        self.degraded = False
        self.done = False
        self.any_text = False
        self._stream = False
        self._val = _JsonValue(sink=self._on_span, keep=True)

    def _on_span(self, span: str, kind: Optional[str]) -> None:
        if kind == "object":
            if not self._stream:
                self._stream = True
                self._val.keep = False
            self._val.raw = []
            self.any_text = True
            self._emit(span)

    def feed(self, text: str) -> int:
        i = self._val.feed(text)
        if self._val.done:
            self.done = True
            if not self._stream:
                self._finalize()
        return i

    def close(self) -> str:
        """End-of-payload (a dialect terminator or EOF closed the value's
        surrounding construct): ``done`` | ``empty`` | ``truncated``.
        A scalar is terminated by the construct end itself (JSON scalars
        only complete on a delimiter char, which a dialect terminator
        eats before the scanner sees it) — finalize it; an unterminated
        string/object/array is genuinely truncated."""
        if self.done:
            return "done"
        v = self._val
        if v.kind is None:
            return "empty"
        if v.kind == "scalar":
            self.done = True
            self._finalize()
            return "done"
        return "truncated"

    def _finalize(self) -> None:
        raw = self._val.raw_text()
        kind = self._val.kind
        self.any_text = True
        if kind == "string":
            s = self._val.decode_string()
            if not self._string_embedded_json:
                self._emit(json.dumps({"value": s}, separators=(",", ":")))
                return
            try:
                parsed = json.loads(s)
            except json.JSONDecodeError:
                self.degraded = True
                self._emit(json.dumps({"__raw__": s}, separators=(",", ":")))
                return
            if isinstance(parsed, dict):
                self._emit(s)
            else:
                self._emit(
                    json.dumps({"value": parsed}, separators=(",", ":"))
                )
            return
        try:
            parsed = json.loads(raw)
        except json.JSONDecodeError:
            self.degraded = True
            self._emit(json.dumps({"__raw__": raw}, separators=(",", ":")))
            return
        self._emit(json.dumps({"value": parsed}, separators=(",", ":")))


class _CallObject:
    """One streamed ``{"name": ..., "arguments": {...}}`` call object.

    Emits ``CallStart`` as soon as the name string completes (arguments
    that arrived first are buffered and flushed right after), argument
    deltas as the arguments value streams, ``CallEnd`` at the closing
    brace. The ``{"function": {...}}`` wrapper form is descended into
    transparently (same key loop, one depth deeper); unknown keys (id,
    type, ...) have their values skipped raw."""

    def __init__(self, m: "_Machine") -> None:
        self.m = m
        self.state = "start"
        self.depth = 0
        self.started = False
        self.done = False
        self.degraded = False
        self.index: Optional[int] = None
        self.call_id: Optional[str] = None
        self.name: Optional[str] = None
        self._key: Optional[str] = None
        self._str: Optional[_JsonString] = None
        self._val: Optional[_JsonValue] = None
        self._args: Optional[_ArgsValue] = None
        self._args_seen = False
        self._args_emitted = False
        self._args_buf: List[str] = []

    # -- emission ----------------------------------------------------------

    def _emit_args(self, text: str) -> None:
        if not text:
            return
        self._args_emitted = True
        if self.started:
            self.m._emit(ArgsDelta(self.index, text))
        else:
            self._args_buf.append(text)

    def _set_name(self, name: str) -> None:
        if not isinstance(name, str) or not name:
            raise _MachineDegrade("bad_name")
        self.name = name
        if not self.started:
            self.started = True
            self.index = self.m.ctx.alloc_index()
            self.call_id = self.m.ctx.new_call_id()
            self.m._emit(CallStart(self.index, name, self.call_id))
            if self._args_buf:
                buffered, self._args_buf = "".join(self._args_buf), []
                self.m._emit(ArgsDelta(self.index, buffered))

    def _close(self) -> None:
        if not self.started:
            raise _MachineDegrade("no_name")
        if not self._args_emitted:
            self.m._emit(ArgsDelta(self.index, "{}"))
        if self._args is not None and self._args.degraded:
            self.degraded = True
        self.m._emit(CallEnd(self.index, degraded=self.degraded))
        self.done = True

    # -- consumption -------------------------------------------------------

    def feed(self, text: str) -> int:
        i, n = 0, len(text)
        while i < n and not self.done:
            st = self.state
            if st == "key_str":
                i += self._str.feed(text[i:])
                if self._str.done:
                    self._key = self._str.value()
                    self._str = None
                    self.state = "colon"
                continue
            if st == "value_args":
                i += self._args.feed(text[i:])
                if self._args.done:
                    self.state = "key"
                continue
            if st == "value_name":
                i += self._val.feed(text[i:])
                if self._val.done:
                    if self._val.kind != "string":
                        raise _MachineDegrade("bad_name")
                    self._set_name(self._val.decode_string())
                    self._val = None
                    self.state = "key"
                continue
            if st == "value_skip":
                i += self._val.feed(text[i:])
                if self._val.done:
                    self._val = None
                    self.state = "key"
                continue
            c = text[i]
            if st == "start":
                if c in _WS:
                    i += 1
                    continue
                if c == "{":
                    self.depth = 1
                    self.state = "key"
                    i += 1
                    continue
                raise _MachineDegrade("not_object")
            if st == "key":
                if c in _WS or c == ",":
                    i += 1
                    continue
                if c == '"':
                    self._str = _JsonString()
                    self.state = "key_str"
                    i += 1
                    continue
                if c == "}":
                    i += 1
                    self.depth -= 1
                    if self.depth == 0:
                        self._close()
                    continue
                raise _MachineDegrade("bad_token")
            if st == "colon":
                if c in _WS:
                    i += 1
                    continue
                if c == ":":
                    self.state = "value_start"
                    i += 1
                    continue
                raise _MachineDegrade("bad_token")
            if st == "value_start":
                if c in _WS:
                    i += 1
                    continue
                key = self._key
                if key in ("arguments", "parameters") and not self._args_seen:
                    self._args_seen = True
                    self._args = _ArgsValue(self._emit_args)
                    self.state = "value_args"
                    continue
                if key == "name":
                    self._val = _JsonValue(keep=True)
                    self.state = "value_name"
                    continue
                if key == "function" and c == "{":
                    self.depth += 1
                    self.state = "key"
                    i += 1
                    continue
                self._val = _JsonValue()
                self.state = "value_skip"
                continue
            raise _MachineDegrade("bad_token")  # pragma: no cover
        return i


class _CallsValue:
    """One call object OR a JSON list of call objects (the shared inner
    engine of the json dialect, hermes payloads, and mistral)."""

    def __init__(self, m: "_Machine") -> None:
        self.m = m
        self.state = "start"
        self.done = False
        self._list = False
        self._call: Optional[_CallObject] = None

    def feed(self, text: str) -> int:
        i, n = 0, len(text)
        while i < n and not self.done:
            if self.state == "call":
                k = self._call.feed(text[i:])
                i += k
                if self._call.done:
                    self._call = None
                    if self._list:
                        self.state = "sep"
                    else:
                        self.done = True
                elif k == 0:
                    break
                continue
            c = text[i]
            if c in _WS:
                i += 1
                continue
            if self.state == "start":
                if c == "{":
                    self._call = _CallObject(self.m)
                    self.state = "call"
                    continue
                if c == "[":
                    self._list = True
                    self.state = "item"
                    i += 1
                    continue
                raise _MachineDegrade("not_call")
            if self.state == "item":
                if c == "{":
                    self._call = _CallObject(self.m)
                    self.state = "call"
                    continue
                if c == "]":
                    i += 1
                    self.done = True
                    continue
                raise _MachineDegrade("bad_list")
            if self.state == "sep":
                if c == ",":
                    self.state = "item"
                    i += 1
                    continue
                if c == "]":
                    i += 1
                    self.done = True
                    continue
                raise _MachineDegrade("bad_list")
        return i

    @property
    def open_call(self) -> Optional[_CallObject]:
        return self._call


# ---------------------------------------------------------------------------
# Machine base
# ---------------------------------------------------------------------------


class _Machine:
    """Base plumbing for one jailed segment: ``feed`` appends to the
    unprocessed tail (``_pend``) and steps the state machine; ``_raw``
    tracks raw text consumed since the last emitted event (the
    degrade-to-content replay buffer AND the jail's buffer-cap
    accounting); ``done`` + ``trailing`` hand unconsumed text back to the
    jail's detector (back-to-back calls with content between them)."""

    dialect = "?"

    def __init__(self, ctx: _JailCtx) -> None:
        self.ctx = ctx
        self.done = False
        self.trailing = ""
        self.open_index: Optional[int] = None
        self.calls_done = 0
        # True once ANY event left this machine. Gates degrade-to-content:
        # the raw tail is only an exact replay while nothing was emitted
        # (events can land mid-_step, before the between-step raw trim, so
        # replaying raw after an emission would duplicate the call's text
        # on the wire as content).
        self.emitted_any = False
        self._pend = ""
        self._raw: List[str] = []
        self._raw_len = 0
        self._out: List[object] = []
        self._resolved = False

    # -- event plumbing ----------------------------------------------------

    def _emit(self, ev: object) -> None:
        self._out.append(ev)
        self.emitted_any = True
        if isinstance(ev, CallStart):
            self.open_index = ev.index
        elif isinstance(ev, CallEnd):
            self.open_index = None
            self.calls_done += 1
        self._resolved = True

    def _discard(self) -> None:
        """Mark consumed raw as structurally resolved (dropped segments,
        e.g. harmony analysis) so it neither replays nor counts toward
        the buffer cap."""
        self._resolved = True

    def feed(self, text: str) -> List[object]:
        self._out = []
        self._pend += text
        self._raw.append(text)
        self._raw_len += len(text)
        try:
            while not self.done:
                self._resolved = False
                progressed = self._step()
                if self._resolved:
                    # Everything up to the unprocessed tail is resolved
                    # into events (or dropped); only the tail can still
                    # degrade to content.
                    self._raw = [self._pend]
                    self._raw_len = len(self._pend)
                if not progressed:
                    break
        except _MachineDegrade as exc:
            exc.events = self._out
            raise
        return self._out

    def _step(self) -> bool:
        raise NotImplementedError

    # -- degrade / finish --------------------------------------------------

    def raw_text(self) -> str:
        return "".join(self._raw)

    def raw_len(self) -> int:
        return self._raw_len

    def finish(self) -> List[object]:
        """Stream ended mid-construct: seal an open call as truncated;
        otherwise un-emitted jailed text degrades to content (exact
        replay — only while nothing was emitted, see ``emitted_any``)."""
        self._out = []
        if self.open_index is not None:
            self._emit(CallEnd(self.open_index, error="truncated",
                               degraded=True))
        elif not self.emitted_any:
            raw = self.raw_text()
            if raw.strip():
                self._out.append(ContentDelta(raw))
        self._pend = ""
        self._raw = []
        self._raw_len = 0
        return self._out

    def _finish_done(self) -> None:
        self.done = True
        self.trailing, self._pend = self._pend, ""
        self._resolved = True


class _JsonMachine(_Machine):
    """Dialect ``json``: the stream itself is a call object or a list of
    them (jailed from the first ``{`` / ``[``)."""

    dialect = "json"

    def __init__(self, ctx: _JailCtx) -> None:
        super().__init__(ctx)
        self._calls = _CallsValue(self)

    def _step(self) -> bool:
        if not self._pend:
            return False
        k = self._calls.feed(self._pend)
        self._pend = self._pend[k:]
        if self._calls.done:
            self._finish_done()
            return True
        return False


class _MistralMachine(_Machine):
    """Dialect ``mistral``: ``[TOOL_CALLS]`` then a JSON call list."""

    dialect = "mistral"
    MARKER = "[TOOL_CALLS]"

    def __init__(self, ctx: _JailCtx) -> None:
        super().__init__(ctx)
        self._skip = len(self.MARKER)
        self._calls = _CallsValue(self)

    def _step(self) -> bool:
        if self._skip:
            if len(self._pend) < self._skip:
                return False
            self._pend = self._pend[self._skip:]
            self._skip = 0
            return True
        if not self._pend:
            return False
        k = self._calls.feed(self._pend)
        self._pend = self._pend[k:]
        if self._calls.done:
            self._finish_done()
            return True
        return False


class _TagBlockMachine(_Machine):
    """``<tool_call>`` block: sniffs hermes (JSON payload) vs xml
    (``<function=NAME><parameter=K>V</parameter>...</function>``).
    XML parameters stream element-wise: each completed element appends
    one JSON fragment to the arguments string."""

    MARKER = "<tool_call>"
    CLOSE = "</tool_call>"
    FN_OPEN = "<function="
    FN_CLOSE = "</function>"
    P_OPEN = "<parameter="
    P_CLOSE = "</parameter>"

    def __init__(self, ctx: _JailCtx, force: Optional[str] = None) -> None:
        super().__init__(ctx)
        self._force = force
        self.dialect = force or "hermes"
        self.state = "marker"
        self._skip = len(self.MARKER)
        self._calls: Optional[_CallsValue] = None
        self._buf = ""  # tag-name / parameter-value capture
        self._pkey: Optional[str] = None
        self._nparams = 0
        self._call_index: Optional[int] = None

    def _step(self) -> bool:
        st = self.state
        if st == "marker":
            if len(self._pend) < self._skip:
                return False
            self._pend = self._pend[self._skip:]
            self._skip = 0
            self.state = "sniff"
            return True
        if st == "sniff":
            p = self._pend.lstrip(_WS)
            self._pend = p
            if not p:
                return False
            c = p[0]
            if c == "<":
                if self._force == "hermes":
                    raise _MachineDegrade("drift")
                if p.startswith(self.FN_OPEN):
                    self.dialect = "xml"
                    self._pend = p[len(self.FN_OPEN):]
                    self.state = "xml_name"
                    return True
                if self.FN_OPEN.startswith(p):
                    return False
                raise _MachineDegrade("drift")
            if c in "{[":
                if self._force == "xml":
                    raise _MachineDegrade("drift")
                self.dialect = "hermes"
                self._calls = _CallsValue(self)
                self.state = "payload"
                return True
            raise _MachineDegrade("drift")
        if st == "payload":
            if not self._pend:
                return False
            k = self._calls.feed(self._pend)
            self._pend = self._pend[k:]
            if self._calls.done:
                self.state = "close"
                return True
            return False
        if st == "close":
            p = self._pend.lstrip(_WS)
            self._pend = p
            if not p:
                return False
            if p.startswith(self.CLOSE):
                self._pend = p[len(self.CLOSE):]
                if self.dialect == "xml":
                    self._xml_end_call()
                self._finish_done()
                return True
            if self.CLOSE.startswith(p):
                return False
            raise _MachineDegrade("missing_close")
        if st == "xml_name":
            idx = self._pend.find(">")
            if idx == -1:
                self._buf += self._pend
                self._pend = ""
                return False
            name = self._buf + self._pend[:idx]
            self._pend = self._pend[idx + 1:]
            self._buf = ""
            if not _NAME_RE.match(name):
                raise _MachineDegrade("bad_name")
            self._call_index = self.ctx.alloc_index()
            self._emit(
                CallStart(self._call_index, name, self.ctx.new_call_id())
            )
            self.state = "xml_params"
            return True
        if st == "xml_params":
            p = self._pend.lstrip(_WS)
            self._pend = p
            if not p:
                return False
            if p.startswith(self.P_OPEN):
                self._pend = p[len(self.P_OPEN):]
                self.state = "xml_pkey"
                return True
            if p.startswith(self.FN_CLOSE):
                self._pend = p[len(self.FN_CLOSE):]
                self._emit(ArgsDelta(
                    self._call_index, "}" if self._nparams else "{}"
                ))
                self.state = "close"
                return True
            if self.P_OPEN.startswith(p) or self.FN_CLOSE.startswith(p):
                return False
            raise _MachineDegrade("drift")
        if st == "xml_pkey":
            idx = self._pend.find(">")
            if idx == -1:
                self._buf += self._pend
                self._pend = ""
                return False
            self._pkey = self._buf + self._pend[:idx]
            self._pend = self._pend[idx + 1:]
            self._buf = ""
            if not _NAME_RE.match(self._pkey):
                raise _MachineDegrade("bad_name")
            self.state = "xml_pval"
            return True
        if st == "xml_pval":
            self._buf += self._pend
            self._pend = ""
            idx = self._buf.find(self.P_CLOSE)
            if idx == -1:
                return False
            value = self._buf[:idx].strip()
            self._pend = self._buf[idx + len(self.P_CLOSE):]
            self._buf = ""
            try:
                parsed = json.loads(value)
            except json.JSONDecodeError:
                parsed = value
            frag = (
                ("{" if self._nparams == 0 else ",")
                + json.dumps(self._pkey)
                + ":"
                + json.dumps(parsed, separators=(",", ":"))
            )
            self._nparams += 1
            self._emit(ArgsDelta(self._call_index, frag))
            self.state = "xml_params"
            return True
        raise _MachineDegrade("drift")  # pragma: no cover

    def _xml_end_call(self) -> None:
        self._emit(CallEnd(self._call_index))


class _HarmonyMachine(_Machine):
    """gpt-oss harmony channels. Routing: ``analysis`` is reasoning and
    is dropped; ``commentary to=functions.NAME`` is a tool call whose
    JSON payload streams as argument deltas; ``final`` streams to
    content (whitespace-trimmed per segment, matching the one-shot
    parser). The machine owns the stream to its end — harmony formats
    the whole response once a channel marker appears."""

    dialect = "harmony"
    CHANNEL = "<|channel|>"
    MESSAGE = "<|message|>"
    TERMS = ("<|call|>", "<|end|>", "<|channel|>", "<|start|>")

    def __init__(self, ctx: _JailCtx) -> None:
        super().__init__(ctx)
        self.state = "marker"
        self._skip = len(self.CHANNEL)
        self._hbuf = ""
        self._mode: Optional[str] = None
        self._args: Optional[_ArgsValue] = None
        self._call_index: Optional[int] = None
        self._lead = False
        self._ws_hold = ""

    def _step(self) -> bool:
        st = self.state
        if st == "marker":
            if len(self._pend) < self._skip:
                return False
            self._pend = self._pend[self._skip:]
            self._skip = 0
            self.state = "header"
            return True
        if st == "header":
            self._hbuf += self._pend
            self._pend = ""
            idx = self._hbuf.find(self.MESSAGE)
            if idx == -1:
                return False
            header = self._hbuf[:idx]
            self._pend = self._hbuf[idx + len(self.MESSAGE):]
            self._hbuf = ""
            self._begin_segment(header.strip())
            self.state = "body"
            return True
        if st == "body":
            if not self._pend:
                return False
            idx, term = find_first(self._pend, self.TERMS)
            if idx == -1:
                part, self._pend = holdback_split(self._pend, self.TERMS)
                if part:
                    self._route(part)
                return False
            part = self._pend[:idx]
            self._pend = self._pend[idx + len(term):]
            if part:
                self._route(part)
            self._end_segment()
            if term == self.CHANNEL:
                self.state = "header"
            else:
                self.state = "filler"
            return True
        if st == "filler":
            idx = self._pend.find(self.CHANNEL)
            if idx == -1:
                _, self._pend = holdback_split(self._pend, (self.CHANNEL,))
                self._discard()
                return False
            self._pend = self._pend[idx + len(self.CHANNEL):]
            self._discard()
            self.state = "header"
            return True
        raise _MachineDegrade("drift")  # pragma: no cover

    def _begin_segment(self, header: str) -> None:
        if header.startswith("analysis"):
            self._mode = "analysis"
        elif header.startswith("final"):
            self._mode = "final"
            self._lead = True
            self._ws_hold = ""
        elif header.startswith("commentary"):
            m = re.search(r"to=functions\.([\w.-]+)", header)
            if m is None:
                self._mode = "drop"
            else:
                self._mode = "call"
                self._call_index = self.ctx.alloc_index()
                self._emit(CallStart(
                    self._call_index, m.group(1), self.ctx.new_call_id()
                ))
                self._args = _ArgsValue(
                    self._emit_args, string_embedded_json=False
                )
        else:
            raise _MachineDegrade("drift")

    def _emit_args(self, text: str) -> None:
        if text:
            self._emit(ArgsDelta(self._call_index, text))

    def _route(self, part: str) -> None:
        mode = self._mode
        if mode == "call":
            self._args.feed(part)
            # Trailing text after a complete payload (usually ws) is
            # structural filler.
            self._discard()
            return
        if mode == "final":
            if self._lead:
                part = part.lstrip()
                if not part:
                    self._discard()
                    return
                self._lead = False
            s = self._ws_hold + part
            emit_part = s.rstrip()
            self._ws_hold = s[len(emit_part):]
            if emit_part:
                self._emit(ContentDelta(emit_part))
            else:
                self._discard()
            return
        # analysis / drop: reasoning or non-function commentary — dropped
        # as it arrives (an endless analysis channel must not grow the
        # jail buffer).
        self._discard()

    def _end_segment(self) -> None:
        if self._mode == "call":
            self._seal_call()
        elif self._mode == "final":
            self._ws_hold = ""
        self._mode = None

    def _seal_call(self) -> None:
        args = self._args
        status = args.close() if args is not None else "empty"
        if status == "done":
            # Scalar/string payloads finalize at the terminator (the
            # one-shot parser's {"value": ...} / verbatim-object shapes).
            self._emit(CallEnd(self._call_index, degraded=args.degraded))
        elif status == "empty":
            self._emit(ArgsDelta(self._call_index, "{}"))
            self._emit(CallEnd(self._call_index))
        else:
            # Payload ended (terminator / EOF) mid-JSON: the emitted
            # deltas are sealed as a truncated call.
            self._emit(CallEnd(self._call_index, error="truncated",
                               degraded=True))
        self._args = None
        self._call_index = None

    def finish(self) -> List[object]:
        self._out = []
        if self.state == "body":
            # A body running to EOF is complete by the harmony grammar
            # (the one-shot regexes accept ``$`` as a terminator).
            self._end_segment()
        elif self.open_index is not None:
            self._emit(CallEnd(self.open_index, error="truncated",
                               degraded=True))
        elif self.state == "header" and not self.emitted_any:
            raw = self.raw_text()
            if raw.strip():
                self._out.append(ContentDelta(raw))
        self._pend = ""
        self._raw = []
        self._raw_len = 0
        return self._out


class _DsmlMachine(_Machine):
    """DeepSeek DSML: ``<｜DSML｜function_calls>`` block of invokes with
    typed parameter elements. Element-wise streaming: each completed
    ``<｜DSML｜parameter ...>`` appends one JSON fragment."""

    dialect = "dsml"
    MARK = "<｜DSML｜"
    OPEN_TAIL = "function_calls>"
    P_CLOSE = "</｜DSML｜parameter>"
    INVOKE_RE = re.compile(r'^<｜DSML｜invoke\s+name="([^"]+)"\s*>$')
    PARAM_RE = re.compile(
        r'^<｜DSML｜parameter\s+name="([^"]+)"'
        r'(?:\s+string="(true|false)")?\s*>$'
    )
    BLOCK_CLOSE = "</｜DSML｜function_calls>"
    INVOKE_CLOSE = "</｜DSML｜invoke>"

    def __init__(self, ctx: _JailCtx) -> None:
        super().__init__(ctx)
        self.state = "marker"
        self._skip = len(self.MARK)
        self._tbuf = ""
        self._vbuf = ""
        self._pkey: Optional[str] = None
        self._pstring: Optional[str] = None
        self._nparams = 0
        self._call_index: Optional[int] = None

    def _take_tag(self) -> Optional[str]:
        """Accumulate ``self._pend`` until a ``>`` closes the tag."""
        self._tbuf += self._pend
        self._pend = ""
        idx = self._tbuf.find(">")
        if idx == -1:
            return None
        tag = self._tbuf[: idx + 1]
        self._pend = self._tbuf[idx + 1:]
        self._tbuf = ""
        return tag

    def _step(self) -> bool:
        st = self.state
        if st == "marker":
            if len(self._pend) < self._skip:
                return False
            self._pend = self._pend[self._skip:]
            self._skip = 0
            self.state = "open"
            return True
        if st == "open":
            p = self._pend
            if p.startswith(self.OPEN_TAIL):
                self._pend = p[len(self.OPEN_TAIL):]
                self.state = "body"
                return True
            if self.OPEN_TAIL.startswith(p):
                return False
            raise _MachineDegrade("drift")
        if st in ("body", "params"):
            if not self._tbuf:
                p = self._pend.lstrip(_WS)
                self._pend = p
                if not p:
                    return False
                if p[0] != "<":
                    raise _MachineDegrade("drift")
            tag = self._take_tag()
            if tag is None:
                return False
            if st == "body":
                m = self.INVOKE_RE.match(tag)
                if m is not None:
                    self._call_index = self.ctx.alloc_index()
                    self._nparams = 0
                    self._emit(CallStart(
                        self._call_index, m.group(1), self.ctx.new_call_id()
                    ))
                    self.state = "params"
                    return True
                if tag == self.BLOCK_CLOSE:
                    self._finish_done()
                    return True
                raise _MachineDegrade("drift")
            m = self.PARAM_RE.match(tag)
            if m is not None:
                self._pkey, self._pstring = m.group(1), m.group(2)
                self._vbuf = ""
                self.state = "pvalue"
                return True
            if tag == self.INVOKE_CLOSE:
                self._emit(ArgsDelta(
                    self._call_index, "}" if self._nparams else "{}"
                ))
                self._emit(CallEnd(self._call_index))
                self.state = "body"
                return True
            raise _MachineDegrade("drift")
        if st == "pvalue":
            self._vbuf += self._pend
            self._pend = ""
            idx = self._vbuf.find(self.P_CLOSE)
            if idx == -1:
                return False
            value = self._vbuf[:idx].strip()
            self._pend = self._vbuf[idx + len(self.P_CLOSE):]
            self._vbuf = ""
            if self._pstring == "false":
                try:
                    parsed = json.loads(value)
                except json.JSONDecodeError:
                    parsed = value
            else:
                parsed = value
            frag = (
                ("{" if self._nparams == 0 else ",")
                + json.dumps(self._pkey)
                + ":"
                + json.dumps(parsed, separators=(",", ":"))
            )
            self._nparams += 1
            self._emit(ArgsDelta(self._call_index, frag))
            self.state = "params"
            return True
        raise _MachineDegrade("drift")  # pragma: no cover


class _PyLiteral:
    """One Python literal expression consumed up to a top-level ``,`` or
    ``)`` — quote-aware (single/double, escapes) and bracket-aware, so
    nested JSON text inside a string argument never splits early."""

    def __init__(self) -> None:
        self.text: List[str] = []
        self.done = False
        self.term: Optional[str] = None
        self._depth = 0
        self._quote: Optional[str] = None
        self._esc = False

    def feed(self, text: str) -> int:
        i, n = 0, len(text)
        start = 0
        while i < n:
            c = text[i]
            if self._quote is not None:
                i += 1
                if self._esc:
                    self._esc = False
                elif c == "\\":
                    self._esc = True
                elif c == self._quote:
                    self._quote = None
                continue
            if c in "'\"":
                self._quote = c
                i += 1
                continue
            if c in "([{":
                self._depth += 1
                i += 1
                continue
            if c in ")]}":
                if self._depth == 0:
                    if c == ")":
                        self.done = True
                        self.term = c
                        break
                    raise _MachineDegrade("bad_nesting")
                self._depth -= 1
                i += 1
                continue
            if c == "," and self._depth == 0:
                self.done = True
                self.term = c
                break
            i += 1
        self.text.append(text[start:i])
        return i

    def raw(self) -> str:
        return "".join(self.text)


_IDENT_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_."
)
_IDENT_RE = re.compile(r"^[A-Za-z_][\w.]*$")


class _PythonicMachine(_Machine):
    """Pinned ``pythonic`` dialect: ``[fn(a=1, b="x"), g()]``.
    Element-wise streaming: each completed keyword argument appends one
    JSON fragment; positional arguments are malformed by the dialect and
    degrade (the one-shot parser rejects them too)."""

    dialect = "pythonic"

    def __init__(self, ctx: _JailCtx) -> None:
        super().__init__(ctx)
        self.state = "openbr"
        self._ibuf = ""
        self._lit: Optional[_PyLiteral] = None
        self._key: Optional[str] = None
        self._nargs = 0
        self._call_index: Optional[int] = None

    def _ident_split(self) -> Optional[str]:
        """Take leading identifier chars from ``_pend`` into ``_ibuf``;
        returns the first non-identifier char (unconsumed) or None when
        more input is needed."""
        p = self._pend
        k = 0
        while k < len(p) and p[k] in _IDENT_CHARS:
            k += 1
        self._ibuf += p[:k]
        self._pend = p[k:]
        if not self._pend:
            return None
        return self._pend[0]

    def _close_call(self) -> None:
        self._emit(ArgsDelta(
            self._call_index, "}" if self._nargs else "{}"
        ))
        self._emit(CallEnd(self._call_index))
        self.state = "sep"

    def _step(self) -> bool:
        st = self.state
        if st == "openbr":
            if not self._pend:
                return False
            if self._pend[0] != "[":
                raise _MachineDegrade("not_call")
            self._pend = self._pend[1:]
            self.state = "call_or_end"
            return True
        if st == "aval":
            if not self._pend:
                return False
            k = self._lit.feed(self._pend)
            self._pend = self._pend[k:]
            if not self._lit.done:
                return False
            raw = self._lit.raw().strip()
            try:
                v = ast.literal_eval(raw)
                frag_v = json.dumps(v, separators=(",", ":"))
            except (ValueError, SyntaxError, TypeError,
                    MemoryError, RecursionError):
                raise _MachineDegrade("bad_literal")
            frag = (
                ("{" if self._nargs == 0 else ",")
                + json.dumps(self._key) + ":" + frag_v
            )
            self._nargs += 1
            self._emit(ArgsDelta(self._call_index, frag))
            term, self._pend = self._pend[0], self._pend[1:]
            self._lit = None
            if term == ",":
                self.state = "arg_or_close"
            else:
                self._close_call()
            return True
        p = self._pend.lstrip(_WS) if not self._ibuf else self._pend
        self._pend = p
        if st == "call_or_end":
            if not p and not self._ibuf:
                return False
            if not self._ibuf and p[0] == "]":
                self._pend = p[1:]
                self._finish_done()
                return True
            nxt = self._ident_split()
            if nxt is None:
                return False
            name, self._ibuf = self._ibuf, ""
            if nxt != "(" or not _IDENT_RE.match(name):
                raise _MachineDegrade("drift")
            self._pend = self._pend[1:]
            self._call_index = self.ctx.alloc_index()
            self._nargs = 0
            self._emit(CallStart(
                self._call_index, name, self.ctx.new_call_id()
            ))
            self.state = "arg_or_close"
            return True
        if st == "arg_or_close":
            if not p and not self._ibuf:
                return False
            if not self._ibuf and p[0] == ")":
                self._pend = p[1:]
                self._close_call()
                return True
            if not self._ibuf and p[0] not in _IDENT_CHARS:
                raise _MachineDegrade("positional")
            nxt = self._ident_split()
            if nxt is None:
                return False
            key, self._ibuf = self._ibuf, ""
            if nxt != "=" or not _IDENT_RE.match(key):
                raise _MachineDegrade("positional")
            self._pend = self._pend[1:]
            self._key = key
            self._lit = _PyLiteral()
            self.state = "aval"
            return True
        if st == "sep":
            if not p:
                return False
            if p[0] == ",":
                self._pend = p[1:]
                self.state = "call_or_end"
                return True
            if p[0] == "]":
                self._pend = p[1:]
                self._finish_done()
                return True
            raise _MachineDegrade("drift")
        raise _MachineDegrade("drift")  # pragma: no cover


# ---------------------------------------------------------------------------
# Dialect registry (the jail's detector uses this)
# ---------------------------------------------------------------------------

# Auto-detect markers → machine factory. hermes and xml share the
# <tool_call> marker; _TagBlockMachine sniffs which one it is.
AUTO_MARKERS = (
    ("<tool_call>", lambda ctx: _TagBlockMachine(ctx)),
    ("[TOOL_CALLS]", lambda ctx: _MistralMachine(ctx)),
    ("<|channel|>", lambda ctx: _HarmonyMachine(ctx)),
    ("<｜DSML｜", lambda ctx: _DsmlMachine(ctx)),
)

# Pinned dialect → (markers, machine factory).
PINNED = {
    "json": (("{", "["), _JsonMachine),
    "pythonic": (("[",), _PythonicMachine),
    "hermes": (
        ("<tool_call>",), lambda ctx: _TagBlockMachine(ctx, force="hermes")
    ),
    "xml": (
        ("<tool_call>",), lambda ctx: _TagBlockMachine(ctx, force="xml")
    ),
    "mistral": (("[TOOL_CALLS]",), _MistralMachine),
    "harmony": (("<|channel|>",), _HarmonyMachine),
    "dsml": (("<｜DSML｜",), _DsmlMachine),
}
