"""Reasoning-content extraction (<think> ... </think> and friends).

Reference parity: lib/parsers/src/reasoning/{base_parser,gpt_oss_parser,
granite_parser}.rs — split generated text into `reasoning_content` and
`content`. The streaming parser is a small state machine that survives tags
straddling delta boundaries.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

KNOWN_TAGS = {
    "think": ("<think>", "</think>"),
    "reasoning": ("<reasoning>", "</reasoning>"),
    "seed": ("<seed:think>", "</seed:think>"),
}


def split_reasoning(text: str, style: str = "think") -> Tuple[str, str]:
    """One-shot split of a complete response → (reasoning, content)."""
    open_tag, close_tag = KNOWN_TAGS[style]
    start = text.find(open_tag)
    if start == -1:
        # Some models emit the close tag only (reasoning-first templates).
        end_only = text.find(close_tag)
        if end_only != -1:
            return text[:end_only].strip(), text[end_only + len(close_tag):].lstrip("\n")
        return "", text
    end = text.find(close_tag, start)
    if end == -1:
        return text[start + len(open_tag):].strip(), ""
    reasoning = text[start + len(open_tag): end].strip()
    content = (text[:start] + text[end + len(close_tag):]).lstrip("\n")
    return reasoning, content


@dataclass
class _State:
    mode: str = "content"  # content | reasoning
    buffer: str = ""  # held-back text that may be a partial tag


class ReasoningParser:
    """Streaming splitter: feed text deltas, get (reasoning_delta,
    content_delta) pairs. Holds back a suffix that could be a partial tag."""

    def __init__(self, style: str = "think", starts_in_reasoning: bool = False) -> None:
        self.open_tag, self.close_tag = KNOWN_TAGS[style]
        self._s = _State(mode="reasoning" if starts_in_reasoning else "content")

    def _active_tag(self) -> str:
        return self.close_tag if self._s.mode == "reasoning" else self.open_tag

    def feed(self, delta: str) -> Tuple[str, str]:
        reasoning_out = []
        content_out = []
        text = self._s.buffer + delta
        self._s.buffer = ""
        while text:
            tag = self._active_tag()
            idx = text.find(tag)
            if idx != -1:
                emitted, text = text[:idx], text[idx + len(tag):]
                if self._s.mode == "reasoning":
                    reasoning_out.append(emitted)
                    self._s.mode = "content"
                else:
                    content_out.append(emitted)
                    self._s.mode = "reasoning"
                continue
            # No full tag: hold back the longest suffix that is a prefix of
            # the tag we're looking for.
            hold = 0
            for n in range(min(len(tag) - 1, len(text)), 0, -1):
                if tag.startswith(text[-n:]):
                    hold = n
                    break
            emit, self._s.buffer = (text[:-hold], text[-hold:]) if hold else (text, "")
            (reasoning_out if self._s.mode == "reasoning" else content_out).append(emit)
            break
        return "".join(reasoning_out), "".join(content_out)

    def flush(self) -> Tuple[str, str]:
        """End of stream: release any held-back partial tag as-is."""
        tail = self._s.buffer
        self._s.buffer = ""
        if not tail:
            return "", ""
        if self._s.mode == "reasoning":
            return tail, ""
        return "", tail
