"""Reasoning-content extraction (<think> ... </think> and friends).

Reference parity: lib/parsers/src/reasoning/{base_parser,gpt_oss_parser,
granite_parser}.rs — split generated text into `reasoning_content` and
`content`. The streaming parser is a small state machine that survives tags
straddling delta boundaries. Styles may have several equivalent marker
spellings (granite emits prose markers in two variants each).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence, Tuple

from dynamo_tpu.parsers.holdback import find_first as _find_first
from dynamo_tpu.parsers.holdback import holdback_split

# style → (open-tag variants, close-tag variants). The first variant is the
# canonical spelling; all variants are recognized on input.
KNOWN_MARKERS = {
    "think": (("<think>",), ("</think>",)),
    "reasoning": (("<reasoning>",), ("</reasoning>",)),
    "seed": (("<seed:think>",), ("</seed:think>",)),
    # ref: granite_parser.rs:19-23 — prose markers, two spellings each.
    "granite": (
        ("Here's my thought process:", "Here is my thought process:"),
        ("Here's my response:", "Here is my response:"),
    ),
}

# Backwards-compatible view for single-tag styles.
KNOWN_TAGS = {
    style: (opens[0], closes[0])
    for style, (opens, closes) in KNOWN_MARKERS.items()
}


def split_reasoning(text: str, style: str = "think") -> Tuple[str, str]:
    """One-shot split of a complete response → (reasoning, content)."""
    opens, closes = KNOWN_MARKERS[style]
    start, open_tag = _find_first(text, opens)
    if start == -1:
        # Some models emit the close tag only (reasoning-first templates).
        end_only, close_tag = _find_first(text, closes)
        if end_only != -1:
            return (
                text[:end_only].strip(),
                text[end_only + len(close_tag):].lstrip(),
            )
        return "", text
    end, close_tag = _find_first(text, closes, start)
    if end == -1:
        return text[start + len(open_tag):].strip(), ""
    reasoning = text[start + len(open_tag): end].strip()
    content = (text[:start] + text[end + len(close_tag):]).lstrip()
    return reasoning, content


@dataclass
class _State:
    mode: str = "content"  # content | reasoning
    buffer: str = ""  # held-back text that may be a partial tag


class ReasoningParser:
    """Streaming splitter: feed text deltas, get (reasoning_delta,
    content_delta) pairs. Holds back a suffix that could be a partial tag."""

    def __init__(self, style: str = "think", starts_in_reasoning: bool = False) -> None:
        self.open_tags, self.close_tags = KNOWN_MARKERS[style]
        self._s = _State(mode="reasoning" if starts_in_reasoning else "content")

    def _active_tags(self) -> Sequence[str]:
        return self.close_tags if self._s.mode == "reasoning" else self.open_tags

    def feed(self, delta: str) -> Tuple[str, str]:
        reasoning_out = []
        content_out = []
        text = self._s.buffer + delta
        self._s.buffer = ""
        while text:
            tags = self._active_tags()
            idx, tag = _find_first(text, tags)
            if idx != -1:
                emitted, text = text[:idx], text[idx + len(tag):]
                if self._s.mode == "reasoning":
                    reasoning_out.append(emitted)
                    self._s.mode = "content"
                else:
                    content_out.append(emitted)
                    self._s.mode = "reasoning"
                continue
            # No full tag: hold back the longest suffix that is a prefix of
            # any tag variant we're looking for (parsers/holdback.py — the
            # same scheme the tool-call jail uses).
            emit, self._s.buffer = holdback_split(text, tags)
            (reasoning_out if self._s.mode == "reasoning" else content_out).append(emit)
            break
        return "".join(reasoning_out), "".join(content_out)

    def flush(self) -> Tuple[str, str]:
        """End of stream: release any held-back partial tag as-is."""
        tail = self._s.buffer
        self._s.buffer = ""
        if not tail:
            return "", ""
        if self._s.mode == "reasoning":
            return tail, ""
        return "", tail
