"""Tool-call parsing across model dialects.

Reference parity: lib/parsers/src/tool_calling/{json,pythonic,xml,…} —
normalize whatever the model emitted into OpenAI tool_calls entries.
Dialects:
  json     — bare {"name": ..., "arguments"|"parameters": {...}} or a list
  hermes   — <tool_call>{json}</tool_call> (Qwen/Hermes templates)
  mistral  — [TOOL_CALLS]{json list}
  pythonic — [fn(a=1, b="x"), ...] python-literal calls (llama-3.2 style)
"""

from __future__ import annotations

import ast
import json
import re
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


@dataclass
class ToolCall:
    name: str
    arguments: Dict[str, Any] = field(default_factory=dict)
    call_id: str = ""

    def to_openai(self) -> Dict[str, Any]:
        return {
            "id": self.call_id or f"call-{uuid.uuid4().hex[:24]}",
            "type": "function",
            "function": {
                "name": self.name,
                "arguments": json.dumps(self.arguments, separators=(",", ":")),
            },
        }


def _normalize(obj: Any) -> Optional[ToolCall]:
    if not isinstance(obj, dict):
        return None
    name = obj.get("name")
    if not name and isinstance(obj.get("function"), dict):
        inner = obj["function"]
        name = inner.get("name")
        obj = inner
    if not isinstance(name, str) or not name:
        return None
    args = obj.get("arguments", obj.get("parameters", {}))
    if isinstance(args, str):
        try:
            args = json.loads(args)
        except json.JSONDecodeError:
            args = {"__raw__": args}
    if not isinstance(args, dict):
        args = {"value": args}
    return ToolCall(name=name, arguments=args)


def _parse_json_calls(text: str) -> List[ToolCall]:
    text = text.strip()
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        return []
    items = obj if isinstance(obj, list) else [obj]
    calls = [c for c in (_normalize(i) for i in items) if c is not None]
    return calls


_HERMES_RE = re.compile(r"<tool_call>\s*(.*?)\s*</tool_call>", re.DOTALL)
_MISTRAL_RE = re.compile(r"\[TOOL_CALLS\]\s*(\[.*\]|\{.*\})", re.DOTALL)


def _parse_hermes(text: str) -> Tuple[List[ToolCall], str]:
    calls: List[ToolCall] = []
    for m in _HERMES_RE.finditer(text):
        calls.extend(_parse_json_calls(m.group(1)))
    remainder = _HERMES_RE.sub("", text).strip()
    return calls, remainder


def _parse_mistral(text: str) -> Tuple[List[ToolCall], str]:
    m = _MISTRAL_RE.search(text)
    if not m:
        return [], text
    calls = _parse_json_calls(m.group(1))
    remainder = (text[: m.start()] + text[m.end():]).strip()
    return calls, remainder


def _parse_pythonic(text: str) -> List[ToolCall]:
    text = text.strip()
    if not (text.startswith("[") and text.endswith("]")):
        return []
    try:
        tree = ast.parse(text, mode="eval")
    except SyntaxError:
        return []
    if not isinstance(tree.body, ast.List):
        return []
    calls: List[ToolCall] = []
    for node in tree.body.elts:
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)):
            return []
        args: Dict[str, Any] = {}
        try:
            for kw in node.keywords:
                if kw.arg is None:
                    return []
                args[kw.arg] = ast.literal_eval(kw.value)
        except (ValueError, SyntaxError):
            return []
        calls.append(ToolCall(name=node.func.id, arguments=args))
    return calls


def detect_and_parse_tool_calls(
    text: str, dialect: Optional[str] = None
) -> Tuple[List[ToolCall], str]:
    """Returns (tool_calls, remaining_content). ``dialect`` pins a format;
    None auto-detects (hermes → mistral → json → pythonic)."""
    if dialect == "hermes":
        return _parse_hermes(text)
    if dialect == "mistral":
        return _parse_mistral(text)
    if dialect == "json":
        calls = _parse_json_calls(text)
        return calls, "" if calls else text
    if dialect == "pythonic":
        calls = _parse_pythonic(text)
        return calls, "" if calls else text

    calls, remainder = _parse_hermes(text)
    if calls:
        return calls, remainder
    calls, remainder = _parse_mistral(text)
    if calls:
        return calls, remainder
    calls = _parse_json_calls(text)
    if calls:
        return calls, ""
    calls = _parse_pythonic(text)
    if calls:
        return calls, ""
    return [], text
