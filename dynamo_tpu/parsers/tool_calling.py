"""Tool-call parsing across model dialects.

Reference parity: lib/parsers/src/tool_calling/{json,pythonic,xml,harmony,
dsml} — normalize whatever the model emitted into OpenAI tool_calls entries.
Dialects:
  json     — bare {"name": ..., "arguments"|"parameters": {...}} or a list
  hermes   — <tool_call>{json}</tool_call> (Qwen/Hermes templates)
  mistral  — [TOOL_CALLS]{json list}
  pythonic — [fn(a=1, b="x"), ...] python-literal calls (llama-3.2 style)
  harmony  — gpt-oss channel format: <|channel|>commentary
             to=functions.NAME <|constrain|>json<|message|>{...}
  dsml     — DeepSeek markup: <｜DSML｜invoke name=...> with typed
             <｜DSML｜parameter> children
  xml      — <tool_call><function=NAME><parameter=K>V</parameter>... form
"""

from __future__ import annotations

import ast
import json
import re
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


@dataclass
class ToolCall:
    name: str
    arguments: Dict[str, Any] = field(default_factory=dict)
    call_id: str = ""
    # True when the argument string was unparseable and shipped as a
    # lossy {"__raw__": ...} wrap — surfaced on the emitted call (and
    # counted per dialect) so clients and the SLO plane can see lossy
    # parses instead of silently acting on mangled arguments.
    degraded: bool = False

    def to_openai(self) -> Dict[str, Any]:
        entry: Dict[str, Any] = {
            "id": self.call_id or f"call-{uuid.uuid4().hex[:24]}",
            "type": "function",
            "function": {
                "name": self.name,
                "arguments": json.dumps(self.arguments, separators=(",", ":")),
            },
        }
        if self.degraded:
            entry["degraded"] = True
        return entry


def _normalize(obj: Any) -> Optional[ToolCall]:
    if not isinstance(obj, dict):
        return None
    name = obj.get("name")
    if not name and isinstance(obj.get("function"), dict):
        inner = obj["function"]
        name = inner.get("name")
        obj = inner
    if not isinstance(name, str) or not name:
        return None
    args = obj.get("arguments", obj.get("parameters", {}))
    degraded = False
    if isinstance(args, str):
        try:
            args = json.loads(args)
        except json.JSONDecodeError:
            args = {"__raw__": args}
            degraded = True
    if not isinstance(args, dict):
        args = {"value": args}
    return ToolCall(name=name, arguments=args, degraded=degraded)


def _parse_json_calls(text: str) -> List[ToolCall]:
    text = text.strip()
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        return []
    items = obj if isinstance(obj, list) else [obj]
    calls = [c for c in (_normalize(i) for i in items) if c is not None]
    return calls


_HERMES_RE = re.compile(r"<tool_call>\s*(.*?)\s*</tool_call>", re.DOTALL)
_MISTRAL_RE = re.compile(r"\[TOOL_CALLS\]\s*(\[.*\]|\{.*\})", re.DOTALL)


def _parse_hermes(text: str) -> Tuple[List[ToolCall], str]:
    calls: List[ToolCall] = []
    for m in _HERMES_RE.finditer(text):
        calls.extend(_parse_json_calls(m.group(1)))
    remainder = _HERMES_RE.sub("", text).strip()
    return calls, remainder


def _parse_mistral(text: str) -> Tuple[List[ToolCall], str]:
    m = _MISTRAL_RE.search(text)
    if not m:
        return [], text
    calls = _parse_json_calls(m.group(1))
    remainder = (text[: m.start()] + text[m.end():]).strip()
    return calls, remainder


def _parse_pythonic(text: str) -> List[ToolCall]:
    text = text.strip()
    if not (text.startswith("[") and text.endswith("]")):
        return []
    try:
        tree = ast.parse(text, mode="eval")
    except SyntaxError:
        return []
    if not isinstance(tree.body, ast.List):
        return []
    calls: List[ToolCall] = []
    for node in tree.body.elts:
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)):
            return []
        args: Dict[str, Any] = {}
        try:
            for kw in node.keywords:
                if kw.arg is None:
                    return []
                args[kw.arg] = ast.literal_eval(kw.value)
        except (ValueError, SyntaxError):
            return []
        calls.append(ToolCall(name=node.func.id, arguments=args))
    return calls


_HARMONY_CALL_RE = re.compile(
    r"<\|channel\|>commentary\s+to=functions\.([\w.-]+)\s*"
    r"(?:<\|constrain\|>\w+)?<\|message\|>(.*?)(?=<\|call\|>|<\|end\|>|<\|channel\|>|<\|start\|>|$)",
    re.DOTALL,
)
_HARMONY_ANALYSIS_RE = re.compile(
    r"<\|channel\|>analysis<\|message\|>(.*?)(?=<\|end\|>|<\|channel\|>|<\|start\|>|$)",
    re.DOTALL,
)
_HARMONY_FINAL_RE = re.compile(
    r"<\|channel\|>final<\|message\|>(.*?)(?=<\|end\|>|<\|channel\|>|<\|start\|>|$)",
    re.DOTALL,
)


def _parse_harmony(text: str) -> Tuple[List[ToolCall], str]:
    """gpt-oss harmony channels (ref: harmony/harmony_parser.rs:33-86):
    tool calls ride the commentary channel addressed to functions.*; user
    text rides the final channel (analysis is reasoning, dropped here)."""
    if "<|channel|>" not in text:
        return [], text
    calls: List[ToolCall] = []
    for m in _HARMONY_CALL_RE.finditer(text):
        name, payload = m.group(1), m.group(2).strip()
        degraded = False
        try:
            args = json.loads(payload)
        except json.JSONDecodeError:
            args = {"__raw__": payload}
            degraded = True
        if not isinstance(args, dict):
            args = {"value": args}
        calls.append(ToolCall(name=name, arguments=args, degraded=degraded))
    finals = _HARMONY_FINAL_RE.findall(text)
    remainder = "".join(f.strip() for f in finals)
    return calls, remainder


_DSML_MARK = "<｜DSML｜"  # fullwidth vertical bars (DeepSeek tokens)
_DSML_INVOKE_RE = re.compile(
    r"<｜DSML｜invoke\s+name=\"([^\"]+)\">(.*?)</｜DSML｜invoke>",
    re.DOTALL,
)
_DSML_PARAM_RE = re.compile(
    r"<｜DSML｜parameter\s+name=\"([^\"]+)\"(?:\s+string=\"(true|false)\")?\s*>"
    r"(.*?)</｜DSML｜parameter>",
    re.DOTALL,
)
_DSML_BLOCK_RE = re.compile(
    r"<｜DSML｜function_calls>.*?</｜DSML｜function_calls>",
    re.DOTALL,
)


def _parse_dsml(text: str) -> Tuple[List[ToolCall], str]:
    """DeepSeek DSML (ref: dsml/parser.rs:13-21). Non-string parameter
    values are JSON-decoded (string="false" marks typed values)."""
    if _DSML_MARK not in text:
        return [], text
    calls: List[ToolCall] = []
    for m in _DSML_INVOKE_RE.finditer(text):
        name, body = m.group(1), m.group(2)
        args: Dict[str, Any] = {}
        for pm in _DSML_PARAM_RE.finditer(body):
            pname, is_string, value = pm.group(1), pm.group(2), pm.group(3).strip()
            if is_string == "false":
                try:
                    args[pname] = json.loads(value)
                except json.JSONDecodeError:
                    args[pname] = value
            else:
                args[pname] = value
        calls.append(ToolCall(name=name, arguments=args))
    remainder = _DSML_BLOCK_RE.sub("", text)
    # strip orphan DSML fragments outside a complete block
    remainder = re.sub(r"<｜DSML｜[^>]*>", "", remainder).strip()
    return calls, remainder


_XML_FN_RE = re.compile(
    r"<tool_call>\s*<function=([\w.-]+)>(.*?)</function>\s*</tool_call>",
    re.DOTALL,
)
_XML_PARAM_RE = re.compile(
    r"<parameter=([\w.-]+)>(.*?)</parameter>", re.DOTALL
)


def _parse_xml(text: str) -> Tuple[List[ToolCall], str]:
    """<tool_call><function=NAME><parameter=K>V</parameter>... form
    (ref: xml/parser.rs:30)."""
    calls: List[ToolCall] = []
    for m in _XML_FN_RE.finditer(text):
        args: Dict[str, Any] = {}
        for pm in _XML_PARAM_RE.finditer(m.group(2)):
            value = pm.group(2).strip()
            try:
                args[pm.group(1)] = json.loads(value)
            except json.JSONDecodeError:
                args[pm.group(1)] = value
        calls.append(ToolCall(name=m.group(1), arguments=args))
    remainder = _XML_FN_RE.sub("", text).strip()
    return calls, remainder


def _count_degraded(calls: List[ToolCall], dialect: str) -> None:
    """Lossy {"__raw__": ...} argument wraps are an SLO-visible event:
    counted per dialect (parser_degraded_args_total) next to the
    ``degraded: true`` marker already on the emitted call."""
    n = sum(1 for c in calls if c.degraded)
    if not n:
        return
    from dynamo_tpu.parsers.observe import parser_plane

    plane = parser_plane()
    for _ in range(n):
        plane.note_degraded_args(dialect)


def detect_and_parse_tool_calls(
    text: str, dialect: Optional[str] = None
) -> Tuple[List[ToolCall], str]:
    """Returns (tool_calls, remaining_content). ``dialect`` pins a format;
    None auto-detects (hermes → mistral → json → pythonic)."""
    if dialect == "hermes":
        calls, remainder = _parse_hermes(text)
        _count_degraded(calls, "hermes")
        return calls, remainder
    if dialect == "mistral":
        calls, remainder = _parse_mistral(text)
        _count_degraded(calls, "mistral")
        return calls, remainder
    if dialect == "json":
        calls = _parse_json_calls(text)
        _count_degraded(calls, "json")
        return calls, "" if calls else text
    if dialect == "pythonic":
        calls = _parse_pythonic(text)
        return calls, "" if calls else text
    if dialect == "harmony":
        calls, remainder = _parse_harmony(text)
        _count_degraded(calls, "harmony")
        return calls, remainder
    if dialect == "dsml":
        calls, remainder = _parse_dsml(text)
        _count_degraded(calls, "dsml")
        return calls, remainder
    if dialect == "xml":
        calls, remainder = _parse_xml(text)
        _count_degraded(calls, "xml")
        return calls, remainder

    for name, parser in (("harmony", _parse_harmony), ("dsml", _parse_dsml),
                         ("xml", _parse_xml), ("hermes", _parse_hermes),
                         ("mistral", _parse_mistral)):
        calls, remainder = parser(text)
        if calls:
            _count_degraded(calls, name)
            return calls, remainder
    calls = _parse_json_calls(text)
    if calls:
        _count_degraded(calls, "json")
        return calls, ""
    calls = _parse_pythonic(text)
    if calls:
        return calls, ""
    return [], text
