"""Suffix-holdback for markers straddling streaming delta boundaries.

Every streaming parser in this package (the reasoning splitter, the
tool-call jail's dialect detector, and the per-dialect machines in
parsers/incremental.py) faces the same problem: a marker like
``</tool_call>`` or ``<|channel|>`` can arrive split across two deltas,
so the longest suffix of the visible text that is a prefix of any marker
must be held back one delta instead of emitted. Two hand-rolled copies
of that scheme (jail.py + reasoning.py) had already started to drift;
this module is the single implementation both import.

Semantics:
  * ``find_first(text, markers)`` — earliest complete occurrence of any
    marker (ties broken by position, then by the order markers are
    given), as ``(index, marker)`` or ``(-1, "")``.
  * ``holdback_split(text, markers)`` — ``(emit, hold)`` where ``hold``
    is the longest suffix of ``text`` that is a proper prefix of at
    least one marker (and therefore might complete into that marker on
    the next delta). ``emit + hold == text`` always; a text containing a
    COMPLETE marker is the caller's case to handle first (call
    ``find_first`` before ``holdback_split``).
"""

from __future__ import annotations

from typing import Sequence, Tuple


def find_first(
    text: str, markers: Sequence[str], start: int = 0
) -> Tuple[int, str]:
    """Earliest complete occurrence of any marker → (index, marker), or
    (-1, "") when none occurs."""
    best, best_m = -1, ""
    for m in markers:
        i = text.find(m, start)
        if i != -1 and (best == -1 or i < best):
            best, best_m = i, m
    return best, best_m


def holdback_split(
    text: str, markers: Sequence[str]
) -> Tuple[str, str]:
    """Split ``text`` into ``(emit, hold)``: ``hold`` is the longest
    suffix that is a proper prefix of any marker. Assumes no COMPLETE
    marker occurs in ``text`` (handle that with ``find_first`` first —
    this function only guards the boundary-straddle case)."""
    if not text or not markers:
        return text, ""
    max_n = min(max(len(m) for m in markers) - 1, len(text))
    for n in range(max_n, 0, -1):
        tail = text[-n:]
        for m in markers:
            if m.startswith(tail):
                return text[:-n], tail
    return text, ""
