"""TieredKvManager: the offload/onboard engine over the storage tiers.

Reference parity: lib/llm/src/block_manager/offload.rs (async offload engine
with bounded queues + filters) and the onboard path (matched blocks brought
device-side before prefill, SURVEY §3.4). Write-through: blocks are queued
for offload when they commit on-device, so device eviction never loses
content; onboarding extends the device prefix match at admission time.
"""

from __future__ import annotations

import asyncio
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from dynamo_tpu.kvbm.tiers import HostTier
from dynamo_tpu.runtime.tasks import reap_task
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class KvbmMetrics:
    """Canonical KVBM metric families (runtime/metric_names.py ALL_KVBM).

    One instance is shared by everything that moves KV for a process: the
    TieredKvManager (native-engine offload/onboard), and optionally the
    connector leader/worker (external-engine seam, which counts
    pool-pressure truncations and revoked loads). Tier occupancy and
    hit/miss totals are sampled from the tiers' own TierStats at scrape
    time, so the attributes tests already read stay the source of truth."""

    def __init__(self) -> None:
        from dynamo_tpu.runtime import metric_names as mn
        from dynamo_tpu.runtime.metrics_core import MetricsRegistry

        self.registry = MetricsRegistry()
        self.offload_duration = self.registry.histogram(
            mn.KVBM_OFFLOAD_DURATION,
            "Wall time of one offload burst (device -> tiers)",
            ["tier"],
        )
        self.onboard_duration = self.registry.histogram(
            mn.KVBM_ONBOARD_DURATION,
            "Wall time of one onboard walk (tiers -> device), labeled by "
            "the deepest tier the run resolved from",
            ["tier"],
        )
        self.offload_blocks = self.registry.counter(
            mn.KVBM_OFFLOAD_BLOCKS_TOTAL, "KV blocks offloaded device->tiers"
        )
        self.offload_bytes = self.registry.counter(
            mn.KVBM_OFFLOAD_BYTES_TOTAL, "KV bytes offloaded device->tiers"
        )
        self.onboard_blocks = self.registry.counter(
            mn.KVBM_ONBOARD_BLOCKS_TOTAL, "KV blocks onboarded tiers->device"
        )
        self.onboard_bytes = self.registry.counter(
            mn.KVBM_ONBOARD_BYTES_TOTAL, "KV bytes onboarded tiers->device"
        )
        self.lookup_hits = self.registry.counter(
            mn.KVBM_LOOKUP_HITS_TOTAL, "Tier lookup hits", ["tier"]
        )
        self.lookup_misses = self.registry.counter(
            mn.KVBM_LOOKUP_MISSES_TOTAL, "Tier lookup misses", ["tier"]
        )
        self.tier_blocks = self.registry.gauge(
            mn.KVBM_TIER_BLOCKS, "Blocks resident per tier", ["tier"]
        )
        self.tier_evictions = self.registry.counter(
            mn.KVBM_TIER_EVICTIONS_TOTAL,
            "Evictions per tier by reason (arena_full = straight spill "
            "past a full pinned arena, capacity = LRU overflow)",
            ["tier", "reason"],
        )
        self.pool_pressure_truncations = self.registry.counter(
            mn.KVBM_POOL_PRESSURE_TRUNCATIONS_TOTAL,
            "Promised KVBM matches shrunk because the engine pool could not "
            "allocate the full run",
        )
        self.failed_loads = self.registry.counter(
            mn.KVBM_FAILED_LOADS_TOTAL,
            "Instructed loads revoked because the block vanished from the "
            "tiers before transfer (engine must recompute)",
        )
        self.offload_missed = self.registry.counter(
            mn.KVBM_OFFLOAD_MISSED_TOTAL,
            "Write-through losses: committed blocks gone from the device "
            "pool before the offload worker could gather them",
            ["reason"],
        )
        self.prefetches = self.registry.counter(
            mn.KVBM_PREFETCHES_TOTAL,
            "Speculative onboard leases by settlement (claimed | revoked "
            "| skipped | error)",
            ["outcome"],
        )
        self.prefetch_blocks = self.registry.counter(
            mn.KVBM_PREFETCH_BLOCKS_TOTAL,
            "Blocks moved under a speculative lease: used = claimed by "
            "admission, wasted = onboarded then never claimed",
            ["outcome"],
        )
        self.prefetch_overlap = self.registry.histogram(
            mn.KVBM_PREFETCH_OVERLAP_SECONDS,
            "Onboard wall time hidden behind queue wait + suffix prefill "
            "(walk duration minus the stall admission observed)",
        )
        self._tier_sources: Dict[str, Any] = {}
        self.registry.on_render(self._sample_tiers)

    def watch_tier(self, name: str, tier: Any) -> None:
        """Sample ``tier`` (``.stats`` TierStats + ``__len__``) at scrape
        time under the given tier label."""
        self._tier_sources[name] = tier

    def unwatch_tier(self, name: str) -> None:
        """Departed-tier GC: stop sampling and drop the occupancy gauge
        series (counters keep their monotonic history)."""
        self._tier_sources.pop(name, None)
        self.tier_blocks.remove(tier=name)

    def _sample_tiers(self) -> None:
        for name, tier in self._tier_sources.items():
            stats = getattr(tier, "stats", None)
            if stats is not None:
                self.lookup_hits.set_total(stats.hits, tier=name)
                self.lookup_misses.set_total(stats.misses, tier=name)
                by_reason = getattr(stats, "evicted_by_reason", None) or {}
                accounted = 0
                for reason, n in by_reason.items():
                    self.tier_evictions.set_total(n, tier=name, reason=reason)
                    accounted += n
                # Tier impls that bump .evicted without a reason (foreign
                # TierStats ducks) still reconcile to the labeled total.
                if stats.evicted > accounted:
                    self.tier_evictions.set_total(
                        stats.evicted - accounted, tier=name, reason="unknown"
                    )
            try:
                self.tier_blocks.set(len(tier), tier=name)
            except TypeError:
                pass

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics=openmetrics)


@dataclass
class OffloadFilter:
    """Which committed blocks get offloaded (ref: offload/filter.rs —
    chain-depth AND frequency admission).

    ``min_chain_depth`` skips shallow chains (short prompts rarely reused);
    ``min_frequency`` > 1 offloads a hash only once it has committed that
    many times (the reference's count-based filter: one-shot prompts never
    earn host space, recurring prefixes do); ``max_per_burst`` bounds the
    per-wakeup device→host traffic. Frequency counts live in a bounded
    LRU so the filter itself can't grow without limit.

    ``popular`` (wired by TieredKvManager to its sketch-backed protected
    map) is a fast-path past the chain-depth gate: a hot-but-shallow
    prefix the router keeps matching must never be filtered out of the
    tiers. The frequency gate still applies — popularity proves reuse,
    not that THIS commit is worth the wire yet.
    """

    min_chain_depth: int = 0
    min_frequency: int = 1
    max_per_burst: int = 32
    max_tracked_hashes: int = 65536
    popular: Optional[Callable[[int], bool]] = None

    def __post_init__(self) -> None:
        self._counts: "OrderedDict[int, int]" = OrderedDict()

    def _is_popular(self, block_hash: Optional[int]) -> bool:
        if self.popular is None or block_hash is None:
            return False
        try:
            return bool(self.popular(block_hash))
        except Exception:
            # A popularity-source bug must cost the fast-path, never the
            # commit notification that called us.
            logger.debug("offload popularity probe failed", exc_info=True)
            return False

    def admit(self, chain_depth: int, block_hash: Optional[int] = None) -> bool:
        if chain_depth < self.min_chain_depth and not self._is_popular(block_hash):
            return False
        if self.min_frequency <= 1 or block_hash is None:
            return True
        n = self._counts.pop(block_hash, 0) + 1
        self._counts[block_hash] = n  # most-recently-seen last
        while len(self._counts) > self.max_tracked_hashes:
            self._counts.popitem(last=False)
        return n >= self.min_frequency


# Waste bound per speculative lease: a mispredicted hint can never drag
# more than this many blocks through the tiers (docs/design_docs/
# kv_prefetch.md "waste bounds").
PREFETCH_MAX_BLOCKS = 256

# Blocks per pipelined onboard batch: tier reads of batch i+1 overlap the
# device scatter of batch i, so this is also the bounded in-flight window
# that keeps speculative HBM pressure a small fraction of the pool (the
# PR 8 admission watermark still governs total occupancy — imports stop
# when the pool runs dry).
ONBOARD_BATCH_BLOCKS = 8

# Sketch anchors expanded into the protected-prefix map, and how deep a
# parent chain the expansion walks (a protected anchor protects its whole
# prefix: evicting an ancestor breaks the chain below it).
PROTECT_TOP_K = 128
PROTECT_WALK_DEPTH = 1024


class KvPrefetch:
    """Revocable lease over one speculative onboard walk.

    Created by ``TieredKvManager.prefetch()`` when a routed request
    arrives with a tier-resident hint; the walk runs concurrently with
    the request's queue wait and is joined by admission via ``wait()`` +
    ``claim()``. Revocation (abort/shed/close) is cooperative — the walk
    checks ``revoked`` between batches — and the settlement is exactly
    once: pins released, the lease counted claimed/revoked/skipped/error,
    moved blocks counted used/wasted. The walk task never raises; errors
    settle the lease as wasted and admission falls back to the serial
    onboard path.
    """

    __slots__ = (
        "manager", "hashes", "task", "walk_installed", "pinned_ids",
        "pinned_hashes", "revoked", "revoke_reason", "claimed", "settled",
        "walk_done", "error", "source", "t_start", "t_done",
    )

    def __init__(self, manager: "TieredKvManager", hashes: List[int]) -> None:
        self.manager = manager
        self.hashes = hashes
        self.task: Optional[asyncio.Task] = None
        self.walk_installed = 0  # blocks the walk moved tiers -> device
        self.pinned_ids: List[int] = []
        self.pinned_hashes: List[int] = []
        self.revoked = False
        self.revoke_reason: Optional[str] = None
        self.claimed = False
        self.settled = False
        self.walk_done = False
        self.error = False
        self.source: Optional[str] = None  # deepest tier the walk hit
        self.t_start = time.monotonic()
        self.t_done: Optional[float] = None

    @property
    def matched(self) -> int:
        """Leading device-resident blocks held under the lease."""
        return len(self.pinned_hashes)

    async def wait(self) -> int:
        """Join the walk (admission's stall point). Never raises."""
        if self.task is not None:
            await self.task
        return self.matched

    def claim(self, stall_s: float = 0.0) -> None:
        """Admission took over the blocks (after its OWN pin_prefix, so
        refcounts never dip to zero in between). ``stall_s`` is how long
        admission actually waited in ``wait()`` — the walk time minus the
        stall is the overlap the speculation bought."""
        self.manager._settle_prefetch(self, used=True, stall_s=stall_s)

    def revoke(self, reason: str) -> None:
        """Release the lease (abort/shed/close). Idempotent; a no-op once
        claimed. Mid-walk, the walk sees the flag between batches and its
        finally settles; after the walk, settle here and now."""
        if self.claimed or self.revoked:
            return
        self.revoked = True
        self.revoke_reason = reason
        if self.walk_done:
            self.manager._settle_prefetch(self, used=False)


class TieredKvManager:
    def __init__(
        self,
        top_tier: HostTier,
        *,
        filter: Optional[OffloadFilter] = None,
        remote: Optional[Any] = None,  # G4 RemoteTier (kvbm/remote.py)
        metrics: Optional[KvbmMetrics] = None,
        plane: Optional[Any] = None,  # KvReusePlane override (tests/bench)
    ) -> None:
        self.tier = top_tier
        self.remote = remote
        self.filter = filter or OffloadFilter()
        self.metrics = metrics or KvbmMetrics()
        # Tier integrity events for /debug/flight (DYN005 owner "kvbm";
        # single writer: the manager's event loop — tier reads only ever
        # happen on it, from onboard and the offload spill path).
        from dynamo_tpu.runtime.device_observe import FlightRecorder

        self.flight = FlightRecorder("kvbm", capacity=128)
        # Tier-flow ring for the KV-reuse plane (DYN005 owner "kvcache";
        # single writer: this manager's event loop — offload bursts,
        # onboard walks, and the eviction/sketch delta syncs below all run
        # on it). Distinct from "kvbm" (integrity events) so reuse-flow
        # archaeology is not interleaved with corruption forensics.
        self.kv_flight = FlightRecorder("kvcache", capacity=256)
        # KV-reuse plane feeds: evictions and sketch replacements are
        # mirrored as DELTAS at the manager's sync points, so several
        # managers in one process stay additive on the global counters.
        from dynamo_tpu.runtime.kv_reuse_observe import global_plane

        self.kv_plane = plane if plane is not None else global_plane()
        self._evict_seen: Dict[Tuple[str, str], int] = {}
        self._sketch_replacements_seen = self.kv_plane.sketch.replacements
        self.last_onboard_source: Optional[str] = None
        self.metrics.watch_tier(getattr(top_tier, "name", "host"), top_tier)
        if top_tier.next_tier is not None:
            self.metrics.watch_tier(
                getattr(top_tier.next_tier, "name", "disk"), top_tier.next_tier
            )
            if hasattr(top_tier.next_tier, "on_corruption"):
                tier_name = getattr(top_tier.next_tier, "name", "disk")
                top_tier.next_tier.on_corruption = (
                    lambda block_hash, detail, _t=tier_name:
                    self._note_tier_corruption(_t, block_hash, detail)
                )
        if remote is not None:
            self.metrics.watch_tier("remote", remote)
        # Live per-tier occupancy for GET /debug/kvcache (several managers
        # per process each get a distinct source label).
        self._plane_label = "kvbm"
        if self._plane_label in self.kv_plane._tier_sources:
            self._plane_label = f"kvbm@{id(self):x}"
        self.kv_plane.register_tier_source(
            self._plane_label, self.tier_occupancy
        )
        # hash → chain depth, queued for offload
        self._pending: "asyncio.Queue[Tuple[int, int]]" = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        self._engine: Optional[Any] = None
        self.offloaded = 0
        self.onboarded = 0
        # Popularity-driven eviction (kv_prefetch.md): the sketch tracks
        # chain ANCHORS, the tiers evict BLOCKS — the bridge is a bounded
        # parent map (child hash -> parent hash, fed by notify_commit)
        # that lets the scorer expand a hot anchor into its whole prefix
        # chain. The derived "protected map" is rebuilt lazily when the
        # sketch moves, never per eviction.
        cap = getattr(top_tier, "capacity", 0) or 0
        if top_tier.next_tier is not None:
            cap += getattr(top_tier.next_tier, "capacity", 0) or 0
        self._parents_cap = max(4096, 2 * cap)
        self._parents: "OrderedDict[int, Optional[int]]" = OrderedDict()
        self._protected: Dict[int, float] = {}
        self._protected_stamp: Optional[Tuple[int, int]] = None
        self._protected_next = 0.0
        top_tier.scorer = self._popularity_score
        if top_tier.next_tier is not None and hasattr(top_tier.next_tier, "scorer"):
            top_tier.next_tier.scorer = self._popularity_score
        if self.filter.popular is None:
            self.filter.popular = self._is_protected
        # Outstanding speculative leases, so close() can revoke them all.
        self._prefetches: set = set()

    # -- wiring -------------------------------------------------------------

    def attach(self, engine: Any) -> None:
        """Attach to a JaxEngine: the engine calls notify_commit() for every
        committed block; onboarding hooks into admission via
        engine.kvbm = self (see engines/tpu/engine.py)."""
        self._engine = engine
        engine.kvbm = self

    def _note_tier_corruption(
        self, tier: str, block_hash: int, detail: str
    ) -> None:
        self.flight.record(
            "tier_corrupt", tier=tier, block=f"{block_hash:016x}",
            detail=detail,
        )

    def tier_occupancy(self) -> Dict[str, Any]:
        """Per-tier blocks + TierStats for GET /debug/kvcache."""
        out: Dict[str, Any] = {}
        for name, tier in self.metrics._tier_sources.items():
            entry: Dict[str, Any] = {}
            try:
                entry["blocks"] = len(tier)
            except TypeError:
                pass
            stats = getattr(tier, "stats", None)
            if stats is not None:
                entry.update(stats.to_dict())
                by_reason = getattr(stats, "evicted_by_reason", None)
                if by_reason:
                    entry["evicted_by_reason"] = dict(by_reason)
            out[name] = entry
        return out

    def _sync_plane(self) -> None:
        """Mirror eviction/corruption/sketch-churn deltas into the global
        KV-reuse plane and the kvcache flight ring. Runs on the manager's
        event loop after offload bursts and onboard walks (the only paths
        that mutate the tiers), keeping the ring single-writer and several
        managers additive on the process-global counters."""
        for name, tier in self.metrics._tier_sources.items():
            stats = getattr(tier, "stats", None)
            if stats is None:
                continue
            reasons = dict(getattr(stats, "evicted_by_reason", None) or {})
            corrupt = getattr(stats, "corrupt", 0)
            if corrupt:
                reasons["corrupt"] = corrupt
            for reason, total in reasons.items():
                seen = self._evict_seen.get((name, reason), 0)
                if total > seen:
                    self._evict_seen[(name, reason)] = total
                    self.kv_plane.note_eviction(name, reason, total - seen)
                    self.kv_flight.record(
                        "evict", tier=name, reason=reason, n=total - seen
                    )
        replaced = self.kv_plane.sketch.replacements
        if replaced > self._sketch_replacements_seen:
            self.kv_flight.record(
                "sketch_replace",
                n=replaced - self._sketch_replacements_seen,
                tracked=len(self.kv_plane.sketch),
            )
            self._sketch_replacements_seen = replaced

    def notify_commit(
        self,
        block_hash: int,
        chain_depth: int,
        parent: Optional[int] = None,
    ) -> None:
        # Parent first, filter second: the eviction scorer must be able
        # to expand anchors through blocks the offload filter rejected.
        if parent is not None or block_hash not in self._parents:
            self._parents[block_hash] = parent
        self._parents.move_to_end(block_hash)
        while len(self._parents) > self._parents_cap:
            self._parents.popitem(last=False)
        if self.filter.admit(chain_depth, block_hash) and not self.tier.contains(block_hash):
            self._pending.put_nowait((block_hash, chain_depth))
            self._ensure_task()

    # -- popularity scoring (tiers.Scorer; sketch-agnostic seam) -------------

    def _maybe_rebuild_protected(self) -> None:
        now = time.monotonic()
        if now < self._protected_next:
            return
        # Throttle regardless of outcome: at most ~2 rebuilds/sec even
        # under eviction storms.
        self._protected_next = now + 0.5
        stamp = self.kv_plane.sketch.stamp()
        if stamp == self._protected_stamp:
            return
        protected: Dict[int, float] = {}
        for anchor, score in self.kv_plane.sketch.top_scores(PROTECT_TOP_K).items():
            h: Optional[int] = anchor
            for _ in range(PROTECT_WALK_DEPTH):
                if h is None:
                    break
                prev = protected.get(h)
                if prev is None or score > prev:
                    protected[h] = score
                h = self._parents.get(h)
        self._protected = protected
        self._protected_stamp = stamp

    def _popularity_score(self, block_hash: int) -> Optional[float]:
        """tiers.Scorer: decayed popularity of the hottest prefix this
        block is part of, or None when no tracked anchor covers it."""
        self._maybe_rebuild_protected()
        return self._protected.get(block_hash)

    def _is_protected(self, block_hash: int) -> bool:
        """OffloadFilter.popular: is the block under a top-K anchor?"""
        return self._popularity_score(block_hash) is not None

    def _ensure_task(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(
                self._offload_loop(), name="kvbm-offload"
            )

    # -- offload (G1 → G2) ---------------------------------------------------

    async def _offload_loop(self) -> None:
        while True:
            burst: List[int] = []
            h, _ = await self._pending.get()
            burst.append(h)
            while len(burst) < self.filter.max_per_burst and not self._pending.empty():
                burst.append(self._pending.get_nowait()[0])
            try:
                await self._offload_burst(burst)
            except Exception:
                logger.exception("KV offload burst failed")
            if self._pending.empty():
                return  # re-spawned on next commit

    async def _offload_burst(self, hashes: List[int]) -> None:
        assert self._engine is not None
        todo = [h for h in hashes if not self.tier.contains(h)]
        if not todo:
            return
        t0 = time.monotonic()
        moved = 0
        # Wire-form export (disagg/wire.py): quantized pools offload their
        # {q8, scales} form verbatim — G2/G3 hold half the dense footprint
        # and onboarding restores bit-exact pool content. The export stops
        # at the first device miss; exporting one by one keeps it simple
        # and each block is a single chain element.
        for h in todo:
            found, wire = await self._engine.export_blocks_wire_async([h])
            if not found:
                # Evicted before we got to it: the write-through promise
                # silently lost a block — count it so filter/burst tuning
                # has a loss signal to steer by.
                self.metrics.offload_missed.inc(reason="device_evicted")
                continue
            if wire.quantized:
                self.tier.put(
                    h, wire.k[0], wire.v[0], wire.k_scale[0], wire.v_scale[0]
                )
            else:
                self.tier.put(h, wire.k[0], wire.v[0])
            if self.remote is not None:
                # G4 write-behind: the shared store absorbs it
                # asynchronously. The remote tier stays dense (it serves
                # engines of ANY pool dtype).
                dk, dv = wire.to_dense()
                self.remote.put(h, dk[0], dv[0])
            self.offloaded += 1
            moved += 1
            self.metrics.offload_blocks.inc()
            self.metrics.offload_bytes.inc(int(wire.nbytes))
        dt = time.monotonic() - t0
        self.metrics.offload_duration.observe(
            dt, tier=getattr(self.tier, "name", "host")
        )
        self.kv_flight.record(
            "offload_burst", blocks=moved, queued=len(todo),
            ms=round(dt * 1000.0, 3),
        )
        self._sync_plane()

    # -- onboard (G2/G3 → G1) ------------------------------------------------

    def match_chain(self, block_hashes: List[int]) -> int:
        """Leading blocks available in the tiers."""
        n = 0
        for h in block_hashes:
            if not self.tier.contains(h) and (
                self.tier.next_tier is None or not self.tier.next_tier.contains(h)
            ):
                break
            n += 1
        return n

    async def _import_batch(
        self, hashes: List[int], blocks: List[tuple], anchor: Optional[int]
    ) -> int:
        """Install one batch device-side. Splits into uniform-form
        sub-runs (a tier can hold a mix of dense and quantized blocks
        across engine-dtype generations); each sub-run after the first
        anchors on its predecessor's tail so the chain stays
        parent-linked. Returns blocks installed (< len(hashes) = pool
        dry)."""
        from dynamo_tpu.disagg.wire import tier_block_wire

        installed = 0
        i = 0
        while i < len(hashes):
            j = i + 1
            while j < len(hashes) and len(blocks[j]) == len(blocks[i]):
                j += 1
            wire = tier_block_wire(blocks[i:j])
            n = await self._engine.import_blocks_wire_async(
                hashes[i:j], wire, anchor_parent=anchor
            )
            installed += n
            self.metrics.onboard_bytes.inc(
                int(wire.nbytes * (n / max(len(wire), 1)))
            )
            if n < j - i:
                break  # pool dry mid-run
            anchor = hashes[j - 1]
            i = j
        return installed

    async def onboard(
        self,
        block_hashes: List[int],
        *,
        should_stop: Optional[Callable[[], bool]] = None,
    ) -> int:
        """Bring a leading run of blocks onto the device (before prefill).
        Returns how many blocks were installed.

        Pipelined: tier reads proceed in ONBOARD_BATCH_BLOCKS batches
        with the previous batch's device scatter still in flight — host
        page-ins and disk .npz reads run on the event-loop thread while
        ``import_blocks_wire_async`` awaits the device executor, so the
        two legs genuinely overlap. One batch in flight bounds the
        speculative HBM footprint; the import path itself stops when the
        pool runs dry. ``should_stop`` is the cooperative revocation
        probe (speculative leases), checked between batches.
        """
        assert self._engine is not None

        t0 = time.monotonic()
        # Deepest tier the walk resolved from (hit attribution for the
        # KV-reuse plane; checked BEFORE get() because get() promotes).
        tier_rank = {getattr(self.tier, "name", "host"): 0}
        if self.tier.next_tier is not None:
            tier_rank[getattr(self.tier.next_tier, "name", "disk")] = 1
        deepest: Optional[str] = None
        installed = 0
        walked = 0
        anchor: Optional[int] = None
        import_task: Optional[asyncio.Task] = None
        in_flight: List[int] = []
        idx = 0
        dry = False
        try:
            while idx < len(block_hashes) and not dry:
                if should_stop is not None and should_stop():
                    break
                batch_hashes: List[int] = []
                batch_blocks: List[tuple] = []
                while (
                    idx < len(block_hashes)
                    and len(batch_hashes) < ONBOARD_BATCH_BLOCKS
                ):
                    h = block_hashes[idx]
                    if self.tier.contains(h):
                        src = getattr(self.tier, "name", "host")
                    elif (
                        self.tier.next_tier is not None
                        and self.tier.next_tier.contains(h)
                    ):
                        src = getattr(self.tier.next_tier, "name", "disk")
                    else:
                        src = "remote"
                    blk = self.tier.get(h)
                    if blk is None and self.remote is not None:
                        # G4 fallback: a shared-store hit extends the run
                        # (and lands in the host tier for next time).
                        blk = await self.remote.get_async(h)
                        if blk is not None:
                            self.tier.put(h, *blk)
                    if blk is None:
                        dry = True
                        break
                    if deepest is None or tier_rank.get(src, 2) > tier_rank.get(deepest, 2):
                        deepest = src
                    batch_hashes.append(h)
                    batch_blocks.append(blk)
                    idx += 1
                # Join the in-flight import before dispatching the next:
                # the parent anchor of batch i+1 is only valid once batch
                # i fully installed.
                if import_task is not None:
                    n = await import_task
                    import_task = None
                    installed += n
                    if n < len(in_flight):
                        break  # pool dry
                    anchor = in_flight[-1]
                if batch_hashes:
                    walked += len(batch_hashes)
                    in_flight = batch_hashes
                    import_task = asyncio.ensure_future(
                        self._import_batch(batch_hashes, batch_blocks, anchor)
                    )
            if import_task is not None:
                installed += await import_task
                import_task = None
        except BaseException:
            # A tier read blew up (injected fault, IO error) with a
            # scatter still in flight: land the scatter before unwinding
            # so the pool is never left with an orphan import task.
            if import_task is not None:
                try:
                    installed += await import_task
                except Exception:
                    logger.debug(
                        "onboard import failed during unwind", exc_info=True
                    )
            raise
        finally:
            self.last_onboard_source = deepest
            if walked:
                self.onboarded += installed
                self.metrics.onboard_blocks.inc(installed)
                dt = time.monotonic() - t0
                self.metrics.onboard_duration.observe(dt, tier=deepest or "host")
                self.kv_flight.record(
                    "onboard", blocks=installed, run=walked,
                    tier=deepest or "host", ms=round(dt * 1000.0, 3),
                )
                self._sync_plane()
        return installed

    # -- speculative onboarding (router hint → revocable lease) --------------

    def prefetch(self, block_hashes: List[int]) -> Optional["KvPrefetch"]:
        """Start a speculative onboard walk for a routed request's
        predicted prefix, ahead of admission. Returns a revocable
        ``KvPrefetch`` lease (or None when there is nothing to do). The
        walk is capped at PREFETCH_MAX_BLOCKS — the waste bound when the
        hint turns out wrong."""
        if self._engine is None or not block_hashes:
            return None
        pf = KvPrefetch(self, list(block_hashes[:PREFETCH_MAX_BLOCKS]))
        self._prefetches.add(pf)
        pf.task = asyncio.get_running_loop().create_task(
            self._run_prefetch(pf), name="kvbm-prefetch"
        )
        return pf

    async def _run_prefetch(self, pf: "KvPrefetch") -> None:
        from dynamo_tpu.runtime import fault_names
        from dynamo_tpu.runtime.faults import fault_point

        try:
            # Chaos seam: ONE hit per speculative lease, before any tier
            # read or scatter — an injection models the prefetch machinery
            # dying outright (tests/test_kvbm.py replays this; the lease
            # settles as error and admission falls back to serial onboard).
            fault_point(fault_names.KVBM_PREFETCH)
            n_dev = self._engine.pool.match_prefix(pf.hashes)
            if n_dev < len(pf.hashes):
                pf.walk_installed = await self.onboard(
                    pf.hashes, should_stop=lambda: pf.revoked
                )
                pf.source = self.last_onboard_source
            if not pf.revoked:
                # Take the lease: pin the leading device-resident run so
                # pool eviction cannot undo the speculative work before
                # admission joins (admission re-pins, THEN claims — the
                # refcount never dips to zero in between).
                matched, ids = self._engine.pool.pin_prefix(pf.hashes)
                pf.pinned_ids = list(ids)
                pf.pinned_hashes = pf.hashes[:matched]
        except Exception:
            # The walk never raises into wait(): an error settles the
            # lease as wasted and the request recomputes its prefix.
            logger.debug("speculative prefetch walk failed", exc_info=True)
            pf.error = True
        finally:
            pf.walk_done = True
            pf.t_done = time.monotonic()
            if pf.error or pf.revoked:
                self._settle_prefetch(pf, used=False)
            elif not pf.pinned_hashes and not pf.walk_installed:
                # Nothing tier-resident after all: settle now as skipped
                # (there is no lease to hold open).
                self._settle_prefetch(pf, used=False)

    def _settle_prefetch(
        self, pf: "KvPrefetch", *, used: bool, stall_s: float = 0.0
    ) -> None:
        """Exactly-once lease settlement: release pins, count the
        outcome, record the flight event. Single-writer on the manager's
        event loop (DYN005: both rings stay owned here)."""
        if pf.settled:
            return
        pf.settled = True
        self._prefetches.discard(pf)
        if pf.pinned_ids:
            # Both paths release the lease's own pins: on claim the
            # admission pin (taken first) keeps the blocks active; on
            # revocation they fall back to reclaimable cached blocks.
            self._engine.pool.release(pf.pinned_ids, pf.pinned_hashes)
        walk_s = (pf.t_done or time.monotonic()) - pf.t_start
        if used:
            pf.claimed = True
            outcome = "claimed"
            if pf.pinned_hashes:
                self.metrics.prefetch_blocks.inc(
                    len(pf.pinned_hashes), outcome="used"
                )
            overlap = max(0.0, walk_s - max(0.0, stall_s))
            self.metrics.prefetch_overlap.observe(overlap)
        else:
            outcome = (
                "error" if pf.error
                else "revoked" if pf.revoked
                else "skipped"
            )
            overlap = 0.0
            if pf.walk_installed:
                # The bounded cost of speculation: blocks dragged through
                # the tiers that no admission ever claimed.
                self.metrics.prefetch_blocks.inc(
                    pf.walk_installed, outcome="wasted"
                )
        self.metrics.prefetches.inc(outcome=outcome)
        self.kv_flight.record(
            "prefetch", outcome=outcome, hint=len(pf.hashes),
            matched=len(pf.pinned_hashes), moved=pf.walk_installed,
            tier=pf.source or "device", reason=pf.revoke_reason or "",
            walk_ms=round(walk_s * 1000.0, 3),
            overlap_ms=round(overlap * 1000.0, 3),
        )
        pf.pinned_ids = []
        pf.pinned_hashes = []

    def register_metrics(self, server: Any) -> None:
        """Expose this manager's metric families on a SystemStatusServer."""
        server.register_metrics(self.metrics.render)
        server.register_flight(self.flight.name, self.flight.snapshot)
        server.register_flight(self.kv_flight.name, self.kv_flight.snapshot)

    def stats(self) -> Dict[str, Any]:
        out = {
            "offloaded": self.offloaded,
            "onboarded": self.onboarded,
            "host": self.tier.stats.to_dict(),
            "host_blocks": len(self.tier),
        }
        if self.tier.next_tier is not None:
            out["disk"] = self.tier.next_tier.stats.to_dict()
            out["disk_blocks"] = len(self.tier.next_tier)
        if self.remote is not None:
            out["remote"] = self.remote.stats.to_dict()
        return out

    async def close(self) -> None:
        # Revoke outstanding speculative leases (their walks stop at the
        # next batch boundary and settle as revoked/wasted).
        for pf in list(self._prefetches):
            pf.revoke("close")
        tasks = [
            pf.task for pf in list(self._prefetches)
            if pf.task is not None and not pf.task.done()
        ]
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
        if self._task is not None and not self._task.done():
            self._task.cancel()
            await reap_task(self._task, "kvbm consolidator", logger)
        # Departed-tier GC: this manager's occupancy gauges and its live
        # tier source leave the scrape with it (zero-residue audit — a
        # long-lived SystemStatusServer must not keep advertising the
        # occupancy of tiers that no longer exist).
        for name in list(self.metrics._tier_sources):
            self.metrics.unwatch_tier(name)
        self.kv_plane.forget_tier_source(self._plane_label)
        if self.remote is not None:
            await self.remote.close()
