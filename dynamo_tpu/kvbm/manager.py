"""TieredKvManager: the offload/onboard engine over the storage tiers.

Reference parity: lib/llm/src/block_manager/offload.rs (async offload engine
with bounded queues + filters) and the onboard path (matched blocks brought
device-side before prefill, SURVEY §3.4). Write-through: blocks are queued
for offload when they commit on-device, so device eviction never loses
content; onboarding extends the device prefix match at admission time.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from dynamo_tpu.kvbm.tiers import HostTier
from dynamo_tpu.runtime.tasks import reap_task
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class KvbmMetrics:
    """Canonical KVBM metric families (runtime/metric_names.py ALL_KVBM).

    One instance is shared by everything that moves KV for a process: the
    TieredKvManager (native-engine offload/onboard), and optionally the
    connector leader/worker (external-engine seam, which counts
    pool-pressure truncations and revoked loads). Tier occupancy and
    hit/miss totals are sampled from the tiers' own TierStats at scrape
    time, so the attributes tests already read stay the source of truth."""

    def __init__(self) -> None:
        from dynamo_tpu.runtime import metric_names as mn
        from dynamo_tpu.runtime.metrics_core import MetricsRegistry

        self.registry = MetricsRegistry()
        self.offload_blocks = self.registry.counter(
            mn.KVBM_OFFLOAD_BLOCKS_TOTAL, "KV blocks offloaded device->tiers"
        )
        self.offload_bytes = self.registry.counter(
            mn.KVBM_OFFLOAD_BYTES_TOTAL, "KV bytes offloaded device->tiers"
        )
        self.onboard_blocks = self.registry.counter(
            mn.KVBM_ONBOARD_BLOCKS_TOTAL, "KV blocks onboarded tiers->device"
        )
        self.onboard_bytes = self.registry.counter(
            mn.KVBM_ONBOARD_BYTES_TOTAL, "KV bytes onboarded tiers->device"
        )
        self.lookup_hits = self.registry.counter(
            mn.KVBM_LOOKUP_HITS_TOTAL, "Tier lookup hits", ["tier"]
        )
        self.lookup_misses = self.registry.counter(
            mn.KVBM_LOOKUP_MISSES_TOTAL, "Tier lookup misses", ["tier"]
        )
        self.tier_blocks = self.registry.gauge(
            mn.KVBM_TIER_BLOCKS, "Blocks resident per tier", ["tier"]
        )
        self.tier_evictions = self.registry.counter(
            mn.KVBM_TIER_EVICTIONS_TOTAL, "LRU evictions per tier", ["tier"]
        )
        self.pool_pressure_truncations = self.registry.counter(
            mn.KVBM_POOL_PRESSURE_TRUNCATIONS_TOTAL,
            "Promised KVBM matches shrunk because the engine pool could not "
            "allocate the full run",
        )
        self.failed_loads = self.registry.counter(
            mn.KVBM_FAILED_LOADS_TOTAL,
            "Instructed loads revoked because the block vanished from the "
            "tiers before transfer (engine must recompute)",
        )
        self._tier_sources: Dict[str, Any] = {}
        self.registry.on_render(self._sample_tiers)

    def watch_tier(self, name: str, tier: Any) -> None:
        """Sample ``tier`` (``.stats`` TierStats + ``__len__``) at scrape
        time under the given tier label."""
        self._tier_sources[name] = tier

    def _sample_tiers(self) -> None:
        for name, tier in self._tier_sources.items():
            stats = getattr(tier, "stats", None)
            if stats is not None:
                self.lookup_hits.set_total(stats.hits, tier=name)
                self.lookup_misses.set_total(stats.misses, tier=name)
                self.tier_evictions.set_total(stats.evicted, tier=name)
            try:
                self.tier_blocks.set(len(tier), tier=name)
            except TypeError:
                pass

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics=openmetrics)


@dataclass
class OffloadFilter:
    """Which committed blocks get offloaded (ref: offload/filter.rs —
    chain-depth AND frequency admission).

    ``min_chain_depth`` skips shallow chains (short prompts rarely reused);
    ``min_frequency`` > 1 offloads a hash only once it has committed that
    many times (the reference's count-based filter: one-shot prompts never
    earn host space, recurring prefixes do); ``max_per_burst`` bounds the
    per-wakeup device→host traffic. Frequency counts live in a bounded
    LRU so the filter itself can't grow without limit.
    """

    min_chain_depth: int = 0
    min_frequency: int = 1
    max_per_burst: int = 32
    max_tracked_hashes: int = 65536

    def __post_init__(self) -> None:
        from collections import OrderedDict

        self._counts: "OrderedDict[int, int]" = OrderedDict()

    def admit(self, chain_depth: int, block_hash: Optional[int] = None) -> bool:
        if chain_depth < self.min_chain_depth:
            return False
        if self.min_frequency <= 1 or block_hash is None:
            return True
        n = self._counts.pop(block_hash, 0) + 1
        self._counts[block_hash] = n  # most-recently-seen last
        while len(self._counts) > self.max_tracked_hashes:
            self._counts.popitem(last=False)
        return n >= self.min_frequency


class TieredKvManager:
    def __init__(
        self,
        top_tier: HostTier,
        *,
        filter: Optional[OffloadFilter] = None,
        remote: Optional[Any] = None,  # G4 RemoteTier (kvbm/remote.py)
        metrics: Optional[KvbmMetrics] = None,
    ) -> None:
        self.tier = top_tier
        self.remote = remote
        self.filter = filter or OffloadFilter()
        self.metrics = metrics or KvbmMetrics()
        # Tier integrity events for /debug/flight (DYN005 owner "kvbm";
        # single writer: the manager's event loop — tier reads only ever
        # happen on it, from onboard and the offload spill path).
        from dynamo_tpu.runtime.device_observe import FlightRecorder

        self.flight = FlightRecorder("kvbm", capacity=128)
        self.metrics.watch_tier(getattr(top_tier, "name", "host"), top_tier)
        if top_tier.next_tier is not None:
            self.metrics.watch_tier(
                getattr(top_tier.next_tier, "name", "disk"), top_tier.next_tier
            )
            if hasattr(top_tier.next_tier, "on_corruption"):
                tier_name = getattr(top_tier.next_tier, "name", "disk")
                top_tier.next_tier.on_corruption = (
                    lambda block_hash, detail, _t=tier_name:
                    self._note_tier_corruption(_t, block_hash, detail)
                )
        if remote is not None:
            self.metrics.watch_tier("remote", remote)
        # hash → chain depth, queued for offload
        self._pending: "asyncio.Queue[Tuple[int, int]]" = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        self._engine: Optional[Any] = None
        self.offloaded = 0
        self.onboarded = 0

    # -- wiring -------------------------------------------------------------

    def attach(self, engine: Any) -> None:
        """Attach to a JaxEngine: the engine calls notify_commit() for every
        committed block; onboarding hooks into admission via
        engine.kvbm = self (see engines/tpu/engine.py)."""
        self._engine = engine
        engine.kvbm = self

    def _note_tier_corruption(
        self, tier: str, block_hash: int, detail: str
    ) -> None:
        self.flight.record(
            "tier_corrupt", tier=tier, block=f"{block_hash:016x}",
            detail=detail,
        )

    def notify_commit(self, block_hash: int, chain_depth: int) -> None:
        if self.filter.admit(chain_depth, block_hash) and not self.tier.contains(block_hash):
            self._pending.put_nowait((block_hash, chain_depth))
            self._ensure_task()

    def _ensure_task(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_event_loop().create_task(
                self._offload_loop(), name="kvbm-offload"
            )

    # -- offload (G1 → G2) ---------------------------------------------------

    async def _offload_loop(self) -> None:
        while True:
            burst: List[int] = []
            h, _ = await self._pending.get()
            burst.append(h)
            while len(burst) < self.filter.max_per_burst and not self._pending.empty():
                burst.append(self._pending.get_nowait()[0])
            try:
                await self._offload_burst(burst)
            except Exception:
                logger.exception("KV offload burst failed")
            if self._pending.empty():
                return  # re-spawned on next commit

    async def _offload_burst(self, hashes: List[int]) -> None:
        assert self._engine is not None
        todo = [h for h in hashes if not self.tier.contains(h)]
        if not todo:
            return
        # Wire-form export (disagg/wire.py): quantized pools offload their
        # {q8, scales} form verbatim — G2/G3 hold half the dense footprint
        # and onboarding restores bit-exact pool content. The export stops
        # at the first device miss; exporting one by one keeps it simple
        # and each block is a single chain element.
        for h in todo:
            found, wire = await self._engine.export_blocks_wire_async([h])
            if not found:
                continue  # evicted before we got to it; write-through missed
            if wire.quantized:
                self.tier.put(
                    h, wire.k[0], wire.v[0], wire.k_scale[0], wire.v_scale[0]
                )
            else:
                self.tier.put(h, wire.k[0], wire.v[0])
            if self.remote is not None:
                # G4 write-behind: the shared store absorbs it
                # asynchronously. The remote tier stays dense (it serves
                # engines of ANY pool dtype).
                dk, dv = wire.to_dense()
                self.remote.put(h, dk[0], dv[0])
            self.offloaded += 1
            self.metrics.offload_blocks.inc()
            self.metrics.offload_bytes.inc(int(wire.nbytes))

    # -- onboard (G2/G3 → G1) ------------------------------------------------

    def match_chain(self, block_hashes: List[int]) -> int:
        """Leading blocks available in the tiers."""
        n = 0
        for h in block_hashes:
            if not self.tier.contains(h) and (
                self.tier.next_tier is None or not self.tier.next_tier.contains(h)
            ):
                break
            n += 1
        return n

    async def onboard(self, block_hashes: List[int]) -> int:
        """Bring a leading run of blocks onto the device (before prefill).
        Returns how many blocks were installed."""
        assert self._engine is not None
        from dynamo_tpu.disagg.wire import tier_block_wire

        run: List[int] = []
        blocks: List[tuple] = []
        for h in block_hashes:
            blk = self.tier.get(h)
            if blk is None and self.remote is not None:
                # G4 fallback: a shared-store hit extends the run (and lands
                # in the host tier for next time).
                blk = await self.remote.get_async(h)
                if blk is not None:
                    self.tier.put(h, *blk)
            if blk is None:
                break
            run.append(h)
            blocks.append(blk)
        if not run:
            return 0

        # Install in uniform-form sub-runs (a tier can hold a mix of dense
        # and quantized blocks across engine-dtype generations); each
        # sub-run after the first anchors on its predecessor's tail so the
        # chain stays parent-linked.
        installed = 0
        anchor = None
        i = 0
        while i < len(run):
            j = i + 1
            while j < len(run) and len(blocks[j]) == len(blocks[i]):
                j += 1
            wire = tier_block_wire(blocks[i:j])
            n = await self._engine.import_blocks_wire_async(
                run[i:j], wire, anchor_parent=anchor
            )
            installed += n
            self.metrics.onboard_bytes.inc(
                int(wire.nbytes * (n / max(len(wire), 1)))
            )
            if n < j - i:
                break  # pool dry mid-run
            anchor = run[j - 1]
            i = j
        self.onboarded += installed
        self.metrics.onboard_blocks.inc(installed)
        return installed

    def register_metrics(self, server: Any) -> None:
        """Expose this manager's metric families on a SystemStatusServer."""
        server.register_metrics(self.metrics.render)
        server.register_flight(self.flight.name, self.flight.snapshot)

    def stats(self) -> Dict[str, Any]:
        out = {
            "offloaded": self.offloaded,
            "onboarded": self.onboarded,
            "host": self.tier.stats.to_dict(),
            "host_blocks": len(self.tier),
        }
        if self.tier.next_tier is not None:
            out["disk"] = self.tier.next_tier.stats.to_dict()
            out["disk_blocks"] = len(self.tier.next_tier)
        if self.remote is not None:
            out["remote"] = self.remote.stats.to_dict()
        return out

    async def close(self) -> None:
        if self._task is not None and not self._task.done():
            self._task.cancel()
            await reap_task(self._task, "kvbm consolidator", logger)
        if self.remote is not None:
            await self.remote.close()
