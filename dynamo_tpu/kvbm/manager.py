"""TieredKvManager: the offload/onboard engine over the storage tiers.

Reference parity: lib/llm/src/block_manager/offload.rs (async offload engine
with bounded queues + filters) and the onboard path (matched blocks brought
device-side before prefill, SURVEY §3.4). Write-through: blocks are queued
for offload when they commit on-device, so device eviction never loses
content; onboarding extends the device prefix match at admission time.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from dynamo_tpu.kvbm.tiers import HostTier
from dynamo_tpu.runtime.tasks import reap_task
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class KvbmMetrics:
    """Canonical KVBM metric families (runtime/metric_names.py ALL_KVBM).

    One instance is shared by everything that moves KV for a process: the
    TieredKvManager (native-engine offload/onboard), and optionally the
    connector leader/worker (external-engine seam, which counts
    pool-pressure truncations and revoked loads). Tier occupancy and
    hit/miss totals are sampled from the tiers' own TierStats at scrape
    time, so the attributes tests already read stay the source of truth."""

    def __init__(self) -> None:
        from dynamo_tpu.runtime import metric_names as mn
        from dynamo_tpu.runtime.metrics_core import MetricsRegistry

        self.registry = MetricsRegistry()
        self.offload_duration = self.registry.histogram(
            mn.KVBM_OFFLOAD_DURATION,
            "Wall time of one offload burst (device -> tiers)",
            ["tier"],
        )
        self.onboard_duration = self.registry.histogram(
            mn.KVBM_ONBOARD_DURATION,
            "Wall time of one onboard walk (tiers -> device), labeled by "
            "the deepest tier the run resolved from",
            ["tier"],
        )
        self.offload_blocks = self.registry.counter(
            mn.KVBM_OFFLOAD_BLOCKS_TOTAL, "KV blocks offloaded device->tiers"
        )
        self.offload_bytes = self.registry.counter(
            mn.KVBM_OFFLOAD_BYTES_TOTAL, "KV bytes offloaded device->tiers"
        )
        self.onboard_blocks = self.registry.counter(
            mn.KVBM_ONBOARD_BLOCKS_TOTAL, "KV blocks onboarded tiers->device"
        )
        self.onboard_bytes = self.registry.counter(
            mn.KVBM_ONBOARD_BYTES_TOTAL, "KV bytes onboarded tiers->device"
        )
        self.lookup_hits = self.registry.counter(
            mn.KVBM_LOOKUP_HITS_TOTAL, "Tier lookup hits", ["tier"]
        )
        self.lookup_misses = self.registry.counter(
            mn.KVBM_LOOKUP_MISSES_TOTAL, "Tier lookup misses", ["tier"]
        )
        self.tier_blocks = self.registry.gauge(
            mn.KVBM_TIER_BLOCKS, "Blocks resident per tier", ["tier"]
        )
        self.tier_evictions = self.registry.counter(
            mn.KVBM_TIER_EVICTIONS_TOTAL,
            "Evictions per tier by reason (arena_full = straight spill "
            "past a full pinned arena, capacity = LRU overflow)",
            ["tier", "reason"],
        )
        self.pool_pressure_truncations = self.registry.counter(
            mn.KVBM_POOL_PRESSURE_TRUNCATIONS_TOTAL,
            "Promised KVBM matches shrunk because the engine pool could not "
            "allocate the full run",
        )
        self.failed_loads = self.registry.counter(
            mn.KVBM_FAILED_LOADS_TOTAL,
            "Instructed loads revoked because the block vanished from the "
            "tiers before transfer (engine must recompute)",
        )
        self._tier_sources: Dict[str, Any] = {}
        self.registry.on_render(self._sample_tiers)

    def watch_tier(self, name: str, tier: Any) -> None:
        """Sample ``tier`` (``.stats`` TierStats + ``__len__``) at scrape
        time under the given tier label."""
        self._tier_sources[name] = tier

    def unwatch_tier(self, name: str) -> None:
        """Departed-tier GC: stop sampling and drop the occupancy gauge
        series (counters keep their monotonic history)."""
        self._tier_sources.pop(name, None)
        self.tier_blocks.remove(tier=name)

    def _sample_tiers(self) -> None:
        for name, tier in self._tier_sources.items():
            stats = getattr(tier, "stats", None)
            if stats is not None:
                self.lookup_hits.set_total(stats.hits, tier=name)
                self.lookup_misses.set_total(stats.misses, tier=name)
                by_reason = getattr(stats, "evicted_by_reason", None) or {}
                accounted = 0
                for reason, n in by_reason.items():
                    self.tier_evictions.set_total(n, tier=name, reason=reason)
                    accounted += n
                # Tier impls that bump .evicted without a reason (foreign
                # TierStats ducks) still reconcile to the labeled total.
                if stats.evicted > accounted:
                    self.tier_evictions.set_total(
                        stats.evicted - accounted, tier=name, reason="unknown"
                    )
            try:
                self.tier_blocks.set(len(tier), tier=name)
            except TypeError:
                pass

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics=openmetrics)


@dataclass
class OffloadFilter:
    """Which committed blocks get offloaded (ref: offload/filter.rs —
    chain-depth AND frequency admission).

    ``min_chain_depth`` skips shallow chains (short prompts rarely reused);
    ``min_frequency`` > 1 offloads a hash only once it has committed that
    many times (the reference's count-based filter: one-shot prompts never
    earn host space, recurring prefixes do); ``max_per_burst`` bounds the
    per-wakeup device→host traffic. Frequency counts live in a bounded
    LRU so the filter itself can't grow without limit.
    """

    min_chain_depth: int = 0
    min_frequency: int = 1
    max_per_burst: int = 32
    max_tracked_hashes: int = 65536

    def __post_init__(self) -> None:
        from collections import OrderedDict

        self._counts: "OrderedDict[int, int]" = OrderedDict()

    def admit(self, chain_depth: int, block_hash: Optional[int] = None) -> bool:
        if chain_depth < self.min_chain_depth:
            return False
        if self.min_frequency <= 1 or block_hash is None:
            return True
        n = self._counts.pop(block_hash, 0) + 1
        self._counts[block_hash] = n  # most-recently-seen last
        while len(self._counts) > self.max_tracked_hashes:
            self._counts.popitem(last=False)
        return n >= self.min_frequency


class TieredKvManager:
    def __init__(
        self,
        top_tier: HostTier,
        *,
        filter: Optional[OffloadFilter] = None,
        remote: Optional[Any] = None,  # G4 RemoteTier (kvbm/remote.py)
        metrics: Optional[KvbmMetrics] = None,
    ) -> None:
        self.tier = top_tier
        self.remote = remote
        self.filter = filter or OffloadFilter()
        self.metrics = metrics or KvbmMetrics()
        # Tier integrity events for /debug/flight (DYN005 owner "kvbm";
        # single writer: the manager's event loop — tier reads only ever
        # happen on it, from onboard and the offload spill path).
        from dynamo_tpu.runtime.device_observe import FlightRecorder

        self.flight = FlightRecorder("kvbm", capacity=128)
        # Tier-flow ring for the KV-reuse plane (DYN005 owner "kvcache";
        # single writer: this manager's event loop — offload bursts,
        # onboard walks, and the eviction/sketch delta syncs below all run
        # on it). Distinct from "kvbm" (integrity events) so reuse-flow
        # archaeology is not interleaved with corruption forensics.
        self.kv_flight = FlightRecorder("kvcache", capacity=256)
        # KV-reuse plane feeds: evictions and sketch replacements are
        # mirrored as DELTAS at the manager's sync points, so several
        # managers in one process stay additive on the global counters.
        from dynamo_tpu.runtime.kv_reuse_observe import global_plane

        self.kv_plane = global_plane()
        self._evict_seen: Dict[Tuple[str, str], int] = {}
        self._sketch_replacements_seen = self.kv_plane.sketch.replacements
        self.last_onboard_source: Optional[str] = None
        self.metrics.watch_tier(getattr(top_tier, "name", "host"), top_tier)
        if top_tier.next_tier is not None:
            self.metrics.watch_tier(
                getattr(top_tier.next_tier, "name", "disk"), top_tier.next_tier
            )
            if hasattr(top_tier.next_tier, "on_corruption"):
                tier_name = getattr(top_tier.next_tier, "name", "disk")
                top_tier.next_tier.on_corruption = (
                    lambda block_hash, detail, _t=tier_name:
                    self._note_tier_corruption(_t, block_hash, detail)
                )
        if remote is not None:
            self.metrics.watch_tier("remote", remote)
        # Live per-tier occupancy for GET /debug/kvcache (several managers
        # per process each get a distinct source label).
        self._plane_label = "kvbm"
        if self._plane_label in self.kv_plane._tier_sources:
            self._plane_label = f"kvbm@{id(self):x}"
        self.kv_plane.register_tier_source(
            self._plane_label, self.tier_occupancy
        )
        # hash → chain depth, queued for offload
        self._pending: "asyncio.Queue[Tuple[int, int]]" = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        self._engine: Optional[Any] = None
        self.offloaded = 0
        self.onboarded = 0

    # -- wiring -------------------------------------------------------------

    def attach(self, engine: Any) -> None:
        """Attach to a JaxEngine: the engine calls notify_commit() for every
        committed block; onboarding hooks into admission via
        engine.kvbm = self (see engines/tpu/engine.py)."""
        self._engine = engine
        engine.kvbm = self

    def _note_tier_corruption(
        self, tier: str, block_hash: int, detail: str
    ) -> None:
        self.flight.record(
            "tier_corrupt", tier=tier, block=f"{block_hash:016x}",
            detail=detail,
        )

    def tier_occupancy(self) -> Dict[str, Any]:
        """Per-tier blocks + TierStats for GET /debug/kvcache."""
        out: Dict[str, Any] = {}
        for name, tier in self.metrics._tier_sources.items():
            entry: Dict[str, Any] = {}
            try:
                entry["blocks"] = len(tier)
            except TypeError:
                pass
            stats = getattr(tier, "stats", None)
            if stats is not None:
                entry.update(stats.to_dict())
                by_reason = getattr(stats, "evicted_by_reason", None)
                if by_reason:
                    entry["evicted_by_reason"] = dict(by_reason)
            out[name] = entry
        return out

    def _sync_plane(self) -> None:
        """Mirror eviction/corruption/sketch-churn deltas into the global
        KV-reuse plane and the kvcache flight ring. Runs on the manager's
        event loop after offload bursts and onboard walks (the only paths
        that mutate the tiers), keeping the ring single-writer and several
        managers additive on the process-global counters."""
        for name, tier in self.metrics._tier_sources.items():
            stats = getattr(tier, "stats", None)
            if stats is None:
                continue
            reasons = dict(getattr(stats, "evicted_by_reason", None) or {})
            corrupt = getattr(stats, "corrupt", 0)
            if corrupt:
                reasons["corrupt"] = corrupt
            for reason, total in reasons.items():
                seen = self._evict_seen.get((name, reason), 0)
                if total > seen:
                    self._evict_seen[(name, reason)] = total
                    self.kv_plane.note_eviction(name, reason, total - seen)
                    self.kv_flight.record(
                        "evict", tier=name, reason=reason, n=total - seen
                    )
        replaced = self.kv_plane.sketch.replacements
        if replaced > self._sketch_replacements_seen:
            self.kv_flight.record(
                "sketch_replace",
                n=replaced - self._sketch_replacements_seen,
                tracked=len(self.kv_plane.sketch),
            )
            self._sketch_replacements_seen = replaced

    def notify_commit(self, block_hash: int, chain_depth: int) -> None:
        if self.filter.admit(chain_depth, block_hash) and not self.tier.contains(block_hash):
            self._pending.put_nowait((block_hash, chain_depth))
            self._ensure_task()

    def _ensure_task(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_event_loop().create_task(
                self._offload_loop(), name="kvbm-offload"
            )

    # -- offload (G1 → G2) ---------------------------------------------------

    async def _offload_loop(self) -> None:
        while True:
            burst: List[int] = []
            h, _ = await self._pending.get()
            burst.append(h)
            while len(burst) < self.filter.max_per_burst and not self._pending.empty():
                burst.append(self._pending.get_nowait()[0])
            try:
                await self._offload_burst(burst)
            except Exception:
                logger.exception("KV offload burst failed")
            if self._pending.empty():
                return  # re-spawned on next commit

    async def _offload_burst(self, hashes: List[int]) -> None:
        assert self._engine is not None
        todo = [h for h in hashes if not self.tier.contains(h)]
        if not todo:
            return
        t0 = time.monotonic()
        moved = 0
        # Wire-form export (disagg/wire.py): quantized pools offload their
        # {q8, scales} form verbatim — G2/G3 hold half the dense footprint
        # and onboarding restores bit-exact pool content. The export stops
        # at the first device miss; exporting one by one keeps it simple
        # and each block is a single chain element.
        for h in todo:
            found, wire = await self._engine.export_blocks_wire_async([h])
            if not found:
                continue  # evicted before we got to it; write-through missed
            if wire.quantized:
                self.tier.put(
                    h, wire.k[0], wire.v[0], wire.k_scale[0], wire.v_scale[0]
                )
            else:
                self.tier.put(h, wire.k[0], wire.v[0])
            if self.remote is not None:
                # G4 write-behind: the shared store absorbs it
                # asynchronously. The remote tier stays dense (it serves
                # engines of ANY pool dtype).
                dk, dv = wire.to_dense()
                self.remote.put(h, dk[0], dv[0])
            self.offloaded += 1
            moved += 1
            self.metrics.offload_blocks.inc()
            self.metrics.offload_bytes.inc(int(wire.nbytes))
        dt = time.monotonic() - t0
        self.metrics.offload_duration.observe(
            dt, tier=getattr(self.tier, "name", "host")
        )
        self.kv_flight.record(
            "offload_burst", blocks=moved, queued=len(todo),
            ms=round(dt * 1000.0, 3),
        )
        self._sync_plane()

    # -- onboard (G2/G3 → G1) ------------------------------------------------

    def match_chain(self, block_hashes: List[int]) -> int:
        """Leading blocks available in the tiers."""
        n = 0
        for h in block_hashes:
            if not self.tier.contains(h) and (
                self.tier.next_tier is None or not self.tier.next_tier.contains(h)
            ):
                break
            n += 1
        return n

    async def onboard(self, block_hashes: List[int]) -> int:
        """Bring a leading run of blocks onto the device (before prefill).
        Returns how many blocks were installed."""
        assert self._engine is not None
        from dynamo_tpu.disagg.wire import tier_block_wire

        t0 = time.monotonic()
        run: List[int] = []
        blocks: List[tuple] = []
        # Deepest tier the run resolved from (hit attribution for the
        # KV-reuse plane; checked BEFORE get() because get() promotes).
        tier_rank = {getattr(self.tier, "name", "host"): 0}
        if self.tier.next_tier is not None:
            tier_rank[getattr(self.tier.next_tier, "name", "disk")] = 1
        deepest: Optional[str] = None
        for h in block_hashes:
            if self.tier.contains(h):
                src = getattr(self.tier, "name", "host")
            elif (
                self.tier.next_tier is not None
                and self.tier.next_tier.contains(h)
            ):
                src = getattr(self.tier.next_tier, "name", "disk")
            else:
                src = "remote"
            blk = self.tier.get(h)
            if blk is None and self.remote is not None:
                # G4 fallback: a shared-store hit extends the run (and lands
                # in the host tier for next time).
                blk = await self.remote.get_async(h)
                if blk is not None:
                    self.tier.put(h, *blk)
            if blk is None:
                break
            if deepest is None or tier_rank.get(src, 2) > tier_rank.get(deepest, 2):
                deepest = src
            run.append(h)
            blocks.append(blk)
        self.last_onboard_source = deepest
        if not run:
            return 0

        # Install in uniform-form sub-runs (a tier can hold a mix of dense
        # and quantized blocks across engine-dtype generations); each
        # sub-run after the first anchors on its predecessor's tail so the
        # chain stays parent-linked.
        installed = 0
        anchor = None
        i = 0
        while i < len(run):
            j = i + 1
            while j < len(run) and len(blocks[j]) == len(blocks[i]):
                j += 1
            wire = tier_block_wire(blocks[i:j])
            n = await self._engine.import_blocks_wire_async(
                run[i:j], wire, anchor_parent=anchor
            )
            installed += n
            self.metrics.onboard_bytes.inc(
                int(wire.nbytes * (n / max(len(wire), 1)))
            )
            if n < j - i:
                break  # pool dry mid-run
            anchor = run[j - 1]
            i = j
        self.onboarded += installed
        self.metrics.onboard_blocks.inc(installed)
        dt = time.monotonic() - t0
        self.metrics.onboard_duration.observe(dt, tier=deepest or "host")
        self.kv_flight.record(
            "onboard", blocks=installed, run=len(run),
            tier=deepest or "host", ms=round(dt * 1000.0, 3),
        )
        self._sync_plane()
        return installed

    def register_metrics(self, server: Any) -> None:
        """Expose this manager's metric families on a SystemStatusServer."""
        server.register_metrics(self.metrics.render)
        server.register_flight(self.flight.name, self.flight.snapshot)
        server.register_flight(self.kv_flight.name, self.kv_flight.snapshot)

    def stats(self) -> Dict[str, Any]:
        out = {
            "offloaded": self.offloaded,
            "onboarded": self.onboarded,
            "host": self.tier.stats.to_dict(),
            "host_blocks": len(self.tier),
        }
        if self.tier.next_tier is not None:
            out["disk"] = self.tier.next_tier.stats.to_dict()
            out["disk_blocks"] = len(self.tier.next_tier)
        if self.remote is not None:
            out["remote"] = self.remote.stats.to_dict()
        return out

    async def close(self) -> None:
        if self._task is not None and not self._task.done():
            self._task.cancel()
            await reap_task(self._task, "kvbm consolidator", logger)
        # Departed-tier GC: this manager's occupancy gauges and its live
        # tier source leave the scrape with it (zero-residue audit — a
        # long-lived SystemStatusServer must not keep advertising the
        # occupancy of tiers that no longer exist).
        for name in list(self.metrics._tier_sources):
            self.metrics.unwatch_tier(name)
        self.kv_plane.forget_tier_source(self._plane_label)
        if self.remote is not None:
            await self.remote.close()
