"""Storage tiers: host RAM (G2) and local disk (G3).

Reference parity: lib/llm/src/block_manager/storage/{mod,disk}.rs + the
pinned-host pool. Blocks are content-addressed (chained hash → arrays of
shape [L, block_size, KH, D]); each tier is LRU-bounded and spills
evictions down to the next tier when one is attached.

Block forms: a tier entry is a tuple of arrays —
  (k, v)                        dense, any dtype
  (k_q8, v_q8, k_scale, v_scale) pool-native quantized (int8 payloads +
                                 [L, KH, BS] f32 scales, disagg/wire.py)
Quantized offload stores the wire form VERBATIM, so G2/G3 hold half the
dense footprint and onboarding re-installs bit-exact pool content.
Consumers that need dense arrays funnel through
disagg/wire.py::dense_tier_block.
"""

from __future__ import annotations

import io
import os
import zipfile
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from dynamo_tpu.kvbm.integrity import array_crc32, note_corruption
from dynamo_tpu.runtime import fault_names
from dynamo_tpu.runtime.faults import fault_payload, fault_point
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# (k, v) dense or (k_q8, v_q8, k_scale, v_scale) quantized
Block = Tuple[np.ndarray, ...]

# Popularity scorer: block_hash -> decayed score, or None when the signal
# source has nothing on the block. Tiers stay sketch-agnostic — the
# callable is wired by TieredKvManager (its protected-prefix map over the
# PR 16 sketch); tiers only compare the floats it returns.
Scorer = Callable[[int], Optional[float]]

# How many LRU-oldest entries a scored eviction considers. Bounds both
# the per-eviction cost (window scorer calls) and the worst-case
# deviation from plain LRU (a hot block can sit at most window-1 slots
# from the LRU head before recency alone saves it).
EVICT_SCAN_WINDOW = 8


def _pop_victim(lru: OrderedDict, scorer: Optional[Scorer]):
    """Pop the eviction victim ``(key, value)`` from an LRU OrderedDict.

    With no scorer this IS ``popitem(last=False)`` — plain LRU. With one,
    scan the EVICT_SCAN_WINDOW oldest entries and evict the least popular:
    unscored entries (scorer returned None) go first, then ascending
    score, with LRU age as the tiebreak. A scorer failure costs ranking
    quality for this pass, never the eviction itself.
    """
    if scorer is None:
        return lru.popitem(last=False)
    victim = None
    best = None
    for i, h in enumerate(lru):
        if i >= EVICT_SCAN_WINDOW:
            break
        try:
            s = scorer(h)
        except Exception:
            logger.debug("eviction scorer failed; falling back to LRU",
                         exc_info=True)
            victim = None
            break
        if s is None:
            # Unscored beats any score, and no later unscored entry can
            # be older than this one: done.
            victim = h
            break
        key = (s, i)
        if best is None or key < best:
            best = key
            victim = h
    if victim is None:
        return lru.popitem(last=False)
    return victim, lru.pop(victim)


@dataclass
class TierStats:
    hits: int = 0
    misses: int = 0
    stored: int = 0
    evicted: int = 0
    # CRC-failed / unreadable persisted blocks, each ALSO counted a miss.
    corrupt: int = 0
    # Eviction split by reason (arena_full = straight spill past a full
    # pinned arena, capacity = LRU overflow); sum == evicted. Sampled into
    # {tier, reason}-labeled counters by KvbmMetrics at scrape time.
    evicted_by_reason: Dict[str, int] = field(default_factory=dict)

    def note_evicted(self, reason: str) -> None:
        self.evicted += 1
        self.evicted_by_reason[reason] = (
            self.evicted_by_reason.get(reason, 0) + 1
        )

    def to_dict(self) -> Dict[str, int]:
        return {"hits": self.hits, "misses": self.misses,
                "stored": self.stored, "evicted": self.evicted,
                "corrupt": self.corrupt}


class HostTier:
    """G2: host-RAM block store, LRU-bounded by block count.

    With ``arena_bytes`` set, block payloads live in a preallocated Arena
    (runtime/memory.py, the dynamo-memory role): a hard byte cap and zero
    per-block allocator churn. Blocks the arena cannot fit (fragmentation)
    spill straight to the next tier."""

    name = "host"

    def __init__(
        self,
        capacity_blocks: int,
        *,
        next_tier: Optional["DiskTier"] = None,
        arena_bytes: Optional[int] = None,
    ) -> None:
        self.capacity = capacity_blocks
        self.next_tier = next_tier
        self._blocks: "OrderedDict[int, Optional[Block]]" = OrderedDict()
        self._staging = None
        if arena_bytes:
            from dynamo_tpu.runtime.memory import BlockStagingPool

            self._staging = BlockStagingPool(arena_bytes)
        self.stats = TierStats()
        # Optional popularity scorer (see _pop_victim); None = plain LRU.
        self.scorer: Optional[Scorer] = None

    def __len__(self) -> int:
        return len(self._blocks)

    def contains(self, block_hash: int) -> bool:
        return block_hash in self._blocks

    def put(self, block_hash: int, *arrays: np.ndarray) -> None:
        # Chaos seam: offload callers (kvbm/manager.py burst loop) log and
        # drop the burst; the block simply stays un-offloaded.
        fault_point(fault_names.KVBM_TIER_WRITE, tier=self.name)
        if block_hash in self._blocks:
            self._blocks.move_to_end(block_hash)
            return
        blk: Block = tuple(np.asarray(a) for a in arrays)
        if self._staging is not None:
            if not self._staging.put(block_hash, *blk):
                # Arena full: skip G2, spill straight down.
                self.stats.note_evicted("arena_full")
                if self.next_tier is not None:
                    self.next_tier.put(block_hash, *blk)
                return
            self._blocks[block_hash] = None  # payload lives in the arena
        else:
            self._blocks[block_hash] = blk
        self.stats.stored += 1
        while len(self._blocks) > self.capacity:
            h, blk = _pop_victim(self._blocks, self.scorer)
            if self._staging is not None:
                blk = self._staging.get(h)
                spill = (
                    None if blk is None else tuple(np.array(a) for a in blk)
                )
                self._staging.pop(h)
                blk = spill
            self.stats.note_evicted("capacity")
            if self.next_tier is not None and blk is not None:
                self.next_tier.put(h, *blk)  # G2 → G3 spill

    def get(self, block_hash: int) -> Optional[Block]:
        # Chaos seam: onboard callers (engines/tpu/admission.py) catch and
        # fall back to local prefill — an injected read failure costs
        # recompute, never correctness.
        fault_point(fault_names.KVBM_TIER_READ, tier=self.name)
        if block_hash in self._blocks:
            self._blocks.move_to_end(block_hash)
            if self._staging is not None:
                blk = self._staging.get(block_hash)
                if blk is not None:
                    # Copies, not views: a later put() on this tier can evict
                    # the block and recycle its arena region while the caller
                    # still holds the arrays (onboard chains do exactly this).
                    blk = tuple(np.array(a) for a in blk)
            else:
                blk = self._blocks[block_hash]
            if blk is not None:
                self.stats.hits += 1
                return blk
        self.stats.misses += 1
        if self.next_tier is not None:
            lower = self.next_tier.get(block_hash)
            if lower is not None:
                self.put(block_hash, *lower)  # promote G3 → G2
                return lower
        return None

    def clear(self) -> None:
        if self._staging is not None:
            for h in list(self._blocks):
                self._staging.pop(h)
        self._blocks.clear()


def _npz_safe(a: np.ndarray) -> np.ndarray:
    """bf16 lacks npz support → view as uint16 (dtype remembered aside)."""
    if a.dtype.str == "<V2" or "bfloat16" in str(a.dtype):
        return a.view(np.uint16)
    return a


class DiskTier:
    """G3: one .npz file per block under a spool directory, LRU-bounded.

    Every array in a spill carries a CRC32 (``crc_*`` fields) verified on
    read: a corrupt or truncated file is a COUNTED miss (TierStats.corrupt
    + dynamo_tpu_kvbm_restore_corruption_total{source="disk"} + the
    manager's flight ring via ``on_corruption``) and the entry is dropped
    — never a crash, never garbage KV onboarded into the pool."""

    name = "disk"

    def __init__(self, root: str, capacity_blocks: int = 4096) -> None:
        self.root = root
        self.capacity = capacity_blocks
        os.makedirs(root, exist_ok=True)
        self._lru: "OrderedDict[int, str]" = OrderedDict()
        self.stats = TierStats()
        # Optional popularity scorer (see _pop_victim); None = plain LRU.
        self.scorer: Optional[Scorer] = None
        # (block_hash, detail) -> None; TieredKvManager wires this to its
        # flight ring so corruption shows up in /debug/flight.
        self.on_corruption: Optional[Callable[[int, str], None]] = None
        # Recover existing spool contents (checkpoint/resume of the cache).
        for fname in sorted(os.listdir(root)):
            if fname.endswith(".npz"):
                try:
                    self._lru[int(fname[:-4], 16)] = os.path.join(root, fname)
                except ValueError:
                    continue

    def __len__(self) -> int:
        return len(self._lru)

    def _path(self, block_hash: int) -> str:
        return os.path.join(self.root, f"{block_hash:016x}.npz")

    def contains(self, block_hash: int) -> bool:
        return block_hash in self._lru

    def put(self, block_hash: int, *arrays: np.ndarray) -> None:
        if block_hash in self._lru:
            # Duplicate spill: still ONE seam hit per put (stable chaos
            # schedules), but there is no payload to corrupt.
            fault_point(fault_names.KVBM_TIER_WRITE, tier=self.name)
            self._lru.move_to_end(block_hash)
            return
        path = self._path(block_hash)
        blk = tuple(np.asarray(a) for a in arrays)
        fields = {
            "k": _npz_safe(blk[0]),
            "v": _npz_safe(blk[1]),
            "dtype": str(blk[0].dtype),
            # Per-array CRC32 of the stored (npz-safe) form; verified on
            # every read before the block can onboard.
            "crc_k": np.uint32(array_crc32(_npz_safe(blk[0]))),
            "crc_v": np.uint32(array_crc32(_npz_safe(blk[1]))),
        }
        if len(blk) == 4:
            # Quantized wire form: int8 payloads + f32 scales, stored as-is
            # (half the dense spool footprint).
            fields["k_scale"] = blk[2]
            fields["v_scale"] = blk[3]
            fields["crc_k_scale"] = np.uint32(array_crc32(blk[2]))
            fields["crc_v_scale"] = np.uint32(array_crc32(blk[3]))
        # Serialize to memory first: the chaos seam can then corrupt the
        # SERIALIZED bytes (kind="corrupt" — modeling silent disk/page
        # damage) or raise (connection/timeout/error kinds), exactly one
        # hit per put either way.
        buf = io.BytesIO()
        np.savez(buf, **fields)
        raw = fault_payload(
            fault_names.KVBM_TIER_WRITE, buf.getvalue(), tier=self.name
        )
        with open(path, "wb") as f:
            f.write(raw)
        self._lru[block_hash] = path
        self.stats.stored += 1
        while len(self._lru) > self.capacity:
            h, p = _pop_victim(self._lru, self.scorer)
            self.stats.note_evicted("capacity")
            try:
                os.unlink(p)
            except FileNotFoundError:
                pass

    def _note_corruption(self, block_hash: int, path: str, detail: str) -> None:
        """Corruption is a counted miss: metric + stats + manager flight
        event, entry dropped, file removed (it can never verify again)."""
        self.stats.corrupt += 1
        note_corruption(self.name)
        logger.warning(
            "disk-tier block %016x failed integrity (%s); dropping %s",
            block_hash, detail, path,
        )
        if self.on_corruption is not None:
            self.on_corruption(block_hash, detail)
        self._lru.pop(block_hash, None)
        try:
            os.unlink(path)
        except OSError:
            pass

    def get(self, block_hash: int) -> Optional[Block]:
        path = self._lru.get(block_hash)
        if path is None:
            fault_point(fault_names.KVBM_TIER_READ, tier=self.name)
            self.stats.misses += 1
            return None
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            # Vanished/unreadable file: plain miss (the pre-CRC contract);
            # a transient IO error must not burn the entry as corrupt.
            # Still one seam hit per get — otherwise every later hit
            # number shifts and a chaos schedule pinned with at=(n,)
            # fires on the wrong call.
            fault_point(fault_names.KVBM_TIER_READ, tier=self.name)
            self._lru.pop(block_hash, None)
            self.stats.misses += 1
            return None
        # Chaos seam (one hit per get, same as the miss path): raising
        # kinds model IO failure and PROPAGATE to the onboard caller, as
        # before; kind="corrupt" flips a bit of the bytes just read —
        # which the CRC check below must catch.
        raw = fault_payload(fault_names.KVBM_TIER_READ, raw, tier=self.name)
        try:
            with np.load(io.BytesIO(raw), allow_pickle=False) as z:
                dtype = str(z["dtype"])
                k, v = z["k"], z["v"]
                for field, arr in (
                    ("crc_k", k), ("crc_v", v),
                    ("crc_k_scale", z["k_scale"] if "k_scale" in z.files else None),
                    ("crc_v_scale", z["v_scale"] if "v_scale" in z.files else None),
                ):
                    # Pre-CRC spills (no crc_* fields) read unverified.
                    if arr is not None and field in z.files:
                        if array_crc32(arr) != int(z[field]):
                            self._note_corruption(
                                block_hash, path, f"{field} mismatch"
                            )
                            self.stats.misses += 1
                            return None
                if "bfloat16" in dtype:
                    import ml_dtypes

                    k = k.view(ml_dtypes.bfloat16)
                    v = v.view(ml_dtypes.bfloat16)
                if "k_scale" in z.files:
                    blk: Block = (k, v, z["k_scale"], z["v_scale"])
                else:
                    blk = (k, v)
        except (OSError, KeyError, ValueError, zipfile.BadZipFile) as exc:
            # Truncated/garbled npz: same counted-miss contract as a CRC
            # mismatch (np.load surfaces these shapes for partial writes).
            self._note_corruption(
                block_hash, path, f"{type(exc).__name__}: {exc}"
            )
            self.stats.misses += 1
            return None
        self._lru.move_to_end(block_hash)
        self.stats.hits += 1
        return blk

    def clear(self) -> None:
        for _, path in self._lru.items():
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
        self._lru.clear()
