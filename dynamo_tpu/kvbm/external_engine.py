"""External-engine adapter: a whole serving engine behind the KVBM
connector seam.

Reference parity: the reference's core business is serving engines it does
NOT own through exactly this surface (kvbm vllm_integration's
connector_leader/connector_worker pair wrapped by the engine-side adapter
classes in components/src/dynamo/vllm). This module is that adapter for a
JAX engine standing in as the "foreign" engine: KV moves ONLY through
KvConnectorLeader/KvConnectorWorker + the host tier — the adapter never
reaches into another engine's pools — so any engine that can expose
put-block/get-block callbacks gets tiered KV reuse, onboarding, and
write-back without the framework owning its internals.
"""

from __future__ import annotations

import asyncio
from typing import Any, List, Tuple

import numpy as np

from dynamo_tpu.kvbm.connector import KvConnectorLeader, KvConnectorWorker
from dynamo_tpu.tokens.blocks import compute_block_hashes
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class ExternalEngineKvAdapter:
    """Bridge one engine onto the connector halves.

    The engine plays the reference's vLLM role: its scheduler consults the
    LEADER for beyond-cache matches before prefill, its per-rank worker
    executes the leader's opaque transfer instructions via two callbacks
    that are the only place engine memory is touched.

    ``salt``: requests whose engine hashes carry a per-request salt (LoRA
    adapter, multimodal content — see admission.py) must pass the SAME salt
    here, or their blocks can neither match nor round-trip.

    Transfers on one adapter are serialized (one leader/worker pair holds
    one bound metadata blob at a time); the engine keeps serving decode
    between them."""

    def __init__(self, engine: Any, tier: Any) -> None:
        self.engine = engine
        self.block_size = engine.args.block_size
        self.leader = KvConnectorLeader(tier, self.block_size)
        self.worker = KvConnectorWorker(tier)
        self.worker.register_kv_caches(self._put_block, self._get_block)
        self._lock = asyncio.Lock()  # meta bind → execute is a critical section
        self.loads = 0
        self.saves = 0

    # -- engine-memory callbacks (the register_kv_caches contract) ---------

    def _put_block(self, engine_block_id: int, k: np.ndarray, v: np.ndarray):
        self.engine.runner.scatter_blocks(
            [engine_block_id], np.asarray(k)[None], np.asarray(v)[None]
        )

    def _get_block(self, engine_block_id: int) -> Tuple[np.ndarray, np.ndarray]:
        k, v = self.engine.runner.gather_blocks([engine_block_id])
        return k[0], v[0]

    # -- scheduler-side flows ----------------------------------------------

    async def onboard(
        self, request_id: str, prompt: List[int], *, salt: int = 0
    ) -> int:
        """Pre-admission: ask the leader what the KVBM can supply beyond
        the engine's own prefix cache, execute the load instructions, and
        commit the landed blocks so admission sees them as ordinary prefix
        hits. Returns blocks onboarded."""
        e = self.engine
        hashes = compute_block_hashes(prompt, self.block_size, salt=salt)
        async with self._lock:
            # Pin the engine-matched prefix through the transfer: alloc()'s
            # LRU eviction must not recycle the blocks the match (and the
            # commit parent chain) depend on — same invariant admission
            # establishes with pin-before-alloc.
            engine_matched, pinned = e.pool.pin_prefix(hashes)
            try:
                return await self._onboard_locked(
                    request_id, hashes, engine_matched
                )
            finally:
                if pinned:
                    e.pool.release(pinned, hashes[: len(pinned)])
                self.leader.forget(request_id)

    async def _onboard_locked(
        self, request_id: str, hashes: List[int], engine_matched: int
    ) -> int:
        e = self.engine
        new_tokens, _is_async = self.leader.get_num_new_matched_tokens(
            request_id, hashes, engine_matched * self.block_size
        )
        if new_tokens <= 0:
            return 0
        span = range(
            engine_matched, engine_matched + new_tokens // self.block_size
        )
        ids_full: List[int] = [-1] * len(hashes)
        allocated: List[Tuple[int, int]] = []  # (position, engine block id)
        for i in span:
            b = e.pool.alloc()
            if b is None:
                break
            ids_full[i] = b
            allocated.append((i, b))
        if not allocated:
            return 0
        # Pool pressure may have cut the allocation short: shrink the match
        # so the leader never emits instructions targeting the -1 slots.
        self.leader.limit_match(request_id, len(allocated))
        self.leader.update_state_after_alloc(request_id, ids_full)
        self.worker.bind_connector_metadata(self.leader.build_connector_meta())
        try:
            await e._device(self.worker.start_load_kv)
        finally:
            self.worker.clear_connector_metadata()
        failed = {
            h
            for hs in self.worker.get_failed_loads().values()
            for h in hs
        }
        parent = hashes[engine_matched - 1] if engine_matched else None
        committed = 0
        chain_broken = False
        for i, b in allocated:
            h = hashes[i]
            if chain_broken or h in failed:
                # a failed load revokes the match promise for this block
                # AND everything after it (prefix chains must be gapless)
                chain_broken = True
                e.pool.release([b], [])
                continue
            e.pool.commit(b, h, parent)
            e.pool.release([b], [h])  # cached, unreferenced
            parent = h
            committed += 1
        self.loads += committed
        return committed

    async def offload(
        self, request_id: str, prompt: List[int], *, salt: int = 0
    ) -> int:
        """Post-request write-back: the leader decides which committed
        blocks the tier lacks; the worker reads them out of engine memory
        and stores them. Returns blocks saved."""
        e = self.engine
        hashes = compute_block_hashes(prompt, self.block_size, salt=salt)
        async with self._lock:
            matched, ids = e.pool.pin_prefix(hashes)
            try:
                pairs = list(zip(hashes[:matched], ids))
                if not self.leader.request_finished(request_id, pairs):
                    return 0
                self.worker.bind_connector_metadata(
                    self.leader.build_connector_meta()
                )
                try:
                    n = await e._device(self.worker.save_kv_blocks)
                finally:
                    self.worker.clear_connector_metadata()
                self.saves += n
                return n
            finally:
                if ids:
                    e.pool.release(ids, hashes[: len(ids)])
