"""G4: remote KV block store — a shared cache service over the runtime.

Reference parity: KVBM's G4 remote tier (block_manager storage backends
reaching object/remote stores via NIXL). TPU-native shape: a standalone
``kvstore`` component any worker can mount under its disk tier; transfers
ride the existing request plane (msgpack + pack_array), so one deployment
flag turns a pool of workers into shared-cache peers.

  KvStoreHandler  — the service side (bounded LRU of content-hashed blocks)
  RemoteTier      — the client side, implementing the tier protocol
                    (contains/put/get) under HostTier/DiskTier chaining.
"""

from __future__ import annotations

import asyncio
from collections import OrderedDict
from typing import Any, AsyncIterator, Dict, Optional, Tuple

import numpy as np

from dynamo_tpu.disagg.handlers import pack_array, unpack_array
from dynamo_tpu.kvbm.tiers import TierStats
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

Block = Tuple[np.ndarray, np.ndarray]


class KvStoreHandler:
    """Serve a shared KV block store endpoint.

    Ops (one request → one response item):
      {"op": "put", "hash": h, "k": packed, "v": packed}   → {"ok": true}
      {"op": "get", "hash": h}       → {"k": packed, "v": packed} | {"miss": true}
      {"op": "contains", "hash": h}  → {"present": bool}
      {"op": "stats"}                → counters
    """

    def __init__(self, capacity_blocks: int = 65536) -> None:
        self.capacity = capacity_blocks
        self._blocks: "OrderedDict[int, Block]" = OrderedDict()
        self.stats = TierStats()

    async def generate(self, request: Any, context: Any) -> AsyncIterator[Dict[str, Any]]:
        op = request.get("op")
        if op == "put":
            h = int(request["hash"])
            if h not in self._blocks:
                self._blocks[h] = (
                    unpack_array(request["k"]).copy(),
                    unpack_array(request["v"]).copy(),
                )
                self.stats.stored += 1
                while len(self._blocks) > self.capacity:
                    self._blocks.popitem(last=False)
                    self.stats.note_evicted("capacity")
            else:
                self._blocks.move_to_end(h)
            yield {"ok": True}
        elif op == "get":
            blk = self._blocks.get(int(request["hash"]))
            if blk is None:
                self.stats.misses += 1
                yield {"miss": True}
            else:
                self._blocks.move_to_end(int(request["hash"]))
                self.stats.hits += 1
                yield {"k": pack_array(blk[0]), "v": pack_array(blk[1])}
        elif op == "contains":
            yield {"present": int(request["hash"]) in self._blocks}
        elif op == "stats":
            yield {"blocks": len(self._blocks), **self.stats.to_dict()}
        else:
            yield {"error": f"unknown kvstore op {op!r}"}


class RemoteTier:
    """Tier-protocol client for a KvStoreHandler endpoint.

    The tier protocol is synchronous (HostTier/DiskTier call it from the
    event loop), so the client schedules network ops on the running loop and
    blocks only where the protocol demands a value (get/contains); puts are
    fire-and-forget tasks (write-behind, like the G3 spill path).
    """

    name = "remote"

    def __init__(self, client_factory, *, timeout_s: float = 10.0) -> None:
        self._factory = client_factory  # async () -> runtime Client
        self._client = None
        self.timeout_s = timeout_s
        self.stats = TierStats()
        self._pending: set = set()

    async def _ensure(self):
        if self._client is None:
            self._client = await self._factory()
        return self._client

    async def _call(self, request: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        client = await self._ensure()
        from dynamo_tpu.runtime.context import Context
        from dynamo_tpu.runtime.engine import collect

        out = await asyncio.wait_for(
            collect(client.generate(request, Context())), timeout=self.timeout_s
        )
        return out[-1] if out else None

    # -- tier protocol (loop-thread callers) --------------------------------

    def put(self, block_hash: int, k: np.ndarray, v: np.ndarray) -> None:
        async def _put():
            try:
                await self._call(
                    {"op": "put", "hash": block_hash,
                     "k": pack_array(k), "v": pack_array(v)}
                )
                self.stats.stored += 1
            except Exception:
                logger.exception("remote tier put failed")

        task = asyncio.get_running_loop().create_task(_put())
        self._pending.add(task)
        task.add_done_callback(self._pending.discard)

    def contains(self, block_hash: int) -> bool:
        # Synchronous protocol + async transport: only answerable when
        # called from outside the loop; tier chaining uses get() directly.
        return False

    def get(self, block_hash: int) -> Optional[Block]:
        """Blocking fetch — must NOT be called from the event loop thread
        (the async path is get_async; DiskTier chains via that)."""
        raise RuntimeError("RemoteTier.get is async-only; use get_async")

    async def get_async(self, block_hash: int) -> Optional[Block]:
        try:
            out = await self._call({"op": "get", "hash": block_hash})
        except Exception:
            logger.exception("remote tier get failed")
            return None
        if not out or out.get("miss") or out.get("error"):
            self.stats.misses += 1
            return None
        self.stats.hits += 1
        return unpack_array(out["k"]), unpack_array(out["v"])

    async def flush(self) -> None:
        """Wait for write-behind puts (tests/shutdown)."""
        if self._pending:
            await asyncio.gather(*list(self._pending), return_exceptions=True)

    async def close(self) -> None:
        await self.flush()
        if self._client is not None:
            await self._client.close()
            self._client = None
