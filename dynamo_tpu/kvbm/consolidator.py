"""KV-event consolidator: raw engine events → clean router events.

Reference parity: lib/llm/src/block_manager/kv_consolidator/tracker.rs —
external engines (vLLM-style) emit raw per-physical-block events that are
noisy from a router's point of view: duplicates after restarts, remove
events for hashes never stored, interleaved store/remove churn within one
scheduler tick, and per-rank duplication under tensor parallelism. The
consolidator tracks the logical resident set and emits only NET changes,
batched per flush — so the event plane and every subscribed router index
see a compact, monotonic stream.

Used by the C-ABI publisher path (native/kv_publisher.py) and any
connector-integrated external engine; the native JaxEngine's BlockPool
already emits clean logical events and does not need one.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from dynamo_tpu.engines.mock.kv_manager import KvEvent
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class _Pending:
    stored: Dict[int, Optional[int]] = field(default_factory=dict)  # h → parent
    removed: Set[int] = field(default_factory=set)


class KvEventConsolidator:
    """Dedup + net-change batching for raw KV event streams.

    Feed raw events with :meth:`on_raw_event` (any thread-safe single
    consumer); call :meth:`flush` to emit the net batch downstream (e.g.
    KvEventPublisher.on_kv_event). A store+remove of the same hash within
    one flush window cancels out entirely; duplicate stores of a resident
    hash and removes of a non-resident hash are dropped.
    """

    def __init__(
        self,
        emit: Callable[[KvEvent], None],
        *,
        dedup_ranks: bool = True,
    ) -> None:
        self._emit = emit
        self._resident: Dict[int, Optional[int]] = {}  # hash → parent
        self._pending = _Pending()
        self._dedup_ranks = dedup_ranks
        self.raw_events = 0
        self.emitted_events = 0

    # -- ingestion ---------------------------------------------------------

    def on_raw_event(self, event: KvEvent, rank: int = 0) -> None:
        """Ingest one raw event. Under TP, every rank reports the same
        logical mutation — rank > 0 duplicates are dropped up front."""
        self.raw_events += 1
        if self._dedup_ranks and rank != 0:
            return
        if event.kind == "stored":
            parent = event.parent_hash
            for h in event.block_hashes:
                if h in self._pending.removed:
                    # remove→store within the window: net effect is store
                    self._pending.removed.discard(h)
                if h not in self._resident:
                    self._pending.stored[h] = parent
                parent = h
        elif event.kind == "removed":
            for h in event.block_hashes:
                if h in self._pending.stored:
                    # store→remove within the window: cancels out
                    del self._pending.stored[h]
                elif h in self._resident:
                    self._pending.removed.add(h)
                # never-resident removes are dropped (restart echoes)
        elif event.kind == "cleared":
            self._pending.stored.clear()
            self._pending.removed = set(self._resident)
        else:
            logger.warning("consolidator: unknown raw event kind %r", event.kind)

    # -- flush -------------------------------------------------------------

    def flush(self) -> int:
        """Emit the net batch; returns how many events went downstream."""
        emitted = 0
        if self._pending.removed:
            self._emit(
                KvEvent(kind="removed", block_hashes=sorted(self._pending.removed))
            )
            for h in self._pending.removed:
                self._resident.pop(h, None)
            emitted += 1
        if self._pending.stored:
            # Group into parent-linked runs so downstream indexers get
            # chain-shaped stores. Insertion order is USUALLY topological,
            # but a store→remove→re-store of a parent within one window
            # re-inserts it AFTER its children — re-sort parents-first.
            items = self._topo_order(self._pending.stored)
            run: List[int] = []
            run_parent: Optional[int] = None
            prev: Optional[int] = None
            for h, parent in items:
                if not run:
                    run, run_parent = [h], parent
                elif parent == prev:
                    run.append(h)
                else:
                    self._emit(
                        KvEvent(kind="stored", block_hashes=run,
                                parent_hash=run_parent)
                    )
                    emitted += 1
                    run, run_parent = [h], parent
                prev = h
            if run:
                self._emit(
                    KvEvent(kind="stored", block_hashes=run, parent_hash=run_parent)
                )
                emitted += 1
            self._resident.update(self._pending.stored)
        self._pending = _Pending()
        self.emitted_events += emitted
        return emitted

    @staticmethod
    def _topo_order(stored):
        """[(h, parent)] with every pending parent before its children
        (unknown/already-resident parents count as satisfied)."""
        pending = dict(stored)
        ordered = []
        placed = set()
        while pending:
            progressed = False
            for h, parent in list(pending.items()):
                if parent not in pending or parent in placed:
                    ordered.append((h, parent))
                    placed.add(h)
                    del pending[h]
                    progressed = True
            if not progressed:  # cycle (corrupt input): emit as-is
                ordered.extend(pending.items())
                break
        return ordered

    # -- introspection -----------------------------------------------------

    @property
    def resident_blocks(self) -> int:
        return len(self._resident)

    def committed_view(self) -> List[Tuple[int, Optional[int]]]:
        """[(hash, parent)] — plugs into KvEventPublisher.set_snapshot_fn
        so consolidated external engines answer re-sync requests too."""
        return list(self._resident.items())
