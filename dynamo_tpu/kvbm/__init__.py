"""KVBM-equivalent: multi-tier KV cache (HBM → host RAM → disk → remote).

Reference parity: lib/llm/src/block_manager* (SURVEY §2.1 KVBM row) —
CacheLevel G1 device / G2 pinned host / G3 local disk / G4 remote
(block_manager.rs:62–75), pools with reuse & eviction (pool/managed.rs),
async offload/onboard engine with filters (offload.rs, offload/filter.rs).

TPU-first redesign: every tier is content-addressed by the same chained
block hash the router and disagg layers use. G1 is the engine's BlockPool in
HBM; G2/G3 live here; G4 is any peer engine reachable over the request plane
(disagg/handlers.py KvTransferHandler — same protocol). Offload is
write-through on block commit (device gather batched on the engine's device
thread); onboard runs at admission, extending the device prefix match before
prefill. The reference's block_copy.cu becomes a donated-buffer jit scatter
(engines/tpu/engine.py _scatter_blocks).
"""

from dynamo_tpu.kvbm.tiers import DiskTier, HostTier, TierStats
from dynamo_tpu.kvbm.manager import OffloadFilter, TieredKvManager
from dynamo_tpu.kvbm.remote import KvStoreHandler, RemoteTier
from dynamo_tpu.kvbm.connector import KvConnectorLeader, KvConnectorWorker
from dynamo_tpu.kvbm.consolidator import KvEventConsolidator

__all__ = [
    "DiskTier", "HostTier", "TierStats", "OffloadFilter", "TieredKvManager",
    "KvStoreHandler", "RemoteTier", "KvConnectorLeader", "KvConnectorWorker",
    "KvEventConsolidator",
]
