"""KVBM connector API: the tiered KV store for EXTERNAL engines.

Reference parity: lib/bindings/kvbm python vllm_integration —
connector_leader.py (scheduler-side: get_num_new_matched_tokens :116,
update_state_after_alloc :144, build_connector_meta :152,
request_finished :228) and connector_worker.py (per-rank:
register_kv_caches :61, bind_connector_metadata :128, start_load_kv :148,
save_kv_layer :165, get_finished :187).

The native JaxEngine integrates with TieredKvManager directly
(kvbm/manager.py); this module is the arms-length API for engines the
framework does NOT own: the engine's scheduler asks the leader what the
KVBM can supply beyond its own cache, the leader emits transfer
instructions as opaque metadata, and the engine's per-rank worker executes
them against device memory through two engine-supplied callbacks. TPU
note: the callbacks hand over numpy arrays — the engine decides how they
map to device HBM (jax.device_put into its paged cache, a pallas gather,
whatever fits its layout); the connector never touches device state.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import msgpack
import numpy as np

from dynamo_tpu.disagg.wire import dense_tier_block
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# put_block(engine_block_id, k, v) — write one block into the engine cache
PutBlockFn = Callable[[int, np.ndarray, np.ndarray], None]
# get_block(engine_block_id) -> (k, v) — read one block out of the engine
GetBlockFn = Callable[[int], Tuple[np.ndarray, np.ndarray]]


@dataclass
class _RequestSlot:
    """Leader-side per-request transfer state (ref: _create_slot :250)."""

    token_hashes: List[int]
    matched: int = 0  # blocks the KVBM can supply
    engine_matched: int = 0  # blocks the engine already had
    block_ids: List[int] = field(default_factory=list)  # engine block ids


class KvConnectorLeader:
    """Scheduler-side half: match decisions + transfer-instruction builder."""

    def __init__(self, tier: Any, block_size: int, *, metrics: Any = None) -> None:
        self.tier = tier  # HostTier-compatible: contains/get/put
        self.block_size = block_size
        self._slots: Dict[str, _RequestSlot] = {}
        self._pending_saves: Dict[str, List[Tuple[int, int]]] = {}
        # Shared KvbmMetrics (kvbm/manager.py) when the host process exposes
        # a /metrics surface; duck-typed so the connector stays arms-length.
        self._metrics = metrics
        self.pool_pressure_truncations = 0

    def get_num_new_matched_tokens(
        self,
        request_id: str,
        token_hashes: List[int],
        num_engine_matched_tokens: int = 0,
    ) -> Tuple[int, bool]:
        """How many MORE tokens the KVBM can supply beyond the engine's own
        prefix-cache hit. Returns (num_new_tokens, load_is_async) — matching
        the reference's contract (:116)."""
        engine_blocks = num_engine_matched_tokens // self.block_size
        matched = engine_blocks
        while matched < len(token_hashes) and self.tier.contains(
            token_hashes[matched]
        ):
            matched += 1
        slot = _RequestSlot(
            token_hashes=list(token_hashes),
            matched=matched,
            engine_matched=engine_blocks,
        )
        self._slots[request_id] = slot
        new_tokens = (matched - engine_blocks) * self.block_size
        return new_tokens, new_tokens > 0

    def limit_match(self, request_id: str, num_blocks: int) -> None:
        """Engine could only allocate ``num_blocks`` of the promised match
        (pool pressure): shrink the slot so build_connector_meta never
        emits load instructions for unallocated positions."""
        slot = self._slots.get(request_id)
        if slot is not None:
            limited = slot.engine_matched + num_blocks
            if limited < slot.matched:
                # Pool pressure made the KVBM's match promise partially
                # undeliverable — a planner watching truncations knows the
                # engine pool, not the tiers, is the bottleneck.
                self.pool_pressure_truncations += 1
                if self._metrics is not None:
                    self._metrics.pool_pressure_truncations.inc()
            slot.matched = min(slot.matched, limited)

    def forget(self, request_id: str) -> None:
        """Drop a slot without a write-back decision (onboard-only flows —
        request_finished is the full-lifecycle form)."""
        self._slots.pop(request_id, None)

    def update_state_after_alloc(
        self, request_id: str, block_ids: List[int]
    ) -> None:
        """The engine allocated device blocks for the request; remember the
        hash→engine-block pairing for the transfer (:144)."""
        slot = self._slots.get(request_id)
        if slot is None:
            raise KeyError(f"no connector slot for request {request_id!r}")
        slot.block_ids = list(block_ids)

    def build_connector_meta(self) -> bytes:
        """Serialize this scheduling step's transfer instructions (:152).
        Consumed exactly once by bind_connector_metadata on the worker."""
        loads = []
        for rid, slot in self._slots.items():
            if not slot.block_ids or slot.matched <= slot.engine_matched:
                continue
            for i in range(slot.engine_matched, slot.matched):
                if i < len(slot.block_ids):
                    loads.append(
                        (rid, slot.token_hashes[i], slot.block_ids[i])
                    )
            # Mark consumed: later scheduling steps for a long-running
            # request must not re-emit (and re-transfer) the same loads.
            slot.engine_matched = slot.matched
        saves = []
        for rid, pairs in self._pending_saves.items():
            for h, bid in pairs:
                saves.append((rid, h, bid))
        self._pending_saves.clear()
        return msgpack.packb(
            {"loads": loads, "saves": saves}, use_bin_type=True
        )

    def request_finished(
        self, request_id: str, block_hashes_and_ids: List[Tuple[int, int]]
    ) -> bool:
        """Request done: queue write-back of its committed blocks that the
        KVBM doesn't hold yet (:228). Returns True when an async save was
        scheduled (the engine must keep the blocks alive until the worker
        reports the save finished)."""
        self._slots.pop(request_id, None)
        to_save = [
            (h, bid)
            for h, bid in block_hashes_and_ids
            if not self.tier.contains(h)
        ]
        if to_save:
            self._pending_saves[request_id] = to_save
        return bool(to_save)


class KvConnectorWorker:
    """Per-rank half: executes the leader's transfer instructions against
    engine memory via the registered callbacks."""

    def __init__(self, tier: Any, *, metrics: Any = None) -> None:
        self.tier = tier
        self._put: Optional[PutBlockFn] = None
        self._get: Optional[GetBlockFn] = None
        self._meta: Optional[Dict[str, Any]] = None
        self._finished_loads: Set[str] = set()
        self._finished_saves: Set[str] = set()
        self._failed_loads: Dict[str, List[int]] = {}
        # Shared KvbmMetrics (kvbm/manager.py): the external-engine seam
        # reports through the same ALL_KVBM families as the native manager.
        self._metrics = metrics

    def register_kv_caches(self, put_block: PutBlockFn, get_block: GetBlockFn) -> None:
        """The engine's device-memory accessors (ref: register_kv_caches
        :61 — there a dict of torch tensors; here two callbacks so the
        engine owns its TPU cache layout)."""
        self._put = put_block
        self._get = get_block

    def bind_connector_metadata(self, blob: bytes) -> None:
        self._meta = msgpack.unpackb(blob, raw=False, strict_map_key=False)

    def clear_connector_metadata(self) -> None:
        self._meta = None

    def start_load_kv(self) -> int:
        """Onboard every instructed block tier→engine (:148). Returns the
        number of blocks loaded. A block evicted between match and load is
        reported via get_failed_loads() — the engine MUST recompute those
        token positions (the match promise is revoked); such a request is
        never reported load-finished."""
        if self._put is None:
            raise RuntimeError("register_kv_caches must be called first")
        meta = self._meta or {}
        n = 0
        touched: Set[str] = set()
        for rid, block_hash, engine_block_id in meta.get("loads", ()):
            touched.add(rid)
            blk = self.tier.get(block_hash)
            if blk is None:
                logger.warning(
                    "KV block %x vanished before load (request %s): "
                    "engine must recompute", block_hash, rid,
                )
                self._failed_loads.setdefault(rid, []).append(block_hash)
                if self._metrics is not None:
                    self._metrics.failed_loads.inc()
                continue
            # The shared tier may hold quantized wire-form blocks (native
            # engine offload); the external-engine seam hands over dense.
            bk, bv = dense_tier_block(blk)
            self._put(engine_block_id, bk, bv)
            n += 1
            if self._metrics is not None:
                self._metrics.onboard_blocks.inc()
                self._metrics.onboard_bytes.inc(
                    int(bk.nbytes) + int(bv.nbytes)
                )
        for rid in touched:
            if rid not in self._failed_loads:
                self._finished_loads.add(rid)
        return n

    def get_failed_loads(self) -> Dict[str, List[int]]:
        """request id → block hashes whose load failed since the last call.
        The engine must re-prefill those positions instead of trusting the
        leader's earlier match."""
        failed = self._failed_loads
        self._failed_loads = {}
        return failed

    def save_kv_blocks(self) -> int:
        """Offload every instructed block engine→tier (:165). Returns the
        number of blocks saved."""
        if self._get is None:
            raise RuntimeError("register_kv_caches must be called first")
        meta = self._meta or {}
        n = 0
        for rid, block_hash, engine_block_id in meta.get("saves", ()):
            k, v = self._get(engine_block_id)
            ka, va = np.asarray(k), np.asarray(v)
            self.tier.put(block_hash, ka, va)
            n += 1
            self._finished_saves.add(rid)
            if self._metrics is not None:
                self._metrics.offload_blocks.inc()
                self._metrics.offload_bytes.inc(int(ka.nbytes) + int(va.nbytes))
        return n

    def get_finished(self) -> Tuple[Set[str], Set[str]]:
        """(finished_loading, finished_saving) request ids since the last
        call (:187) — the engine uses the save set to release blocks it
        kept alive for write-back."""
        loads, saves = self._finished_loads, self._finished_saves
        self._finished_loads, self._finished_saves = set(), set()
        return loads, saves
