"""Standalone shared KV block store service (the G4 tier's server side).

Reference parity: the remote end of KVBM's G4 tier. Workers point their
TieredKvManager at this endpoint (kvbm/remote.py RemoteTier) to share
offloaded KV across a pool.

Usage:
  python -m dynamo_tpu.kvbm --namespace prod --capacity-blocks 65536
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu import config
from dynamo_tpu.kvbm.remote import KvStoreHandler
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.utils.logging import configure_logging


async def main() -> None:
    parser = argparse.ArgumentParser("dynamo-tpu kvstore (shared KV tier)")
    parser.add_argument("--namespace", default=config.NAMESPACE.get())
    parser.add_argument("--component", default="kvstore")
    parser.add_argument("--endpoint", default="blocks")
    parser.add_argument("--capacity-blocks", type=int, default=65536)
    args = parser.parse_args()

    configure_logging()
    runtime = DistributedRuntime.from_settings()
    handler = KvStoreHandler(capacity_blocks=args.capacity_blocks)
    endpoint = (
        runtime.namespace(args.namespace)
        .component(args.component)
        .endpoint(args.endpoint)
    )
    served = await endpoint.serve_endpoint(handler.generate)
    print(f"kvstore serving {args.namespace}/{args.component}/{args.endpoint}",
          flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await served.shutdown(grace_period=config.GRACE_PERIOD.get())
        await runtime.shutdown(grace_period=config.GRACE_PERIOD.get())


if __name__ == "__main__":
    asyncio.run(main())
