"""Persisted-KV integrity: CRC32 of every array, corruption = counted miss.

Two surfaces persist KV across process lifetimes — the warm-cache
checkpoint (engines/tpu/kv_checkpoint.py, the chrek/CRIU role) and the
KVBM disk tier's per-block npz spills (kvbm/tiers.py G3). Both now stamp a
CRC32 per array at write time and verify at read time: a corrupt or
truncated file becomes a COUNTED miss (the lint-pinned
``dynamo_tpu_kvbm_restore_corruption_total{source}`` counter plus a flight
event at the owning ring), never a crash and never silently-garbage KV
attending into live sequences.

The counter is process-global (one registry, one series per source) so the
checkpoint path — which runs with or without a TieredKvManager — and every
tier instance share it; ``attach_engine`` registers the render on the
system server.
"""

from __future__ import annotations

import zlib
from typing import Dict

import numpy as np

from dynamo_tpu.runtime import metric_names as mn
from dynamo_tpu.runtime.metrics_core import MetricsRegistry

_REGISTRY = MetricsRegistry()
RESTORE_CORRUPTION = _REGISTRY.counter(
    mn.KVBM_RESTORE_CORRUPTION_TOTAL,
    "Persisted KV (checkpoint arrays, disk-tier npz spills) whose CRC32 "
    "failed on restore — counted as a miss, never installed",
    ["source"],
)


def array_crc32(a: np.ndarray) -> int:
    """CRC32 over an array's raw bytes (dtype-agnostic: bf16 and friends
    hash through a uint8 view of their own buffer)."""
    arr = np.ascontiguousarray(a)
    # No .tobytes(): the uint8 view feeds zlib through the buffer
    # protocol in place — a copy would double peak RSS for the multi-GB
    # checkpoint arrays at exactly the moment (drain/shutdown) memory
    # pressure is highest.
    return zlib.crc32(arr.view(np.uint8).reshape(-1)) & 0xFFFFFFFF


def note_corruption(source: str, n: int = 1) -> None:
    RESTORE_CORRUPTION.inc(n, source=source)


def corruption_counts() -> Dict[str, int]:
    """source → corruption count (bench/tests; scrape-free)."""
    return {
        str(key[0]): int(value)
        for key, value in RESTORE_CORRUPTION._values.items()
    }


def render_integrity_metrics(openmetrics: bool = False) -> str:
    return _REGISTRY.render(openmetrics=openmetrics)
