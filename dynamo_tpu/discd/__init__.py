"""Control-plane services entrypoint (discd discovery + ZMQ event broker).

The single-process stand-in for the reference's etcd + nats-server pair
(tests/conftest.py in the reference boots both per session — SURVEY §4).

    python -m dynamo_tpu.discd --port 6180 --xsub 6181 --xpub 6182
"""
