from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.runtime.discovery.discd import DiscdServer
from dynamo_tpu.runtime.events.zmq_plane import EventBroker
from dynamo_tpu.utils.logging import configure_logging


async def main() -> None:
    parser = argparse.ArgumentParser("dynamo-tpu control plane services")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=6180, help="discd KV port")
    parser.add_argument("--xsub", type=int, default=6181, help="event broker XSUB port")
    parser.add_argument("--xpub", type=int, default=6182, help="event broker XPUB port")
    parser.add_argument("--no-events", action="store_true", help="discovery only")
    parser.add_argument("--events-log", default=None,
                        help="durable event log path (JetStream role): "
                        "persists every event with a sequence number and "
                        "serves replay on --replay-port")
    parser.add_argument("--replay-port", type=int, default=6183)
    parser.add_argument("--snapshot", default=None,
                        help="keyspace+lease snapshot file: restored at "
                        "startup, written on change — a crashed discd comes "
                        "back with the same keys and live lease ids (the "
                        "etcd-durability role, single-node form)")
    args = parser.parse_args()

    configure_logging()
    server = DiscdServer(args.host, args.port, snapshot_path=args.snapshot)
    await server.start()
    broker = None
    if not args.no_events:
        broker = EventBroker(
            args.host, args.xsub, args.xpub,
            log_path=args.events_log,
            replay_port=args.replay_port if args.events_log else 0,
        )
        broker.start()
    print(
        f"discd ready: discovery {args.host}:{server.bound_port}"
        + (f", events {broker.address}" if broker else "")
        + (f", replay :{broker.replay_port}" if broker and broker.log_path else ""),
        flush=True,
    )
    try:
        await asyncio.Event().wait()
    finally:
        if broker:
            await broker.close()
        await server.stop()


if __name__ == "__main__":
    asyncio.run(main())
