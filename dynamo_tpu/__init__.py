"""dynamo_tpu — a TPU-native distributed LLM inference serving framework.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of NVIDIA Dynamo
(reference: /root/reference): OpenAI-compatible frontend, KV-cache-aware
routing, disaggregated prefill/decode, multi-tier KV block management,
an SLA-driven autoscaling planner, and a native JAX inference engine with
paged attention and continuous batching.

Layering (mirrors reference SURVEY.md §1, re-designed TPU-first):

    runtime/    distributed runtime: components, endpoints, request plane,
                discovery plane, event plane        (ref: lib/runtime)
    tokens/     token block hashing + radix trees   (ref: lib/tokens, lib/kv-router)
    llm/        protocols, preprocessor, detokenizer, model cards,
                migration                           (ref: lib/llm)
    http/       OpenAI-compatible HTTP frontend     (ref: lib/llm/src/http)
    router/     KV-aware routing                    (ref: lib/llm/src/kv_router)
    engines/    mock engine + native JAX engine     (ref: lib/mocker + external vLLM)
    models/     JAX model definitions (llama, qwen)
    ops/        pallas kernels (paged attention, block copy)
    parallel/   mesh/sharding policies, ring attention
    kvbm/       multi-tier KV block manager         (ref: lib/llm/src/block_manager)
    planner/    SLA autoscaler                      (ref: components/planner)
    parsers/    tool-call & reasoning parsers       (ref: lib/parsers)
"""

from dynamo_tpu._version import __version__

__all__ = ["__version__"]
