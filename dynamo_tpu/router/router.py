"""KvRouter: ties the indexer + scheduler to the event plane and the Client.

Reference parity: lib/llm/src/kv_router.rs (KvRouter :320, find_best_match
:501, AsyncEngine impl :720) and subscriber.rs (event plane → indexer pump).

Usage (frontend side):

    client = await endpoint.client(RouterMode.KV)
    router = KvRouter(runtime, namespace, component, block_size=16)
    await router.start()
    router.attach(client)          # installs the kv picker
    ... client.generate(preprocessed_request) now routes KV-aware ...

Workers run KvEventPublisher/LoadPublisher (publisher.py) so the router sees
their cache contents and load.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, Optional, Sequence, Tuple

from dynamo_tpu.router.indexer import KvIndexer
from dynamo_tpu.router.protocols import (
    LoadSnapshot,
    RouterEvent,
    WorkerKey,
    kv_events_topic,
    kv_sync_topic,
    load_topic,
)
from dynamo_tpu.router.scheduler import (
    KvRouterConfig,
    KvScheduler,
    TransferContext,
)
from dynamo_tpu.runtime import lifecycle
from dynamo_tpu.runtime.tasks import reap_task
from dynamo_tpu.tokens.blocks import adapter_salt, compute_block_hashes
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _worker_label(worker: Any) -> str:
    if isinstance(worker, tuple):
        return ":".join(str(p) for p in worker)
    return str(worker)


class RouterMetrics:
    """Canonical router metric families (runtime/metric_names.py ALL_ROUTER)
    on a private registry; ``render`` plugs into the system status server's
    ``register_metrics`` seam. Per-worker load gauges sample the scheduler's
    cost-model state at scrape time (on_render), so the exposed load is the
    same signal ``select_worker`` is acting on."""

    def __init__(self, scheduler: KvScheduler) -> None:
        from dynamo_tpu.runtime import metric_names as mn
        from dynamo_tpu.runtime.metrics_core import COUNT_BUCKETS, MetricsRegistry

        self._scheduler = scheduler
        self.registry = MetricsRegistry()
        self.decisions = self.registry.counter(
            mn.ROUTER_DECISIONS_TOTAL,
            "Routing decisions by reason "
            "(kv_overlap|load_only|pinned|fallback|no_worker)",
            ["reason"],
        )
        self.overlap_blocks = self.registry.histogram(
            mn.ROUTER_OVERLAP_BLOCKS,
            "Predicted prefix-overlap blocks per routed request",
            buckets=COUNT_BUCKETS,
        )
        self.worker_load = self.registry.gauge(
            mn.ROUTER_WORKER_LOAD_BLOCKS,
            "Predicted active decode blocks per worker (reported + in-flight)",
            ["worker"],
        )
        self.worker_kv_usage = self.registry.gauge(
            mn.ROUTER_WORKER_KV_USAGE,
            "Last reported KV-cache usage fraction per worker",
            ["worker"],
        )
        self.kv_events = self.registry.counter(
            mn.ROUTER_KV_EVENTS_TOTAL,
            "KV cache events applied to the router index",
        )
        self.link_bandwidth = self.registry.gauge(
            mn.ROUTER_LINK_BANDWIDTH,
            "Per-(src, dst) transfer-bandwidth EWMA the link-cost term is "
            "acting on (measured pairs only; unmeasured quote the seed)",
            ["src", "dst"],
        )
        self._gauge_workers: set = set()
        self._gauge_links: set = set()
        self.registry.on_render(self._sample_workers)

    def _sample_workers(self) -> None:
        view = self._scheduler.load_view()
        labels = set()
        for worker, (load_blocks, kv_usage) in view.items():
            label = _worker_label(worker)
            labels.add(label)
            self.worker_load.set(load_blocks, worker=label)
            self.worker_kv_usage.set(kv_usage, worker=label)
        for gone in self._gauge_workers - labels:
            self.worker_load.remove(worker=gone)
            self.worker_kv_usage.remove(worker=gone)
        self._gauge_workers = labels
        links = set()
        for (src, dst), bw in self._scheduler.link_costs.pairs().items():
            pair = (str(src), _worker_label(dst))
            links.add(pair)
            self.link_bandwidth.set(bw, src=pair[0], dst=pair[1])
        for src, dst in self._gauge_links - links:
            self.link_bandwidth.remove(src=src, dst=dst)
        self._gauge_links = links

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics=openmetrics)


class KvRouter:
    def __init__(
        self,
        runtime: Any,
        namespace: str,
        component: str,
        *,
        block_size: int = 16,
        config: Optional[KvRouterConfig] = None,
        use_kv_events: bool = True,
        prune_config: Optional[Any] = None,
    ) -> None:
        self._runtime = runtime
        self.namespace = namespace
        self.component = component
        self.block_size = block_size
        self.use_kv_events = use_kv_events
        if use_kv_events:
            self.indexer = KvIndexer(block_size)
        else:
            # Approximate mode (ref: kv_router.rs:359): no event feed — the
            # router's own routing decisions seed the index, TTL-pruned.
            from dynamo_tpu.router.approx import ApproxKvIndexer

            self.indexer = ApproxKvIndexer(block_size, prune_config)
        self.scheduler = KvScheduler(config)
        # drop_worker is the single purge path: the scheduler fans the
        # radix-index removal out through this callback, so a crash-plane
        # drop (or a rejoin under a fresh incarnation) reconciles charges,
        # link pairs, breaker faults AND radix entries in one call. The
        # KV-reuse popularity sketch rides the same fan-out (zero-residue
        # audit: a departed worker's hits must not keep a prefix hot).
        self.scheduler.add_drop_callback(self.indexer.remove_worker)
        from dynamo_tpu.runtime.kv_reuse_observe import global_plane

        self.kv_plane = global_plane()
        self.scheduler.add_drop_callback(self.kv_plane.drop_worker)
        self.metrics = RouterMetrics(self.scheduler)
        self._tasks: list = []
        self._subs: list = []
        # request identity -> stack of (worker, charged blocks, report gen);
        # one entry popped per stream end. A stack (not a single slot) keeps
        # the accounting balanced when a caller passes the SAME request
        # object to concurrent generate() calls (hedging/retries): pairing
        # may momentarily cross over, but every charge is released exactly
        # once.
        self._inflight: Dict[int, list] = {}
        # Notified after each applied KV event so tests (and operators) can
        # await "indexer has seen N events" instead of sleeping.
        self._events_cond: Optional[asyncio.Condition] = None
        # Re-sync request throttling: worker → loop-monotonic of last request.
        self._sync_requested: Dict[Optional[WorkerKey], float] = {}
        self._sync_cooldown_s = 2.0

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        plane = self._runtime.event_plane
        load_sub = plane.subscribe(load_topic(self.namespace, self.component))
        self._subs = [load_sub]
        loop = asyncio.get_running_loop()
        self._tasks = [
            loop.create_task(self._pump_load(load_sub), name="kv-router-load"),
        ]
        if self.use_kv_events:
            kv_sub = plane.subscribe(
                kv_events_topic(self.namespace, self.component)
            )
            self._subs.append(kv_sub)
            self._tasks.append(
                loop.create_task(self._pump_kv(kv_sub), name="kv-router-events")
            )
            # A (re)started router has an empty index: broadcast a snapshot
            # request so publishers replay their committed state immediately
            # (JetStream re-sync role) instead of the index warming over TTLs.
            await self._request_sync(None)

    async def stop(self) -> None:
        for sub in self._subs:
            await sub.aclose()
        for task in self._tasks:
            task.cancel()
        for task in self._tasks:
            await reap_task(task, "router subscription pump", logger)
        self._tasks = []
        self._subs = []

    async def _pump_kv(self, sub) -> None:
        async for _topic, payload in sub:
            try:
                event = RouterEvent.from_dict(payload)
                if hasattr(self.indexer, "has_gap") and self.indexer.has_gap(event):
                    await self._request_sync(event.worker)
                self.indexer.apply(event)
            except Exception:
                logger.exception("bad KV event payload")
            else:
                self.metrics.kv_events.inc()
            if self._events_cond is not None:
                async with self._events_cond:
                    self._events_cond.notify_all()

    async def _request_sync(self, worker: Optional[WorkerKey]) -> None:
        """Ask publishers (one worker, or all with None) for a snapshot."""
        now = asyncio.get_running_loop().time()
        last = self._sync_requested.get(worker)
        if last is not None and now - last < self._sync_cooldown_s:
            return
        self._sync_requested[worker] = now
        try:
            await self._runtime.event_plane.publish(
                kv_sync_topic(self.namespace, self.component),
                {"worker_id": worker[0] if worker else None},
            )
        except Exception:
            logger.exception("failed to publish kv sync request")

    async def wait_for_events(self, count: int, timeout: float = 5.0) -> None:
        """Block until at least ``count`` KV events have been applied to the
        indexer (deterministic alternative to sleeping in tests)."""
        if self._events_cond is None:
            self._events_cond = asyncio.Condition()
        async with self._events_cond:
            await asyncio.wait_for(
                self._events_cond.wait_for(
                    lambda: self.indexer.events_applied >= count
                ),
                timeout,
            )

    async def _pump_load(self, sub) -> None:
        async for _topic, payload in sub:
            try:
                self.scheduler.update_load(LoadSnapshot.from_dict(payload))
            except Exception:
                logger.exception("bad load payload")

    def drop_worker(self, worker: WorkerKey) -> None:
        """Crash-plane reconciliation: one call releases the scheduler's
        in-flight charges, link pairs/faults, and (via the registered drop
        callback) the radix index's entries for this worker."""
        self.scheduler.drop_worker(worker)

    def remove_worker(self, worker: WorkerKey) -> None:
        self.drop_worker(worker)

    def register_metrics(self, server: Any) -> None:
        """Expose this router's metric families on a SystemStatusServer."""
        server.register_metrics(self.metrics.render)

    # -- selection ---------------------------------------------------------

    def find_best_match(
        self,
        token_ids: Sequence[int],
        candidates: Optional[Sequence[WorkerKey]] = None,
        *,
        lora_name: Optional[str] = None,
        transfer: Optional[Any] = None,  # scheduler.TransferContext
    ) -> Tuple[Optional[WorkerKey], int]:
        """Returns (worker, overlap_blocks) — ref: kv_router.rs:501.
        ``lora_name`` salts the hash space the same way the engine does
        (tokens/blocks.py adapter_salt) so overlap is only predicted against
        same-adapter blocks. ``transfer`` prices each candidate's
        overlap-miss pull over the (src, candidate) link — NetKV-style
        network-aware decode placement."""
        hashes = compute_block_hashes(
            token_ids, self.block_size, salt=adapter_salt(lora_name)
        )
        overlaps = self.indexer.find_matches(hashes)
        request_blocks = max(len(hashes), 1)
        worker = self.scheduler.select_worker(
            request_blocks, overlaps, candidates, transfer=transfer
        )
        overlap = overlaps.scores.get(worker, 0) if worker is not None else 0
        if worker is None:
            self.metrics.decisions.inc(reason="no_worker")
        else:
            self.metrics.decisions.inc(
                reason="kv_overlap" if overlap > 0 else "load_only"
            )
            self.metrics.overlap_blocks.observe(overlap)
            if overlap > 0:
                # Popularity feed: the matched prefix is keyed by its
                # block-hash-chain anchor (deepest matched block) and
                # attributed to the chosen worker so drop_worker can purge
                # it. Popularity only — the engine-side hit accounts the
                # ROI counters (a router feed too would double-count).
                self.kv_plane.note_router_match(
                    hashes[overlap - 1],
                    tokens=overlap * self.block_size,
                    worker=worker,
                )
        if not self.use_kv_events and worker is not None:
            # Approximate mode: assume the chosen worker will cache these
            # blocks (ref: kv_router.rs:937 routing-decision recording).
            self.indexer.process_routing_decision(hashes, worker)
        return worker, overlap

    def release(
        self, worker: WorkerKey, charged_blocks: int, report_gen: Optional[int] = None
    ) -> None:
        """Release the in-flight prediction for a finished stream."""
        self.scheduler.complete_request(worker, charged_blocks, report_gen)

    def attach(self, client: Any) -> None:
        """Install this router as the Client's KV-mode instance picker."""

        async def _select(request: Any, instances: Dict[int, Any], sp) -> Optional[int]:
            # Gateway pin (the EPP's x-dynamo-worker header hint,
            # gateway/epp.py): the upstream picker already ran the KV
            # algorithm and charged its own bookkeeping — honor the pin
            # when that instance is still live.
            pin = None
            if isinstance(request, dict):
                pin = request.get("_pinned_worker")
                if pin is None:
                    pin = (request.get("extra") or {}).get("_pinned_worker")
            else:
                # PreprocessedRequest object (the primary HTTP path passes
                # the dataclass itself).
                pin = (getattr(request, "extra", None) or {}).get("_pinned_worker")
            if pin is not None and int(pin) in instances:
                self.metrics.decisions.inc(reason="pinned")
                lifecycle.record(
                    _request_id_of(request), "routed",
                    worker=int(pin), reason="pinned",
                )
                if sp is not None:
                    sp.attributes.update({"worker": int(pin), "pinned": True})
                return int(pin)
            token_ids = _token_ids_of(request)
            if token_ids is None:
                self.metrics.decisions.inc(reason="fallback")
                return None  # not a preprocessed request; fall back
            candidates = [(iid, 0) for iid in instances]
            lora = (
                request.get("lora_name")
                if isinstance(request, dict)
                else getattr(request, "lora_name", None)
            )
            worker, overlap = self.find_best_match(
                token_ids, candidates, lora_name=lora,
                transfer=_transfer_context_of(request),
            )
            if sp is not None:
                # Decision record: how many candidates were actually
                # scored, the overlap/link terms — the "why this worker"
                # answer inside the request's own trace.
                sp.attributes.update({
                    k: v
                    for k, v in self.scheduler.last_decision.items()
                    if v is not None
                })
            if worker is None:
                return None
            n_blocks = max(len(token_ids) // self.block_size, 1)
            self._inflight.setdefault(id(request), []).append(
                (
                    worker,
                    max(n_blocks - overlap, 0),
                    self.scheduler.report_generation(worker),
                )
            )
            # The overlap prediction rides to the worker as its
            # speculative-onboard hint (engines/tpu/engine.py
            # _maybe_prefetch): positive means "start the tier walk at
            # enqueue", zero means the engine never speculates — cold
            # traffic stays prefetch-free by construction.
            if isinstance(request, dict):
                request["estimated_prefix_hit_blocks"] = overlap
            else:
                try:
                    request.estimated_prefix_hit_blocks = overlap
                except AttributeError:
                    pass
            lifecycle.record(
                _request_id_of(request), "routed",
                worker=worker[0], overlap_blocks=overlap,
                prefetch_hint=overlap > 0,
            )
            return worker[0]

        async def picker(
            request: Any, instances: Dict[int, Any], context: Any = None,
        ) -> Optional[int]:
            if context is None:
                return await _select(request, instances, None)
            # Trajectory span: the routing decision is a hop that can
            # dominate tail latency (lock contention, huge fleets) and
            # its attributes answer "why THIS worker" post-hoc.
            from dynamo_tpu.utils.tracing import span

            with span("router.select_worker", context) as sp:
                return await _select(request, instances, sp)

        def on_done(instance_id: Optional[int], request: Any) -> None:
            entries = self._inflight.get(id(request))
            if entries:
                self.release(*entries.pop())
                if not entries:
                    del self._inflight[id(request)]

        client.set_kv_picker(picker)
        client.set_stream_done_callback(on_done)


def _transfer_context_of(request: Any) -> Optional[TransferContext]:
    """Disagg decode placement: a request carrying bootstrap metadata names
    the prefill worker its KV must be pulled FROM and what one block costs
    on the wire (disagg/handlers.py PrefillHandler). No metadata → no link
    term (aggregated routing is unchanged)."""
    dp = (
        request.get("disaggregated_params")
        if isinstance(request, dict)
        else getattr(request, "disaggregated_params", None)
    )
    if dp is None:
        return None
    if isinstance(dp, dict):
        worker_id = dp.get("worker_id")
        info = dp.get("kv_transfer") or {}
    else:
        worker_id = getattr(dp, "worker_id", None)
        info = getattr(dp, "kv_transfer", None) or {}
    block_bytes = info.get("block_bytes")
    if worker_id is None or not block_bytes:
        return None
    return TransferContext(src=int(worker_id), bytes_per_block=int(block_bytes))


def _token_ids_of(request: Any) -> Optional[Sequence[int]]:
    if isinstance(request, dict):
        ids = request.get("token_ids")
        return ids if isinstance(ids, (list, tuple)) else None
    return getattr(request, "token_ids", None)


def _request_id_of(request: Any) -> Optional[str]:
    if isinstance(request, dict):
        rid = request.get("request_id")
        return rid if isinstance(rid, str) else None
    return getattr(request, "request_id", None)
