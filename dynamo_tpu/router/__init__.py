"""KV-aware routing layer.

Reference parity: lib/llm/src/kv_router.rs + lib/kv-router (SURVEY §2.1):
radix-tree indexer fed by worker KV events, cost-model scheduler with
softmax-temperature worker sampling, publishers bridging engine events onto
the event plane, and a KvRouter that plugs into the runtime Client as its
KV-mode instance picker.
"""

from dynamo_tpu.router.protocols import (
    KV_EVENTS_TOPIC,
    LOAD_TOPIC,
    LoadSnapshot,
    RouterEvent,
    kv_events_topic,
    load_topic,
)
from dynamo_tpu.router.indexer import KvIndexer
from dynamo_tpu.router.scheduler import (
    KvRouterConfig,
    KvScheduler,
    LinkCostModel,
    TransferContext,
    WorkerState,
)
from dynamo_tpu.router.publisher import KvEventPublisher, LoadPublisher
from dynamo_tpu.router.router import KvRouter

__all__ = [
    "KV_EVENTS_TOPIC",
    "LOAD_TOPIC",
    "LoadSnapshot",
    "RouterEvent",
    "kv_events_topic",
    "load_topic",
    "KvIndexer",
    "KvRouterConfig",
    "KvScheduler",
    "LinkCostModel",
    "TransferContext",
    "WorkerState",
    "KvEventPublisher",
    "LoadPublisher",
    "KvRouter",
]
