"""Approximate KV indexer: routing-decision-driven, TTL-pruned.

Reference parity: lib/kv-router/src/approx.rs (PruneManager: lazily-staled
expiry heap, size-based pruning deepest-first) and kv_router.rs:359,937
(``use_kv_events=false`` mode — the router records its OWN routing
decisions as if the chosen worker had cached those blocks, since no event
feed exists to tell it the truth).

When to use: engines that don't publish KV events (external engines wired
through the KVBM connector, mockers without an event plane, cross-cluster
routing where the event fan-in is too chatty). The index is optimistic —
TTL expiry ages out blocks the worker has probably evicted, and size
pruning bounds memory. Deeper blocks (larger sequence position) expire
first on prune: the root of a prefix chain is the most shareable part.
"""

from __future__ import annotations

import heapq
import time
from dataclasses import dataclass
from typing import Dict, Hashable, List, Optional, Sequence, Tuple

from dynamo_tpu.router.protocols import WorkerKey
from dynamo_tpu.tokens.radix import OverlapScores
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class PruneConfig:
    """(ref: approx.rs PruneConfig — same defaults)"""

    ttl: float = 120.0  # seconds a recorded block stays credible
    max_tree_size: int = 1 << 20  # blocks before size pruning kicks in
    prune_target_ratio: float = 0.8  # prune down to this fraction of max


class PruneManager:
    """Expiry timers addressable by key, with lazy heap invalidation.

    ``timers`` is the source of truth; the heap may hold stale entries
    (re-inserted keys) which are skipped when popped. Heap order is
    (expiry, depth) so ties expire deepest-first — matching the reference's
    BlockEntry ordering by seq_position for pruning.
    """

    def __init__(self, config: Optional[PruneConfig] = None, *, clock=None) -> None:
        self.config = config or PruneConfig()
        self._clock = clock or time.monotonic
        self._timers: Dict[Hashable, float] = {}
        self._depth: Dict[Hashable, int] = {}
        self._heap: List[Tuple[float, int, Hashable]] = []

    def __len__(self) -> int:
        return len(self._timers)

    def insert(self, keys: Sequence[Hashable], depths: Sequence[int]) -> None:
        """Start (or refresh) the TTL for each key."""
        expiry = self._clock() + self.config.ttl
        for key, depth in zip(keys, depths):
            self._timers[key] = expiry
            self._depth[key] = depth
            heapq.heappush(self._heap, (expiry, depth, key))

    def pop_expired(self) -> List[Hashable]:
        """Remove and return every key whose TTL has elapsed."""
        now = self._clock()
        out: List[Hashable] = []
        while self._heap and self._heap[0][0] <= now:
            expiry, _depth, key = heapq.heappop(self._heap)
            if self._timers.get(key) != expiry:
                continue  # stale heap entry; the key was refreshed
            del self._timers[key]
            self._depth.pop(key, None)
            out.append(key)
        return out

    def next_expiry(self) -> Optional[float]:
        while self._heap:
            expiry, _d, key = self._heap[0]
            if self._timers.get(key) == expiry:
                return expiry
            heapq.heappop(self._heap)
        return None

    def prune(self, current_size: int) -> List[Hashable]:
        """If over max_tree_size, evict earliest-expiring (deepest on ties)
        keys down to target size. Returns the evicted keys."""
        cfg = self.config
        if current_size <= cfg.max_tree_size:
            return []
        target = int(cfg.max_tree_size * cfg.prune_target_ratio)
        out: List[Hashable] = []
        # Max-heap by (expiry, depth) would evict last-to-expire first; the
        # reference evicts by soonest expiry (oldest knowledge) and deepest
        # position — exactly the heap order we already maintain.
        while self._heap and len(self._timers) > target:
            expiry, _d, key = heapq.heappop(self._heap)
            if self._timers.get(key) != expiry:
                continue
            del self._timers[key]
            self._depth.pop(key, None)
            out.append(key)
        return out


@dataclass
class ApproxStats:
    decisions: int = 0
    expired: int = 0
    pruned: int = 0


class ApproxKvIndexer:
    """KvIndexer-compatible surface fed by routing decisions, not events.

    ``process_routing_decision(hashes, worker)`` optimistically stores the
    full block chain for the chosen worker; ``tick()`` (called inline from
    the router on each decision, and cheap when nothing expired) ages out
    stale knowledge. (ref: kv_router.rs process_routing_decision_for_request)
    """

    def __init__(
        self,
        block_size: int,
        config: Optional[PruneConfig] = None,
        *,
        clock=None,
    ) -> None:
        self.block_size = block_size
        from dynamo_tpu.native.radix import make_radix_tree

        self.tree = make_radix_tree()
        self.prune_manager = PruneManager(config, clock=clock)
        self.stats = ApproxStats()
        self._events_applied = 0  # surface parity with KvIndexer

    @property
    def events_applied(self) -> int:
        return self._events_applied

    # -- decisions ---------------------------------------------------------

    def process_routing_decision(
        self, block_hashes: Sequence[int], worker: WorkerKey
    ) -> None:
        if not block_hashes:
            return
        self.tree.store(worker, list(block_hashes), None)
        keys = [(worker, h) for h in block_hashes]
        self.prune_manager.insert(keys, list(range(len(keys))))
        self.stats.decisions += 1
        self.tick()

    def tick(self) -> None:
        """Apply TTL expiry and size pruning to the tree."""
        expired = self.prune_manager.pop_expired()
        for worker, h in expired:
            self.tree.remove(worker, [h])
        self.stats.expired += len(expired)
        pruned = self.prune_manager.prune(self.tree.num_blocks)
        for worker, h in pruned:
            self.tree.remove(worker, [h])
        self.stats.pruned += len(pruned)

    # -- KvIndexer surface -------------------------------------------------

    def apply(self, event) -> None:  # pragma: no cover - defensive
        logger.warning(
            "ApproxKvIndexer ignores KV events (use_kv_events=False); "
            "got %r", getattr(event, "kind", event),
        )

    def remove_worker(self, worker: WorkerKey) -> None:
        self.tree.remove_worker(worker)

    def find_matches(self, block_hashes: Sequence[int]) -> OverlapScores:
        self.tick()
        return self.tree.find_matches(block_hashes)

    def worker_block_count(self, worker: WorkerKey) -> int:
        return self.tree.worker_block_count(worker)
