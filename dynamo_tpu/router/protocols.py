"""Router wire protocols: KV events and load snapshots.

Reference parity: lib/kv-router/src/protocols.rs (RouterEvent, WorkerId,
DpRank, OverlapScores) and the load metrics the scheduler consumes
(kv_router/scheduler.rs ProcessedEndpoints). Everything is a plain dict on
the wire (msgpack/json-able).
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional, Tuple

WorkerKey = Tuple[int, int]  # (worker_id, dp_rank)

KV_EVENTS_TOPIC = "kv_events"
LOAD_TOPIC = "load"
KV_SYNC_TOPIC = "kv_sync"


def kv_events_topic(namespace: str, component: str) -> str:
    return f"{namespace}.{component}.{KV_EVENTS_TOPIC}"


def load_topic(namespace: str, component: str) -> str:
    return f"{namespace}.{component}.{LOAD_TOPIC}"


def kv_sync_topic(namespace: str, component: str) -> str:
    """Snapshot-request channel: a (re)joining router asks publishers for a
    full radix snapshot instead of waiting for TTL churn (the JetStream
    re-sync role, ref: lib/llm/src/kv_router/subscriber.rs:266)."""
    return f"{namespace}.{component}.{KV_SYNC_TOPIC}"


@dataclass
class RouterEvent:
    """One KV-cache mutation at a worker (ref: protocols.rs RouterEvent).

    ``kind="snapshot"`` carries the publisher's full committed-block set:
    ``block_hashes[i]`` pairs with ``parent_hashes[i]`` (None = root), listed
    parents-before-children so an indexer can rebuild its radix by replay.
    """

    worker_id: int
    kind: str  # "stored" | "removed" | "cleared" | "snapshot"
    block_hashes: List[int] = field(default_factory=list)
    parent_hash: Optional[int] = None
    dp_rank: int = 0
    event_id: int = 0  # per-worker monotonic; gaps trigger a sync request
    parent_hashes: Optional[List[Optional[int]]] = None  # snapshot only

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RouterEvent":
        return cls(**d)

    @property
    def worker(self) -> WorkerKey:
        return (self.worker_id, self.dp_rank)


@dataclass
class LoadSnapshot:
    """Periodic worker load report (ref: ForwardPassMetrics / load publishing
    in kv_router/publisher.rs and worker_monitor.rs)."""

    worker_id: int
    dp_rank: int = 0
    active_seqs: int = 0
    waiting: int = 0
    active_blocks: int = 0
    total_blocks: int = 0
    generated_tokens: int = 0  # cumulative, for throughput estimation
    # Engine admission-queue depth (waiting + backpressure-held). The
    # scheduler charges it as extra load so a deep queue deflects new
    # placements BEFORE the worker's KV usage shows the pain.
    queue_depth: int = 0
    # The worker's admission refusal threshold (engine
    # admit_kv_high_watermark): at/above this KV usage the worker is
    # SATURATED — it will hold new admissions rather than preempt — so
    # the router soft-skips it the way busy gating does (< 1.0 enables).
    kv_high_watermark: float = 1.0
    # src prefill worker id → EWMA observed KV-pull bandwidth (bytes/s)
    # measured at THIS worker's transfer path (disagg/handlers.py). Feeds
    # the router's per-(src, dst) link-cost model.
    link_bandwidth: Optional[Dict[int, float]] = None
    # src prefill worker ids whose pull circuit breaker at THIS worker is
    # open — the router prices those (src, this worker) pairs out of
    # disagg decode placement until the breaker's half-open window.
    link_faults: Optional[List[int]] = None
    # Live-handoff drain (runtime/drain.py): True while the worker is
    # draining — it refuses new work with a typed migratable error, so the
    # scheduler must stop placing anything here immediately.
    draining: bool = False
    # Incarnation fencing (runtime/liveness.py): the publishing PROCESS's
    # monotonically fresh incarnation stamp. 0 = an unstamped (pre-crash-
    # plane) publisher; consumers fence only stamped reports, so mixed
    # fleets interoperate.
    incarnation: int = 0
    # Tick-budgeter advertisement (engines/tpu/tick_budget.py): the
    # worker's effective per-tick prefill token budget. 0 = unbudgeted
    # (budgeter off or a pre-budgeter publisher) — the scheduler treats
    # that as unconstrained, so mixed fleets interoperate.
    prefill_budget_tokens: int = 0
    # Budgeter state (BUDGET_STATE_*): 0 off, 1 throughput (at ceiling),
    # 2 adaptive, 3 floor. FLOOR/ADAPTIVE mean the worker is ITL-
    # constrained and prefill-heavy placements should deflect elsewhere.
    budget_state: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "LoadSnapshot":
        snap = cls(**{k: v for k, v in d.items() if k in cls.__dataclass_fields__})
        if snap.link_bandwidth:
            # JSON planes stringify int map keys; normalize on ingest.
            snap.link_bandwidth = {
                int(k): float(v) for k, v in snap.link_bandwidth.items()
            }
        if snap.link_faults:
            snap.link_faults = [int(s) for s in snap.link_faults]
        return snap

    @property
    def worker(self) -> WorkerKey:
        return (self.worker_id, self.dp_rank)

    @property
    def kv_usage(self) -> float:
        return self.active_blocks / self.total_blocks if self.total_blocks else 0.0
