"""Worker-side publishers: KV events and load snapshots onto the event plane.

Reference parity: lib/llm/src/kv_router/publisher.rs (KvEventPublisher :112 —
engine events → event plane) and the load/stat publishing the scheduler
consumes. Engines call a synchronous callback per KV event; the publisher
queues and ships them from an asyncio task (events survive bursts; order is
preserved per worker).
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, Optional

from dynamo_tpu.engines.mock.kv_manager import KvEvent
from dynamo_tpu.router.protocols import (
    LoadSnapshot,
    RouterEvent,
    kv_events_topic,
    kv_sync_topic,
    load_topic,
)
from dynamo_tpu.runtime.liveness import process_incarnation
from dynamo_tpu.runtime.tasks import Backoff, reap_task
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class KvEventPublisher:
    """Bridge engine KV events → event plane topic.

    With ``snapshot_fn`` set (a callable returning the engine's current
    [(block_hash, parent_hash)] committed set), the publisher also answers
    sync requests on the kv_sync topic with a full ``kind="snapshot"`` event
    — the JetStream re-sync role (ref: lib/llm/src/kv_router/subscriber.rs:266)
    so a restarted router rebuilds its index immediately instead of waiting
    for TTL churn. Snapshots ride the same queue as live events, preserving
    the per-worker event order the indexer relies on.
    """

    def __init__(
        self,
        event_plane: Any,
        namespace: str,
        component: str,
        worker_id: int,
        *,
        dp_rank: int = 0,
        snapshot_fn: Optional[Callable[[], list]] = None,
    ) -> None:
        self._plane = event_plane
        self._topic = kv_events_topic(namespace, component)
        self._sync_topic = kv_sync_topic(namespace, component)
        self.worker_id = worker_id
        self.dp_rank = dp_rank
        self._snapshot_fn = snapshot_fn
        self._queue: "asyncio.Queue[Optional[RouterEvent]]" = asyncio.Queue()
        self._event_id = 0
        self._task: Optional[asyncio.Task] = None
        self._sync_task: Optional[asyncio.Task] = None

    def on_kv_event(self, event: KvEvent) -> None:
        """Engine callback (synchronous, loop thread)."""
        self._event_id += 1
        self._queue.put_nowait(
            RouterEvent(
                worker_id=self.worker_id,
                dp_rank=self.dp_rank,
                kind=event.kind,
                block_hashes=list(event.block_hashes),
                parent_hash=event.parent_hash,
                event_id=self._event_id,
            )
        )
        self._ensure_task()

    def set_snapshot_fn(self, fn: Callable[[], list]) -> None:
        """Late-bind the snapshot source (the engine is usually constructed
        after the publisher, taking on_kv_event as a callback) and start
        answering sync requests."""
        self._snapshot_fn = fn
        self.start_sync_responder()

    def enqueue_snapshot(self) -> None:
        """Queue a full-state snapshot event (ordered with live events)."""
        if self._snapshot_fn is None:
            return
        blocks = self._snapshot_fn()
        self._event_id += 1
        self._queue.put_nowait(
            RouterEvent(
                worker_id=self.worker_id,
                dp_rank=self.dp_rank,
                kind="snapshot",
                block_hashes=[h for h, _ in blocks],
                parent_hashes=[p for _, p in blocks],
                event_id=self._event_id,
            )
        )
        self._ensure_task()

    def start_sync_responder(self) -> None:
        """Subscribe to the sync topic and answer requests with snapshots."""
        if self._snapshot_fn is None or self._sync_task is not None:
            return
        self._sync_task = asyncio.get_running_loop().create_task(
            self._sync_pump(), name=f"kv-sync:{self.worker_id:#x}"
        )

    async def _sync_pump(self) -> None:
        sub = None
        try:
            sub = self._plane.subscribe(self._sync_topic)
            async for _topic, req in sub:
                target = (req or {}).get("worker_id")
                if target is not None and target != self.worker_id:
                    continue
                self.enqueue_snapshot()
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("kv sync responder died")
        finally:
            if sub is not None:
                await sub.aclose()

    def _ensure_task(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(
                self._pump(), name=f"kv-event-pub:{self.worker_id:#x}"
            )

    async def _pump(self) -> None:
        while True:
            ev = await self._queue.get()
            if ev is None:
                return
            try:
                await self._plane.publish(self._topic, ev.to_dict())
            except Exception:
                logger.exception("failed to publish KV event")

    async def close(self) -> None:
        if self._sync_task is not None:
            self._sync_task.cancel()
            await reap_task(self._sync_task, "kv-event sync task", logger)
            self._sync_task = None
        if self._task is not None and not self._task.done():
            self._queue.put_nowait(None)
            await self._task
        self._task = None


class LoadPublisher:
    """Periodic load snapshots (ref: worker stat publishing feeding
    scheduler.rs ProcessedEndpoints)."""

    def __init__(
        self,
        event_plane: Any,
        namespace: str,
        component: str,
        worker_id: int,
        stats_fn: Callable[[], dict],
        *,
        dp_rank: int = 0,
        total_blocks: int = 0,
        interval_s: Optional[float] = None,
        link_bandwidth_fn: Optional[Callable[[], dict]] = None,
        link_faults_fn: Optional[Callable[[], list]] = None,
        kv_high_watermark: float = 1.0,
        incarnation: Optional[int] = None,
    ) -> None:
        self._plane = event_plane
        self._topic = load_topic(namespace, component)
        self.worker_id = worker_id
        self.dp_rank = dp_rank
        self._stats_fn = stats_fn
        self._total_blocks = total_blocks
        # Cadence is env-tunable (DYN_TPU_LOAD_REPORT_INTERVAL_S): the
        # liveness detection budget is denominated in these intervals.
        if interval_s is None:
            from dynamo_tpu import config as _cfg

            interval_s = _cfg.LOAD_REPORT_INTERVAL_S.get()
        self.interval_s = interval_s
        # () -> {src prefill worker id: bytes/s} — the decode handler's
        # measured pull bandwidths, carried to the router's link-cost model
        # on every load report. Late-bindable (the handler is usually
        # constructed after the publisher).
        self.link_bandwidth_fn = link_bandwidth_fn
        # () -> [src worker ids with an open pull breaker] — prices those
        # pairs out of disagg placement router-side.
        self.link_faults_fn = link_faults_fn
        # This worker's admission refusal threshold, advertised so the
        # router can deflect placements away once usage reaches it
        # (overload backpressure). The stats dict's own value wins when
        # the engine reports one.
        self.kv_high_watermark = kv_high_watermark
        # Incarnation fence stamp (runtime/liveness.py): consumers use it
        # to drop a zombie's late reports and to spot a restart. Defaults
        # to the process incarnation — one worker per process.
        self.incarnation = (
            incarnation if incarnation is not None else process_incarnation()
        )
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()

    def snapshot(self) -> LoadSnapshot:
        s = self._stats_fn()
        total = self._total_blocks or s.get("total_blocks", 0)
        free = s.get("free_blocks", 0)
        link_bw = self.link_bandwidth_fn() if self.link_bandwidth_fn else None
        link_faults = self.link_faults_fn() if self.link_faults_fn else None
        return LoadSnapshot(
            worker_id=self.worker_id,
            dp_rank=self.dp_rank,
            active_seqs=s.get("active_seqs", 0),
            waiting=s.get("waiting", 0),
            active_blocks=max(total - free, 0),
            total_blocks=total,
            generated_tokens=s.get("generated_tokens", 0),
            queue_depth=s.get("queue_depth", s.get("waiting", 0)),
            kv_high_watermark=float(
                s.get("kv_high_watermark", self.kv_high_watermark)
            ),
            link_bandwidth=link_bw or None,
            link_faults=list(link_faults) if link_faults else None,
            # Drain bit: the engine's stats carry it (JaxEngine sets
            # ``draining`` the moment begin_drain runs; the controller
            # also force-publishes so routers see it within one RTT).
            draining=bool(s.get("draining", 0)),
            incarnation=self.incarnation,
            # Tick-budgeter advertisement: effective per-tick prefill
            # budget + controller state, straight from engine stats
            # (0/0 when the budgeter is off — scheduler ignores it).
            prefill_budget_tokens=int(s.get("prefill_budget_tokens", 0)),
            budget_state=int(s.get("budget_state", 0)),
        )

    async def publish_once(self) -> None:
        await self._plane.publish(self._topic, self.snapshot().to_dict())

    def start(self) -> None:
        if self._task is None:
            self._stop.clear()
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name=f"load-pub:{self.worker_id:#x}"
            )

    async def _run(self) -> None:
        # Publish-failure backoff: an event-plane blip hits EVERY worker's
        # publisher at once — retrying each at its fixed cadence stampedes
        # the recovering broker. The jittered schedule de-synchronizes the
        # herd; the first success resets it. The cap is deliberately BELOW
        # the liveness death budget (dead_after defaults to 5 intervals;
        # worst post-recovery delay here is 2 × 1.5 jitter = 3 intervals),
        # so a brief plane blip can never make healthy workers go silent
        # past the budget and trigger a fleet-wide false-dead storm.
        backoff = Backoff(base_s=self.interval_s, cap_s=2 * self.interval_s)
        while not self._stop.is_set():
            delay = self.interval_s
            try:
                await self.publish_once()
                backoff.reset()
            except Exception:
                logger.exception("failed to publish load snapshot")
                delay = backoff.next_delay()
            try:
                await asyncio.wait_for(self._stop.wait(), timeout=delay)
            except asyncio.TimeoutError:
                pass

    async def close(self) -> None:
        self._stop.set()
        if self._task is not None:
            await self._task
            self._task = None
