"""KvIndexer: event-driven global view of which worker caches which blocks.

Reference parity: lib/kv-router/src/indexer.rs (KvIndexer :110 — single
consumer task applying RouterEvents to the RadixTree, answering overlap
queries). Here the "single thread" is the asyncio loop: apply() is
synchronous and cheap; the subscription pump lives in router.py.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

from dynamo_tpu.router.protocols import RouterEvent, WorkerKey
from dynamo_tpu.tokens.radix import OverlapScores, RadixTree
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class KvIndexer:
    def __init__(self, block_size: int) -> None:
        self.block_size = block_size
        # C++ tree when buildable (native/radix.py), Python tree otherwise.
        from dynamo_tpu.native.radix import make_radix_tree

        self.tree = make_radix_tree()
        self._events_applied = 0
        self._last_event_id: Dict[WorkerKey, int] = {}

    @property
    def events_applied(self) -> int:
        return self._events_applied

    def apply(self, event: RouterEvent) -> None:
        worker = event.worker
        last = self._last_event_id.get(worker)
        if event.event_id and last is not None and event.event_id <= last:
            # In-flight duplicates arriving after a snapshot replaced them
            # would corrupt the rebuilt state — drop, don't re-apply.
            logger.debug(
                "dropping stale KV event %s from worker %s (last %s)",
                event.event_id, worker, last,
            )
            return
        if event.event_id:
            self._last_event_id[worker] = event.event_id
        if event.kind == "stored":
            self.tree.store(worker, event.block_hashes, event.parent_hash)
        elif event.kind == "removed":
            self.tree.remove(worker, event.block_hashes)
        elif event.kind == "cleared":
            self.tree.clear_worker(worker)
        elif event.kind == "snapshot":
            # Full-state resync: replace everything known about this worker.
            self.tree.clear_worker(worker)
            parents = event.parent_hashes or [None] * len(event.block_hashes)
            for h, p in zip(event.block_hashes, parents):
                self.tree.store(worker, [h], p)
        else:
            logger.warning("unknown KV event kind %r", event.kind)
            return
        self._events_applied += 1

    def has_gap(self, event: RouterEvent) -> bool:
        """True when ``event`` implies missed events from its worker (the
        router should request a snapshot)."""
        if not event.event_id or event.kind == "snapshot":
            # A snapshot IS the gap repair — its event_id legitimately jumps
            # past last+1 (live traffic between request and serialization).
            return False
        last = self._last_event_id.get(event.worker)
        if last is None:
            # Unknown worker joining mid-stream ("cleared" also rebases).
            return event.kind != "cleared" and event.event_id > 1
        return event.event_id > last + 1

    def remove_worker(self, worker: WorkerKey) -> None:
        self.tree.remove_worker(worker)
        self._last_event_id.pop(worker, None)

    def find_matches(self, block_hashes: Sequence[int]) -> OverlapScores:
        return self.tree.find_matches(block_hashes)

    def worker_block_count(self, worker: WorkerKey) -> int:
        return self.tree.worker_block_count(worker)
