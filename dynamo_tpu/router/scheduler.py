"""KvScheduler: the worker-selection cost model.

Reference parity: lib/llm/src/kv_router/scheduler.rs — the engine-agnostic
algorithm (scheduler.rs:497–566): for each candidate worker

    potential_prefill_blocks = request_blocks − overlap_blocks(worker)
    potential_decode_blocks  = current active blocks (reported + in-flight)
    logit = overlap_weight × potential_prefill_blocks + potential_decode_blocks

then pick the minimum, or softmax-sample over −logit/temperature when
``router_temperature > 0`` (scheduler.rs softmax_sample :426). In-flight
requests routed between load reports are tracked locally (sequence.rs's
active-sequence prediction, simplified to block deltas with TTL decay).
"""

from __future__ import annotations

import math
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from dynamo_tpu.router.protocols import LoadSnapshot, WorkerKey
from dynamo_tpu.tokens.radix import OverlapScores
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class KvRouterConfig:
    """(ref: scheduler.rs:137 KvRouterConfig)"""

    overlap_score_weight: float = 1.0
    router_temperature: float = 0.0
    # Forget in-flight load predictions after this long without a report.
    inflight_ttl_s: float = 30.0
    # Soft-skip workers above this KV usage unless all are (busy gating).
    busy_kv_usage: float = 0.95


@dataclass
class WorkerState:
    snapshot: Optional[LoadSnapshot] = None
    # Blocks routed here since the last snapshot (prediction, decays).
    inflight_blocks: int = 0
    inflight_at: float = 0.0
    # Bumped on every load report; stale in-flight releases (charged before
    # the report that already absorbed them) are dropped by comparing this.
    report_gen: int = 0

    def decode_blocks(self, ttl: float) -> int:
        base = self.snapshot.active_blocks if self.snapshot else 0
        if self.inflight_blocks and time.monotonic() - self.inflight_at < ttl:
            base += self.inflight_blocks
        return base

    def kv_usage(self) -> float:
        return self.snapshot.kv_usage if self.snapshot else 0.0


class KvScheduler:
    def __init__(self, config: Optional[KvRouterConfig] = None, *, seed: Optional[int] = None) -> None:
        self.config = config or KvRouterConfig()
        self._workers: Dict[WorkerKey, WorkerState] = {}
        self._rand = random.Random(seed)

    # -- state maintenance -------------------------------------------------

    def update_load(self, snapshot: LoadSnapshot) -> None:
        state = self._workers.setdefault(snapshot.worker, WorkerState())
        state.snapshot = snapshot
        state.inflight_blocks = 0  # report supersedes the prediction
        state.report_gen += 1

    def report_generation(self, worker: WorkerKey) -> int:
        state = self._workers.get(worker)
        return state.report_gen if state is not None else 0

    def add_worker(self, worker: WorkerKey) -> None:
        self._workers.setdefault(worker, WorkerState())

    def remove_worker(self, worker: WorkerKey) -> None:
        self._workers.pop(worker, None)

    def workers(self) -> List[WorkerKey]:
        return sorted(self._workers)

    def load_view(self) -> Dict[WorkerKey, Tuple[int, float]]:
        """worker → (predicted decode blocks, kv usage) — the cost-model
        inputs, sampled for the router's per-worker load gauges (the signal
        the planner and FlowKV-style load-aware policies read)."""
        ttl = self.config.inflight_ttl_s
        return {
            w: (state.decode_blocks(ttl), state.kv_usage())
            for w, state in self._workers.items()
        }

    # -- selection ---------------------------------------------------------

    def select_worker(
        self,
        request_blocks: int,
        overlaps: OverlapScores,
        candidates: Optional[Sequence[WorkerKey]] = None,
    ) -> Optional[WorkerKey]:
        """Pick the worker with the lowest predicted cost. ``candidates``
        restricts the choice to live instances (router-side instance map)."""
        cfg = self.config
        pool: List[WorkerKey] = list(candidates) if candidates is not None else self.workers()
        if not pool:
            return None
        for w in pool:
            self.add_worker(w)

        not_busy = [
            w for w in pool if self._workers[w].kv_usage() < cfg.busy_kv_usage
        ]
        if not_busy:
            pool = not_busy

        logits: List[Tuple[WorkerKey, float, int]] = []
        for w in pool:
            overlap = overlaps.scores.get(w, 0)
            prefill = max(request_blocks - overlap, 0)
            decode = self._workers[w].decode_blocks(cfg.inflight_ttl_s)
            logit = cfg.overlap_score_weight * prefill + decode
            logits.append((w, logit, overlap))

        chosen = self._sample(logits, cfg.router_temperature)
        # Predict the routed request's load until the next report lands.
        state = self._workers[chosen]
        state.inflight_blocks += max(
            request_blocks - overlaps.scores.get(chosen, 0), 0
        )
        state.inflight_at = time.monotonic()
        return chosen

    def complete_request(
        self,
        worker: WorkerKey,
        charged_blocks: int,
        report_gen: Optional[int] = None,
    ) -> None:
        """Release the in-flight prediction when a routed stream finishes
        (ref: sequence.rs active-sequence removal on completion). Without
        this, a fully-cached worker keeps looking as loaded as a cold one
        until the next load report, mis-routing cache hits.

        ``report_gen`` (from report_generation() at routing time) guards
        against double-release: if a load report landed after the charge, the
        report already absorbed it, and releasing again would debit charges
        belonging to later requests."""
        state = self._workers.get(worker)
        if state is None:
            return
        if report_gen is not None and report_gen != state.report_gen:
            return
        state.inflight_blocks = max(state.inflight_blocks - charged_blocks, 0)

    def _sample(
        self, logits: List[Tuple[WorkerKey, float, int]], temperature: float
    ) -> WorkerKey:
        if temperature <= 0.0 or len(logits) == 1:
            # Deterministic at temperature 0: break cost ties by preferring
            # the higher prefix overlap (routes to the warm cache), then by
            # worker key for stability across runs.
            return min(logits, key=lambda e: (e[1], -e[2], e[0]))[0]
        # softmax over −logit/T (lower cost → higher probability)
        scaled = [-l / temperature for _, l, _ in logits]
        m = max(scaled)
        exps = [math.exp(s - m) for s in scaled]
        total = sum(exps)
        r = self._rand.random() * total
        acc = 0.0
        for (w, _, _), e in zip(logits, exps):
            acc += e
            if r <= acc:
                return w
        return logits[-1][0]
