"""KvScheduler: the worker-selection cost model.

Reference parity: lib/llm/src/kv_router/scheduler.rs — the engine-agnostic
algorithm (scheduler.rs:497–566): for each candidate worker

    potential_prefill_blocks = request_blocks − overlap_blocks(worker)
    potential_decode_blocks  = current active blocks (reported + in-flight)
    logit = overlap_weight × potential_prefill_blocks + potential_decode_blocks

then pick the minimum, or softmax-sample over −logit/temperature when
``router_temperature > 0`` (scheduler.rs softmax_sample :426). In-flight
requests routed between load reports are tracked locally (sequence.rs's
active-sequence prediction, simplified to block deltas with TTL decay).

Link-cost extension (the NetKV/FlowKV decode-placement insight, PAPERS.md):
when a request carries KV that must be PULLED from a source worker (disagg
decode placement), prefix overlap is not free compute avoided — every
overlap-miss block also rides the (src → candidate) link. The logit gains
an estimated transfer cost in block-equivalents:

    logit += link_cost_weight × prefill_blocks_per_s
             × (miss_blocks × bytes_per_block) / bandwidth(src, candidate)

so a high-overlap candidate behind a slow link LOSES to a low-overlap
candidate on a fast one whenever re-prefilling is cheaper than the wire.
Per-pair bandwidth is an EWMA seeded from the decode workers' own measured
pull rates (disagg/handlers.py), shipped router-ward in load reports.
"""

from __future__ import annotations

import math
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from dynamo_tpu.router.protocols import LoadSnapshot, WorkerKey
from dynamo_tpu.runtime.liveness import IncarnationFence
from dynamo_tpu.tokens.radix import OverlapScores
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class KvRouterConfig:
    """(ref: scheduler.rs:137 KvRouterConfig)"""

    overlap_score_weight: float = 1.0
    router_temperature: float = 0.0
    # Forget in-flight load predictions after this long without a report.
    inflight_ttl_s: float = 30.0
    # Soft-skip workers above this KV usage unless all are (busy gating).
    busy_kv_usage: float = 0.95
    # Overload deflection: block-equivalents charged per request sitting
    # in a candidate's admission queue (LoadSnapshot.queue_depth). A
    # queued request is work the candidate has ACCEPTED but not started —
    # deeper queue, later start, regardless of current KV usage. 0
    # disables (pre-overload-plane behavior).
    queue_depth_weight: float = 4.0
    # Budget deflection (tick budgeter): extra weight on the prefill-
    # blocks term for workers whose budgeter advertises ITL pressure
    # (LoadSnapshot.budget_state ADAPTIVE/FLOOR) — their per-tick prefill
    # budget is squeezed, so new prefill queues behind the budget instead
    # of starting. The term is NON-NEGATIVE by construction (the pruned
    # path's static lower bound stays valid: actual logit ≥ bound). 0
    # disables (pre-budgeter behavior; unbudgeted workers report state 0
    # and are never charged).
    budget_pressure_weight: float = 2.0
    # -- link-cost term (disagg decode placement) --------------------------
    # Multiplier on the transfer-cost block-equivalents; 0 disables the
    # term entirely (pure overlap+load cost, the pre-link behavior).
    link_cost_weight: float = 1.0
    # Converts estimated transfer SECONDS into the logit's block units: how
    # many blocks a worker prefills per second. The default is deliberately
    # conservative (a modest chip at a 16-token block); the planner's
    # observed rates can overwrite it at runtime.
    prefill_blocks_per_s: float = 64.0
    # Seed bandwidth for never-measured (src, dst) pairs. Intra-cluster
    # DCN-class default; measured EWMAs replace it after the first pull.
    default_link_bandwidth: float = 1e9
    # EWMA weight for bandwidth observations folded in from load reports.
    link_ewma_alpha: float = 0.25
    # -- candidate pruning (fleet-scale selection) -------------------------
    # Above this many candidates (and at temperature 0) select_worker
    # prunes instead of scoring every worker: overlap-carrying and
    # link-differentiated candidates are always scored, then a
    # branch-and-bound walk over a (static_cost, worker) rank cache —
    # maintained on load reports — scores rank entries until the next
    # entry's static lower bound can no longer beat the best scored logit
    # (EXACT argmin, the common case) or ``prune_walk_limit`` entries
    # have been scored (bounded best-of-K among the statically
    # least-loaded, reached only when in-flight charges are dense across
    # the whole fleet; suboptimality is then bounded by one report
    # interval's worth of charges). Per-request cost: O(overlap + link +
    # walk) instead of O(workers). 0 disables pruning entirely.
    prune_threshold: int = 32
    prune_walk_limit: int = 8


class LinkCostModel:
    """Per-(src worker id, dst WorkerKey) transfer-bandwidth EWMA.

    Measured at the decode workers' pull paths (disagg/handlers.py), shipped
    here via LoadSnapshot.link_bandwidth, and read by select_worker to price
    a candidate's overlap-miss transfer. Unobserved pairs quote the seed
    default — optimistic, so the link term only demotes a candidate once a
    slow link has actually been SEEN (a never-used pair shouldn't lose to
    speculation)."""

    # Effective bandwidth quoted for a pair whose breaker is open: low
    # enough that any realistic transfer estimate dwarfs re-prefill cost
    # (pricing the pair out), finite so the logit math stays well-formed
    # and an all-faulted candidate set still produces a choice.
    FAULT_BANDWIDTH = 1e3

    def __init__(self, default_bandwidth: float = 1e9, alpha: float = 0.25) -> None:
        self.default_bandwidth = float(default_bandwidth)
        self.alpha = float(alpha)
        self._bw: Dict[Tuple[int, WorkerKey], float] = {}
        # (src, dst) pairs whose decode-side pull breaker is open — set
        # from LoadSnapshot.link_faults, cleared when a report stops
        # carrying the src (breaker closed or half-open window reached).
        self._faults: set = set()
        # src → dsts with a non-default quote (measured EWMA or fault):
        # the pruned selection path reads this per request, so it must be
        # a lookup, not a scan over every measured pair in the fleet.
        self._by_src: Dict[int, set] = {}

    def _index_add(self, src: int, dst: WorkerKey) -> None:
        self._by_src.setdefault(src, set()).add(dst)

    def _index_check(self, src: int, dst: WorkerKey) -> None:
        """Drop (src, dst) from the src index when NEITHER a measurement
        nor a fault keeps it special."""
        if (src, dst) in self._bw or (src, dst) in self._faults:
            return
        dsts = self._by_src.get(src)
        if dsts is not None:
            dsts.discard(dst)
            if not dsts:
                del self._by_src[src]

    def observe(self, src: int, dst: WorkerKey, bytes_per_s: float) -> None:
        if bytes_per_s <= 0:
            return
        key = (src, dst)
        prev = self._bw.get(key)
        self._bw[key] = (
            bytes_per_s if prev is None
            else self.alpha * bytes_per_s + (1 - self.alpha) * prev
        )
        self._index_add(src, dst)

    def set_bandwidth(self, src: int, dst: WorkerKey, bytes_per_s: float) -> None:
        """Pin a pair's bandwidth directly (operator override, tests)."""
        self._bw[(src, dst)] = float(bytes_per_s)
        self._index_add(src, dst)

    def bandwidth(self, src: int, dst: WorkerKey) -> float:
        if (src, dst) in self._faults:
            return self.FAULT_BANDWIDTH
        return self._bw.get((src, dst), self.default_bandwidth)

    def set_fault(self, src: int, dst: WorkerKey, faulted: bool) -> None:
        """Mark/clear a (src, dst) pair as breaker-open. A faulted pair
        quotes FAULT_BANDWIDTH regardless of its measured EWMA — the EWMA
        survives, so a healed pair resumes at its last honest estimate."""
        if faulted:
            self._faults.add((src, dst))
            self._index_add(src, dst)
        else:
            self._faults.discard((src, dst))
            self._index_check(src, dst)

    def sync_faults(self, dst: WorkerKey, srcs) -> None:
        """Replace dst's faulted-src set with what its load report carries
        (the report is authoritative for its own breakers)."""
        want = {int(s) for s in srcs}
        was = {(s, d) for (s, d) in self._faults if d == dst}
        self._faults = {
            (s, d) for (s, d) in self._faults if d != dst
        } | {(s, dst) for s in want}
        for s in want:
            self._index_add(s, dst)
        for s, d in was:
            if s not in want:
                self._index_check(s, d)

    def faulted(self, src: int, dst: WorkerKey) -> bool:
        return (src, dst) in self._faults

    def seconds(self, src: int, dst: WorkerKey, nbytes: int) -> float:
        """Estimated wire seconds to move ``nbytes`` src → dst. Pulling
        from yourself is free (the blocks are already resident)."""
        if nbytes <= 0 or dst[0] == src:
            return 0.0
        return nbytes / max(self.bandwidth(src, dst), 1e-9)

    def pairs(self) -> Dict[Tuple[int, WorkerKey], float]:
        """Measured pairs (for the router's per-pair gauges)."""
        return dict(self._bw)

    def special_dsts(self, src: int):
        """Destinations whose (src, dst) pair quotes something OTHER than
        the seed default (measured EWMA or an open breaker) — the only
        workers the link term can differentiate, hence the only ones the
        pruned candidate path must score individually. One dict lookup:
        per-request cost must not scan the fleet's full pair set."""
        return self._by_src.get(src, ())

    def drop_worker(self, worker: WorkerKey) -> None:
        self._bw = {
            k: v for k, v in self._bw.items()
            if k[1] != worker and k[0] != worker[0]
        }
        self._faults = {
            k for k in self._faults
            if k[1] != worker and k[0] != worker[0]
        }
        self._by_src.pop(worker[0], None)
        for src, dsts in list(self._by_src.items()):
            dsts.discard(worker)
            if not dsts:
                del self._by_src[src]


@dataclass
class TransferContext:
    """Disagg placement context for one selection: KV for every
    overlap-miss block must be pulled from ``src`` (the prefill worker that
    computed it), at ``bytes_per_block`` serialized wire bytes per block
    (pool-native: int8 payload + scales, or dense — the prefill worker
    advertises it in the bootstrap's kv_transfer metadata)."""

    src: int
    bytes_per_block: int


@dataclass
class WorkerState:
    snapshot: Optional[LoadSnapshot] = None
    # Blocks routed here since the last snapshot (prediction, decays).
    inflight_blocks: int = 0
    inflight_at: float = 0.0
    # Bumped on every load report; stale in-flight releases (charged before
    # the report that already absorbed them) are dropped by comparing this.
    report_gen: int = 0
    # Pruned-selection cache, refreshed per load report (update_load):
    # ``eligible`` = not draining, below busy gating, below the advertised
    # admission watermark — the workers the full scan's tier filters keep
    # whenever any such worker exists; ``static_cost`` = the report-only
    # part of the logit (active blocks + weighted queue depth), the total
    # logit for any zero-overlap uncharged candidate up to a shared
    # constant.
    eligible: bool = True
    static_cost: float = 0.0

    def decode_blocks(self, ttl: float) -> int:
        base = self.snapshot.active_blocks if self.snapshot else 0
        if self.inflight_blocks and time.monotonic() - self.inflight_at < ttl:
            base += self.inflight_blocks
        return base

    def kv_usage(self) -> float:
        return self.snapshot.kv_usage if self.snapshot else 0.0

    def queue_depth(self) -> int:
        return self.snapshot.queue_depth if self.snapshot else 0

    def draining(self) -> bool:
        """The worker advertised a live-handoff drain: it refuses every
        new admission with a typed migratable error, so placing work
        there just costs the stream a bounce."""
        return bool(self.snapshot is not None and self.snapshot.draining)

    def budget_pressure(self) -> float:
        """How hard the worker's tick budgeter is squeezing prefill:
        1.0 at the starvation floor (BUDGET_STATE_FLOOR=3), 0.5 while
        adapting (ADAPTIVE=2), 0 otherwise (off/throughput — literals
        mirror engines/tpu/tick_budget.py BUDGET_STATE_*; the router
        stays engine-import-free). Scales the prefill term: an ITL-
        constrained worker trickles prefill at its floor, so sending a
        big prefill there means queueing behind the budget."""
        if self.snapshot is None:
            return 0.0
        state = self.snapshot.budget_state
        if state == 3:
            return 1.0
        if state == 2:
            return 0.5
        return 0.0

    def saturated(self) -> bool:
        """At/above the worker's advertised admission high watermark:
        the engine will HOLD new admissions (backpressure) rather than
        preempt, so a request routed here queues behind the watermark
        instead of prefilling. Workers that never advertised a watermark
        (< 1.0) are never considered saturated."""
        if self.snapshot is None:
            return False
        wm = self.snapshot.kv_high_watermark
        return wm < 1.0 and self.snapshot.kv_usage >= wm


class KvScheduler:
    def __init__(self, config: Optional[KvRouterConfig] = None, *, seed: Optional[int] = None) -> None:
        self.config = config or KvRouterConfig()
        self._workers: Dict[WorkerKey, WorkerState] = {}
        self._rand = random.Random(seed)
        self.link_costs = LinkCostModel(
            self.config.default_link_bandwidth, self.config.link_ewma_alpha
        )
        # Incarnation fence over load reports: a zombie incarnation's late
        # publish is counted and dropped, a restarted worker's first fresh
        # report triggers drop_worker FIRST so old and new state are never
        # conflated (runtime/liveness.py). Distinct seam label from the
        # liveness tracker's "load_report": both consume the same topic
        # (separate subscriptions), so sharing a label would double-count
        # every zombie packet.
        self._fence = IncarnationFence("router_load")
        # Extra purges drop_worker fans out to (the router registers its
        # radix-indexer removal here, so scheduler.drop_worker stays THE
        # single reconciliation path for a vanished worker).
        self._on_drop: List = []
        # Pruned-selection cache: a (static_cost, worker) rank over
        # eligible workers, rebuilt lazily after load reports.
        self._rank: List[Tuple[float, WorkerKey]] = []
        self._rank_dirty = True
        # Instrumentation: workers actually SCORED (logit computed) across
        # all selections — the soak/bench read this to prove per-request
        # scheduling cost stays bounded as the fleet grows.
        self.logit_evals = 0
        self.selections = 0
        # Last select_worker decision (router span attributes): the picker
        # reads this right after the call on the same event loop.
        self.last_decision: Dict[str, object] = {}

    # -- state maintenance -------------------------------------------------

    def add_drop_callback(self, fn) -> None:
        """``fn(worker: WorkerKey)`` runs inside every drop_worker."""
        self._on_drop.append(fn)

    def update_load(self, snapshot: LoadSnapshot) -> bool:
        """Fold one load report into the cost model. Returns False when
        the report was FENCED (a stale incarnation's packet — counted,
        state untouched)."""
        verdict = self._fence.admit(snapshot.worker, snapshot.incarnation)
        if verdict == "stale":
            logger.warning(
                "dropping stale-incarnation load report from %s "
                "(incarnation %d < newest %d)", snapshot.worker,
                snapshot.incarnation, self._fence.newest(snapshot.worker),
            )
            return False
        if verdict == "rejoined":
            # The worker restarted: purge the previous incarnation's
            # charges/links/faults/radix before this report seeds the
            # fresh state (drop_worker also drops the fence entry, so
            # re-admit the new incarnation afterwards).
            self.drop_worker(snapshot.worker)
            self._fence.admit(snapshot.worker, snapshot.incarnation)
        state = self._workers.setdefault(snapshot.worker, WorkerState())
        state.snapshot = snapshot
        state.inflight_blocks = 0  # report supersedes the prediction
        state.report_gen += 1
        self._refresh_state(state)
        self._rank_dirty = True
        # Fold the worker's measured pull bandwidths (src → B/s, observed
        # at ITS end of each link) into the shared link-cost model.
        for src, bw in (snapshot.link_bandwidth or {}).items():
            self.link_costs.observe(int(src), snapshot.worker, float(bw))
        # Breaker advertisement: the report's link_faults is authoritative
        # for this worker's pairs — carried srcs are priced out, absent
        # srcs (breaker closed / probe window) are restored.
        self.link_costs.sync_faults(
            snapshot.worker, snapshot.link_faults or ()
        )
        return True

    def report_generation(self, worker: WorkerKey) -> int:
        state = self._workers.get(worker)
        return state.report_gen if state is not None else 0

    def _refresh_state(self, state: WorkerState) -> None:
        """Recompute the pruned-selection cache for one worker from its
        snapshot (runs once per load report, not per request)."""
        snap = state.snapshot
        if snap is None:
            # Never-reported worker (a fresh scale-up instance): eligible
            # at zero static cost — exactly how the full scan scores it.
            state.eligible = True
            state.static_cost = 0.0
            return
        usage = snap.kv_usage
        wm = snap.kv_high_watermark
        state.eligible = (
            not snap.draining
            and usage < self.config.busy_kv_usage
            and not (wm < 1.0 and usage >= wm)
        )
        qw = self.config.queue_depth_weight
        state.static_cost = snap.active_blocks + (
            qw * snap.queue_depth if qw > 0 else 0.0
        )

    def _rebuild_rank(self) -> None:
        self._rank = sorted(
            (state.static_cost, w)
            for w, state in self._workers.items()
            if state.eligible
        )
        self._rank_dirty = False

    def add_worker(self, worker: WorkerKey) -> None:
        if worker not in self._workers:
            self._workers[worker] = WorkerState()
            self._rank_dirty = True

    def drop_worker(self, worker: WorkerKey) -> None:
        """THE single reconciliation for a vanished worker (crash, lease
        expiry, rejoin under a new incarnation): atomically releases its
        in-flight charges (the WorkerState prediction), its link-cost
        pairs in BOTH directions, its breaker faults, its incarnation
        fence entry, and — via registered drop callbacks — the router's
        radix/popularity entries. Callers must not purge piecemeal; a
        leak audit (tests/test_liveness.py) asserts zero residue after
        this one call."""
        self._workers.pop(worker, None)
        self._rank_dirty = True
        self.link_costs.drop_worker(worker)
        self._fence.drop(worker)
        for fn in self._on_drop:
            try:
                fn(worker)
            except Exception:
                logger.exception("drop_worker callback failed for %s", worker)

    def remove_worker(self, worker: WorkerKey) -> None:
        """Back-compat alias: removal IS the drop_worker reconciliation."""
        self.drop_worker(worker)

    def workers(self) -> List[WorkerKey]:
        return sorted(self._workers)

    def load_view(self) -> Dict[WorkerKey, Tuple[int, float]]:
        """worker → (predicted decode blocks, kv usage) — the cost-model
        inputs, sampled for the router's per-worker load gauges (the signal
        the planner and FlowKV-style load-aware policies read)."""
        ttl = self.config.inflight_ttl_s
        return {
            w: (state.decode_blocks(ttl), state.kv_usage())
            for w, state in self._workers.items()
        }

    # -- selection ---------------------------------------------------------

    def select_worker(
        self,
        request_blocks: int,
        overlaps: OverlapScores,
        candidates: Optional[Sequence[WorkerKey]] = None,
        *,
        transfer: Optional[TransferContext] = None,
    ) -> Optional[WorkerKey]:
        """Pick the worker with the lowest predicted cost. ``candidates``
        restricts the choice to live instances (router-side instance map).
        ``transfer`` (disagg decode placement) adds the estimated wire cost
        of pulling each candidate's overlap-miss blocks from the source
        worker, so a prefix-overlap win never beats a slow link blindly."""
        cfg = self.config
        self.selections += 1
        evals0 = self.logit_evals

        def note(chosen_w, *, pruned: bool) -> None:
            # O(1) decision record for the router's select span.
            self.last_decision = {
                "worker": chosen_w[0] if chosen_w is not None else None,
                "candidates_scored": self.logit_evals - evals0,
                "overlap_blocks": (
                    overlaps.scores.get(chosen_w, 0)
                    if chosen_w is not None else 0
                ),
                # Dispatch metadata: a positive overlap ships with the
                # request as its speculative-onboard hint (the engine
                # starts the tier walk at enqueue — kv_prefetch.md), so
                # the decision record says whether speculation was armed.
                "prefetch_hint": bool(
                    chosen_w is not None
                    and overlaps.scores.get(chosen_w, 0) > 0
                ),
                "request_blocks": request_blocks,
                "pruned": pruned,
                "transfer_src": transfer.src if transfer is not None else None,
                "link_cost_s": (
                    round(
                        self.link_costs.seconds(
                            transfer.src,
                            chosen_w,
                            max(
                                request_blocks
                                - overlaps.scores.get(chosen_w, 0),
                                0,
                            ) * transfer.bytes_per_block,
                        ),
                        6,
                    )
                    if transfer is not None and chosen_w is not None
                    else None
                ),
            }

        # Fleet-scale fast path: above the prune threshold (and at
        # temperature 0, where selection is a pure argmin) score only the
        # candidates that can actually win instead of every worker.
        if (
            cfg.prune_threshold > 0
            and cfg.router_temperature <= 0.0
            and (len(candidates) if candidates is not None else len(self._workers))
            > cfg.prune_threshold
        ):
            chosen = self._select_pruned(
                request_blocks, overlaps, candidates, transfer
            )
            if chosen is not None:
                self._charge(chosen, request_blocks, overlaps)
                note(chosen, pruned=True)
                return chosen
            # No fully-eligible candidate (fleet-wide drain/saturation):
            # fall through to the full tiered scan, whose fallback tiers
            # still produce a best-effort placement.

        pool: List[WorkerKey] = list(candidates) if candidates is not None else self.workers()
        if not pool:
            note(None, pruned=False)
            return None
        for w in pool:
            self.add_worker(w)

        # Drain deflection FIRST (stronger than busy gating): a draining
        # worker refuses new work outright. When every candidate is
        # draining (full-fleet rolling restart mid-wave), the least-loaded
        # still wins below — the typed refusal + frontend migration is the
        # backstop, not a silent placement failure.
        not_draining = [
            w for w in pool if not self._workers[w].draining()
        ]
        if not_draining:
            pool = not_draining

        not_busy = [
            w for w in pool if self._workers[w].kv_usage() < cfg.busy_kv_usage
        ]
        if not_busy:
            pool = not_busy
        # Overload deflection: a worker at its advertised admission
        # high watermark holds new work (engine backpressure) — prefer
        # any unsaturated candidate; when ALL are saturated the least-
        # loaded still wins below (shedding is the frontend's job).
        unsaturated = [w for w in pool if not self._workers[w].saturated()]
        if unsaturated:
            pool = unsaturated

        logits = self._logits(pool, request_blocks, overlaps, transfer)
        chosen = self._sample(logits, cfg.router_temperature)
        self._charge(chosen, request_blocks, overlaps)
        note(chosen, pruned=False)
        return chosen

    def _logits(
        self,
        pool: Sequence[WorkerKey],
        request_blocks: int,
        overlaps: OverlapScores,
        transfer: Optional[TransferContext],
    ) -> List[Tuple[WorkerKey, float, int]]:
        cfg = self.config
        self.logit_evals += len(pool)
        logits: List[Tuple[WorkerKey, float, int]] = []
        for w in pool:
            overlap = overlaps.scores.get(w, 0)
            prefill = max(request_blocks - overlap, 0)
            decode = self._workers[w].decode_blocks(cfg.inflight_ttl_s)
            logit = cfg.overlap_score_weight * prefill + decode
            if cfg.queue_depth_weight > 0:
                # Accepted-but-unstarted work delays this placement the
                # same way resident decode blocks do.
                logit += cfg.queue_depth_weight * self._workers[w].queue_depth()
            if cfg.budget_pressure_weight > 0 and prefill:
                # Budget deflection: an ITL-constrained budgeter trickles
                # prefill at its squeezed per-tick budget, so every
                # overlap-miss block routed there waits for budget grants.
                # Non-negative, so the pruned path's static lower bound
                # (which omits it) stays a valid lower bound.
                bp = self._workers[w].budget_pressure()
                if bp > 0.0:
                    logit += cfg.budget_pressure_weight * bp * prefill
            if transfer is not None and cfg.link_cost_weight > 0:
                # Overlap-miss blocks must also CROSS the (src → w) link:
                # estimated seconds × prefill-rate = block-equivalents.
                wire_s = self.link_costs.seconds(
                    transfer.src, w, prefill * transfer.bytes_per_block
                )
                logit += (
                    cfg.link_cost_weight * cfg.prefill_blocks_per_s * wire_s
                )
            logits.append((w, logit, overlap))
        return logits

    def _charge(
        self, chosen: WorkerKey, request_blocks: int, overlaps: OverlapScores
    ) -> None:
        """Predict the routed request's load until the next report lands."""
        state = self._workers[chosen]
        state.inflight_blocks += max(
            request_blocks - overlaps.scores.get(chosen, 0), 0
        )
        state.inflight_at = time.monotonic()

    def _select_pruned(
        self,
        request_blocks: int,
        overlaps: OverlapScores,
        candidates: Optional[Sequence[WorkerKey]],
        transfer: Optional[TransferContext],
    ) -> Optional[WorkerKey]:
        """Argmin over a pruned candidate set (temperature 0 only).

        Whenever at least one FULLY-ELIGIBLE candidate exists (not
        draining, below busy gating, below its watermark), the full scan's
        tier filters reduce its pool to exactly the eligible candidates.
        Within that pool, a worker with zero overlap and a seed-default
        link quote has logit

            overlap_weight × request_blocks + link_const
            + static_cost + inflight_charge

        where ``static_cost`` (active blocks + weighted queue depth, from
        the last report) is a LOWER bound on the load part — in-flight
        charges only add. So the argmin is found by (a) scoring every
        overlap-carrying and link-differentiated candidate (measured EWMA
        or open breaker for this src — the only workers whose link term
        differs from the shared constant), then (b) walking the cached
        (static_cost, worker) rank in order, scoring each entry exactly,
        and STOPPING once the next entry's static lower bound exceeds the
        best exact logit seen — every unwalked worker can only be worse.
        Tie-breaks match the full scan: the rank is (cost, worker)-sorted
        and _sample orders by (logit, -overlap, worker).

        The walk is additionally capped at ``prune_walk_limit`` scored
        entries: when in-flight charges are dense across the whole fleet
        (every statically-cheap worker carries routed-but-unreported
        work), the bound cannot fire early and exactness would cost
        O(workers) again — the cap degrades selection to the best of the
        K statically-least-loaded workers (plus all specials), whose
        suboptimality is bounded by the charges one report interval can
        accumulate. Equivalence under sparse charges is test-asserted
        across randomized fleets.

        Returns None when no eligible candidate exists — the caller runs
        the full tiered scan with its all-draining/all-busy fallbacks."""
        cfg = self.config
        if self._rank_dirty:
            self._rebuild_rank()
        cand: Optional[set] = None
        if candidates is not None:
            cand = set(candidates)
            unknown = cand - self._workers.keys()
            if unknown:
                for w in unknown:
                    self.add_worker(w)
                self._rebuild_rank()
        special: set = set()
        for w in overlaps.scores:
            if w in self._workers and (cand is None or w in cand):
                special.add(w)
        if transfer is not None and cfg.link_cost_weight > 0:
            for d in self.link_costs.special_dsts(transfer.src):
                if d in self._workers and (cand is None or d in cand):
                    special.add(d)
        # The tier filters would drop ineligible specials whenever any
        # eligible candidate exists — enforce the same here.
        pool: List[WorkerKey] = sorted(
            w for w in special if self._workers[w].eligible
        )
        logits = self._logits(pool, request_blocks, overlaps, transfer)
        best = min(
            ((l, -o, w) for w, l, o in logits), default=None
        )
        # The shared part of every zero-overlap default-link logit: the
        # static rank key completes it to a lower bound.
        base_const = cfg.overlap_score_weight * request_blocks
        if transfer is not None and cfg.link_cost_weight > 0:
            base_const += (
                cfg.link_cost_weight * cfg.prefill_blocks_per_s
                * (request_blocks * transfer.bytes_per_block)
                / max(self.link_costs.default_bandwidth, 1e-9)
            )
        walked = 0
        limit = max(cfg.prune_walk_limit, 1)
        # Entries EXAMINED (scored or skipped) are bounded too: with a
        # small candidate subset of a huge fleet, skip-scanning the whole
        # rank for in-candidate workers would silently restore O(workers)
        # wall cost even while scored logits stayed bounded. Hitting this
        # cap without a scored candidate defers to the full scan.
        examine_cap = max(limit * 8, 64)
        examined = 0
        found_eligible = bool(pool)
        for cost, w in self._rank:
            examined += 1
            if examined > examine_cap:
                break
            if w in special or (cand is not None and w not in cand):
                continue
            state = self._workers.get(w)
            if state is None or not state.eligible:
                continue
            found_eligible = True
            if best is not None and base_const + cost > best[0]:
                break  # exact: nothing later in the rank can win
            entry = self._logits([w], request_blocks, overlaps, transfer)[0]
            key = (entry[1], -entry[2], entry[0])
            if best is None or key < best:
                best = key
            walked += 1
            if walked >= limit:
                break
        if best is None or not found_eligible:
            return None
        return best[2]

    def complete_request(
        self,
        worker: WorkerKey,
        charged_blocks: int,
        report_gen: Optional[int] = None,
    ) -> None:
        """Release the in-flight prediction when a routed stream finishes
        (ref: sequence.rs active-sequence removal on completion). Without
        this, a fully-cached worker keeps looking as loaded as a cold one
        until the next load report, mis-routing cache hits.

        ``report_gen`` (from report_generation() at routing time) guards
        against double-release: if a load report landed after the charge, the
        report already absorbed it, and releasing again would debit charges
        belonging to later requests."""
        state = self._workers.get(worker)
        if state is None:
            return
        if report_gen is not None and report_gen != state.report_gen:
            return
        state.inflight_blocks = max(state.inflight_blocks - charged_blocks, 0)

    def _sample(
        self, logits: List[Tuple[WorkerKey, float, int]], temperature: float
    ) -> WorkerKey:
        if temperature <= 0.0 or len(logits) == 1:
            # Deterministic at temperature 0: break cost ties by preferring
            # the higher prefix overlap (routes to the warm cache), then by
            # worker key for stability across runs.
            return min(logits, key=lambda e: (e[1], -e[2], e[0]))[0]
        # softmax over −logit/T (lower cost → higher probability)
        scaled = [-l / temperature for _, l, _ in logits]
        m = max(scaled)
        exps = [math.exp(s - m) for s in scaled]
        total = sum(exps)
        r = self._rand.random() * total
        acc = 0.0
        for (w, _, _), e in zip(logits, exps):
            acc += e
            if r <= acc:
                return w
        return logits[-1][0]
