"""dynlint: repo-native static analysis for the serving stack's invariants.

PRs 1-4 grew conventions that nothing enforced at rest: every ``jax.jit``
site wrapped in ``watched_jit`` (compile telemetry, PR 4), a decode hot
loop that moves zero host bytes and never blocks on device sync (PR 3),
flight-recorder rings with exactly one writer thread each (PR 4), and a
single canonical metric-name registry (PR 1). Runtime tests only catch a
violation when a test happens to drive the bad path; this package turns
the conventions into machine-checked AST rules that fail tier-1 before a
recompile storm or a torn ring write ever reaches a TPU — the same move
real serving stacks make once invariants outnumber reviewers (the
reference Dynamo gates its Rust core on clippy; JAX ships its own
leak-checker / debug tooling).

Nine passes (docs/design_docs/static_analysis.md has the catalog):

  DYN001  jit-discipline     every jax.jit construction is wrapped in
                             watched_jit and not rebuilt per call/loop
  DYN002  hot-path purity    nothing reachable from the decode hot loop
                             blocks on device sync, logs above DEBUG, or
                             takes an unlisted lock
  DYN003  silent-swallow     no broad ``except: pass`` — narrow it or
                             record the failure
  DYN004  metric closure     constructor metric names <-> metric_names
                             ALL_* tuples, both directions
  DYN005  single-writer      flight-recorder appends attributable to the
          rings              ring's one owning class
  DYN006  fault-point        fault_point() names <-> fault_names
          closure            ALL_FAULT_POINTS, both directions
  DYN007  async lifecycle    get_running_loop over get_event_loop,
                             retained create_task handles, no blocking
                             calls inside async bodies
  DYN008  config-knob        DYN_TPU_* env reads <-> config.py ALL_KNOBS
          closure            registry, both directions
  DYN009  import layering    module-level imports respect the declared
                             layer DAG; cycles and broken lazy-import
                             obligations reported

Ships three ways: ``dynamo-tpu lint`` (analysis/cli.py), the tier-1 gate
(tests/test_dynlint.py, zero non-baselined findings over dynamo_tpu/),
and library use::

    from dynamo_tpu.analysis import run_lint
    findings = run_lint()          # defaults: this package, repo config

Intentionally importable without jax/numpy — the linter must run (and
fail fast) on machines where the serving deps don't.
"""

from dynamo_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Project,
    Rule,
    all_rules,
    load_baseline,
    partition_new,
    register_rule,
    run_lint,
    save_baseline,
)
from dynamo_tpu.analysis.config import LintConfig, repo_config

# Importing the rules package registers the nine passes.
from dynamo_tpu.analysis import rules as _rules  # noqa: F401

__all__ = [
    "Finding",
    "LintConfig",
    "ModuleInfo",
    "Project",
    "Rule",
    "all_rules",
    "load_baseline",
    "partition_new",
    "register_rule",
    "repo_config",
    "run_lint",
    "save_baseline",
]
