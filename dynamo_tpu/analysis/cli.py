"""``dynamo-tpu lint``: run the dynlint passes from the command line.

Exit codes: 0 = no non-baselined findings, 1 = new findings (or an
unreadable baseline), 2 = bad invocation. Deliberately jax-free and
synchronous — the lint gate must run on a CPU-only CI box in well under
the tier-1 five-second budget.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from dynamo_tpu.analysis.core import (
    Finding,
    all_rules,
    load_baseline,
    partition_new,
    run_lint_detailed,
    save_baseline,
)

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")
PACKAGE_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def add_lint_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "--root", default=None,
        help="directory to lint (default: the installed dynamo_tpu "
        "package). A foreign tree runs only the portable rules "
        "(DYN001/DYN003) — the hot-path/metric/ring configs describe "
        "this repo's layout",
    )
    parser.add_argument(
        "--baseline", default=DEFAULT_BASELINE,
        help="grandfathered-findings JSON (default: the checked-in "
        "analysis/baseline.json); pass an empty string to disable",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="text: one finding per line; json: machine-readable report",
    )
    parser.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="rewrite the baseline file from the current findings and "
        "exit 0 (review the diff!)",
    )
    parser.add_argument(
        "--explain", default=None, metavar="RULE",
        help="print the catalog entry for a rule id (title + the rule "
        "module's documentation) and exit",
    )


def explain_rule(rule_id: str) -> Optional[str]:
    """Catalog entry for a rule id: its title plus the rule module's
    docstring (the module doc IS the catalog text — one source for the
    CLI, the tests, and docs/design_docs/static_analysis.md to agree
    on). None for an unknown id."""
    import sys as _sys

    rule_cls = all_rules().get(rule_id)
    if rule_cls is None:
        return None
    doc = (_sys.modules[rule_cls.__module__].__doc__ or "").strip()
    return f"{rule_cls.id} — {rule_cls.title}\n\n{doc}"


def main_lint(args) -> int:
    if getattr(args, "explain", None):
        text = explain_rule(args.explain)
        if text is None:
            print(
                f"unknown rule id {args.explain!r} "
                f"(have: {', '.join(sorted(all_rules()))})",
                file=sys.stderr,
            )
            return 2
        print(text)
        return 0

    rule_ids: Optional[List[str]] = None
    if args.rules:
        rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = set(rule_ids) - set(all_rules())
        if unknown:
            print(
                f"unknown rule id(s): {', '.join(sorted(unknown))} "
                f"(have: {', '.join(sorted(all_rules()))})",
                file=sys.stderr,
            )
            return 2

    foreign = args.root is not None and (
        os.path.realpath(args.root) != os.path.realpath(PACKAGE_ROOT)
    )
    config = None
    if foreign:
        from dynamo_tpu.analysis.config import portable_config

        config = portable_config()
        disabled = {"DYN002", "DYN004", "DYN005", "DYN006", "DYN008",
                    "DYN009"}
        asked_disabled = sorted(set(rule_ids or ()) & disabled)
        if asked_disabled:
            # Explicitly requested rules must not silently no-op into a
            # false 'clean'.
            print(
                f"rule(s) {', '.join(asked_disabled)} are disabled for a "
                "foreign --root (their configs describe the dynamo_tpu "
                "package layout); run them via the library API with your "
                "own LintConfig",
                file=sys.stderr,
            )
            return 2
    result = run_lint_detailed(args.root, config, rule_ids)
    findings = result.findings

    if args.write_baseline:
        if not args.baseline:
            print(
                "--write-baseline needs a --baseline PATH (refusing to "
                "guess a destination)",
                file=sys.stderr,
            )
            return 2
        if foreign and (
            os.path.realpath(args.baseline)
            == os.path.realpath(DEFAULT_BASELINE)
        ):
            print(
                "refusing to overwrite the checked-in package baseline "
                "from a foreign --root; pass an explicit --baseline PATH",
                file=sys.stderr,
            )
            return 2
        save_baseline(findings, args.baseline)
        print(
            f"baseline written: {len(findings)} finding(s) grandfathered "
            f"-> {args.baseline}",
            file=sys.stderr,
        )
        return 0

    baseline_keys = []
    if args.baseline:
        try:
            baseline_keys = load_baseline(args.baseline)
        except FileNotFoundError:
            baseline_keys = []
        except (OSError, ValueError, KeyError) as exc:
            print(f"unreadable baseline {args.baseline}: {exc}",
                  file=sys.stderr)
            return 1
    new, grandfathered = partition_new(findings, baseline_keys)

    if args.format == "json":
        print(json.dumps(
            {
                "new": [f.to_dict() for f in new],
                "grandfathered": [f.to_dict() for f in grandfathered],
                "suppressed": [
                    {**f.to_dict(), "reason": reason}
                    for f, reason in result.suppressed
                ],
                "ok": not new,
            },
            indent=2,
        ))
    else:
        for f in new:
            print(f.render())
        if grandfathered:
            print(
                f"({len(grandfathered)} grandfathered finding(s) in the "
                "baseline not shown)",
                file=sys.stderr,
            )
        summary = (
            "dynlint: clean"
            if not new
            else f"dynlint: {len(new)} new finding(s)"
        )
        print(summary, file=sys.stderr)
    return 1 if new else 0


def _print_findings(findings: List[Finding]) -> None:  # pragma: no cover
    for f in findings:
        print(f.render())


def main(argv=None) -> None:  # pragma: no cover - exercised via cli.__main__
    parser = argparse.ArgumentParser("dynamo-tpu lint")
    add_lint_args(parser)
    raise SystemExit(main_lint(parser.parse_args(argv)))


if __name__ == "__main__":  # pragma: no cover
    main()
