"""dynlint framework: file walker, finding model, suppressions, baseline.

Layers (each rule only sees the two below it):

  Project        parsed package: ModuleInfo per file (source, AST, parent
                 links, suppression table), built once and shared by all
                 rules — the walk + parse is the dominant cost and the
                 tier-1 gate budgets the whole run under 5 s.
  Rule           one registered pass; ``check(project, config)`` yields
                 Findings. Registration is declarative (``@register_rule``)
                 so the CLI/tests enumerate passes without importing them
                 by name.
  Finding        (rule, path, line, message); baseline identity drops the
                 line so grandfathered findings survive unrelated edits to
                 the same file.

Suppressions: ``# dynlint: disable=DYN001[,DYN002][ -- reason]`` on the
finding's line, on any line of the multi-line statement that starts there,
or on a standalone comment line directly above. Rules with
``requires_reason`` (DYN003) reject reason-less suppressions — the
suppression stays visible as a finding until someone writes down why the
swallow is intentional.

This module must not import jax/numpy (see package docstring).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple, Type

_SUPPRESS_RE = re.compile(
    r"#\s*dynlint:\s*disable=(?P<rules>[A-Z0-9, ]+?)"
    r"(?:\s*--\s*(?P<reason>\S.*?))?\s*$"
)

# A line that is ONLY a suppression comment applies to the next line.
_COMMENT_ONLY_RE = re.compile(r"^\s*#")


@dataclass(frozen=True, order=True)
class Finding:
    rule: str
    path: str  # posix path relative to the linted root
    line: int
    message: str
    # Last line of the anchoring statement: a trailing suppression comment
    # anywhere in a multi-line statement covers the finding. Not part of
    # identity/ordering (line drift already excluded from key()).
    end_line: int = field(default=0, compare=False)

    @staticmethod
    def at(
        module: "ModuleInfo", node: ast.AST, rule: str, message: str
    ) -> "Finding":
        line = getattr(node, "lineno", 0) or 0
        end = getattr(node, "end_lineno", line) or line
        # Suppressions may trail the enclosing STATEMENT's closing paren,
        # not just the flagged expression — cover its full span. But only
        # for expression nodes: climbing from an ExceptHandler would span
        # the whole try statement, letting one reasoned suppression
        # silently grandfather a SIBLING broad handler; and a def/class is
        # its own statement (never cover whole bodies).
        stmt = node
        if not isinstance(node, (ast.stmt, ast.excepthandler)):
            for anc in module.ancestors(node):
                if isinstance(anc, ast.stmt):
                    stmt = anc
                    break
        if not isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            end = max(end, getattr(stmt, "end_lineno", end) or end)
        return Finding(
            rule=rule,
            path=module.rel,
            line=line,
            message=message,
            end_line=end,
        )

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: line numbers drift with unrelated edits, so
        grandfathering matches on (rule, path, message) as a multiset."""
        return (self.rule, self.path, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"

    def to_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }


@dataclass
class Suppression:
    rules: Set[str]
    reason: Optional[str]


class ModuleInfo:
    """One parsed source file plus the derived indexes every rule needs:
    parent links (ast has none), line→suppression table, and lazy
    qualname helpers."""

    def __init__(self, path: str, rel: str, source: str) -> None:
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._parents: Dict[ast.AST, ast.AST] = {}
        # ONE walk builds both the parent links and the flat node list.
        # Rules iterate ``nodes`` instead of re-running ast.walk per rule —
        # the tree is only ever traversed once per file (the analyzer's 5s
        # tier-1 budget is mostly ast.walk overhead otherwise).
        nodes: List[ast.AST] = [self.tree]
        i = 0
        while i < len(nodes):
            parent = nodes[i]
            i += 1
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent
                nodes.append(child)
        self.nodes: List[ast.AST] = nodes
        self.suppressions = self._scan_suppressions()

    # -- structure helpers --------------------------------------------------

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def enclosing_function(
        self, node: ast.AST
    ) -> Optional[ast.AST]:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        for anc in self.ancestors(node):
            if isinstance(anc, ast.ClassDef):
                return anc
        return None

    def qualname(self, node: ast.AST) -> str:
        """Dotted context of a node: 'Class.method[.inner]' or '<module>'."""
        parts: List[str] = []
        for anc in self.ancestors(node):
            if isinstance(
                anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                parts.append(anc.name)
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            parts.insert(0, node.name)
        return ".".join(reversed(parts)) or "<module>"

    # -- suppressions -------------------------------------------------------

    def _scan_suppressions(self) -> Dict[int, Suppression]:
        table: Dict[int, Suppression] = {}
        for lineno, line in enumerate(self.lines, 1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {
                r.strip() for r in m.group("rules").split(",") if r.strip()
            }
            sup = Suppression(rules=rules, reason=m.group("reason"))
            if _COMMENT_ONLY_RE.match(line) and line.lstrip().startswith("#"):
                table[lineno + 1] = sup  # standalone: guards the next line
            else:
                table[lineno] = sup  # trailing: guards its own line
        return table

    def suppression_for_span(
        self, start: int, end: int, rule: str
    ) -> Optional[Suppression]:
        """Suppression covering ``rule`` anywhere in [start, end]: a
        trailing comment on any spanned line, or a standalone comment
        directly above ``start`` (already shifted in the table)."""
        for lineno in range(start, max(start, end) + 1):
            sup = self.suppressions.get(lineno)
            if sup is not None and rule in sup.rules:
                return sup
        return None


class Project:
    """All parsed modules under one root directory (non-recursive into
    __pycache__/hidden dirs). A file that fails to parse is itself a
    finding (DYN000) — a syntax error must fail the gate, not silently
    shrink the rule coverage."""

    def __init__(self, root: str, modules: List[ModuleInfo],
                 errors: List[Finding]) -> None:
        self.root = root
        self.modules = modules
        self.errors = errors
        self._by_rel = {m.rel: m for m in modules}

    @classmethod
    def load(cls, root: str) -> "Project":
        root = os.path.abspath(root)
        modules: List[ModuleInfo] = []
        errors: List[Finding] = []
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames
                if d != "__pycache__" and not d.startswith(".")
            )
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                try:
                    with open(path, encoding="utf-8") as f:
                        source = f.read()
                    modules.append(ModuleInfo(path, rel, source))
                except (SyntaxError, ValueError, OSError) as exc:
                    errors.append(
                        Finding(
                            rule="DYN000",
                            path=rel,
                            line=getattr(exc, "lineno", 0) or 0,
                            message=f"unparseable module: {exc}",
                        )
                    )
        return cls(root, modules, errors)

    def module(self, rel: str) -> Optional[ModuleInfo]:
        return self._by_rel.get(rel)


# -- AST utilities shared by rules -------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.jit' for Attribute chains over Names; None for anything whose
    base isn't a plain name (calls, subscripts)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_attr(node: ast.AST) -> Optional[str]:
    """Final attribute/name of a (possibly complex) reference expression:
    ``self.runner.decode_read`` -> 'decode_read', ``foo`` -> 'foo'."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def names_in(node: ast.AST) -> Set[str]:
    """Every Name id and Attribute attr mentioned in a subtree — the cheap
    'does this expression touch X' test rules use for root tracking."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


# -- rule registry ------------------------------------------------------------


class Rule:
    """Base class; subclasses set ``id``/``title`` and implement check().
    ``requires_reason``: inline suppressions must carry '-- reason'."""

    id: str = "DYN000"
    title: str = ""
    requires_reason: bool = False

    def check(self, project: Project, config) -> Iterator[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, Type[Rule]] = {}


def register_rule(cls: Type[Rule]) -> Type[Rule]:
    if cls.id in _REGISTRY and _REGISTRY[cls.id] is not cls:
        raise ValueError(f"duplicate rule id {cls.id}")
    _REGISTRY[cls.id] = cls
    return cls


def all_rules() -> Dict[str, Type[Rule]]:
    return dict(_REGISTRY)


# -- baseline -----------------------------------------------------------------


def load_baseline(path: str) -> List[Tuple[str, str, str]]:
    """Baseline file -> list of finding keys (multiset semantics: two
    identical grandfathered findings need two entries)."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    entries = doc.get("findings", [])
    return [(e["rule"], e["path"], e["message"]) for e in entries]


def save_baseline(findings: Iterable[Finding], path: str) -> None:
    entries = [
        {"rule": f.rule, "path": f.path, "message": f.message}
        for f in sorted(findings)
    ]
    with open(path, "w", encoding="utf-8") as f:
        json.dump(
            {
                "comment": (
                    "dynlint grandfathered findings; regenerate with "
                    "`dynamo-tpu lint --write-baseline` and REVIEW the "
                    "diff — a growing baseline is a failing invariant."
                ),
                "findings": entries,
            },
            f,
            indent=2,
            sort_keys=True,
        )
        f.write("\n")


def partition_new(
    findings: Iterable[Finding], baseline_keys: Iterable[Tuple[str, str, str]]
) -> Tuple[List[Finding], List[Finding]]:
    """(new, grandfathered): each baseline key absorbs ONE matching
    finding (multiset match) so a second copy of a grandfathered bug is
    still new."""
    budget: Dict[Tuple[str, str, str], int] = {}
    for key in baseline_keys:
        budget[key] = budget.get(key, 0) + 1
    new: List[Finding] = []
    old: List[Finding] = []
    for f in sorted(findings):
        k = f.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old


# -- entry point --------------------------------------------------------------


@dataclass
class LintResult:
    findings: List[Finding]
    suppressed: List[Tuple[Finding, Optional[str]]] = field(
        default_factory=list
    )


def run_lint(
    root: Optional[str] = None,
    config=None,
    rule_ids: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Run the registered passes over ``root`` and return the surviving
    findings, sorted. Defaults lint this installed dynamo_tpu package
    under the repo config."""
    return run_lint_detailed(root, config, rule_ids).findings


def run_lint_detailed(
    root: Optional[str] = None,
    config=None,
    rule_ids: Optional[Iterable[str]] = None,
) -> LintResult:
    from dynamo_tpu.analysis.config import repo_config

    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if config is None:
        config = repo_config()
    project = Project.load(root)
    wanted = set(rule_ids) if rule_ids is not None else None
    findings: List[Finding] = list(project.errors)
    suppressed: List[Tuple[Finding, Optional[str]]] = []
    for rule_id, rule_cls in sorted(all_rules().items()):
        if wanted is not None and rule_id not in wanted:
            continue
        rule = rule_cls()
        for finding in rule.check(project, config):
            module = project.module(finding.path)
            sup = (
                module.suppression_for_span(
                    finding.line, finding.end_line or finding.line, rule_id
                )
                if module is not None
                else None
            )
            if sup is None:
                findings.append(finding)
                continue
            if rule.requires_reason and not sup.reason:
                findings.append(
                    Finding(
                        rule=finding.rule,
                        path=finding.path,
                        line=finding.line,
                        message=(
                            finding.message
                            + " [suppression needs a reason: "
                            "'# dynlint: disable="
                            + rule_id
                            + " -- why']"
                        ),
                    )
                )
            else:
                suppressed.append((finding, sup.reason))
    # The over-approximate call graph can reach the same node through two
    # paths; findings are a set, not a trace log.
    return LintResult(findings=sorted(set(findings)), suppressed=suppressed)
