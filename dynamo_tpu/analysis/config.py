"""dynlint configuration: the repo's invariants as data.

``repo_config()`` is THE statement of what PRs 1-4 promised; fixtures and
tests build narrower configs pointing at their own trees. Paths are posix,
relative to the linted root (for the repo config: the ``dynamo_tpu``
package directory)."""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Optional, Tuple


@dataclass(frozen=True)
class JitDisciplineConfig:
    """DYN001. ``builder_name_re``: enclosing functions allowed to
    construct jits (cached program builders); anything else needs either
    module level, a memo-guard (``if key not in cache`` / ``is None``
    ancestor test), or a reasoned suppression."""

    watch_wrapper: str = "watched_jit"
    builder_name_re: str = r"^(__init__|_?build_\w*|_?make_\w*)$"

    def is_builder(self, name: str) -> bool:
        return re.match(self.builder_name_re, name) is not None


@dataclass(frozen=True)
class HotPathConfig:
    """DYN002. ``roots``: (module rel path, qualname) the decode hot loop
    enters through. ``scope``: modules whose functions participate in the
    name-based call graph — the decode plane, deliberately excluding
    runtime/metrics_core.py (its histogram lock is a PR 3/4 decision: one
    uncontended lock per observe, render pays the rest). ``boundaries``:
    sanctioned host-transfer funnels where traversal and bans stop
    (the pipelined readback helper IS the one allowed sync point).
    ``device_roots``: names that hold device arrays — np.asarray/float/int
    over an expression touching one of these is a blocking device sync."""

    roots: FrozenSet[Tuple[str, str]] = frozenset(
        {
            ("engines/tpu/engine.py", "JaxEngine._decode_tick"),
            ("engines/tpu/runner.py", "DeviceRunner.sync_slots"),
            ("engines/tpu/runner.py", "DeviceRunner.sync_tables"),
            ("engines/tpu/runner.py", "DeviceRunner.decode_dispatch"),
            ("engines/tpu/runner.py", "DeviceRunner.decode_read"),
        }
    )
    scope: FrozenSet[str] = frozenset(
        {
            "engines/tpu/engine.py",
            "engines/tpu/runner.py",
            "engines/metrics.py",
            "runtime/device_observe.py",
            # The fault plane's tick seams (fault_point at dispatch/reap)
            # are IN the hot loop — the disabled-plane path must stay a
            # bare flag check, and this scope entry makes the linter walk
            # through faults.py to prove it.
            "runtime/faults.py",
            # Tick budgeter (PR 18): observe_decode runs at every reap —
            # this scope entry makes the linter prove it stays deque-and-
            # arithmetic only. The control law itself is fenced behind the
            # TickBudgeter.evaluate boundary below.
            "engines/tpu/tick_budget.py",
            # Perf ledger (PR 19): observe_decode/observe_prefill run at
            # every reap / prefill round — this scope entry makes the
            # linter prove the feeds stay deque-and-arithmetic only. The
            # sentinel is fenced behind the PerfLedger.evaluate boundary.
            "runtime/perf_ledger.py",
        }
    )
    boundaries: FrozenSet[Tuple[str, str]] = frozenset(
        {
            # The one sanctioned blocking readback: overlapped D2H copies
            # at reap.
            ("engines/tpu/runner.py", "DeviceRunner._get_all"),
            # Program-CREATION helper: runs once per (program, variant)
            # under a double-checked creation lock, never on a steady
            # dispatch (WatchedJit.__call__ is lock-free).
            ("runtime/device_observe.py", "watched_jit"),
            # AIMD control law: time-gated to eval_interval_s (admission
            # side of the tick, never per-reap); may log and emit flight
            # events, so traversal stops here rather than whitelisting
            # those in the decode plane.
            ("engines/tpu/tick_budget.py", "TickBudgeter.evaluate"),
            # Perf sentinel: time-gated to eval_interval_s (per-reap calls
            # return on a subtraction); past the gate it compares windows
            # against fingerprints, counts anomalies, and records flight
            # events — fenced rather than whitelisted, like the budgeter.
            ("runtime/perf_ledger.py", "PerfLedger.evaluate"),
        }
    )
    device_roots: FrozenSet[str] = frozenset(
        {
            "slot_state",
            "slot_tables",
            "k_cache",
            "v_cache",
            "carry_tok",
            "carry_pos",
            "handles",
            "proc_state",
        }
    )
    # Lock attributes the hot path may take (none today; metrics_core is
    # out of scope rather than whitelisted so the list stays honest).
    allowed_locks: FrozenSet[str] = frozenset()


@dataclass(frozen=True)
class SilentSwallowConfig:
    """DYN003. Exception names considered 'broad': catching one of these
    (alone or in a tuple) with a do-nothing body is a silent swallow."""

    broad_names: FrozenSet[str] = frozenset({"Exception", "BaseException"})


@dataclass(frozen=True)
class MetricClosureConfig:
    """DYN004. ``metric_names_rel``: the single module allowed to define
    metric names (loaded by file path — no package import, the linter
    stays jax-free). ``constructor_methods`` / ``constructor_classes``:
    call shapes that register a metric family. ``dynamic_emitters``:
    helper functions whose non-literal call covers every name the helper
    itself defined in the names module (the system server renders the
    engine stats dict through ``engine_gauge(key)`` instead of
    constructing gauge objects)."""

    prefix: str = "dynamo_tpu_"
    metric_names_rel: str = "runtime/metric_names.py"
    constructor_methods: FrozenSet[str] = frozenset(
        {"counter", "gauge", "histogram"}
    )
    constructor_classes: FrozenSet[str] = frozenset(
        {"Counter", "Gauge", "Histogram"}
    )
    dynamic_emitters: FrozenSet[str] = frozenset({"engine_gauge"})


@dataclass(frozen=True)
class RingWriterConfig:
    """DYN005. ``owners``: ring name -> (module rel path, owning class).
    Appends (``<recv>.flight.record(...)``) must resolve to ``self.flight``
    inside the owning class; anything else is a cross-thread write the
    single-writer ring contract cannot survive."""

    ring_attrs: FrozenSet[str] = frozenset({"flight", "kv_flight"})
    recorder_class: str = "FlightRecorder"
    owners: Dict[str, Tuple[str, str]] = field(
        default_factory=lambda: {
            "engine": ("engines/tpu/engine.py", "JaxEngine"),
            "runner": ("engines/tpu/runner.py", "DeviceRunner"),
            # Faultline rings (PR 7): pull retry/breaker history, stream
            # migrations, canary transitions — each single-writer on its
            # owner's event loop.
            "disagg": ("disagg/handlers.py", "DecodeHandler"),
            "migration": ("llm/migration.py", "Migration"),
            "health": ("runtime/health.py", "CanaryHealthChecker"),
            # Overload plane (PR 8): admission sheds + brownout state
            # transitions; single writer: the frontend's event loop.
            "overload": ("runtime/overload.py", "OverloadController"),
            # Drain plane (PR 9): handoff/fallback/requeue history; single
            # writer: the draining worker's event loop.
            "drain": ("runtime/drain.py", "DrainController"),
            # KVBM integrity events (tier corruption); single writer: the
            # manager's event loop (onboard + offload spill paths).
            "kvbm": ("kvbm/manager.py", "TieredKvManager"),
            # KV-reuse plane (PR 16): offload bursts, onboards, tier
            # evictions, sketch replacements; single writer: the manager's
            # event loop (same loop as the kvbm ring).
            "kvcache": ("kvbm/manager.py", "TieredKvManager"),
            # Perf ledger (PR 19): sentinel anomalies + fingerprint
            # load/store outcomes; single writer: the engine tick loop
            # (evaluate rides the reap path; load/store ride start/stop
            # on the same loop).
            "perf": ("runtime/perf_ledger.py", "PerfLedger"),
            # Crash plane (PR 10): worker suspect/dead/rejoin transitions
            # + stale-incarnation drops; single writer: the consuming
            # frontend's event loop (worker_monitor pump + evaluate task).
            "liveness": ("runtime/liveness.py", "LivenessTracker"),
            # Elasticity plane (PR 12): plan-state transitions, holds,
            # scale actuations, drains; single writer: the planner's
            # event loop.
            "planner": ("planner/elastic.py", "ElasticController"),
            # Trajectory plane (PR 13): span/event ingest + slow-capture
            # history; single writer: the frontend's event loop
            # (collector pump + local tracer listener).
            "trajectory": ("runtime/trajectory.py", "TrajectoryStore"),
            # Parser plane (PR 15): tool-call jail commits, completed
            # calls, degradation-ladder activations, parser exceptions;
            # single writer: the frontend's event loop (every jail lives
            # inside an SSE handler there).
            "parser": ("parsers/observe.py", "ParserPlane"),
        }
    )


@dataclass(frozen=True)
class AsyncLifecycleConfig:
    """DYN007. The three async-plane bug classes the last ten PRs kept
    re-fixing, as config:

    ``get_event_loop`` is banned outright — outside a running loop it
    binds (or on 3.12+ raises about) a dead loop that never runs the
    task; ``asyncio.get_running_loop()`` fails loudly at the call site
    instead (the PR 12 Planner lesson, now machine-checked).

    ``create_task`` results must be retained: a bare expression-statement
    discards the only strong reference, so the task is garbage-collected
    mid-flight and its failure is silently dropped. Store it, await it,
    gather it, or route it through ``runtime/tasks.py::reap_task``.

    ``blocking_calls`` / ``blocking_prefixes``: synchronous calls that
    stall the event loop when they appear lexically inside an ``async
    def`` body (nearest enclosing function is async — a nested sync def
    or a lambda handed to ``run_in_executor`` is its own boundary and
    exempt). ``blocking_allowlist`` holds the blessed boundaries as
    (module rel path, enclosing async qualname): every entry is a
    reviewed decision that the call is small, local, and cheaper than an
    executor hop."""

    blocking_calls: FrozenSet[str] = frozenset(
        {
            "time.sleep",
            "subprocess.run",
            "subprocess.call",
            "subprocess.check_call",
            "subprocess.check_output",
            "subprocess.Popen",
            "socket.create_connection",
            "open",
            "io.open",
        }
    )
    blocking_prefixes: Tuple[str, ...] = ("requests.", "urllib.request.")
    blocking_allowlist: FrozenSet[Tuple[str, str]] = frozenset(
        {
            # File-backend discovery: a local-fs dev/test backend by
            # design (discovery/file.py docstring); writes are one small
            # JSON document, atomic-rename, on a control-plane cadence.
            ("runtime/discovery/file.py", "FileDiscovery.put"),
            ("runtime/discovery/file.py", "FileDiscovery.create_lease"),
            ("runtime/discovery/file.py", "FileDiscovery.keep_alive"),
            ("runtime/discovery/file.py", "FileDiscovery.revoke_lease"),
            # Event-plane replay serving: seeks a local append-only log at
            # an indexed offset on the (rare) late-subscriber resync path,
            # never on the publish hot path.
            ("runtime/events/zmq_plane.py", "EventBroker._serve_replay"),
            # Checkpoint manifest commit: a <1 KB JSON + atomic rename;
            # the heavy block data rides gather_and_write under the
            # engine's device executor, not this open().
            ("engines/tpu/kv_checkpoint.py", "save_checkpoint"),
            # Stream recorder: small JSONL lines appended under the
            # recorder lock; documented at the call site as
            # interleaving-safe and failure-disabling.
            ("llm/recorder.py", "StreamRecorder._write"),
            # CLI batch driver: single-user tool, file I/O IS the job.
            ("cli/run.py", "run_batch"),
        }
    )


@dataclass(frozen=True)
class KnobClosureConfig:
    """DYN008. The DYN004/DYN006 mirror for configuration: every
    ``DYN_TPU_*`` environment read resolves through the knob registry
    (``config.py`` ``ALL_KNOBS``: name, default, parser), every declared
    knob has at least one reader, and a literal env-name string at a call
    site is a finding — a renamed or dead knob can never silently diverge
    from the docs. The knobs module is loaded BY FILE PATH (no package
    import — it is dependency-free by design and the linter must run
    without jax installed)."""

    knobs_rel: str = "config.py"
    prefix: str = "DYN_TPU_"
    # Call shapes that read the environment: <...>.get / getenv calls and
    # environ[...] subscripts are matched against these terminal names.
    env_callables: FrozenSet[str] = frozenset({"getenv"})
    environ_names: FrozenSet[str] = frozenset({"environ"})


@dataclass(frozen=True)
class ImportLayeringConfig:
    """DYN009. The declared layer DAG, bottom-up: a module may import
    (at module level) only from its own or a LOWER layer. ``layers`` maps
    layer name -> path prefixes (a trailing '/' matches a directory; an
    exact file name matches a root module); every module must map to
    exactly one layer. ``lazy_obligations`` are known import-cycle
    seams that must stay function-local imports — the PR 7 faults.py /
    metrics_core rule, previously enforced only by a comment. Imports
    under ``if TYPE_CHECKING:`` are annotations-only and exempt."""

    package: str = "dynamo_tpu"
    layers: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
        ("foundation", ("utils/", "config.py", "_version.py", "__init__.py")),
        ("runtime", ("runtime/",)),
        (
            "planes",
            (
                "bench/",
                "disagg/",
                "discd/",
                "engines/",
                "frontend/",
                "gateway/",
                "global_router/",
                "grpc/",
                "http/",
                "kvbm/",
                "llm/",
                "lora/",
                "mocker/",
                "models/",
                "multimodal/",
                "native/",
                "ops/",
                "parallel/",
                "parsers/",
                "planner/",
                "profiler/",
                "router/",
                "tokens/",
                "worker/",
            ),
        ),
        ("surface", ("analysis/", "cli/", "deploy/")),
    )
    lazy_obligations: Tuple[Tuple[str, str, str], ...] = (
        (
            "runtime/faults.py",
            "runtime/metrics_core.py",
            "distributed.py imports faults for fault_point and "
            "metrics_core imports utils.logging — a module-level import "
            "here closes the cycle when utils.logging is the first entry "
            "into the runtime package (PR 7); FaultPlane.__init__ imports "
            "it lazily",
        ),
        (
            "utils/logging.py",
            "runtime/context.py",
            "the formatter needs current_context() per record, but "
            "utils.logging is the first import of half the tree — a "
            "module-level import would drag the runtime package into "
            "every foundation import (and the DAG bans the direction)",
        ),
    )


@dataclass(frozen=True)
class FaultPointConfig:
    """DYN006. ``fault_names_rel``: the single module allowed to declare
    fault-point names (loaded by file path — no package import, the
    linter stays jax-free). ``call_names``: the functions whose first
    argument is a point name (``fault_point`` and any alias)."""

    fault_names_rel: str = "runtime/fault_names.py"
    call_names: FrozenSet[str] = frozenset({"fault_point", "fault_payload"})


@dataclass(frozen=True)
class LintConfig:
    jit: JitDisciplineConfig = field(default_factory=JitDisciplineConfig)
    hot_path: Optional[HotPathConfig] = field(default_factory=HotPathConfig)
    swallow: SilentSwallowConfig = field(default_factory=SilentSwallowConfig)
    metrics: Optional[MetricClosureConfig] = field(
        default_factory=MetricClosureConfig
    )
    rings: Optional[RingWriterConfig] = field(default_factory=RingWriterConfig)
    faults: Optional[FaultPointConfig] = field(
        default_factory=FaultPointConfig
    )
    async_lifecycle: Optional[AsyncLifecycleConfig] = field(
        default_factory=AsyncLifecycleConfig
    )
    knobs: Optional[KnobClosureConfig] = field(
        default_factory=KnobClosureConfig
    )
    layering: Optional[ImportLayeringConfig] = field(
        default_factory=ImportLayeringConfig
    )


def repo_config() -> LintConfig:
    """The dynamo_tpu package's invariants (defaults above ARE the repo
    config; fixtures construct their own)."""
    return LintConfig()


def portable_config() -> LintConfig:
    """Rules meaningful on ANY tree: DYN001 (jit discipline), DYN003
    (silent swallow), and DYN007 (async lifecycle — asyncio semantics are
    universal; the repo's blessed-boundary paths simply won't match a
    foreign tree). The repo-specific passes — hot-path roots, the
    metric-name registry, ring ownership, the fault-point registry, the
    knob registry, the layer DAG — are tied to dynamo_tpu's layout and
    would only emit config-mismatch noise on a foreign ``--root``; they
    are disabled here."""
    return LintConfig(
        hot_path=None,
        metrics=None,
        rings=None,
        faults=None,
        knobs=None,
        layering=None,
    )
