"""DYN006 fault-point closure: both directions of the faultline contract.

Forward: the first argument of every ``fault_point(...)`` call statically
resolves to a member of ``fault_names.ALL_FAULT_POINTS``. A string literal
at a seam is a name the registry can silently drift from (import the
constant); a constant that is not a declared point is a typo that would
make a chaos plan silently never fire; a dynamic expression cannot be
closed at all — every one is a finding.

Reverse: every declared point has at least one seam. A dead point is chaos
coverage that quietly stopped existing — a plan targeting it arms fine and
injects nothing.

Mirror of DYN004 (metric closure): the names module is loaded BY FILE
PATH (no package import) — it is dependency-free by design and the linter
must run without jax installed.
"""

from __future__ import annotations

import ast
import importlib.util
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

from dynamo_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Project,
    Rule,
    register_rule,
)


def _load_names_module(path: str):
    spec = importlib.util.spec_from_file_location("_dynlint_fault_names", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)  # type: ignore[union-attr]
    return mod


def _registry(names_mod) -> Tuple[Dict[str, str], Set[str]]:
    """(const name → value, declared point values)."""
    consts: Dict[str, str] = {
        k: v
        for k, v in vars(names_mod).items()
        if isinstance(v, str) and not k.startswith("_")
    }
    members: Set[str] = set()
    for k, v in vars(names_mod).items():
        if k.startswith("ALL_") and isinstance(v, tuple):
            members |= {x for x in v if isinstance(x, str)}
    return consts, members


def _is_fault_point_call(node: ast.Call, cfg) -> bool:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id in cfg.call_names
    if isinstance(fn, ast.Attribute):
        return fn.attr in cfg.call_names
    return False


@register_rule
class FaultPointClosureRule(Rule):
    id = "DYN006"
    title = "fault-point names close over the fault_names registry"

    def check(self, project: Project, config) -> Iterator[Finding]:
        cfg = config.faults
        if cfg is None:
            return
        names_module = project.module(cfg.fault_names_rel)
        if names_module is None:
            yield Finding(
                rule=self.id,
                path=cfg.fault_names_rel,
                line=1,
                message="fault-names module missing from the linted tree",
            )
            return
        try:
            names_mod = _load_names_module(
                os.path.join(project.root, cfg.fault_names_rel)
            )
        except Exception as exc:
            yield Finding(
                rule=self.id,
                path=cfg.fault_names_rel,
                line=1,
                message=(
                    f"fault-names module failed to load ({exc!r}) — it is "
                    "executed by file path and must stay dependency-free"
                ),
            )
            return
        consts, members = _registry(names_mod)
        covered: Set[str] = set()
        sites: List[Tuple[ModuleInfo, ast.Call]] = []
        for module in project.modules:
            if module.rel == cfg.fault_names_rel:
                continue
            for node in module.nodes:
                if isinstance(node, ast.Call) and _is_fault_point_call(
                    node, cfg
                ):
                    sites.append((module, node))

        for module, node in sites:
            yield from self._check_site(module, node, consts, members, covered)

        for value in sorted(members - covered):
            yield Finding(
                rule=self.id,
                path=cfg.fault_names_rel,
                line=self._def_line(names_module, value, consts),
                message=(
                    f"dead fault point {value!r} — declared but installed "
                    "at no seam; a chaos plan targeting it would inject "
                    "nothing. Install the point or delete the entry"
                ),
            )

    def _check_site(
        self,
        module: ModuleInfo,
        node: ast.Call,
        consts: Dict[str, str],
        members: Set[str],
        covered: Set[str],
    ) -> Iterator[Finding]:
        if not node.args:
            yield Finding.at(
                module, node, self.id,
                f"fault_point() without a point name in "
                f"{module.qualname(node)}",
            )
            return
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            covered.add(arg.value)
            yield Finding.at(
                module, node, self.id,
                f"literal fault-point name {arg.value!r} in "
                f"{module.qualname(node)} — import the constant from "
                "runtime/fault_names.py so the registry cannot drift",
            )
            return
        # fault_names.X / fn.X / bare X resolved through the registry.
        const_name: Optional[str] = None
        if isinstance(arg, ast.Attribute):
            const_name = arg.attr
        elif isinstance(arg, ast.Name):
            const_name = arg.id
        if const_name is None or const_name not in consts:
            yield Finding.at(
                module, node, self.id,
                f"fault-point name in {module.qualname(node)} does not "
                "statically resolve into runtime/fault_names.py — use a "
                "declared constant, not a computed expression",
            )
            return
        value = consts[const_name]
        covered.add(value)
        if value not in members:
            yield Finding.at(
                module, node, self.id,
                f"fault point {const_name} ({value!r}) used in "
                f"{module.qualname(node)} but pinned in no ALL_* tuple — "
                "add it to ALL_FAULT_POINTS in runtime/fault_names.py",
            )

    @staticmethod
    def _def_line(
        names_module: ModuleInfo, value: str, consts: Dict[str, str]
    ) -> int:
        rev = {v: k for k, v in consts.items()}
        want = rev.get(value)
        for node in ast.walk(names_module.tree):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == want:
                        return node.lineno
        return 1
