"""DYN002 hot-path purity: nothing reachable from the decode hot loop may
block on a device sync, log above DEBUG on the steady path, or take an
unlisted lock.

PR 3's contract: steady-state decode ticks move ZERO host bytes and the
only blocking readback is the pipelined ``_get_all`` funnel at reap. The
runtime transfer-counting tests prove it for the paths they drive; this
pass proves it for every path that EXISTS, by walking a conservative
name-based call graph from the configured roots.

Call graph: within the configured module scope, every ``Name`` or
terminal-``Attribute`` reference that matches an indexed function name is
an edge — deliberately over-approximate (a function *referenced* on the
hot path can be *called* there; ``self._device(self._dispatch_on_device,
...)`` style executor indirection must not hide callees). Boundary
functions (the sanctioned readback funnel) stop both traversal and bans.

Banned inside reachable functions:
  * ``jax.device_get(...)``, ``.block_until_ready()``, ``.item()``,
    ``.tolist()`` — unconditional device syncs;
  * ``np.asarray/np.array/float/int`` over an expression touching a
    configured device-state root (host conversion of a device array);
  * logging above DEBUG outside an ``except`` handler (error paths may
    speak; the steady path may not);
  * ``with <lock>`` / ``.acquire()`` on locks not in the whitelist.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, Iterator, List, Set, Tuple

from dynamo_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Project,
    Rule,
    dotted_name,
    names_in,
    register_rule,
    terminal_attr,
)

_LOG_ABOVE_DEBUG = {"info", "warning", "warn", "error", "exception", "critical"}
_SYNC_ATTRS = {"item", "tolist"}
_CONVERTERS = {"float", "int"}
_NP_CONVERTERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}


@dataclass
class _Func:
    module: ModuleInfo
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    qualname: str

    @property
    def key(self) -> Tuple[str, str]:
        return (self.module.rel, self.qualname)


def _index_scope(project: Project, scope) -> Dict[str, List[_Func]]:
    """name -> candidate functions across the scope modules (methods index
    under their bare name so attribute references resolve)."""
    index: Dict[str, List[_Func]] = {}
    for module in project.modules:
        if module.rel not in scope:
            continue
        for node in module.nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                f = _Func(module, node, module.qualname(node))
                index.setdefault(node.name, []).append(f)
    return index


def _local_bindings(func: _Func) -> Set[str]:
    """Names bound inside the function (params + any Store) — a Load of
    one of these is a local value, not a reference to a project function
    that happens to share its name."""
    bound: Set[str] = set()
    args = func.node.args
    for a in (
        list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        + [args.vararg, args.kwarg]
    ):
        if a is not None:
            bound.add(a.arg)
    for node in ast.walk(func.node):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            bound.add(node.id)
    return bound


def _edges(func: _Func, index: Dict[str, List[_Func]]) -> Iterator[_Func]:
    """Over-approximate callees, but only from positions that can invoke:
    the func of a Call, or a Name/Attribute passed as a call argument
    (executor indirection: ``self._device(self.runner.decode_read, ...)``
    must not hide callees). A plain attribute/name LOAD (``stop =
    req.stop``) is data flow, not a call — edging on it drowns the graph
    in same-name coincidences."""
    own_name = getattr(func.node, "name", None)
    local = _local_bindings(func)

    def candidates(ref: ast.AST) -> Iterator[_Func]:
        if isinstance(ref, ast.Attribute):
            name = ref.attr
        elif isinstance(ref, ast.Name):
            if ref.id in local:
                return
            name = ref.id
        else:
            return
        if name == own_name or name not in index:
            return
        yield from index[name]

    for node in ast.walk(func.node):
        if not isinstance(node, ast.Call):
            continue
        yield from candidates(node.func)
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            yield from candidates(arg)


def _in_except_handler(module: ModuleInfo, node: ast.AST) -> bool:
    return any(
        isinstance(anc, ast.ExceptHandler) for anc in module.ancestors(node)
    )


def _looks_like_lock(expr: ast.AST) -> bool:
    name = terminal_attr(expr)
    return name is not None and "lock" in name.lower()


@register_rule
class HotPathPurityRule(Rule):
    id = "DYN002"
    title = "decode hot path must not sync, log, or lock"

    def check(self, project: Project, config) -> Iterator[Finding]:
        cfg = config.hot_path
        if cfg is None:
            return
        index = _index_scope(project, cfg.scope)
        # Resolve roots to functions (a missing root is itself a finding:
        # a rename must update the invariant, not silently drop coverage).
        queue: List[_Func] = []
        seen: Set[Tuple[str, str]] = set()
        all_funcs = {
            f.key: f for funcs in index.values() for f in funcs
        }
        for rel, qual in sorted(cfg.roots):
            f = all_funcs.get((rel, qual))
            if f is None:
                yield Finding(
                    rule=self.id,
                    path=rel,
                    line=1,
                    message=(
                        f"configured hot-path root {qual!r} not found — "
                        "update analysis/config.py to track the rename"
                    ),
                )
                continue
            queue.append(f)
            seen.add(f.key)
        while queue:
            func = queue.pop()
            if func.key in cfg.boundaries:
                continue
            yield from self._check_function(func, cfg)
            for callee in _edges(func, index):
                if callee.key not in seen:
                    seen.add(callee.key)
                    queue.append(callee)

    def _check_function(self, func: _Func, cfg) -> Iterator[Finding]:
        module = func.module
        where = f"hot-path function {func.qualname!r} ({module.rel})"
        for node in ast.walk(func.node):
            # Skip nested defs? No: nested functions run on the hot path
            # too (dispatch closures) — they stay in the walk.
            if isinstance(node, ast.Call):
                yield from self._check_call(module, node, where, cfg)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    ctx = item.context_expr
                    tgt = ctx.func if isinstance(ctx, ast.Call) else ctx
                    if _looks_like_lock(tgt) and (
                        terminal_attr(tgt) not in cfg.allowed_locks
                    ):
                        yield Finding.at(
                            module, node, self.id,
                            f"lock acquired in {where} — the tick thread "
                            "must never wait on another thread; whitelist "
                            "it in analysis/config.py only with a "
                            "measured bound",
                        )

    def _check_call(
        self, module: ModuleInfo, node: ast.Call, where: str, cfg
    ) -> Iterator[Finding]:
        fn = node.func
        dotted = dotted_name(fn)
        attr = fn.attr if isinstance(fn, ast.Attribute) else None

        if dotted == "jax.device_get":
            yield Finding.at(
                module, node, self.id,
                f"jax.device_get in {where} — blocking D2H sync; route "
                "readbacks through the pipelined funnel "
                "(DeviceRunner._get_all)",
            )
            return
        if attr == "block_until_ready":
            yield Finding.at(
                module, node, self.id,
                f".block_until_ready() in {where} — blocking device sync "
                "on the hot path",
            )
            return
        if attr in _SYNC_ATTRS and isinstance(fn, ast.Attribute):
            # .item()/.tolist() also exist on host numpy arrays — only a
            # receiver touching device state is a sync.
            touched = names_in(fn.value) & cfg.device_roots
            if touched:
                yield Finding.at(
                    module, node, self.id,
                    f".{attr}() over device state "
                    f"({', '.join(sorted(touched))}) in {where} — "
                    "synchronous device readback on the hot path",
                )
                return
        if attr == "acquire" and isinstance(fn, ast.Attribute) and (
            _looks_like_lock(fn.value)
            and terminal_attr(fn.value) not in cfg.allowed_locks
        ):
            yield Finding.at(
                module, node, self.id,
                f"lock .acquire() in {where} — the tick thread must "
                "never wait on another thread",
            )
            return

        # Device-array host conversions: only when the argument expression
        # touches a known device-state root (host numpy mirrors convert
        # freely — that's the dirty-slot sync working as designed).
        is_np = dotted in _NP_CONVERTERS
        is_cast = isinstance(fn, ast.Name) and fn.id in _CONVERTERS
        if (is_np or is_cast) and node.args:
            touched = names_in(node.args[0]) & cfg.device_roots
            if touched:
                what = dotted if is_np else fn.id  # type: ignore[union-attr]
                yield Finding.at(
                    module, node, self.id,
                    f"{what}() over device state "
                    f"({', '.join(sorted(touched))}) in {where} — host "
                    "conversion of a device array blocks the tick; keep "
                    "it on device or reap through the funnel",
                )
                return

        # Logging above DEBUG on the steady path.
        if (
            attr in _LOG_ABOVE_DEBUG
            and isinstance(fn, ast.Attribute)
            and terminal_attr(fn.value) in {"logger", "logging", "log"}
            and not _in_except_handler(module, node)
        ):
            yield Finding.at(
                module, node, self.id,
                f"logger.{attr}() on the steady path in {where} — "
                "formatting + handler I/O on the tick thread; use DEBUG, "
                "the flight recorder, or move it into the error path",
            )
