"""DYN001 jit-discipline: every ``jax.jit`` construction is (a) wrapped in
``watched_jit`` so /debug/compiles attributes its cache growth, and (b)
built once — at module level, in a recognized builder function, or behind
a memo guard — never per call or per loop iteration.

(b) is the trace-time half of PR 4's recompile-storm detector: a jit
object constructed inside a per-call body starts with an empty compile
cache EVERY call, so each dispatch pays a full trace+XLA compile that the
runtime signature-budget watcher (which is per jit object) can never see
accumulate.

Recognized safe construction contexts:
  * module level (constant program objects);
  * an enclosing function whose name matches the builder pattern
    (``__init__``, ``_build_*``, ``make_*`` — cached-program factories);
  * a memo guard: the construction sits under an ``if`` whose test is a
    cache-miss check (``key not in cache`` / ``x is None``), the idiom
    llama.py's donated unstack splitter uses.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional

from dynamo_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Project,
    Rule,
    dotted_name,
    register_rule,
)


def _jit_references(module: ModuleInfo) -> List[ast.AST]:
    """Nodes referring to the jit transform itself: ``jax.jit`` attributes
    plus bare names bound by ``from jax import jit``."""
    jit_aliases = set()
    for node in module.nodes:
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for alias in node.names:
                if alias.name == "jit":
                    jit_aliases.add(alias.asname or alias.name)
    refs: List[ast.AST] = []
    for node in module.nodes:
        if isinstance(node, ast.Attribute) and dotted_name(node) == "jax.jit":
            refs.append(node)
        elif isinstance(node, ast.Name) and node.id in jit_aliases:
            refs.append(node)
    return refs


def _is_watch_call(node: ast.AST, wrapper: str) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None
    )
    return name == wrapper


def _memo_guarded(module: ModuleInfo, node: ast.AST) -> bool:
    """True when an ancestor ``if`` test is a cache-miss check: a
    ``not in`` membership test or an ``is None`` comparison."""
    for anc in module.ancestors(node):
        if not isinstance(anc, ast.If):
            continue
        for sub in ast.walk(anc.test):
            if isinstance(sub, ast.Compare) and any(
                isinstance(op, (ast.NotIn, ast.Is)) for op in sub.ops
            ):
                return True
    return False


@register_rule
class JitDisciplineRule(Rule):
    id = "DYN001"
    title = "jax.jit sites must be watched_jit-wrapped and built once"

    def check(self, project: Project, config) -> Iterator[Finding]:
        cfg = config.jit
        for module in project.modules:
            if module.rel.startswith("analysis/"):
                continue  # the linter itself manipulates jit names in text
            for ref in _jit_references(module):
                yield from self._check_ref(module, ref, cfg)

    def _check_ref(
        self, module: ModuleInfo, ref: ast.AST, cfg
    ) -> Iterator[Finding]:
        watched = False
        in_loop = False
        decorated: Optional[ast.AST] = None
        prev: ast.AST = ref
        for anc in module.ancestors(ref):
            if _is_watch_call(anc, cfg.watch_wrapper) and (
                prev in anc.args
                or prev in [kw.value for kw in anc.keywords]
            ):
                watched = True
            if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
                in_loop = True
            if isinstance(
                anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ) and prev in getattr(anc, "decorator_list", ()):
                decorated = anc
            prev = anc

        if decorated is not None:
            yield Finding.at(
                module, ref, self.id,
                f"decorator jit on {module.qualname(decorated)!r} cannot be "
                f"watched — jit the implementation and assign through "
                f"{cfg.watch_wrapper}(name, ...) so /debug/compiles sees "
                f"this program",
            )
            return  # a decorator is module-scoped; skip the context checks
        if not watched:
            yield Finding.at(
                module, ref, self.id,
                f"un-watched jax.jit in {module.qualname(ref)} — wrap the "
                f"jitted callable in {cfg.watch_wrapper}(name, ...) "
                f"(compile telemetry + recompile-storm budget)",
            )
        if in_loop:
            yield Finding.at(
                module, ref, self.id,
                f"jax.jit constructed inside a loop in "
                f"{module.qualname(ref)} — each iteration builds a fresh "
                f"program object with an empty compile cache (recompile "
                f"storm at trace time); hoist it",
            )
            return
        fn = module.enclosing_function(ref)
        if fn is None:
            return  # module level: constant program object
        if cfg.is_builder(fn.name):
            return
        if _memo_guarded(module, ref):
            return
        yield Finding.at(
            module, ref, self.id,
            f"jax.jit constructed in per-call body "
            f"{module.qualname(fn)!r} — every call rebuilds the program "
            f"and repays the XLA compile; hoist to module level, a "
            f"builder ({cfg.builder_name_re}), or a memo guard",
        )
