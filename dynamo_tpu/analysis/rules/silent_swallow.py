"""DYN003 silent-swallow: no broad ``except: pass`` anywhere in the
package. A bare/``Exception``-wide handler whose body does nothing makes
a real failure (a wedged checkpoint write, a dead event plane, a leaked
lease) indistinguishable from success — the flight recorder and request
timelines exist precisely so failures leave a trace.

A handler passes when it either narrows the exception (OSError,
asyncio.CancelledError, ...) or DOES something: logs, records a flight
event, re-raises, returns a degraded value. Intentionally-broad
swallows carry ``# dynlint: disable=DYN003 -- <why>`` — this rule
requires the reason (core enforces it via ``requires_reason``)."""

from __future__ import annotations

import ast
from typing import Iterator

from dynamo_tpu.analysis.core import (
    Finding,
    Project,
    Rule,
    register_rule,
    terminal_attr,
)


def _broad_names(handler: ast.ExceptHandler) -> "list[str]":
    """Names of caught broad exceptions; [''] for a bare except."""
    t = handler.type
    if t is None:
        return ["<bare>"]
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out = []
    for e in elts:
        name = terminal_attr(e)
        if name is not None:
            out.append(name)
    return out


def _is_silent(handler: ast.ExceptHandler) -> bool:
    """Body does nothing observable: only pass/``...``/docstring."""
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / ellipsis
        if isinstance(stmt, ast.Continue):
            continue  # loop-swallow: just as silent as pass
        return False
    return True


@register_rule
class SilentSwallowRule(Rule):
    id = "DYN003"
    title = "no silent broad exception swallows"
    requires_reason = True

    def check(self, project: Project, config) -> Iterator[Finding]:
        broad = config.swallow.broad_names
        for module in project.modules:
            for node in module.nodes:
                if not isinstance(node, ast.ExceptHandler):
                    continue
                names = _broad_names(node)
                hit = [
                    n for n in names if n == "<bare>" or n in broad
                ]
                if not hit or not _is_silent(node):
                    continue
                caught = ", ".join(names)
                yield Finding.at(
                    module, node, self.id,
                    f"silent broad swallow (except {caught}: pass) in "
                    f"{module.qualname(node)} — narrow the exception or "
                    "record the failure (flight recorder / log)",
                )
