"""DYN004 metric-name closure: both directions of the PR 1 contract.

Forward: every metric name reaching a Counter/Gauge/Histogram constructor
resolves to a member of a ``metric_names.ALL_*`` tuple — a string literal
at a constructor site is an emitter bypassing the registry (the runtime
half, test_metric_names_lint.py's grep, catches the literal; this pass
additionally catches a CONSTANT that was never pinned into a family).

Reverse: every ``ALL_*`` entry has at least one constructor site — a name
with no emitter is a dead dashboard series waiting to page someone.
Names defined through a configured dynamic emitter (``engine_gauge``)
are covered by any non-literal call of that emitter (the system server
renders the engine stats dict straight to Prometheus text).

The names module is loaded BY FILE PATH (no package import): it is
dependency-free by design and the linter must run without jax installed.
"""

from __future__ import annotations

import ast
import importlib.util
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

from dynamo_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Project,
    Rule,
    register_rule,
)


def _load_names_module(path: str):
    spec = importlib.util.spec_from_file_location("_dynlint_metric_names", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)  # type: ignore[union-attr]
    return mod


def _registry(names_mod, names_ast: ast.Module, dynamic_emitters) -> Tuple[
    Dict[str, str], Set[str], Dict[str, Set[str]], Set[str]
]:
    """(const name -> value, family-member values, family name -> values,
    dynamically-emitted values)."""
    consts: Dict[str, str] = {
        k: v
        for k, v in vars(names_mod).items()
        if isinstance(v, str) and not k.startswith("_")
    }
    families: Dict[str, Set[str]] = {}
    members: Set[str] = set()
    for k, v in vars(names_mod).items():
        if k.startswith("ALL_") and isinstance(v, tuple):
            vals = {x for x in v if isinstance(x, str)}
            families[k] = vals
            members |= vals
    # Constants whose defining expression is a dynamic-emitter call are
    # rendered generically (no per-name constructor object exists).
    dynamic: Set[str] = set()
    for node in ast.walk(names_ast):
        if not isinstance(node, ast.Assign):
            continue
        val = node.value
        if (
            isinstance(val, ast.Call)
            and isinstance(val.func, ast.Name)
            and val.func.id in dynamic_emitters
        ):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id in consts:
                    dynamic.add(consts[tgt.id])
    return consts, members, families, dynamic


def _constructor_name_arg(node: ast.Call, cfg) -> Optional[ast.AST]:
    """First positional arg when the call shape is a metric constructor."""
    fn = node.func
    is_ctor = (
        isinstance(fn, ast.Attribute) and fn.attr in cfg.constructor_methods
    ) or (isinstance(fn, ast.Name) and fn.id in cfg.constructor_classes)
    if not is_ctor or not node.args:
        return None
    return node.args[0]


@register_rule
class MetricClosureRule(Rule):
    id = "DYN004"
    title = "metric names close over the metric_names registry"

    def check(self, project: Project, config) -> Iterator[Finding]:
        cfg = config.metrics
        if cfg is None:
            return
        names_module = project.module(cfg.metric_names_rel)
        if names_module is None:
            yield Finding(
                rule=self.id,
                path=cfg.metric_names_rel,
                line=1,
                message="metric-names module missing from the linted tree",
            )
            return
        try:
            names_mod = _load_names_module(
                os.path.join(project.root, cfg.metric_names_rel)
            )
        except Exception as exc:
            # The names module is executed by path; it must stay
            # dependency-free. A load failure is a finding, not a crash —
            # same contract as Project.load's DYN000.
            yield Finding(
                rule=self.id,
                path=cfg.metric_names_rel,
                line=1,
                message=(
                    f"metric-names module failed to load ({exc!r}) — it is "
                    "executed by file path and must stay dependency-free"
                ),
            )
            return
        consts, members, families, dynamic = _registry(
            names_mod, names_module.tree, cfg.dynamic_emitters
        )
        covered: Set[str] = set()
        dynamic_emitter_called = False
        sites: List[Tuple[ModuleInfo, ast.Call, ast.AST]] = []
        for module in project.modules:
            if module.rel == cfg.metric_names_rel:
                continue
            for node in module.nodes:
                if not isinstance(node, ast.Call):
                    continue
                if (
                    isinstance(node.func, ast.Name)
                    and node.func.id in cfg.dynamic_emitters
                    and node.args
                    and not isinstance(node.args[0], ast.Constant)
                ):
                    dynamic_emitter_called = True
                arg = _constructor_name_arg(node, cfg)
                if arg is not None:
                    sites.append((module, node, arg))

        for module, node, arg in sites:
            yield from self._check_site(
                module, node, arg, consts, members, covered, cfg
            )

        if dynamic_emitter_called:
            covered |= dynamic
        for family, values in sorted(families.items()):
            for value in sorted(values - covered):
                yield Finding(
                    rule=self.id,
                    path=cfg.metric_names_rel,
                    line=self._def_line(names_module, value, consts),
                    message=(
                        f"dead metric name {value!r} in {family} — no "
                        "constructor site (and no dynamic emitter) "
                        "registers this family; delete it or wire the "
                        "emitter"
                    ),
                )

    def _check_site(
        self, module: ModuleInfo, node: ast.Call, arg: ast.AST,
        consts: Dict[str, str], members: Set[str], covered: Set[str], cfg,
    ) -> Iterator[Finding]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if not arg.value.startswith(cfg.prefix):
                return  # not one of ours (tests, third-party registries)
            covered.add(arg.value)
            yield Finding.at(
                module, node, self.id,
                f"literal metric name {arg.value!r} at a constructor site "
                f"in {module.qualname(node)} — import the constant from "
                "the metric-names registry",
            )
            return
        # mn.X / metric_names.X / bare X resolved through the registry.
        const_name = None
        if isinstance(arg, ast.Attribute):
            const_name = arg.attr
        elif isinstance(arg, ast.Name):
            const_name = arg.id
        if const_name is None or const_name not in consts:
            return  # dynamic expression — the runtime half covers it
        value = consts[const_name]
        if not value.startswith(cfg.prefix):
            return
        covered.add(value)
        if value not in members:
            yield Finding.at(
                module, node, self.id,
                f"metric {const_name} ({value!r}) constructed in "
                f"{module.qualname(node)} but pinned in no ALL_* family — "
                "add it to the matching tuple in the metric-names "
                "registry",
            )

    @staticmethod
    def _def_line(
        names_module: ModuleInfo, value: str, consts: Dict[str, str]
    ) -> int:
        """Line of the constant's assignment in metric_names.py (best
        effort: the first assignment whose target resolves to ``value``)."""
        rev = {v: k for k, v in consts.items()}
        want = rev.get(value)
        for node in ast.walk(names_module.tree):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == want:
                        return node.lineno
        return 1
