"""DYN005 single-writer rings: every flight-recorder append site is
statically attributable to the ring's one owning class.

The FlightRecorder contract (PR 4): ``record`` is lock-free O(1) append
into a preallocated slot, sound ONLY because exactly one thread writes
each ring — the engine tick loop owns the "engine" ring, the device
thread owns the "runner" ring. A second writer tears the index/slot pair
and the post-mortem you need is the one that gets corrupted.

Statically enforced as ownership-by-class:
  * ring constructions ``self.<attr> = FlightRecorder("<name>")`` must
    appear in the configured owning class for that name (unknown ring
    names are findings — new rings register an owner in
    analysis/config.py before they exist);
  * append sites ``<recv>.<attr>.record(...)`` must be ``self.<attr>``
    inside the owning class. Reaching through another object
    (``self.runner.flight.record(...)``) is a cross-thread write by
    construction and is flagged at the call site.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Tuple

from dynamo_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Project,
    Rule,
    register_rule,
)


def _ring_constructions(
    module: ModuleInfo, cfg
) -> Iterator[Tuple[ast.AST, str, Optional[str], str]]:
    """(node, ring name, class name or None, attr) for every
    ``self.<attr> = FlightRecorder("<name>")``."""
    for node in module.nodes:
        if not isinstance(node, ast.Assign):
            continue
        val = node.value
        if not (
            isinstance(val, ast.Call)
            and isinstance(val.func, (ast.Name, ast.Attribute))
            and (
                val.func.id
                if isinstance(val.func, ast.Name)
                else val.func.attr
            )
            == cfg.recorder_class
        ):
            continue
        ring = None
        if val.args and isinstance(val.args[0], ast.Constant):
            ring = val.args[0].value
        for tgt in node.targets:
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
                and tgt.attr in cfg.ring_attrs
            ):
                cls = module.enclosing_class(node)
                yield node, str(ring), cls.name if cls else None, tgt.attr


@register_rule
class RingWriterRule(Rule):
    id = "DYN005"
    title = "flight-recorder rings have exactly one owning class"

    def check(self, project: Project, config) -> Iterator[Finding]:
        cfg = config.rings
        if cfg is None:
            return
        owners: Dict[str, Tuple[str, str]] = dict(cfg.owners)
        for module in project.modules:
            if module.rel.startswith("analysis/"):
                continue
            for node, ring, cls, _attr in _ring_constructions(module, cfg):
                owner = owners.get(ring)
                if owner is None:
                    yield Finding.at(
                        module, node, self.id,
                        f"flight ring {ring!r} constructed in "
                        f"{module.qualname(node)} has no registered owner "
                        "— map it to its one writer class in "
                        "analysis/config.py",
                    )
                elif owner != (module.rel, cls):
                    yield Finding.at(
                        module, node, self.id,
                        f"flight ring {ring!r} constructed in "
                        f"{module.rel}:{cls} but owned by "
                        f"{owner[0]}:{owner[1]} — a second constructor "
                        "means a second writer thread",
                    )
            yield from self._check_appends(module, owners, cfg)

    def _check_appends(
        self, module: ModuleInfo, owners: Dict[str, Tuple[str, str]], cfg
    ) -> Iterator[Finding]:
        if module.rel.startswith("analysis/"):
            return
        for node in module.nodes:
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "record"
                and isinstance(node.func.value, ast.Attribute)
                and node.func.value.attr in cfg.ring_attrs
            ):
                continue
            recv = node.func.value.value  # expr before `.flight.record`
            ctx = module.qualname(node)
            if not (isinstance(recv, ast.Name) and recv.id == "self"):
                yield Finding.at(
                    module, node, self.id,
                    f"flight-ring append through a foreign object in "
                    f"{ctx} — only the owning class may append to its "
                    "ring (single-writer contract); emit an event on "
                    "YOUR ring or route through the owner's thread",
                )
                continue
            cls = module.enclosing_class(node)
            cls_name = cls.name if cls else None
            owning = {
                ring
                for ring, (rel, owner_cls) in owners.items()
                if rel == module.rel and owner_cls == cls_name
            }
            if not owning:
                yield Finding.at(
                    module, node, self.id,
                    f"flight-ring append in {ctx} but "
                    f"{module.rel}:{cls_name} owns no registered ring — "
                    "register the ring's owner in analysis/config.py",
                )
