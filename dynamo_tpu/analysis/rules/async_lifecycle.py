"""DYN007 async-lifecycle discipline: the three asyncio bug classes the
last ten PRs kept re-fixing, as one machine-checked pass.

``asyncio.get_event_loop()`` is banned in favor of
``asyncio.get_running_loop()``. Called before any loop runs (the common
``start()``-from-``__init__``-time mistake) it binds a loop that will
never run the task — the canary sweep, the operator watch, the offload
pump all sat dead until someone noticed the plane was silent. The same
bug was found and fixed twice (PR 12, PR 13) and still existed at 8+
sites when this rule landed. ``get_running_loop()`` raises at the call
site instead.

``create_task()`` results must be retained. The event loop holds only a
weak reference to tasks: a bare fire-and-forget expression-statement
discards the last strong reference, so the task can be garbage-collected
mid-flight and its exception silently dropped. Store it on an attribute,
await it, gather it, or route it through ``runtime/tasks.py::reap_task``
— anything that keeps (and eventually reaps) the handle.

Blocking calls (``time.sleep``, ``subprocess.run``, sync file/socket
I/O, ``requests.*``) lexically inside ``async def`` bodies stall the
event loop for every request it is serving. "Lexically" means the
nearest enclosing function is the async one: a nested sync ``def`` or a
lambda handed to ``run_in_executor`` is its own execution boundary and
exempt. The configured allowlist (AsyncLifecycleConfig) holds the
blessed boundaries — each entry is a reviewed small-local-I/O decision,
not an escape hatch.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from dynamo_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Project,
    Rule,
    dotted_name,
    register_rule,
)


def _nearest_function(
    module: ModuleInfo, node: ast.AST
) -> Optional[ast.AST]:
    """Nearest enclosing function-ish scope (sync def, async def, or
    lambda) — the execution boundary the blocking-call check keys on."""
    for anc in module.ancestors(node):
        if isinstance(
            anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return anc
    return None


@register_rule
class AsyncLifecycleRule(Rule):
    id = "DYN007"
    title = "async lifecycle: running loop, retained tasks, no blocking"

    def check(self, project: Project, config) -> Iterator[Finding]:
        cfg = config.async_lifecycle
        if cfg is None:
            return
        for module in project.modules:
            yield from self._check_module(module, cfg)

    def _check_module(
        self, module: ModuleInfo, cfg
    ) -> Iterator[Finding]:
        for node in module.nodes:
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)

            # -- get_event_loop ban ----------------------------------------
            if dotted in ("asyncio.get_event_loop", "get_event_loop"):
                yield Finding.at(
                    module, node, self.id,
                    f"asyncio.get_event_loop() in {module.qualname(node)} "
                    "— outside a running loop this binds a dead loop that "
                    "never runs the task; use asyncio.get_running_loop() "
                    "so the failure is loud at the call site",
                )

            # -- fire-and-forget create_task -------------------------------
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "create_task"
            ) or (
                isinstance(node.func, ast.Name)
                and node.func.id == "create_task"
            ):
                parent = module.parent(node)
                if isinstance(parent, ast.Expr):
                    yield Finding.at(
                        module, node, self.id,
                        f"fire-and-forget create_task() in "
                        f"{module.qualname(node)} — the loop keeps only a "
                        "weak reference, so the task can be GC'd mid-"
                        "flight and its failure dropped; retain the "
                        "handle (attribute, await, gather, or "
                        "runtime/tasks.py::reap_task)",
                    )

            # -- blocking calls inside async def ---------------------------
            if dotted is None:
                continue
            blocking = dotted in cfg.blocking_calls or any(
                dotted.startswith(p) for p in cfg.blocking_prefixes
            )
            if not blocking:
                continue
            fn = _nearest_function(module, node)
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            qualname = module.qualname(fn)
            if (module.rel, qualname) in cfg.blocking_allowlist:
                continue
            yield Finding.at(
                module, node, self.id,
                f"blocking {dotted}() inside async def {qualname} — "
                "stalls the event loop for every request it serves; "
                "wrap it in run_in_executor or bless the boundary in "
                "AsyncLifecycleConfig.blocking_allowlist with a reason",
            )
