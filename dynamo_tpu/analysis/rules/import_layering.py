"""DYN009 import-layering: the declared layer DAG checked against the
module-level import graph.

The package layers bottom-up (``foundation`` = utils + the knob
registry, ``runtime`` core, the serving ``planes``, and the ``surface``
of deploy/cli/analysis). A module may import — at module level — only
from its own or a lower layer: an up-edge is how import cycles start
(the PR 7 incident: utils.logging pulling the runtime package in at
import time closed a cycle through metrics_core the moment utils was the
first entry into the tree).

Three checks:

* **Direction.** Every module-level intra-package import resolves to a
  target module; importing from a HIGHER layer is a finding. Imports
  under ``if TYPE_CHECKING:`` are annotations-only and exempt; imports
  inside function bodies are the sanctioned lazy pattern and exempt.
* **Cycles.** Strongly-connected components of the module-level import
  graph (same-layer edges are legal, so the DAG check alone cannot see
  them) — every genuine cycle is reported once, anchored at its
  lexicographically-first module.
* **Lazy obligations.** Known cycle seams that must STAY function-local
  imports, as config entries (importer, banned target, why). This turns
  the faults.py/metrics_core comment into a machine-checked invariant.

Resolution is static and conservative: ``from pkg.a.b import c`` tries
``a/b/c`` then ``a/b`` then ``a`` against the linted tree; names that
resolve to nothing in the tree (stdlib, third-party) create no edges.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from dynamo_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Project,
    Rule,
    register_rule,
)


def _is_type_checking_test(test: ast.AST) -> bool:
    if isinstance(test, ast.Name):
        return test.id == "TYPE_CHECKING"
    if isinstance(test, ast.Attribute):
        return test.attr == "TYPE_CHECKING"
    return False


def _dotted_of_rel(rel: str) -> str:
    """'runtime/discovery/file.py' -> 'runtime.discovery.file';
    package __init__ maps to the package path itself."""
    rel = rel[: -len(".py")]
    if rel.endswith("/__init__"):
        rel = rel[: -len("/__init__")]
    if rel == "__init__":
        rel = ""
    return rel.replace("/", ".")


class _Tree:
    """Dotted-name resolution over the linted tree."""

    def __init__(self, project: Project) -> None:
        self.by_dotted: Dict[str, str] = {
            _dotted_of_rel(m.rel): m.rel for m in project.modules
        }

    def resolve(self, dotted: str) -> Optional[str]:
        """Longest prefix of ``dotted`` that names a tree module."""
        while dotted:
            rel = self.by_dotted.get(dotted)
            if rel is not None:
                return rel
            if "." not in dotted:
                return None
            dotted = dotted.rsplit(".", 1)[0]
        return None


def _module_level_imports(
    module: ModuleInfo, tree: _Tree, package: str
) -> List[Tuple[str, ast.stmt]]:
    """(target rel path, import statement) for every module-level
    intra-package import — excluding function bodies (lazy imports) and
    ``if TYPE_CHECKING:`` blocks (annotations only)."""
    out: List[Tuple[str, ast.stmt]] = []
    pkg_prefix = package + "."
    for node in module.nodes:
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        skip = False
        for anc in module.ancestors(node):
            if isinstance(
                anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                skip = True  # lazy import: the sanctioned pattern
                break
            if isinstance(anc, ast.If) and _is_type_checking_test(anc.test):
                skip = True
                break
        if skip:
            continue
        targets: Set[str] = set()
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.name
                # Absolute imports (py3): only names under the package
                # are intra-package — a bare ``import grpc`` is the
                # third-party library even if a ``grpc/`` subpackage
                # exists in the tree.
                if not name.startswith(pkg_prefix):
                    continue
                rel = tree.resolve(name[len(pkg_prefix):])
                if rel is not None:
                    targets.add(rel)
        else:
            if node.level > 0:
                # Relative import: resolve against the importer's package.
                base_parts = module.rel.split("/")[:-1]
                up = node.level - 1
                if up:
                    base_parts = base_parts[: len(base_parts) - up]
                base = ".".join(base_parts)
            else:
                base = node.module or ""
                if base == package:
                    base = ""
                elif base.startswith(pkg_prefix):
                    base = base[len(pkg_prefix):]
                else:
                    continue  # absolute import of an external package
            if node.level > 0 and node.module:
                mod_dotted = (
                    f"{base}.{node.module}" if base else node.module
                )
            else:
                mod_dotted = base
            for alias in node.names:
                cand = (
                    f"{mod_dotted}.{alias.name}" if mod_dotted
                    else alias.name
                )
                rel = tree.resolve(cand)
                if rel is not None:
                    targets.add(rel)
        for rel in sorted(targets):
            if rel != module.rel:
                out.append((rel, node))
    return out


@register_rule
class ImportLayeringRule(Rule):
    id = "DYN009"
    title = "module-level imports respect the declared layer DAG"

    def check(self, project: Project, config) -> Iterator[Finding]:
        cfg = config.layering
        if cfg is None:
            return
        tree = _Tree(project)

        layer_of: Dict[str, Tuple[int, str]] = {}
        unmapped: List[ModuleInfo] = []
        for module in project.modules:
            assigned = None
            for idx, (name, prefixes) in enumerate(cfg.layers):
                for p in prefixes:
                    if (p.endswith("/") and module.rel.startswith(p)) or (
                        module.rel == p
                    ):
                        assigned = (idx, name)
                        break
                if assigned:
                    break
            if assigned is None:
                unmapped.append(module)
            else:
                layer_of[module.rel] = assigned
        for module in unmapped:
            yield Finding(
                rule=self.id,
                path=module.rel,
                line=1,
                message=(
                    "module mapped to no layer — extend "
                    "ImportLayeringConfig.layers so the DAG stays total"
                ),
            )

        obligations = {
            (imp, banned): why for imp, banned, why in cfg.lazy_obligations
        }
        edges: Dict[str, Set[str]] = {}
        first_stmt: Dict[Tuple[str, str], Tuple[ModuleInfo, ast.stmt]] = {}
        for module in project.modules:
            imports = _module_level_imports(module, tree, cfg.package)
            edges[module.rel] = {rel for rel, _ in imports}
            for rel, stmt in imports:
                first_stmt.setdefault((module.rel, rel), (module, stmt))

            for rel, stmt in imports:
                why = obligations.get((module.rel, rel))
                if why is not None:
                    yield Finding.at(
                        module, stmt, self.id,
                        f"module-level import of {rel} violates a lazy-"
                        f"import obligation — {why}. Import it inside the "
                        "function that needs it",
                    )
                src = layer_of.get(module.rel)
                dst = layer_of.get(rel)
                if src is None or dst is None:
                    continue
                if dst[0] > src[0]:
                    yield Finding.at(
                        module, stmt, self.id,
                        f"layer violation: {src[1]} module imports "
                        f"{dst[1]} module {rel} at module level — the "
                        f"DAG is {self._dag_str(cfg)}; invert the "
                        "dependency or make the import lazy",
                    )

        for cycle in self._cycles(edges):
            anchor = cycle[0]
            module = project.module(anchor)
            nxt = next(r for r in sorted(edges[anchor]) if r in set(cycle))
            _, stmt = first_stmt[(anchor, nxt)]
            yield Finding.at(
                module, stmt, self.id,
                "import cycle: " + " -> ".join(cycle + [anchor])
                + " — break it by inverting an edge or making one "
                "import lazy (and declaring the obligation in "
                "ImportLayeringConfig)",
            )

    @staticmethod
    def _dag_str(cfg) -> str:
        return " -> ".join(name for name, _ in cfg.layers)

    @staticmethod
    def _cycles(edges: Dict[str, Set[str]]) -> List[List[str]]:
        """SCCs with more than one member (iterative Tarjan), each
        rotated to start at its lexicographically-first module."""
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        for root in sorted(edges):
            if root in index:
                continue
            work: List[Tuple[str, Optional[str], List[str]]] = [
                (root, None, sorted(edges.get(root, ())))
            ]
            while work:
                v, parent, children = work[-1]
                if v not in index:
                    index[v] = low[v] = counter[0]
                    counter[0] += 1
                    stack.append(v)
                    on_stack.add(v)
                advanced = False
                while children:
                    w = children.pop(0)
                    if w not in edges:
                        continue
                    if w not in index:
                        work.append((w, v, sorted(edges.get(w, ()))))
                        advanced = True
                        break
                    if w in on_stack:
                        low[v] = min(low[v], index[w])
                if advanced:
                    continue
                work.pop()
                if parent is not None:
                    low[parent] = min(low[parent], low[v])
                if low[v] == index[v]:
                    comp: List[str] = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == v:
                            break
                    if len(comp) > 1:
                        comp.sort()
                        sccs.append(comp)
        return sorted(sccs)
