"""The nine dynlint passes. Importing this package registers them."""

from dynamo_tpu.analysis.rules import (  # noqa: F401
    async_lifecycle,
    fault_points,
    hot_path,
    import_layering,
    jit_discipline,
    knob_closure,
    metric_closure,
    ring_writers,
    silent_swallow,
)
