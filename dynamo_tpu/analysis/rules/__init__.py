"""The six dynlint passes. Importing this package registers them."""

from dynamo_tpu.analysis.rules import (  # noqa: F401
    fault_points,
    hot_path,
    jit_discipline,
    metric_closure,
    ring_writers,
    silent_swallow,
)
