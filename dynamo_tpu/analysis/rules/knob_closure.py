"""DYN008 config-knob closure: the DYN004/DYN006 mirror for
configuration.

Forward: every ``os.environ`` / ``os.getenv`` read of a ``DYN_TPU_*``
name outside the knob registry (``config.py``) is a finding — a literal
env-name string at a call site is a name the registry (and the generated
knob reference table in docs/design_docs/) can silently drift from. Read
through the registry constant's ``.get()`` instead: the default, the
parser, and the documentation then live in exactly one place.

Reverse: every knob declared in ``config.py::ALL_KNOBS`` must have at
least one reader — a reference to its registry constant somewhere else
in the package. A dead knob is documentation for behavior that quietly
stopped existing: operators set it and nothing changes.

Mirror of DYN004/DYN006 mechanics: the knobs module is loaded BY FILE
PATH (no package import) — it is dependency-free by design and the
linter must run without jax installed. Declared knobs are the entries of
``ALL_KNOBS`` (each carrying ``name`` / ``default`` / ``parser``);
module-level constants bound to those entries are the reader handles the
reverse check scans for.
"""

from __future__ import annotations

import ast
import importlib.util
import os
from typing import Dict, Iterator, Optional, Set

from dynamo_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Project,
    Rule,
    register_rule,
)


def _load_knobs_module(path: str):
    import sys

    spec = importlib.util.spec_from_file_location("_dynlint_knobs", path)
    mod = importlib.util.module_from_spec(spec)
    # The registry module defines dataclasses, whose machinery resolves
    # annotations through sys.modules[cls.__module__] — register for the
    # duration of exec, then drop (nothing should import "_dynlint_knobs").
    sys.modules["_dynlint_knobs"] = mod
    try:
        spec.loader.exec_module(mod)  # type: ignore[union-attr]
    finally:
        sys.modules.pop("_dynlint_knobs", None)
    return mod


def _env_read_name(node: ast.AST, cfg) -> Optional[str]:
    """The literal env-var name read by this node, if it is an
    environment read with a literal argument: ``os.environ.get("X")``,
    ``os.getenv("X")``, ``environ["X"]``. None otherwise."""
    if isinstance(node, ast.Call):
        fn = node.func
        attr = fn.attr if isinstance(fn, ast.Attribute) else None
        name = fn.id if isinstance(fn, ast.Name) else None
        is_environ_get = (
            attr == "get"
            and isinstance(fn, ast.Attribute)
            and isinstance(fn.value, (ast.Attribute, ast.Name))
            and (
                (
                    isinstance(fn.value, ast.Attribute)
                    and fn.value.attr in cfg.environ_names
                )
                or (
                    isinstance(fn.value, ast.Name)
                    and fn.value.id in cfg.environ_names
                )
            )
        )
        is_getenv = (attr in cfg.env_callables) or (name in cfg.env_callables)
        if (is_environ_get or is_getenv) and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                return arg.value
        return None
    if isinstance(node, ast.Subscript):
        base = node.value
        is_environ = (
            isinstance(base, ast.Attribute) and base.attr in cfg.environ_names
        ) or (isinstance(base, ast.Name) and base.id in cfg.environ_names)
        if is_environ:
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                return sl.value
    return None


@register_rule
class KnobClosureRule(Rule):
    id = "DYN008"
    title = "DYN_TPU_* env reads close over the config.py knob registry"

    def check(self, project: Project, config) -> Iterator[Finding]:
        cfg = config.knobs
        if cfg is None:
            return
        knobs_module = project.module(cfg.knobs_rel)
        if knobs_module is None:
            yield Finding(
                rule=self.id,
                path=cfg.knobs_rel,
                line=1,
                message="knob-registry module missing from the linted tree",
            )
            return
        try:
            knobs_mod = _load_knobs_module(
                os.path.join(project.root, cfg.knobs_rel)
            )
        except Exception as exc:
            yield Finding(
                rule=self.id,
                path=cfg.knobs_rel,
                line=1,
                message=(
                    f"knob-registry module failed to load ({exc!r}) — it "
                    "is executed by file path and must stay dependency-free"
                ),
            )
            return

        all_knobs = getattr(knobs_mod, "ALL_KNOBS", None)
        if not isinstance(all_knobs, tuple):
            yield Finding(
                rule=self.id,
                path=cfg.knobs_rel,
                line=1,
                message=(
                    "knob registry declares no ALL_KNOBS tuple — the "
                    "closure check needs the (name, default, parser) "
                    "entries pinned in one place"
                ),
            )
            return
        declared: Set[str] = {
            k.name
            for k in all_knobs
            if hasattr(k, "name") and isinstance(k.name, str)
        }
        # Registry constant name -> knob env name (reader handles).
        consts: Dict[str, str] = {
            attr: v.name
            for attr, v in vars(knobs_mod).items()
            if not attr.startswith("_")
            and hasattr(v, "name")
            and hasattr(v, "parser")
            and isinstance(getattr(v, "name"), str)
        }
        unbound = declared - set(consts.values())
        for env_name in sorted(unbound):
            yield Finding(
                rule=self.id,
                path=cfg.knobs_rel,
                line=1,
                message=(
                    f"knob {env_name!r} is in ALL_KNOBS but bound to no "
                    "module-level registry constant — readers have no "
                    "handle to reference"
                ),
            )

        read: Set[str] = set()
        for module in project.modules:
            if module.rel == cfg.knobs_rel:
                continue
            for node in module.nodes:
                env_name = _env_read_name(node, cfg)
                if env_name is not None and env_name.startswith(cfg.prefix):
                    yield Finding.at(
                        module, node, self.id,
                        f"ad-hoc environment read of {env_name!r} in "
                        f"{module.qualname(node)} — read through the "
                        "config.py knob registry (declare it there and "
                        "call <KNOB>.get()) so the name, default, and "
                        "parser cannot drift from the docs",
                    )
                # Reader tracking: any reference to a registry constant.
                if isinstance(node, ast.Name) and node.id in consts:
                    read.add(consts[node.id])
                elif isinstance(node, ast.Attribute) and node.attr in consts:
                    read.add(consts[node.attr])

        for env_name in sorted(declared - read - unbound):
            yield Finding(
                rule=self.id,
                path=cfg.knobs_rel,
                line=self._def_line(knobs_module, env_name),
                message=(
                    f"dead knob {env_name!r} — declared in the registry "
                    "but read nowhere; operators setting it change "
                    "nothing. Wire a reader or delete the declaration"
                ),
            )

    @staticmethod
    def _def_line(knobs_module: ModuleInfo, env_name: str) -> int:
        """Line of the declaration whose first call argument is the env
        name (``X = env_int("DYN_TPU_X", ...)``)."""
        for node in knobs_module.nodes:
            if (
                isinstance(node, ast.Call)
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == env_name
            ):
                return node.lineno
        return 1
