"""Encode worker service: the E stage of multimodal E/P/D.

Reference parity: components/src/dynamo/vllm/multimodal_handlers/
encode_worker_handler.py run as its own component. Frontends reach it via
MultimodalPreprocessor (handlers.py).

Usage:
  python -m dynamo_tpu.multimodal --namespace prod --llm-d-model 896
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu import config
from dynamo_tpu.multimodal.encoder import VisionEncoderConfig
from dynamo_tpu.multimodal.handlers import EncodeWorkerHandler
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.utils.logging import configure_logging


async def main() -> None:
    parser = argparse.ArgumentParser("dynamo-tpu encode worker (multimodal E stage)")
    parser.add_argument("--namespace", default=config.NAMESPACE.get())
    parser.add_argument("--component", default="encoder")
    parser.add_argument("--endpoint", default="encode")
    parser.add_argument("--clip-model", default=None,
                        help="HF CLIPVisionModel checkpoint directory "
                        "(real weights; overrides the --vit-* shape flags)")
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--patch-size", type=int, default=32)
    parser.add_argument("--vit-d-model", type=int, default=256)
    parser.add_argument("--vit-layers", type=int, default=2)
    parser.add_argument("--llm-d-model", type=int, required=True,
                        help="target LLM hidden size (embedding projection)")
    args = parser.parse_args()
    if args.image_size % args.patch_size != 0:
        parser.error(
            f"--image-size {args.image_size} must be divisible by "
            f"--patch-size {args.patch_size}"
        )
    n_heads = VisionEncoderConfig.n_heads
    if args.vit_d_model % n_heads != 0:
        parser.error(
            f"--vit-d-model {args.vit_d_model} must be divisible by "
            f"n_heads={n_heads}"
        )

    configure_logging()
    runtime = DistributedRuntime.from_settings()
    if args.clip_model:
        from dynamo_tpu.multimodal.encoder import load_clip_vision

        params, vcfg = load_clip_vision(args.clip_model, args.llm_d_model)
        handler = EncodeWorkerHandler(vcfg, params=params)
        print(f"loaded CLIP vision tower from {args.clip_model}", flush=True)
    else:
        handler = EncodeWorkerHandler(
            VisionEncoderConfig(
                image_size=args.image_size,
                patch_size=args.patch_size,
                d_model=args.vit_d_model,
                n_layers=args.vit_layers,
                out_dim=args.llm_d_model,
            )
        )
    endpoint = (
        runtime.namespace(args.namespace)
        .component(args.component)
        .endpoint(args.endpoint)
    )
    served = await endpoint.serve_endpoint(handler.generate)
    print(
        f"encode worker serving {args.namespace}/{args.component}/{args.endpoint} "
        f"({handler.config.n_patches} tokens/image)",
        flush=True,
    )
    try:
        await asyncio.Event().wait()
    finally:
        await served.shutdown(grace_period=config.GRACE_PERIOD.get())
        await runtime.shutdown(grace_period=config.GRACE_PERIOD.get())


if __name__ == "__main__":
    asyncio.run(main())
