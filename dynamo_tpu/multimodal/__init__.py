"""Multimodal serving: media decode, vision encode workers, E/P/D flow.

Reference parity: lib/llm/src/preprocessor/media/ (fetch+decode) and
components/src/dynamo/vllm/multimodal_handlers/ (EncodeWorkerHandler →
PD workers consuming precomputed embeddings). TPU-native: the vision
encoder is a jitted ViT (patch-embed matmul + small transformer) and
image embeddings splice into the LLM prefill via an embedding-override
path in forward_paged — no torch, no CUDA preprocessing.
"""

from dynamo_tpu.multimodal.encoder import (
    VisionEncoderConfig,
    encode_images,
    init_vision_params,
    load_clip_vision,
)
from dynamo_tpu.multimodal.handlers import (
    EncodeWorkerHandler,
    MultimodalPreprocessor,
)
from dynamo_tpu.multimodal.media import fetch_media

__all__ = [
    "VisionEncoderConfig",
    "encode_images",
    "init_vision_params",
    "load_clip_vision",
    "EncodeWorkerHandler",
    "MultimodalPreprocessor",
    "fetch_media",
]
