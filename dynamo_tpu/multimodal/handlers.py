"""E/P/D staged multimodal flow.

Reference parity: components/src/dynamo/vllm/multimodal_handlers/
(EncodeWorkerHandler :52 — vision tower as its own component;
PreprocessedHandler/worker_handler — P/D workers consuming precomputed
embeddings instead of raw media). The flow here:

  frontend → MultimodalPreprocessor operator
      extracts image parts from chat content,
      calls the encode component (EncodeWorkerHandler) over the runtime,
      replaces each image with `n_patches` placeholder tokens and attaches
      packed embeddings + positions to PreprocessedRequest.extra
  → P/D workers: JaxEngine splices the embeddings over the placeholder
      positions during prefill (models/llama.py embedding override).
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

import jax
import numpy as np

from dynamo_tpu.disagg.handlers import pack_array, unpack_array
from dynamo_tpu.multimodal.encoder import (
    VisionEncoderConfig,
    encode_images,
    init_vision_params,
)
from dynamo_tpu.multimodal.media import MediaError, fetch_media
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Placeholder token id spliced into prompts where image embeddings land.
# Real VLM checkpoints define their own (e.g. <image>); engines only ever
# see positions, so any in-vocab id works for random-init serving.
DEFAULT_IMAGE_TOKEN_ID = 0


class EncodeWorkerHandler:
    """The E stage: media URLs in, packed embeddings out.

    Serves ``{"media": [url, ...]}`` → one response
    ``{"embeddings": packed [N, n_patches, out_dim], "n_tokens": int}``.
    """

    def __init__(
        self,
        config: Optional[VisionEncoderConfig] = None,
        *,
        params: Optional[Any] = None,
        seed: int = 0,
    ) -> None:
        self.config = config or VisionEncoderConfig()
        self.params = (
            params
            if params is not None
            else init_vision_params(self.config, jax.random.PRNGKey(seed))
        )
        self.encoded_images = 0

    async def generate(self, request: Any, context: Any) -> AsyncIterator[Dict[str, Any]]:
        urls: List[str] = list(request.get("media", []))
        if not urls:
            yield {"error": "no media in request"}
            return
        try:
            images = np.stack(
                [fetch_media(u, image_size=self.config.image_size) for u in urls]
            )
        except MediaError as exc:
            yield {"error": str(exc)}
            return
        embeds = encode_images(self.params, images, self.config)
        self.encoded_images += len(urls)
        yield {
            "embeddings": pack_array(np.asarray(embeds, dtype=np.float32)),
            "n_tokens": self.config.n_patches,
        }


def extract_image_parts(messages: List[Dict[str, Any]]) -> Tuple[List[Dict[str, Any]], List[str]]:
    """Split OpenAI chat messages into (text-only messages, image URLs).

    Handles the standard content-parts form:
    ``{"type": "image_url", "image_url": {"url": ...}}`` mixed with text
    parts (ref: preprocessor media extraction).
    """
    urls: List[str] = []
    out: List[Dict[str, Any]] = []
    for msg in messages:
        content = msg.get("content")
        if not isinstance(content, list):
            out.append(msg)
            continue
        texts: List[str] = []
        for part in content:
            kind = part.get("type")
            if kind == "image_url":
                url = (part.get("image_url") or {}).get("url", "")
                urls.append(url)
                texts.append("<image>")
            elif kind == "text":
                texts.append(part.get("text", ""))
        out.append({**msg, "content": " ".join(texts)})
    return out, urls


class MultimodalPreprocessor:
    """Pipeline operator in front of OpenAIPreprocessor's output: encodes
    images via the encode component and splices placeholders + embeddings
    into the preprocessed request (the ECProcessor role)."""

    def __init__(
        self,
        encode_client_factory,  # async () -> Client for the encode endpoint
        *,
        image_token_id: int = DEFAULT_IMAGE_TOKEN_ID,
    ) -> None:
        self._factory = encode_client_factory
        self._client = None
        self.image_token_id = image_token_id

    async def _encode(self, urls: List[str]) -> Tuple[np.ndarray, int]:
        if self._client is None:
            self._client = await self._factory()
        result: Optional[Dict[str, Any]] = None
        async for item in self._client.generate({"media": urls}):
            result = item
        if not result or result.get("error"):
            raise RuntimeError(
                f"encode worker failed: {(result or {}).get('error', 'no response')}"
            )
        return unpack_array(result["embeddings"]), int(result["n_tokens"])

    async def generate(self, request: Any, context: Any, next: Any):
        """Operator protocol: enrich, then delegate downstream. Sits after
        the OpenAIPreprocessor (which extracts media URLs into extra)."""
        if isinstance(request, dict):
            urls = request.pop("_mm_media", None) or (
                request.get("extra", {}).pop("_mm_media", None)
            )
        else:
            urls = request.extra.pop("_mm_media", None) if request.extra else None
        if urls:
            embeds, n_tokens = await self._encode(list(urls))
            token_ids = (
                request["token_ids"] if isinstance(request, dict) else request.token_ids
            )
            # Append one placeholder run per image ahead of the text prompt
            # (simplest canonical layout; real VLM templates position them).
            positions = []
            prefix: List[int] = []
            for i in range(embeds.shape[0]):
                positions.append(len(prefix))
                prefix.extend([self.image_token_id] * n_tokens)
            new_ids = prefix + list(token_ids)
            extra = {
                "mm_embeds": pack_array(
                    embeds.reshape(-1, embeds.shape[-1]).astype(np.float32)
                ),
                "mm_positions": positions,
                "mm_tokens_per_image": n_tokens,
            }
            if isinstance(request, dict):
                request["token_ids"] = new_ids
                request.setdefault("extra", {}).update(extra)
            else:
                request.token_ids = new_ids
                request.extra.update(extra)
        async for item in next.generate(request, context):
            yield item
