"""Media fetch + decode → fixed-size RGB arrays.

Reference parity: lib/llm/src/preprocessor/media/{loader.rs,decoders} —
the reference fetches http(s)/data-URI media and decodes to tensors.
Zero-egress environment: data URIs and local paths are functional; http(s)
raises with guidance (deployments with egress can override the fetcher).
"""

from __future__ import annotations

import base64
import binascii
import io
import os
from typing import Tuple

import numpy as np


class MediaError(ValueError):
    """Bad media reference or undecodable payload."""


def fetch_media(url: str, *, image_size: int = 224) -> np.ndarray:
    """Resolve ``url`` to an RGB uint8 array [image_size, image_size, 3].

    Supports ``data:image/*;base64,...`` URIs and local file paths
    (``file://...`` or bare paths).
    """
    if url.startswith("data:"):
        try:
            _, b64 = url.split(",", 1)
            raw = base64.b64decode(b64, validate=True)
        except (ValueError, binascii.Error) as exc:
            raise MediaError(f"bad data URI: {exc}") from exc
        return _decode_image(raw, image_size)
    if url.startswith(("http://", "https://")):
        raise MediaError(
            "remote media fetch requires network egress; pass a data: URI "
            "or a local file path"
        )
    path = url[len("file://"):] if url.startswith("file://") else url
    if not os.path.exists(path):
        raise MediaError(f"no such media file: {path}")
    with open(path, "rb") as f:
        return _decode_image(f.read(), image_size)


def _decode_image(raw: bytes, image_size: int) -> np.ndarray:
    from PIL import Image, UnidentifiedImageError

    try:
        img = Image.open(io.BytesIO(raw)).convert("RGB")
    except UnidentifiedImageError as exc:
        raise MediaError(f"undecodable image payload: {exc}") from exc
    img = img.resize((image_size, image_size))
    return np.asarray(img, dtype=np.uint8)


def encode_image_data_uri(array: np.ndarray) -> str:
    """Inverse helper (tests/tools): RGB array → PNG data URI."""
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(array).save(buf, format="PNG")
    return "data:image/png;base64," + base64.b64encode(buf.getvalue()).decode()
