"""Jitted ViT vision encoder producing LLM-space image embeddings.

The encode-worker compute (ref: encode_worker_handler.py runs a vision
tower through vLLM); here it is a compact functional ViT: patch embedding
as one reshape+matmul (lands on the MXU), pre-norm transformer blocks, and
a projection to the language model's d_model. Weights are random-init until
real VLM checkpoints are mapped — the E/P/D flow, transport, and splice
are what this stage of the build exercises end-to-end.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class VisionEncoderConfig:
    image_size: int = 224
    patch_size: int = 32
    d_model: int = 256
    n_layers: int = 2
    n_heads: int = 4
    d_ff: int = 512
    out_dim: int = 128  # language model d_model

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * 3


def init_vision_params(config: VisionEncoderConfig, key: jax.Array) -> Dict[str, Any]:
    c = config
    keys = jax.random.split(key, 8)

    def norm(k, shape, scale):
        return jax.random.normal(k, shape, dtype=jnp.float32) * scale

    L, d = c.n_layers, c.d_model
    return {
        "patch_proj": norm(keys[0], (c.patch_dim, d), c.patch_dim**-0.5),
        "pos_embed": norm(keys[1], (c.n_patches, d), 0.02),
        "layers": {
            "norm1": jnp.ones((L, d)),
            "wqkv": norm(keys[2], (L, d, 3 * d), d**-0.5),
            "wo": norm(keys[3], (L, d, d), d**-0.5),
            "norm2": jnp.ones((L, d)),
            "w1": norm(keys[4], (L, d, c.d_ff), d**-0.5),
            "w2": norm(keys[5], (L, c.d_ff, d), c.d_ff**-0.5),
        },
        "final_norm": jnp.ones((d,)),
        "out_proj": norm(keys[6], (d, c.out_dim), d**-0.5),
    }


def _ln(x, w):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-6) * w


@functools.partial(jax.jit, static_argnums=(2,))
def encode_images(
    params: Dict[str, Any],
    images: jnp.ndarray,  # [N, H, W, 3] uint8
    config: VisionEncoderConfig,
) -> jnp.ndarray:
    """[N, n_patches, out_dim] image embeddings."""
    c = config
    N = images.shape[0]
    p = c.patch_size
    g = c.image_size // p
    x = images.astype(jnp.float32) / 127.5 - 1.0
    # [N, g, p, g, p, 3] → [N, g*g, p*p*3]: patchify as a reshape, then one
    # big matmul instead of a conv (identical math, simpler tiling).
    x = x.reshape(N, g, p, g, p, 3).transpose(0, 1, 3, 2, 4, 5)
    x = x.reshape(N, g * g, c.patch_dim)
    x = x @ params["patch_proj"] + params["pos_embed"]

    def block(x, lp):
        h = _ln(x, lp["norm1"])
        qkv = h @ lp["wqkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        hd = c.d_model // c.n_heads
        q = q.reshape(N, -1, c.n_heads, hd).transpose(0, 2, 1, 3)
        k = k.reshape(N, -1, c.n_heads, hd).transpose(0, 2, 1, 3)
        v = v.reshape(N, -1, c.n_heads, hd).transpose(0, 2, 1, 3)
        attn = jax.nn.softmax(q @ k.swapaxes(-1, -2) / hd**0.5, axis=-1)
        o = (attn @ v).transpose(0, 2, 1, 3).reshape(N, -1, c.d_model)
        x = x + o @ lp["wo"]
        h = _ln(x, lp["norm2"])
        x = x + jax.nn.gelu(h @ lp["w1"]) @ lp["w2"]
        return x, None

    x, _ = jax.lax.scan(block, x, params["layers"])
    x = _ln(x, params["final_norm"])
    return x @ params["out_proj"]
