"""Jitted CLIP-architecture vision encoder producing LLM-space embeddings.

The encode-worker compute (ref: components/src/dynamo/vllm/
multimodal_handlers/encode_worker_handler.py runs a vision tower through
vLLM); here it is a functional CLIP vision transformer — the architecture
real VLM checkpoints (LLaVA-style) ship — executed as one jitted program:
patch "conv" as reshape+matmul (identical math, lands on the MXU), class
token, pre-LN blocks with q/k/v/out biases and quick-GELU MLPs, final
post-LN, then a projection into the language model's embedding space.

``load_clip_vision`` maps a real HF CLIPVisionModel safetensors checkpoint
into this layout (parity-tested against transformers CPU in
tests/test_multimodal.py); ``init_vision_params`` random-inits the same
layout for shape-only tests and benches.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class VisionEncoderConfig:
    image_size: int = 224
    patch_size: int = 32
    d_model: int = 256
    n_layers: int = 2
    n_heads: int = 4
    d_ff: int = 512
    out_dim: int = 128  # language model d_model
    layer_norm_eps: float = 1e-5

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * 3

    @classmethod
    def from_hf_config(cls, cfg: Dict[str, Any], out_dim: int) -> "VisionEncoderConfig":
        v = cfg.get("vision_config", cfg)
        return cls(
            image_size=v["image_size"],
            patch_size=v["patch_size"],
            d_model=v["hidden_size"],
            n_layers=v["num_hidden_layers"],
            n_heads=v["num_attention_heads"],
            d_ff=v["intermediate_size"],
            out_dim=out_dim,
            layer_norm_eps=v.get("layer_norm_eps", 1e-5),
        )


def init_vision_params(config: VisionEncoderConfig, key: jax.Array) -> Dict[str, Any]:
    c = config
    keys = jax.random.split(key, 10)

    def norm(k, shape, scale):
        return jax.random.normal(k, shape, dtype=jnp.float32) * scale

    L, d = c.n_layers, c.d_model
    return {
        "patch_proj": norm(keys[0], (c.patch_dim, d), c.patch_dim**-0.5),
        "class_embed": norm(keys[7], (d,), 0.02),
        "pos_embed": norm(keys[1], (c.n_patches + 1, d), 0.02),
        "pre_norm": {"w": jnp.ones((d,)), "b": jnp.zeros((d,))},
        "layers": {
            "norm1_w": jnp.ones((L, d)), "norm1_b": jnp.zeros((L, d)),
            "wq": norm(keys[2], (L, d, d), d**-0.5), "bq": jnp.zeros((L, d)),
            "wk": norm(keys[8], (L, d, d), d**-0.5), "bk": jnp.zeros((L, d)),
            "wv": norm(keys[9], (L, d, d), d**-0.5), "bv": jnp.zeros((L, d)),
            "wo": norm(keys[3], (L, d, d), d**-0.5), "bo": jnp.zeros((L, d)),
            "norm2_w": jnp.ones((L, d)), "norm2_b": jnp.zeros((L, d)),
            "w1": norm(keys[4], (L, d, c.d_ff), d**-0.5),
            "b1": jnp.zeros((L, c.d_ff)),
            "w2": norm(keys[5], (L, c.d_ff, d), c.d_ff**-0.5),
            "b2": jnp.zeros((L, d)),
        },
        "post_norm": {"w": jnp.ones((d,)), "b": jnp.zeros((d,))},
        "out_proj": norm(keys[6], (d, c.out_dim), d**-0.5),
    }


def load_clip_vision(
    model_dir: str, out_dim: int, *,
    projector: Optional[np.ndarray] = None,
):
    """Map an HF CLIPVisionModel checkpoint → (params, config).

    ``projector``: optional [d_model, out_dim] multimodal projector (e.g.
    a LLaVA mm_projector weight); random when absent (the tower is still
    the real checkpoint — parity holds through post_norm).
    Ref name map: vision_model.embeddings.{patch_embedding.weight,
    class_embedding, position_embedding.weight}, pre_layrnorm (sic, the
    HF spelling), encoder.layers.N.{layer_norm1,self_attn.{q,k,v,out}_proj,
    layer_norm2,mlp.{fc1,fc2}}, post_layernorm.
    """
    import json

    from dynamo_tpu.models.hf_loader import _SafetensorsReader

    with open(os.path.join(model_dir, "config.json")) as f:
        cfg_json = json.load(f)
    config = VisionEncoderConfig.from_hf_config(cfg_json, out_dim)
    r = _SafetensorsReader(model_dir)

    def get(name: str) -> np.ndarray:
        for prefix in ("vision_model.", "model.vision_model.", ""):
            if prefix + name in r:
                return np.asarray(r.get(prefix + name), dtype=np.float32)
        raise KeyError(name)

    L, d = config.n_layers, config.d_model
    # Patch conv [d, 3, p, p] → matmul weight [p*p*3, d] matching the
    # patchify reshape below ([p, p, 3] row-major per patch).
    conv = get("embeddings.patch_embedding.weight")  # [d, 3, p, p]
    patch_proj = conv.transpose(2, 3, 1, 0).reshape(config.patch_dim, d)

    def stack(fmt: str, transpose: bool = False):
        arrs = [get(fmt.format(i)) for i in range(L)]
        if transpose:
            arrs = [a.T for a in arrs]
        return jnp.asarray(np.stack(arrs))

    params = {
        "patch_proj": jnp.asarray(patch_proj),
        "class_embed": jnp.asarray(get("embeddings.class_embedding")),
        "pos_embed": jnp.asarray(get("embeddings.position_embedding.weight")),
        "pre_norm": {
            "w": jnp.asarray(get("pre_layrnorm.weight")),
            "b": jnp.asarray(get("pre_layrnorm.bias")),
        },
        "layers": {
            "norm1_w": stack("encoder.layers.{}.layer_norm1.weight"),
            "norm1_b": stack("encoder.layers.{}.layer_norm1.bias"),
            "wq": stack("encoder.layers.{}.self_attn.q_proj.weight", True),
            "bq": stack("encoder.layers.{}.self_attn.q_proj.bias"),
            "wk": stack("encoder.layers.{}.self_attn.k_proj.weight", True),
            "bk": stack("encoder.layers.{}.self_attn.k_proj.bias"),
            "wv": stack("encoder.layers.{}.self_attn.v_proj.weight", True),
            "bv": stack("encoder.layers.{}.self_attn.v_proj.bias"),
            "wo": stack("encoder.layers.{}.self_attn.out_proj.weight", True),
            "bo": stack("encoder.layers.{}.self_attn.out_proj.bias"),
            "norm2_w": stack("encoder.layers.{}.layer_norm2.weight"),
            "norm2_b": stack("encoder.layers.{}.layer_norm2.bias"),
            "w1": stack("encoder.layers.{}.mlp.fc1.weight", True),
            "b1": stack("encoder.layers.{}.mlp.fc1.bias"),
            "w2": stack("encoder.layers.{}.mlp.fc2.weight", True),
            "b2": stack("encoder.layers.{}.mlp.fc2.bias"),
        },
        "post_norm": {
            "w": jnp.asarray(get("post_layernorm.weight")),
            "b": jnp.asarray(get("post_layernorm.bias")),
        },
        "out_proj": (
            jnp.asarray(np.asarray(projector, dtype=np.float32))
            if projector is not None
            else init_vision_params(config, jax.random.PRNGKey(0))["out_proj"]
        ),
    }
    return params, config


def _ln(x, w, b, eps):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * w + b


def _quick_gelu(x):
    return x * jax.nn.sigmoid(1.702 * x)


def _encode_images_impl(
    params: Dict[str, Any],
    images: jnp.ndarray,  # [N, H, W, 3] uint8 or pre-normalized float
    config: VisionEncoderConfig,
    raw_hidden: bool = False,  # True → post-norm hidden states (parity)
) -> jnp.ndarray:
    """[N, n_patches, out_dim] LLM-space patch embeddings (class token
    dropped, LLaVA-style), or [N, n_patches+1, d_model] with
    ``raw_hidden`` (the CLIPVisionModel last_hidden_state for parity).

    Jitted + watched as ``encode_images`` below (DYN001: a decorator jit
    is invisible to /debug/compiles)."""
    c = config
    N = images.shape[0]
    p = c.patch_size
    g = c.image_size // p
    eps = c.layer_norm_eps
    x = images.astype(jnp.float32)
    if images.dtype == jnp.uint8:
        x = x / 127.5 - 1.0
    # [N, g, p, g, p, 3] → [N, g*g, p*p*3]: patchify as a reshape, then one
    # big matmul instead of a conv (identical math, simpler tiling).
    x = x.reshape(N, g, p, g, p, 3).transpose(0, 1, 3, 2, 4, 5)
    x = x.reshape(N, g * g, c.patch_dim)
    x = x @ params["patch_proj"]
    cls = jnp.broadcast_to(params["class_embed"], (N, 1, c.d_model))
    x = jnp.concatenate([cls, x], axis=1) + params["pos_embed"]
    x = _ln(x, params["pre_norm"]["w"], params["pre_norm"]["b"], eps)

    T = c.n_patches + 1
    hd = c.d_model // c.n_heads

    def block(x, lp):
        h = _ln(x, lp["norm1_w"], lp["norm1_b"], eps)
        q = h @ lp["wq"] + lp["bq"]
        k = h @ lp["wk"] + lp["bk"]
        v = h @ lp["wv"] + lp["bv"]
        q = q.reshape(N, T, c.n_heads, hd).transpose(0, 2, 1, 3)
        k = k.reshape(N, T, c.n_heads, hd).transpose(0, 2, 1, 3)
        v = v.reshape(N, T, c.n_heads, hd).transpose(0, 2, 1, 3)
        attn = jax.nn.softmax(q @ k.swapaxes(-1, -2) * hd**-0.5, axis=-1)
        o = (attn @ v).transpose(0, 2, 1, 3).reshape(N, T, c.d_model)
        x = x + o @ lp["wo"] + lp["bo"]
        h = _ln(x, lp["norm2_w"], lp["norm2_b"], eps)
        x = x + _quick_gelu(h @ lp["w1"] + lp["b1"]) @ lp["w2"] + lp["b2"]
        return x, None

    x, _ = jax.lax.scan(block, x, params["layers"])
    # NOTE: HF CLIPVisionModel.last_hidden_state is BEFORE post_layernorm
    # (it only normalizes the pooled CLS token), and LLaVA's projector also
    # consumes pre-post-LN hidden states — match both. post_norm weights
    # stay loaded for pooled-embedding use.
    if raw_hidden:
        return x
    return x[:, 1:] @ params["out_proj"]  # patches only, LLM space


from dynamo_tpu.runtime.device_observe import watched_jit  # noqa: E402

# Signatures track distinct [N, H, W] image batch shapes; the media
# pipeline normalizes to one resolution, so the default budget holds.
encode_images = watched_jit(
    "multimodal.encode_images",
    functools.partial(jax.jit, static_argnums=(2, 3))(_encode_images_impl),
)
