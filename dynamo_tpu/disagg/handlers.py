"""Worker-side disaggregation handlers.

Reference parity: components/src/dynamo/vllm/handlers.py
(PrefillWorkerHandler :1469, DecodeWorkerHandler :1254) re-designed around
content-addressed KV blocks instead of NIXL descriptors.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

import numpy as np

from dynamo_tpu import config
from dynamo_tpu.disagg.errors import DisaggTransferError, classify_failure
from dynamo_tpu.disagg.wire import (
    WIRE_VERSION,
    KvWireBlocks,
    pack_array,
    pack_kv,
    reply_wire_nbytes,
    unpack_array,
    unpack_reply,
    wire_block_bytes,
)
from dynamo_tpu.llm.protocols.common import (
    BackendOutput,
    DisaggregatedParams,
    FinishReason,
    PreprocessedRequest,
)
from dynamo_tpu.runtime import fault_names, lifecycle, trajectory
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.device_observe import FlightRecorder
from dynamo_tpu.runtime.faults import fault_point, note_activity
from dynamo_tpu.runtime.liveness import (
    StaleIncarnationError,
    note_stale_drop,
    process_incarnation,
)
from dynamo_tpu.tokens.blocks import compute_block_hashes
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tracing import export_span

logger = get_logger(__name__)

# Wire dtypes a v2 importer can install (every cell of the interop matrix
# lands in engine.import_blocks_wire_async). Advertised in the pull
# request's ``wire.accept`` so exporters ship pool-native.
ACCEPT_WIRE_DTYPES = ("int8", "bfloat16", "float32", "float16")

# EWMA weight for per-(src, dst) observed transfer bandwidth. One pull is a
# noisy sample (chunking, event-loop contention); 0.25 converges in a few
# pulls without letting one outlier swing the router's link-cost view.
LINK_BW_EWMA_ALPHA = 0.25

# Forget a source's bandwidth after this long without a pull from it.
# Without the TTL, a departed prefill worker's entry would be republished
# in every load report FOREVER — resurrecting the pairs the scheduler's
# remove_worker purged and leaking dead-worker gauge series.
LINK_BW_TTL_S = 600.0

# -- self-healing pull knobs (env-overridable; ctor args win) ----------------
# Bounded retry: attempts per pull (1 = the old single-shot behavior).
PULL_MAX_ATTEMPTS = config.PULL_ATTEMPTS.get()
# Exponential backoff between attempts: base × 2^(attempt-1), capped.
PULL_BACKOFF_BASE_S = config.PULL_BACKOFF_S.get()
PULL_BACKOFF_CAP_S = 2.0
# Per-ATTEMPT timeout when the request carries no deadline; with a
# deadline, each attempt gets min(this, time remaining) so a dead wire
# can never eat the whole request budget.
PULL_DEFAULT_TIMEOUT_S = config.PULL_TIMEOUT_S.get()
# Circuit breaker: consecutive pull failures from one src before the
# (src → this worker) pair opens, and how long it stays priced out of
# placement before the next pull is admitted as the half-open probe.
BREAKER_OPEN_AFTER = config.BREAKER_OPEN_AFTER.get()
BREAKER_COOLDOWN_S = config.BREAKER_COOLDOWN_S.get()


class CircuitBreaker:
    """Per-(src prefill worker) pull breaker.

    closed → open after ``open_after`` consecutive failures; open →
    half_open when ``allow()`` is first called after ``cooldown_s`` (that
    caller IS the probe; concurrent pulls fail fast until it resolves);
    half_open → closed on probe success, → open (fresh cooldown) on probe
    failure. ``advertised()`` is True only while open AND inside the
    cooldown window — that is the interval load reports carry the src in
    ``link_faults`` so the router prices the pair out of disagg placement;
    after the window the pair becomes placeable again and the first pull
    probes it.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(
        self,
        open_after: int = BREAKER_OPEN_AFTER,
        cooldown_s: float = BREAKER_COOLDOWN_S,
        *,
        clock=time.monotonic,
        on_transition=None,  # (old_state, new_state) -> None
    ) -> None:
        self.open_after = open_after
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._on_transition = on_transition
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self.opened_at = 0.0

    def _transition(self, new_state: str) -> None:
        if new_state == self.state:
            return
        old, self.state = self.state, new_state
        if new_state == self.OPEN:
            self.opened_at = self._clock()
        if self._on_transition is not None:
            self._on_transition(old, new_state)

    def allow(self) -> bool:
        """May a pull from this src proceed right now?"""
        if self.state == self.CLOSED:
            return True
        if self.state == self.OPEN:
            if self._clock() - self.opened_at >= self.cooldown_s:
                self._transition(self.HALF_OPEN)
                return True  # this caller is the probe
            return False
        return False  # half-open: a probe is already in flight

    def record_success(self) -> None:
        self.consecutive_failures = 0
        self._transition(self.CLOSED)

    def abort_probe(self) -> None:
        """The half-open probe was cancelled without resolving (client
        disconnect mid-pull): return to OPEN with a fresh cooldown.
        Without this the breaker wedges in HALF_OPEN forever — allow()
        never admits another probe and advertised() never prices the
        pair out. Not a failure: cancellation says nothing about the
        link, so the consecutive count is untouched."""
        if self.state == self.HALF_OPEN:
            self._transition(self.OPEN)

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        if self.state == self.HALF_OPEN or (
            self.state == self.CLOSED
            and self.consecutive_failures >= self.open_after
        ):
            self._transition(self.OPEN)

    def advertised(self) -> bool:
        return (
            self.state == self.OPEN
            and self._clock() - self.opened_at < self.cooldown_s
        )


def _engine_wire_dtype(engine: Any) -> str:
    """Pool-native wire dtype tag of an engine's KV pool."""
    if getattr(engine.args, "kv_cache_dtype", None) == "int8":
        return "int8"
    return str(np.dtype(engine.args.config.dtype).name)


def _engine_wire_block_bytes(engine: Any, wire_dtype: str) -> int:
    """Per-block wire bytes of an engine's export (k+v, scales included)."""
    cfg = engine.args.config
    return wire_block_bytes(
        cfg.n_layers, engine.args.block_size, cfg.n_kv_heads, cfg.head_dim_,
        wire_dtype,
    )


class DisaggMetrics:
    """Canonical disagg transfer families (runtime/metric_names.py
    ALL_DISAGG). One instance per DecodeHandler; ``render`` plugs into the
    system server's ``register_metrics`` seam. The handler's plain counters
    (``transfers``/``transfer_failures``/…) stay — tests and the aggregate
    rate math read them — these are their scrapeable form."""

    def __init__(self) -> None:
        from dynamo_tpu.runtime import metric_names as mn
        from dynamo_tpu.runtime.metrics_core import MetricsRegistry

        self.registry = MetricsRegistry()
        self.transfers = self.registry.counter(
            mn.DISAGG_TRANSFERS_TOTAL, "KV pulls from prefill workers"
        )
        self.transfer_failures = self.registry.counter(
            mn.DISAGG_TRANSFER_FAILURES_TOTAL,
            "Failed KV pull attempts by classified kind (timeout vs "
            "connection vs decode). An attempt that exhausts retries IS "
            "the 2x-cost path: a second full local prefill",
            ["error_kind"],
        )
        self.pull_retries = self.registry.counter(
            mn.DISAGG_PULL_RETRIES_TOTAL,
            "Retried pull attempts (anchor-resume: only the not-yet-"
            "imported tail re-rides the wire)",
        )
        self.breaker_transitions = self.registry.counter(
            mn.DISAGG_BREAKER_TRANSITIONS_TOTAL,
            "Per-src circuit-breaker transitions; an open breaker is "
            "advertised in load reports and prices the (src, this "
            "worker) pair out of disagg placement",
            ["src", "to"],
        )
        self.breaker_open = self.registry.gauge(
            mn.DISAGG_BREAKER_OPEN,
            "1 while the pull breaker for a src prefill worker is open",
            ["src"],
        )
        self.blocks_pulled = self.registry.counter(
            mn.DISAGG_BLOCKS_PULLED_TOTAL, "KV blocks imported from prefill"
        )
        self.bytes_pulled = self.registry.counter(
            mn.DISAGG_BYTES_PULLED_TOTAL, "KV bytes pulled over the wire"
        )
        self.kv_wire_bytes = self.registry.counter(
            mn.DISAGG_KV_WIRE_BYTES_TOTAL,
            "Serialized KV payload bytes pulled, by wire dtype — int8 vs "
            "dense is THE transfer-bound disagg lever",
            ["dtype"],
        )
        self.transfer_duration = self.registry.histogram(
            mn.DISAGG_TRANSFER_DURATION,
            "Wall time of one KV pull (request-scoped, chunks included)",
        )
        self.link_bandwidth = self.registry.gauge(
            mn.DISAGG_LINK_BANDWIDTH,
            "EWMA of observed KV transfer bandwidth per (src prefill "
            "worker, dst decode worker) pair — the router's link-cost "
            "input",
            ["src", "dst"],
        )
        self._link_source = None
        self._dst_label = "local"
        self._link_srcs: set = set()
        self._breaker_source = None
        self._breaker_srcs: set = set()
        self.registry.on_render(self._sample_links)
        self.registry.on_render(self._sample_breakers)

    def watch_links(self, bandwidth_fn, dst_label: str) -> None:
        """Sample ``bandwidth_fn()`` (src worker id → bytes/s EWMA) into
        the per-pair gauge at scrape time; series for sources that aged
        out of the EWMA table are dropped."""
        self._link_source = bandwidth_fn
        self._dst_label = dst_label

    def watch_breakers(self, states_fn) -> None:
        """Sample ``states_fn()`` (src worker id → CircuitBreaker) into the
        per-src open gauge at scrape time; departed srcs drop."""
        self._breaker_source = states_fn

    def _sample_links(self) -> None:
        if self._link_source is None:
            return
        live = set()
        for src, bw in self._link_source().items():
            label = str(src)
            live.add(label)
            self.link_bandwidth.set(bw, src=label, dst=self._dst_label)
        for gone in self._link_srcs - live:
            self.link_bandwidth.remove(src=gone, dst=self._dst_label)
        self._link_srcs = live

    def _sample_breakers(self) -> None:
        if self._breaker_source is None:
            return
        live = set()
        for src, breaker in self._breaker_source().items():
            label = str(src)
            live.add(label)
            self.breaker_open.set(
                0 if breaker.state == CircuitBreaker.CLOSED else 1, src=label
            )
        for gone in self._breaker_srcs - live:
            self.breaker_open.remove(src=gone)
        self._breaker_srcs = live

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics=openmetrics)


class PrefillHandler:
    """Serve a prefill worker: compute prompt KV + first token, return
    bootstrap metadata (ref: PrefillWorkerHandler.generate handlers.py:1498)."""

    def __init__(self, engine: Any, worker_id: int) -> None:
        self._engine = engine
        self.worker_id = worker_id

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[BackendOutput]:
        req = (
            request
            if isinstance(request, PreprocessedRequest)
            else PreprocessedRequest.from_dict(dict(request))
        )
        prompt = list(req.token_ids)
        block_size = self._engine.args.block_size
        hashes = compute_block_hashes(prompt, block_size)
        prefill_req = PreprocessedRequest.from_dict(req.to_dict())
        prefill_req.stop.max_tokens = 1
        prefill_req.stop.min_tokens = None
        prefill_req.stop.ignore_eos = True

        first: Optional[BackendOutput] = None
        async for out in self._engine.generate(prefill_req, context):
            if out.error:
                yield out
                return
            if out.token_ids:
                first = out
                break
        if first is None:
            yield BackendOutput(
                error="prefill produced no token", finish_reason=FinishReason.ERROR
            )
            return
        wire_dtype = _engine_wire_dtype(self._engine)
        yield BackendOutput(
            token_ids=first.token_ids,
            logprobs=first.logprobs,
            cumulative_tokens=1,
            disaggregated_params=DisaggregatedParams(
                worker_id=self.worker_id,
                prefilled_tokens=len(prompt),
                kv_transfer={
                    "block_hashes": hashes,
                    "block_size": block_size,
                    "first_token": first.token_ids[0],
                    # Incarnation fencing: the decode worker's pull only
                    # trusts replies stamped with THIS incarnation — a
                    # restarted prefill worker no longer holds the
                    # promised blocks, and a zombie's pool is stale.
                    "incarnation": process_incarnation(),
                    # Transfer-cost inputs for link-aware decode placement
                    # (router/scheduler.py TransferContext): what one
                    # overlap-miss block costs on the wire from THIS worker.
                    "wire_dtype": wire_dtype,
                    "block_bytes": _engine_wire_block_bytes(
                        self._engine, wire_dtype
                    ),
                },
            ),
            finish_reason=FinishReason.LENGTH,
        )


# Target size of one streamed KV chunk. Bounds the host-memory spike and
# the serialization stall of a transfer (a 70B-class prompt's KV is
# hundreds of MB — as ONE message it blocks the event loop and doubles
# peak host memory; as ~8 MB chunks it pipelines: the exporter gathers
# chunk N+1 while chunk N is on the wire and the importer scatters chunk
# N-1, and the importer's engine keeps serving decode ticks between
# chunks). Ref: the reference streams device-direct chunked/overlapped
# (lib/llm/src/block_manager/block/transfer/cuda.rs:1, lib/memory/src/nixl/).
KV_CHUNK_BYTES = config.KV_CHUNK_BYTES.get()


class KvTransferHandler:
    """Serve content-addressed KV block export (the 'kv' side-channel
    endpoint; plays the role of the NIXL read target).

    Streams the payload as bounded chunks: each reply message carries
    ≤ ~KV_CHUNK_BYTES of blocks plus ``done`` on the final message. Device
    gathers happen per chunk, so HBM→host readback overlaps the previous
    chunk's network write instead of spiking once."""

    def __init__(self, engine: Any, chunk_bytes: Optional[int] = None) -> None:
        self._engine = engine
        self.chunk_bytes = chunk_bytes or KV_CHUNK_BYTES

    def _negotiate_wire_dtype(self, request: Any) -> Optional[str]:
        """Wire dtype this reply ships, or None for the v1 dense schema.

        A request without a ``wire`` envelope comes from a v1 importer:
        answer in the v1 shape (dense ``k``/``v``, int8 pools dequantized)
        so old decode workers keep interoperating. A v2 importer gets the
        pool-native form unless its ``accept`` list vetoes it — then the
        exporter ships a dense dtype the importer DID list (for any pool
        form, not just int8), falling back to the pool's dense dtype when
        the accept list names nothing we can produce."""
        wire_req = request.get("wire") or {}
        if int(wire_req.get("version") or 1) < WIRE_VERSION:
            return None
        native = _engine_wire_dtype(self._engine)
        accept = wire_req.get("accept")
        if not accept or native in accept:
            return native
        for cand in ("bfloat16", "float32", "float16"):
            if cand in accept:
                return cand
        return (
            str(np.dtype(self._engine.args.config.dtype).name)
            if native == "int8" else native
        )

    def _blocks_per_chunk(self, wire_dtype: Optional[str] = None) -> int:
        """Chunk sizing by the bytes THIS reply actually ships: v1 replies
        (wire_dtype None) densify int8 pools to the v1 bf16 wire, so they
        must be sized by the dense block, not the pool-native one."""
        if wire_dtype is None:
            wire_dtype = (
                "bfloat16" if _engine_wire_dtype(self._engine) == "int8"
                else str(np.dtype(self._engine.args.config.dtype).name)
            )
        block_bytes = _engine_wire_block_bytes(self._engine, wire_dtype)
        return max(1, self.chunk_bytes // max(block_bytes, 1))

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        hashes: List[int] = list(request.get("block_hashes") or [])
        wire_dtype = self._negotiate_wire_dtype(request)
        per = self._blocks_per_chunk(wire_dtype)
        # Every reply chunk carries the exporter's incarnation so the
        # importer can fence a zombie/restarted exporter's payload.
        inc = process_incarnation()
        sent_any = False
        for off in range(0, len(hashes), per):
            chunk = hashes[off : off + per]
            # Chaos seam: an export failing mid-stream kills this reply
            # stream; the puller classifies it and retries from its last
            # imported anchor.
            fault_point(fault_names.DISAGG_KV_EXPORT, off=off)
            if wire_dtype is None:
                # v1 importer: dense k/v fields.
                found, k, v = await self._engine.export_blocks_async(chunk)
                if not found:
                    break  # chain broken (evicted): stop at last good run
                sent_any = True
                done = off + per >= len(hashes) or len(found) < len(chunk)
                yield {
                    "found": found,
                    "k": pack_array(k),
                    "v": pack_array(v),
                    "done": done,
                    "inc": inc,
                }
            else:
                found, wire = await self._engine.export_blocks_wire_async(chunk)
                if not found:
                    break
                if wire.dtype != wire_dtype:
                    # negotiated down: ship the dense dtype the importer
                    # accepted (dequant or cast)
                    wire = KvWireBlocks.dense(*wire.to_dense(wire_dtype))
                sent_any = True
                done = off + per >= len(hashes) or len(found) < len(chunk)
                yield {"found": found, "kv": pack_kv(wire), "done": done,
                       "inc": inc}
            if len(found) < len(chunk):
                return
        if not sent_any:
            yield {"found": [], "kv": None, "k": None, "v": None,
                   "done": True, "inc": inc}


class DecodeHandler:
    """Serve a decode worker: import transferred KV (if the request carries
    disaggregated_params), then generate normally — prefix-cached admission
    picks up the imported blocks (ref: DecodeWorkerHandler handlers.py:1254)."""

    def __init__(
        self, engine: Any, kv_client_factory=None,
        *, worker_id: Optional[int] = None,
        fallback_local_prefill: bool = True,
        pull_attempts: Optional[int] = None,
        pull_timeout_s: Optional[float] = None,
        breaker_open_after: Optional[int] = None,
        breaker_cooldown_s: Optional[float] = None,
        backoff_base_s: Optional[float] = None,
    ) -> None:
        self._engine = engine
        # async () -> Client for the prefill component's "kv" endpoint
        self._kv_client_factory = kv_client_factory
        self._kv_client = None
        # This worker's identity — the ``dst`` of every (src prefill
        # worker, dst decode worker) bandwidth pair it measures.
        self.worker_id = worker_id
        # Strict disagg: with fallback disabled, a terminally-failed pull
        # raises DisaggTransferError (MIGRATABLE) instead of silently
        # re-prefilling — the frontend re-dispatches to another worker.
        self.fallback_local_prefill = fallback_local_prefill
        self.pull_attempts = pull_attempts or PULL_MAX_ATTEMPTS
        self.pull_timeout_s = pull_timeout_s or PULL_DEFAULT_TIMEOUT_S
        self.backoff_base_s = (
            PULL_BACKOFF_BASE_S if backoff_base_s is None else backoff_base_s
        )
        self._breaker_open_after = breaker_open_after or BREAKER_OPEN_AFTER
        self._breaker_cooldown_s = breaker_cooldown_s or BREAKER_COOLDOWN_S
        # Observability for the fallback path: a transfer failure silently
        # converting into a second full prefill is a 2× cost bug that MUST
        # be visible in metrics (r3 review finding).
        self.transfers = 0
        self.transfer_failures = 0
        self.transfer_failures_by_kind: Dict[str, int] = {}
        self.pull_retries = 0
        self.pull_fallbacks = 0  # pulls that gave up (the real 2× path)
        self.breaker_opens = 0
        self.blocks_pulled = 0
        self.bytes_pulled = 0
        # Serialized KV payload bytes by wire dtype (the kv_wire_bytes_total
        # counter's host-side mirror; bench reads this).
        self.wire_bytes_by_dtype: Dict[str, int] = {}
        self.transfer_seconds = 0.0  # summed per-pull elapsed (can overlap)
        # Window edges for aggregate-rate math: concurrent pulls overlap,
        # so bytes / (last_end - first_start) is the honest achieved rate
        # while summed per-pull seconds would understate it.
        self.transfer_first_start = 0.0
        self.transfer_last_end = 0.0
        # src prefill worker id → (EWMA pull bandwidth B/s, last-pull
        # monotonic). Seeds the router's link-cost model via load reports
        # (router/publisher.py link_bandwidth_fn); entries not refreshed
        # within LINK_BW_TTL_S age out so a departed prefill worker stops
        # being republished (and can't resurrect scheduler-purged pairs).
        self._link_bw: Dict[int, Tuple[float, float]] = {}
        # src prefill worker id → CircuitBreaker over pulls from it.
        self._breakers: Dict[int, CircuitBreaker] = {}
        # Retry/breaker history for post-mortems. Single writer: every
        # record happens on the handler's event loop (DYN005 owner
        # "disagg").
        self.flight = FlightRecorder("disagg", capacity=512)
        self.metrics = DisaggMetrics()
        self.metrics.watch_links(
            self.link_bandwidth,
            str(worker_id) if worker_id is not None else "local",
        )
        self.metrics.watch_breakers(lambda: dict(self._breakers))

    def link_bandwidth(self) -> Dict[int, float]:
        """src prefill worker id → EWMA observed transfer bandwidth, B/s
        (sources without a pull in LINK_BW_TTL_S are pruned)."""
        now = time.monotonic()
        self._link_bw = {
            src: (bw, at) for src, (bw, at) in self._link_bw.items()
            if now - at < LINK_BW_TTL_S
        }
        return {src: bw for src, (bw, _) in self._link_bw.items()}

    def _observe_link(self, src: int, nbytes: int, seconds: float) -> None:
        if nbytes <= 0 or seconds <= 0:
            return
        bw = nbytes / seconds
        prev = self._link_bw.get(src)
        self._link_bw[src] = (
            bw if prev is None
            else LINK_BW_EWMA_ALPHA * bw + (1 - LINK_BW_EWMA_ALPHA) * prev[0],
            time.monotonic(),
        )

    def register_metrics(self, server: Any) -> None:
        """Expose this handler's transfer families on a SystemStatusServer."""
        server.register_metrics(self.metrics.render)
        server.register_flight(self.flight.name, self.flight.snapshot)

    # -- circuit breaker ----------------------------------------------------

    def _breaker_for(self, src: int) -> CircuitBreaker:
        breaker = self._breakers.get(src)
        if breaker is None:
            def on_transition(old: str, new: str, _src=src) -> None:
                self.flight.record(
                    "breaker", src=_src, frm=old, to=new,
                )
                self.metrics.breaker_transitions.inc(src=str(_src), to=new)
                if new == CircuitBreaker.OPEN:
                    self.breaker_opens += 1
                    note_activity("breaker_opens")

            breaker = CircuitBreaker(
                self._breaker_open_after, self._breaker_cooldown_s,
                on_transition=on_transition,
            )
            self._breakers[src] = breaker
        return breaker

    def open_breaker_srcs(self) -> List[int]:
        """src prefill worker ids whose breaker is inside its open window —
        published in load reports (LoadSnapshot.link_faults) so the
        router's LinkCostModel prices the (src, this worker) pair out of
        disagg placement until the half-open probe window. Non-int keys
        (a bootstrap that omitted worker_id breakers under None) are not
        publishable as link pairs and are excluded — the router could
        neither normalize nor match them."""
        return sorted(
            src for src, b in self._breakers.items()
            if isinstance(src, int) and b.advertised()
        )

    def _first_missing(self, hashes: List[int]) -> Optional[int]:
        """Index of the first block NOT resident in the pool, or None when
        the whole chain is already installed. Recomputed before every
        attempt: blocks committed by a failed attempt stay committed, so a
        retry resumes from the last imported anchor instead of re-pulling
        (anchor-resume — the wire only ever carries the missing tail)."""
        pool = self._engine.pool
        for i, h in enumerate(hashes):
            if not pool.contains(h):
                return i
        return None

    def _attempt_timeout(self, context: Optional[Context]) -> Optional[float]:
        """Per-attempt wall budget: the configured timeout, shrunk to the
        request's remaining Context deadline when it carries one."""
        remaining = context.time_remaining() if context is not None else None
        if remaining is None:
            return self.pull_timeout_s
        return min(self.pull_timeout_s, remaining)

    async def _pull_once(
        self,
        want: List[int],
        anchor: Optional[int],
        src: Optional[int],
        acct: Dict[str, int],
        expect_inc: Optional[int] = None,
    ) -> None:
        """One pull attempt over the missing tail. Chunked: each reply is a
        bounded slice, imported as it lands — device scatters and the
        decode loop's ticks interleave with the next chunk's network read
        instead of waiting for one monolithic payload. Wire bytes are
        accounted at RECEIPT (a chunk that lands but fails to import still
        crossed the network — the accounting the anchor-resume tests
        assert), blocks at successful import. Progress accumulates into
        ``acct`` IN PLACE (not a return value): a raising attempt's
        partial imports/bytes must survive into the pull's totals, and
        ``self.bytes_pulled`` deltas can't be used — concurrent pulls
        would attribute each other's bytes to their own link."""
        if self._kv_client is None:
            self._kv_client = await self._kv_client_factory()
        async for reply in self._kv_client.direct(
            {
                "op": "export",
                "block_hashes": want,
                # Schema v2 negotiation: ship pool-native (int8 stays
                # int8 on the wire); v1 exporters ignore this and reply
                # dense.
                "wire": {
                    "version": WIRE_VERSION,
                    "accept": list(ACCEPT_WIRE_DTYPES),
                },
            }, src
        ):
            # Incarnation fence: the bootstrap named the incarnation that
            # computed (and promised) these blocks. A reply stamped with
            # any OTHER incarnation — a zombie's late chunks, or a
            # restarted exporter whose pool no longer holds them — is
            # counted and dropped, never scattered into our pool.
            reply_inc = reply.get("inc")
            if (
                expect_inc and reply_inc is not None
                and reply_inc != expect_inc
            ):
                note_stale_drop("pull_reply")
                raise StaleIncarnationError(
                    f"KV pull reply from prefill worker {src} carries "
                    f"incarnation {reply_inc}, bootstrap promised "
                    f"{expect_inc} — the worker restarted; re-prefill"
                )
            found = reply.get("found") or []
            wire = unpack_reply(reply)
            if not found or wire is None:
                break
            chunk_bytes = reply_wire_nbytes(reply)
            acct["bytes"] += chunk_bytes
            self.bytes_pulled += chunk_bytes
            self.wire_bytes_by_dtype[wire.dtype] = (
                self.wire_bytes_by_dtype.get(wire.dtype, 0) + chunk_bytes
            )
            self.metrics.bytes_pulled.inc(chunk_bytes)
            self.metrics.kv_wire_bytes.inc(chunk_bytes, dtype=wire.dtype)
            # Chaos seams: the wire dying with this chunk received but not
            # imported, and the import (device scatter) itself failing.
            fault_point(fault_names.DISAGG_PULL_CHUNK, src=src)
            fault_point(fault_names.DISAGG_KV_IMPORT, src=src)
            n = await self._engine.import_blocks_wire_async(
                found, wire, anchor_parent=anchor
            )
            acct["blocks"] += n
            self.blocks_pulled += n
            self.metrics.blocks_pulled.inc(n)
            if n < len(found):
                # Pool dry mid-chunk: anchoring later chunks on an
                # uninstalled hash would commit children whose parent
                # never committed (pool invariant) and every further
                # chunk would transfer + scatter into a full pool.
                logger.warning(
                    "KV pool dry after importing %d/%d blocks of a "
                    "chunk; stopping the pull early", n, len(found),
                )
                break
            anchor = found[-1]
            if reply.get("done", True):
                break

    async def _pull_blocks(
        self,
        dp: DisaggregatedParams,
        context: Optional[Context] = None,
        trace_id: Optional[str] = None,
    ) -> int:
        info = dp.kv_transfer or {}
        hashes = list(info.get("block_hashes") or [])
        if not hashes or self._kv_client_factory is None:
            return 0
        # Skip blocks already resident (earlier transfer or shared prefix).
        if self._first_missing(hashes) is None:
            return 0
        src = dp.worker_id
        expect_inc = info.get("incarnation")
        breaker = self._breaker_for(src)
        if not breaker.allow():
            # Fail fast: the (src → me) link is open-circuit. No wire time
            # is spent; either re-prefill locally or hand the stream back
            # for migration to a worker with a working link.
            self.flight.record("pull_rejected", src=src, state=breaker.state)
            self.pull_fallbacks += 1
            if not self.fallback_local_prefill:
                raise DisaggTransferError(
                    f"pull breaker for prefill worker {src} is "
                    f"{breaker.state}; local prefill fallback disabled"
                )
            return 0
        self.transfers += 1
        self.metrics.transfers.inc()
        t0 = time.monotonic()
        if not self.transfer_first_start:
            self.transfer_first_start = t0
        self.flight.record("pull_start", src=src, blocks=len(hashes))
        # Trajectory span events: each retry/terminal failure is stamped
        # onto the pull span so the stitched view shows WHERE the
        # kv_transfer phase's time went (attempt boundaries, error kinds).
        span_events: List[Dict[str, Any]] = []
        # Per-PULL progress, mutated inside _pull_once so a raising
        # attempt's partial imports survive, and isolated from concurrent
        # pulls (which share self.bytes_pulled).
        acct = {"blocks": 0, "bytes": 0}
        last_error: Optional[BaseException] = None
        attempt = 0
        while True:
            attempt += 1
            missing_from = self._first_missing(hashes)
            if missing_from is None:
                break  # everything landed
            want = hashes[missing_from:]
            # The block the next chunk chains from: the last resident
            # block before the missing run (imports from the FAILED
            # attempt included — that is the resume point).
            anchor = hashes[missing_from - 1] if missing_from > 0 else None
            timeout = self._attempt_timeout(context)
            try:
                if timeout is not None and timeout <= 0:
                    raise asyncio.TimeoutError(
                        "request deadline exhausted before the pull"
                    )
                await asyncio.wait_for(
                    self._pull_once(want, anchor, src, acct, expect_inc),
                    timeout,
                )
                breaker.record_success()
                break
            except asyncio.CancelledError:
                # Cancellation resolves nothing about the link: if this
                # attempt was the half-open probe, hand the breaker back
                # to OPEN (a wedged HALF_OPEN admits no further probes).
                breaker.abort_probe()
                raise
            except Exception as exc:
                kind = classify_failure(exc)
                last_error = exc
                self.transfer_failures += 1
                self.transfer_failures_by_kind[kind] = (
                    self.transfer_failures_by_kind.get(kind, 0) + 1
                )
                self.metrics.transfer_failures.inc(error_kind=kind)
                breaker.record_failure()
                self.flight.record(
                    "pull_error", src=src, attempt=attempt,
                    error_kind=kind, error=f"{type(exc).__name__}: {exc}",
                )
                remaining = (
                    context.time_remaining() if context is not None else None
                )
                if (
                    attempt >= self.pull_attempts
                    or not breaker.allow()
                    or (remaining is not None and remaining <= 0)
                ):
                    logger.exception(
                        "KV pull from prefill worker %s failed terminally "
                        "(%s, attempt %d/%d) after %d blocks",
                        src, kind, attempt, self.pull_attempts,
                        acct["blocks"],
                    )
                    break
                self.pull_retries += 1
                self.metrics.pull_retries.inc()
                note_activity("pull_retries")
                span_events.append({
                    "name": f"retry:{kind}", "time_s": time.time(),
                })
                trajectory.note_event(
                    trace_id, "disagg", "pull_retry",
                    src=src, attempt=attempt, error_kind=kind,
                )
                delay = min(
                    self.backoff_base_s * 2 ** (attempt - 1),
                    PULL_BACKOFF_CAP_S,
                )
                if remaining is not None:
                    delay = min(delay, remaining)
                logger.warning(
                    "KV pull from prefill worker %s failed (%s, attempt "
                    "%d/%d); resuming from anchor after %d imported blocks "
                    "in %.3fs",
                    src, kind, attempt, self.pull_attempts,
                    acct["blocks"], delay,
                )
                if delay > 0:
                    await asyncio.sleep(delay)
        now = time.monotonic()
        self.transfer_seconds += now - t0
        self.transfer_last_end = now
        # Per-(src, dst) bandwidth: this pull's achieved rate feeds the
        # EWMA the router's link-cost model consumes via load reports.
        self._observe_link(src, acct["bytes"], now - t0)
        # Exemplar: a transfer-latency spike on a dashboard resolves to the
        # trace (and thus the /debug/requests timeline) that caused it.
        self.metrics.transfer_duration.observe(now - t0, trace_id=trace_id)
        pull_ok = last_error is None or self._first_missing(hashes) is None
        self.flight.record(
            "pull_done", src=src, blocks=acct["blocks"],
            bytes=acct["bytes"], attempts=attempt, ok=pull_ok,
        )
        if trace_id:
            # Trajectory kv_transfer phase span: the whole pull — retries
            # and backoff included — attributed in the stitched view.
            export_span(
                "disagg.pull", context,
                start_mono=t0, end_mono=now,
                proc=(
                    f"worker-{self.worker_id:#x}"
                    if isinstance(self.worker_id, int) else None
                ),
                status="ok" if pull_ok else "error: pull_failed",
                events=span_events,
                src=src, blocks=acct["blocks"], bytes=acct["bytes"],
                attempts=attempt, retries=attempt - 1,
            )
        if last_error is not None and self._first_missing(hashes) is not None:
            # Terminal failure: the chain is still incomplete.
            self.pull_fallbacks += 1
            if not self.fallback_local_prefill:
                raise DisaggTransferError(
                    f"KV pull from prefill worker {src} failed after "
                    f"{attempt} attempt(s): {last_error!r}; local prefill "
                    "fallback disabled"
                ) from last_error
            logger.warning(
                "decoding with local prefill after failed pull from "
                "worker %s (fallback #%d — a recurring fallback means "
                "every request pays prefill TWICE)",
                src, self.pull_fallbacks,
            )
        return acct["blocks"]

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[BackendOutput]:
        req = (
            request
            if isinstance(request, PreprocessedRequest)
            else PreprocessedRequest.from_dict(dict(request))
        )
        if req.disaggregated_params is not None:
            t0 = time.monotonic()
            pulled = await self._pull_blocks(
                req.disaggregated_params,
                context=context,
                trace_id=lifecycle.trace_id_of(context),
            )
            lifecycle.record(
                req.request_id, "kv_transfer",
                context=context,
                blocks=pulled,
                worker=req.disaggregated_params.worker_id,
                duration_ms=round((time.monotonic() - t0) * 1000, 3),
            )
            if pulled:
                logger.info(
                    "imported %d KV blocks from prefill worker %s",
                    pulled, req.disaggregated_params.worker_id,
                )
        async for out in self._engine.generate(req, context):
            yield out
