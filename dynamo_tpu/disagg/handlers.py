"""Worker-side disaggregation handlers.

Reference parity: components/src/dynamo/vllm/handlers.py
(PrefillWorkerHandler :1469, DecodeWorkerHandler :1254) re-designed around
content-addressed KV blocks instead of NIXL descriptors.
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Dict, List, Optional

import numpy as np

from dynamo_tpu.llm.protocols.common import (
    BackendOutput,
    DisaggregatedParams,
    FinishReason,
    PreprocessedRequest,
)
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.tokens.blocks import compute_block_hashes
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def pack_array(a) -> Dict[str, Any]:
    arr = np.asarray(a)
    return {"b": arr.tobytes(), "shape": list(arr.shape), "dtype": str(arr.dtype)}


def unpack_array(d: Dict[str, Any]) -> np.ndarray:
    import ml_dtypes  # noqa: F401  (registers bfloat16 with numpy)

    return np.frombuffer(d["b"], dtype=np.dtype(d["dtype"])).reshape(d["shape"])


class PrefillHandler:
    """Serve a prefill worker: compute prompt KV + first token, return
    bootstrap metadata (ref: PrefillWorkerHandler.generate handlers.py:1498)."""

    def __init__(self, engine: Any, worker_id: int) -> None:
        self._engine = engine
        self.worker_id = worker_id

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[BackendOutput]:
        req = (
            request
            if isinstance(request, PreprocessedRequest)
            else PreprocessedRequest.from_dict(dict(request))
        )
        prompt = list(req.token_ids)
        block_size = self._engine.args.block_size
        hashes = compute_block_hashes(prompt, block_size)
        prefill_req = PreprocessedRequest.from_dict(req.to_dict())
        prefill_req.stop.max_tokens = 1
        prefill_req.stop.min_tokens = None
        prefill_req.stop.ignore_eos = True

        first: Optional[BackendOutput] = None
        async for out in self._engine.generate(prefill_req, context):
            if out.error:
                yield out
                return
            if out.token_ids:
                first = out
                break
        if first is None:
            yield BackendOutput(
                error="prefill produced no token", finish_reason=FinishReason.ERROR
            )
            return
        yield BackendOutput(
            token_ids=first.token_ids,
            logprobs=first.logprobs,
            cumulative_tokens=1,
            disaggregated_params=DisaggregatedParams(
                worker_id=self.worker_id,
                prefilled_tokens=len(prompt),
                kv_transfer={
                    "block_hashes": hashes,
                    "block_size": block_size,
                    "first_token": first.token_ids[0],
                },
            ),
            finish_reason=FinishReason.LENGTH,
        )


class KvTransferHandler:
    """Serve content-addressed KV block export (the 'kv' side-channel
    endpoint; plays the role of the NIXL read target)."""

    def __init__(self, engine: Any) -> None:
        self._engine = engine

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        hashes: List[int] = list(request.get("block_hashes") or [])
        found, k, v = await self._engine.export_blocks_async(hashes)
        if not found:
            yield {"found": [], "k": None, "v": None}
            return
        yield {"found": found, "k": pack_array(k), "v": pack_array(v)}


class DecodeHandler:
    """Serve a decode worker: import transferred KV (if the request carries
    disaggregated_params), then generate normally — prefix-cached admission
    picks up the imported blocks (ref: DecodeWorkerHandler handlers.py:1254)."""

    def __init__(self, engine: Any, kv_client_factory=None) -> None:
        self._engine = engine
        # async () -> Client for the prefill component's "kv" endpoint
        self._kv_client_factory = kv_client_factory
        self._kv_client = None

    async def _pull_blocks(self, dp: DisaggregatedParams) -> int:
        info = dp.kv_transfer or {}
        hashes = list(info.get("block_hashes") or [])
        if not hashes or self._kv_client_factory is None:
            return 0
        # Skip blocks already resident (earlier transfer or shared prefix).
        missing_from = 0
        pool = self._engine.pool
        for i, h in enumerate(hashes):
            if not pool.contains(h):
                missing_from = i
                break
        else:
            return 0
        want = hashes[missing_from:]
        if self._kv_client is None:
            self._kv_client = await self._kv_client_factory()
        try:
            async for reply in self._kv_client.direct(
                {"op": "export", "block_hashes": want}, dp.worker_id
            ):
                if not reply.get("found"):
                    return 0
                k = unpack_array(reply["k"])
                v = unpack_array(reply["v"])
                return await self._engine.import_blocks_async(reply["found"], k, v)
        except Exception:
            logger.exception(
                "KV pull from prefill worker %s failed; decoding with local prefill",
                dp.worker_id,
            )
        return 0

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[BackendOutput]:
        req = (
            request
            if isinstance(request, PreprocessedRequest)
            else PreprocessedRequest.from_dict(dict(request))
        )
        if req.disaggregated_params is not None:
            pulled = await self._pull_blocks(req.disaggregated_params)
            if pulled:
                logger.info(
                    "imported %d KV blocks from prefill worker %s",
                    pulled, req.disaggregated_params.worker_id,
                )
        async for out in self._engine.generate(req, context):
            yield out
