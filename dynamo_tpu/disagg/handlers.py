"""Worker-side disaggregation handlers.

Reference parity: components/src/dynamo/vllm/handlers.py
(PrefillWorkerHandler :1469, DecodeWorkerHandler :1254) re-designed around
content-addressed KV blocks instead of NIXL descriptors.
"""

from __future__ import annotations

import os
import time
from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

import numpy as np

from dynamo_tpu.disagg.wire import (
    WIRE_VERSION,
    KvWireBlocks,
    pack_array,
    pack_kv,
    reply_wire_nbytes,
    unpack_array,
    unpack_reply,
    wire_block_bytes,
)
from dynamo_tpu.llm.protocols.common import (
    BackendOutput,
    DisaggregatedParams,
    FinishReason,
    PreprocessedRequest,
)
from dynamo_tpu.runtime import lifecycle
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.tokens.blocks import compute_block_hashes
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Wire dtypes a v2 importer can install (every cell of the interop matrix
# lands in engine.import_blocks_wire_async). Advertised in the pull
# request's ``wire.accept`` so exporters ship pool-native.
ACCEPT_WIRE_DTYPES = ("int8", "bfloat16", "float32", "float16")

# EWMA weight for per-(src, dst) observed transfer bandwidth. One pull is a
# noisy sample (chunking, event-loop contention); 0.25 converges in a few
# pulls without letting one outlier swing the router's link-cost view.
LINK_BW_EWMA_ALPHA = 0.25

# Forget a source's bandwidth after this long without a pull from it.
# Without the TTL, a departed prefill worker's entry would be republished
# in every load report FOREVER — resurrecting the pairs the scheduler's
# remove_worker purged and leaking dead-worker gauge series.
LINK_BW_TTL_S = 600.0


def _engine_wire_dtype(engine: Any) -> str:
    """Pool-native wire dtype tag of an engine's KV pool."""
    if getattr(engine.args, "kv_cache_dtype", None) == "int8":
        return "int8"
    return str(np.dtype(engine.args.config.dtype).name)


def _engine_wire_block_bytes(engine: Any, wire_dtype: str) -> int:
    """Per-block wire bytes of an engine's export (k+v, scales included)."""
    cfg = engine.args.config
    return wire_block_bytes(
        cfg.n_layers, engine.args.block_size, cfg.n_kv_heads, cfg.head_dim_,
        wire_dtype,
    )


class DisaggMetrics:
    """Canonical disagg transfer families (runtime/metric_names.py
    ALL_DISAGG). One instance per DecodeHandler; ``render`` plugs into the
    system server's ``register_metrics`` seam. The handler's plain counters
    (``transfers``/``transfer_failures``/…) stay — tests and the aggregate
    rate math read them — these are their scrapeable form."""

    def __init__(self) -> None:
        from dynamo_tpu.runtime import metric_names as mn
        from dynamo_tpu.runtime.metrics_core import MetricsRegistry

        self.registry = MetricsRegistry()
        self.transfers = self.registry.counter(
            mn.DISAGG_TRANSFERS_TOTAL, "KV pulls from prefill workers"
        )
        self.transfer_failures = self.registry.counter(
            mn.DISAGG_TRANSFER_FAILURES_TOTAL,
            "Failed KV pulls — each one IS the 2x-cost path: the decode "
            "worker falls back to a second full local prefill",
        )
        self.blocks_pulled = self.registry.counter(
            mn.DISAGG_BLOCKS_PULLED_TOTAL, "KV blocks imported from prefill"
        )
        self.bytes_pulled = self.registry.counter(
            mn.DISAGG_BYTES_PULLED_TOTAL, "KV bytes pulled over the wire"
        )
        self.kv_wire_bytes = self.registry.counter(
            mn.DISAGG_KV_WIRE_BYTES_TOTAL,
            "Serialized KV payload bytes pulled, by wire dtype — int8 vs "
            "dense is THE transfer-bound disagg lever",
            ["dtype"],
        )
        self.transfer_duration = self.registry.histogram(
            mn.DISAGG_TRANSFER_DURATION,
            "Wall time of one KV pull (request-scoped, chunks included)",
        )
        self.link_bandwidth = self.registry.gauge(
            mn.DISAGG_LINK_BANDWIDTH,
            "EWMA of observed KV transfer bandwidth per (src prefill "
            "worker, dst decode worker) pair — the router's link-cost "
            "input",
            ["src", "dst"],
        )
        self._link_source = None
        self._dst_label = "local"
        self._link_srcs: set = set()
        self.registry.on_render(self._sample_links)

    def watch_links(self, bandwidth_fn, dst_label: str) -> None:
        """Sample ``bandwidth_fn()`` (src worker id → bytes/s EWMA) into
        the per-pair gauge at scrape time; series for sources that aged
        out of the EWMA table are dropped."""
        self._link_source = bandwidth_fn
        self._dst_label = dst_label

    def _sample_links(self) -> None:
        if self._link_source is None:
            return
        live = set()
        for src, bw in self._link_source().items():
            label = str(src)
            live.add(label)
            self.link_bandwidth.set(bw, src=label, dst=self._dst_label)
        for gone in self._link_srcs - live:
            self.link_bandwidth.remove(src=gone, dst=self._dst_label)
        self._link_srcs = live

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics=openmetrics)


class PrefillHandler:
    """Serve a prefill worker: compute prompt KV + first token, return
    bootstrap metadata (ref: PrefillWorkerHandler.generate handlers.py:1498)."""

    def __init__(self, engine: Any, worker_id: int) -> None:
        self._engine = engine
        self.worker_id = worker_id

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[BackendOutput]:
        req = (
            request
            if isinstance(request, PreprocessedRequest)
            else PreprocessedRequest.from_dict(dict(request))
        )
        prompt = list(req.token_ids)
        block_size = self._engine.args.block_size
        hashes = compute_block_hashes(prompt, block_size)
        prefill_req = PreprocessedRequest.from_dict(req.to_dict())
        prefill_req.stop.max_tokens = 1
        prefill_req.stop.min_tokens = None
        prefill_req.stop.ignore_eos = True

        first: Optional[BackendOutput] = None
        async for out in self._engine.generate(prefill_req, context):
            if out.error:
                yield out
                return
            if out.token_ids:
                first = out
                break
        if first is None:
            yield BackendOutput(
                error="prefill produced no token", finish_reason=FinishReason.ERROR
            )
            return
        wire_dtype = _engine_wire_dtype(self._engine)
        yield BackendOutput(
            token_ids=first.token_ids,
            logprobs=first.logprobs,
            cumulative_tokens=1,
            disaggregated_params=DisaggregatedParams(
                worker_id=self.worker_id,
                prefilled_tokens=len(prompt),
                kv_transfer={
                    "block_hashes": hashes,
                    "block_size": block_size,
                    "first_token": first.token_ids[0],
                    # Transfer-cost inputs for link-aware decode placement
                    # (router/scheduler.py TransferContext): what one
                    # overlap-miss block costs on the wire from THIS worker.
                    "wire_dtype": wire_dtype,
                    "block_bytes": _engine_wire_block_bytes(
                        self._engine, wire_dtype
                    ),
                },
            ),
            finish_reason=FinishReason.LENGTH,
        )


# Target size of one streamed KV chunk. Bounds the host-memory spike and
# the serialization stall of a transfer (a 70B-class prompt's KV is
# hundreds of MB — as ONE message it blocks the event loop and doubles
# peak host memory; as ~8 MB chunks it pipelines: the exporter gathers
# chunk N+1 while chunk N is on the wire and the importer scatters chunk
# N-1, and the importer's engine keeps serving decode ticks between
# chunks). Ref: the reference streams device-direct chunked/overlapped
# (lib/llm/src/block_manager/block/transfer/cuda.rs:1, lib/memory/src/nixl/).
KV_CHUNK_BYTES = int(os.environ.get("DYN_TPU_KV_CHUNK_BYTES", 8 << 20))


class KvTransferHandler:
    """Serve content-addressed KV block export (the 'kv' side-channel
    endpoint; plays the role of the NIXL read target).

    Streams the payload as bounded chunks: each reply message carries
    ≤ ~KV_CHUNK_BYTES of blocks plus ``done`` on the final message. Device
    gathers happen per chunk, so HBM→host readback overlaps the previous
    chunk's network write instead of spiking once."""

    def __init__(self, engine: Any, chunk_bytes: Optional[int] = None) -> None:
        self._engine = engine
        self.chunk_bytes = chunk_bytes or KV_CHUNK_BYTES

    def _negotiate_wire_dtype(self, request: Any) -> Optional[str]:
        """Wire dtype this reply ships, or None for the v1 dense schema.

        A request without a ``wire`` envelope comes from a v1 importer:
        answer in the v1 shape (dense ``k``/``v``, int8 pools dequantized)
        so old decode workers keep interoperating. A v2 importer gets the
        pool-native form unless its ``accept`` list vetoes it — then the
        exporter ships a dense dtype the importer DID list (for any pool
        form, not just int8), falling back to the pool's dense dtype when
        the accept list names nothing we can produce."""
        wire_req = request.get("wire") or {}
        if int(wire_req.get("version") or 1) < WIRE_VERSION:
            return None
        native = _engine_wire_dtype(self._engine)
        accept = wire_req.get("accept")
        if not accept or native in accept:
            return native
        for cand in ("bfloat16", "float32", "float16"):
            if cand in accept:
                return cand
        return (
            str(np.dtype(self._engine.args.config.dtype).name)
            if native == "int8" else native
        )

    def _blocks_per_chunk(self, wire_dtype: Optional[str] = None) -> int:
        """Chunk sizing by the bytes THIS reply actually ships: v1 replies
        (wire_dtype None) densify int8 pools to the v1 bf16 wire, so they
        must be sized by the dense block, not the pool-native one."""
        if wire_dtype is None:
            wire_dtype = (
                "bfloat16" if _engine_wire_dtype(self._engine) == "int8"
                else str(np.dtype(self._engine.args.config.dtype).name)
            )
        block_bytes = _engine_wire_block_bytes(self._engine, wire_dtype)
        return max(1, self.chunk_bytes // max(block_bytes, 1))

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        hashes: List[int] = list(request.get("block_hashes") or [])
        wire_dtype = self._negotiate_wire_dtype(request)
        per = self._blocks_per_chunk(wire_dtype)
        sent_any = False
        for off in range(0, len(hashes), per):
            chunk = hashes[off : off + per]
            if wire_dtype is None:
                # v1 importer: dense k/v fields.
                found, k, v = await self._engine.export_blocks_async(chunk)
                if not found:
                    break  # chain broken (evicted): stop at last good run
                sent_any = True
                done = off + per >= len(hashes) or len(found) < len(chunk)
                yield {
                    "found": found,
                    "k": pack_array(k),
                    "v": pack_array(v),
                    "done": done,
                }
            else:
                found, wire = await self._engine.export_blocks_wire_async(chunk)
                if not found:
                    break
                if wire.dtype != wire_dtype:
                    # negotiated down: ship the dense dtype the importer
                    # accepted (dequant or cast)
                    wire = KvWireBlocks.dense(*wire.to_dense(wire_dtype))
                sent_any = True
                done = off + per >= len(hashes) or len(found) < len(chunk)
                yield {"found": found, "kv": pack_kv(wire), "done": done}
            if len(found) < len(chunk):
                return
        if not sent_any:
            yield {"found": [], "kv": None, "k": None, "v": None, "done": True}


class DecodeHandler:
    """Serve a decode worker: import transferred KV (if the request carries
    disaggregated_params), then generate normally — prefix-cached admission
    picks up the imported blocks (ref: DecodeWorkerHandler handlers.py:1254)."""

    def __init__(
        self, engine: Any, kv_client_factory=None,
        *, worker_id: Optional[int] = None,
    ) -> None:
        self._engine = engine
        # async () -> Client for the prefill component's "kv" endpoint
        self._kv_client_factory = kv_client_factory
        self._kv_client = None
        # This worker's identity — the ``dst`` of every (src prefill
        # worker, dst decode worker) bandwidth pair it measures.
        self.worker_id = worker_id
        # Observability for the fallback path: a transfer failure silently
        # converting into a second full prefill is a 2× cost bug that MUST
        # be visible in metrics (r3 review finding).
        self.transfers = 0
        self.transfer_failures = 0
        self.blocks_pulled = 0
        self.bytes_pulled = 0
        # Serialized KV payload bytes by wire dtype (the kv_wire_bytes_total
        # counter's host-side mirror; bench reads this).
        self.wire_bytes_by_dtype: Dict[str, int] = {}
        self.transfer_seconds = 0.0  # summed per-pull elapsed (can overlap)
        # Window edges for aggregate-rate math: concurrent pulls overlap,
        # so bytes / (last_end - first_start) is the honest achieved rate
        # while summed per-pull seconds would understate it.
        self.transfer_first_start = 0.0
        self.transfer_last_end = 0.0
        # src prefill worker id → (EWMA pull bandwidth B/s, last-pull
        # monotonic). Seeds the router's link-cost model via load reports
        # (router/publisher.py link_bandwidth_fn); entries not refreshed
        # within LINK_BW_TTL_S age out so a departed prefill worker stops
        # being republished (and can't resurrect scheduler-purged pairs).
        self._link_bw: Dict[int, Tuple[float, float]] = {}
        self.metrics = DisaggMetrics()
        self.metrics.watch_links(
            self.link_bandwidth,
            str(worker_id) if worker_id is not None else "local",
        )

    def link_bandwidth(self) -> Dict[int, float]:
        """src prefill worker id → EWMA observed transfer bandwidth, B/s
        (sources without a pull in LINK_BW_TTL_S are pruned)."""
        now = time.monotonic()
        self._link_bw = {
            src: (bw, at) for src, (bw, at) in self._link_bw.items()
            if now - at < LINK_BW_TTL_S
        }
        return {src: bw for src, (bw, _) in self._link_bw.items()}

    def _observe_link(self, src: int, nbytes: int, seconds: float) -> None:
        if nbytes <= 0 or seconds <= 0:
            return
        bw = nbytes / seconds
        prev = self._link_bw.get(src)
        self._link_bw[src] = (
            bw if prev is None
            else LINK_BW_EWMA_ALPHA * bw + (1 - LINK_BW_EWMA_ALPHA) * prev[0],
            time.monotonic(),
        )

    def register_metrics(self, server: Any) -> None:
        """Expose this handler's transfer families on a SystemStatusServer."""
        server.register_metrics(self.metrics.render)

    async def _pull_blocks(
        self, dp: DisaggregatedParams, trace_id: Optional[str] = None
    ) -> int:
        info = dp.kv_transfer or {}
        hashes = list(info.get("block_hashes") or [])
        if not hashes or self._kv_client_factory is None:
            return 0
        # Skip blocks already resident (earlier transfer or shared prefix).
        missing_from = 0
        pool = self._engine.pool
        for i, h in enumerate(hashes):
            if not pool.contains(h):
                missing_from = i
                break
        else:
            return 0
        want = hashes[missing_from:]
        if self._kv_client is None:
            self._kv_client = await self._kv_client_factory()
        self.transfers += 1
        self.metrics.transfers.inc()
        t0 = time.monotonic()
        if not self.transfer_first_start:
            self.transfer_first_start = t0
        imported = 0
        pulled_bytes = 0
        # The block every chunk chains from: the last resident block before
        # the missing run, then the tail of each imported chunk.
        anchor = hashes[missing_from - 1] if missing_from > 0 else None
        try:
            # Chunked pull: each reply is a bounded slice, imported as it
            # lands — device scatters and the decode loop's ticks interleave
            # with the next chunk's network read instead of waiting for one
            # monolithic payload.
            async for reply in self._kv_client.direct(
                {
                    "op": "export",
                    "block_hashes": want,
                    # Schema v2 negotiation: ship pool-native (int8 stays
                    # int8 on the wire); v1 exporters ignore this and reply
                    # dense.
                    "wire": {
                        "version": WIRE_VERSION,
                        "accept": list(ACCEPT_WIRE_DTYPES),
                    },
                }, dp.worker_id
            ):
                found = reply.get("found") or []
                wire = unpack_reply(reply)
                if not found or wire is None:
                    break
                n = await self._engine.import_blocks_wire_async(
                    found, wire, anchor_parent=anchor
                )
                imported += n
                self.blocks_pulled += n
                chunk_bytes = reply_wire_nbytes(reply)
                pulled_bytes += chunk_bytes
                self.bytes_pulled += chunk_bytes
                self.wire_bytes_by_dtype[wire.dtype] = (
                    self.wire_bytes_by_dtype.get(wire.dtype, 0) + chunk_bytes
                )
                self.metrics.blocks_pulled.inc(n)
                self.metrics.bytes_pulled.inc(chunk_bytes)
                self.metrics.kv_wire_bytes.inc(chunk_bytes, dtype=wire.dtype)
                if n < len(found):
                    # Pool dry mid-chunk: anchoring later chunks on an
                    # uninstalled hash would commit children whose parent
                    # never committed (pool invariant) and every further
                    # chunk would transfer + scatter into a full pool.
                    logger.warning(
                        "KV pool dry after importing %d/%d blocks of a "
                        "chunk; stopping the pull early", n, len(found),
                    )
                    break
                anchor = found[-1]
                if reply.get("done", True):
                    break
        except Exception:
            self.transfer_failures += 1
            self.metrics.transfer_failures.inc()
            logger.exception(
                "KV pull from prefill worker %s failed after %d blocks; "
                "decoding with local prefill (fallback #%d — a recurring "
                "fallback means every request pays prefill TWICE)",
                dp.worker_id, imported, self.transfer_failures,
            )
        now = time.monotonic()
        self.transfer_seconds += now - t0
        self.transfer_last_end = now
        # Per-(src, dst) bandwidth: this pull's achieved rate feeds the
        # EWMA the router's link-cost model consumes via load reports.
        self._observe_link(dp.worker_id, pulled_bytes, now - t0)
        # Exemplar: a transfer-latency spike on a dashboard resolves to the
        # trace (and thus the /debug/requests timeline) that caused it.
        self.metrics.transfer_duration.observe(now - t0, trace_id=trace_id)
        return imported

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[BackendOutput]:
        req = (
            request
            if isinstance(request, PreprocessedRequest)
            else PreprocessedRequest.from_dict(dict(request))
        )
        if req.disaggregated_params is not None:
            t0 = time.monotonic()
            pulled = await self._pull_blocks(
                req.disaggregated_params,
                trace_id=lifecycle.trace_id_of(context),
            )
            lifecycle.record(
                req.request_id, "kv_transfer",
                context=context,
                blocks=pulled,
                worker=req.disaggregated_params.worker_id,
                duration_ms=round((time.monotonic() - t0) * 1000, 3),
            )
            if pulled:
                logger.info(
                    "imported %d KV blocks from prefill worker %s",
                    pulled, req.disaggregated_params.worker_id,
                )
        async for out in self._engine.generate(req, context):
            yield out
