"""Worker-side disaggregation handlers.

Reference parity: components/src/dynamo/vllm/handlers.py
(PrefillWorkerHandler :1469, DecodeWorkerHandler :1254) re-designed around
content-addressed KV blocks instead of NIXL descriptors.
"""

from __future__ import annotations

import os
import time
from typing import Any, AsyncIterator, Dict, List, Optional

import numpy as np

from dynamo_tpu.llm.protocols.common import (
    BackendOutput,
    DisaggregatedParams,
    FinishReason,
    PreprocessedRequest,
)
from dynamo_tpu.runtime import lifecycle
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.tokens.blocks import compute_block_hashes
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class DisaggMetrics:
    """Canonical disagg transfer families (runtime/metric_names.py
    ALL_DISAGG). One instance per DecodeHandler; ``render`` plugs into the
    system server's ``register_metrics`` seam. The handler's plain counters
    (``transfers``/``transfer_failures``/…) stay — tests and the aggregate
    rate math read them — these are their scrapeable form."""

    def __init__(self) -> None:
        from dynamo_tpu.runtime import metric_names as mn
        from dynamo_tpu.runtime.metrics_core import MetricsRegistry

        self.registry = MetricsRegistry()
        self.transfers = self.registry.counter(
            mn.DISAGG_TRANSFERS_TOTAL, "KV pulls from prefill workers"
        )
        self.transfer_failures = self.registry.counter(
            mn.DISAGG_TRANSFER_FAILURES_TOTAL,
            "Failed KV pulls — each one IS the 2x-cost path: the decode "
            "worker falls back to a second full local prefill",
        )
        self.blocks_pulled = self.registry.counter(
            mn.DISAGG_BLOCKS_PULLED_TOTAL, "KV blocks imported from prefill"
        )
        self.bytes_pulled = self.registry.counter(
            mn.DISAGG_BYTES_PULLED_TOTAL, "KV bytes pulled over the wire"
        )
        self.transfer_duration = self.registry.histogram(
            mn.DISAGG_TRANSFER_DURATION,
            "Wall time of one KV pull (request-scoped, chunks included)",
        )

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics=openmetrics)


def pack_array(a) -> Dict[str, Any]:
    arr = np.asarray(a)
    return {"b": arr.tobytes(), "shape": list(arr.shape), "dtype": str(arr.dtype)}


def unpack_array(d: Dict[str, Any]) -> np.ndarray:
    import ml_dtypes  # noqa: F401  (registers bfloat16 with numpy)

    return np.frombuffer(d["b"], dtype=np.dtype(d["dtype"])).reshape(d["shape"])


class PrefillHandler:
    """Serve a prefill worker: compute prompt KV + first token, return
    bootstrap metadata (ref: PrefillWorkerHandler.generate handlers.py:1498)."""

    def __init__(self, engine: Any, worker_id: int) -> None:
        self._engine = engine
        self.worker_id = worker_id

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[BackendOutput]:
        req = (
            request
            if isinstance(request, PreprocessedRequest)
            else PreprocessedRequest.from_dict(dict(request))
        )
        prompt = list(req.token_ids)
        block_size = self._engine.args.block_size
        hashes = compute_block_hashes(prompt, block_size)
        prefill_req = PreprocessedRequest.from_dict(req.to_dict())
        prefill_req.stop.max_tokens = 1
        prefill_req.stop.min_tokens = None
        prefill_req.stop.ignore_eos = True

        first: Optional[BackendOutput] = None
        async for out in self._engine.generate(prefill_req, context):
            if out.error:
                yield out
                return
            if out.token_ids:
                first = out
                break
        if first is None:
            yield BackendOutput(
                error="prefill produced no token", finish_reason=FinishReason.ERROR
            )
            return
        yield BackendOutput(
            token_ids=first.token_ids,
            logprobs=first.logprobs,
            cumulative_tokens=1,
            disaggregated_params=DisaggregatedParams(
                worker_id=self.worker_id,
                prefilled_tokens=len(prompt),
                kv_transfer={
                    "block_hashes": hashes,
                    "block_size": block_size,
                    "first_token": first.token_ids[0],
                },
            ),
            finish_reason=FinishReason.LENGTH,
        )


# Target size of one streamed KV chunk. Bounds the host-memory spike and
# the serialization stall of a transfer (a 70B-class prompt's KV is
# hundreds of MB — as ONE message it blocks the event loop and doubles
# peak host memory; as ~8 MB chunks it pipelines: the exporter gathers
# chunk N+1 while chunk N is on the wire and the importer scatters chunk
# N-1, and the importer's engine keeps serving decode ticks between
# chunks). Ref: the reference streams device-direct chunked/overlapped
# (lib/llm/src/block_manager/block/transfer/cuda.rs:1, lib/memory/src/nixl/).
KV_CHUNK_BYTES = int(os.environ.get("DYN_TPU_KV_CHUNK_BYTES", 8 << 20))


class KvTransferHandler:
    """Serve content-addressed KV block export (the 'kv' side-channel
    endpoint; plays the role of the NIXL read target).

    Streams the payload as bounded chunks: each reply message carries
    ≤ ~KV_CHUNK_BYTES of blocks plus ``done`` on the final message. Device
    gathers happen per chunk, so HBM→host readback overlaps the previous
    chunk's network write instead of spiking once."""

    def __init__(self, engine: Any, chunk_bytes: Optional[int] = None) -> None:
        self._engine = engine
        self.chunk_bytes = chunk_bytes or KV_CHUNK_BYTES

    def _blocks_per_chunk(self) -> int:
        from dynamo_tpu.engines.tpu.runner import kv_wire_itemsize

        cfg = self._engine.args.config
        itemsize = kv_wire_itemsize(
            cfg.dtype, getattr(self._engine.args, "kv_cache_dtype", None)
        )
        block_bytes = (
            2 * cfg.n_layers * self._engine.args.block_size
            * cfg.n_kv_heads * cfg.head_dim_ * itemsize
        )
        return max(1, self.chunk_bytes // max(block_bytes, 1))

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        hashes: List[int] = list(request.get("block_hashes") or [])
        per = self._blocks_per_chunk()
        sent_any = False
        for off in range(0, len(hashes), per):
            chunk = hashes[off : off + per]
            found, k, v = await self._engine.export_blocks_async(chunk)
            if not found:
                break  # chain broken (evicted): stop at the last good run
            sent_any = True
            done = off + per >= len(hashes) or len(found) < len(chunk)
            yield {
                "found": found,
                "k": pack_array(k),
                "v": pack_array(v),
                "done": done,
            }
            if len(found) < len(chunk):
                return
        if not sent_any:
            yield {"found": [], "k": None, "v": None, "done": True}


class DecodeHandler:
    """Serve a decode worker: import transferred KV (if the request carries
    disaggregated_params), then generate normally — prefix-cached admission
    picks up the imported blocks (ref: DecodeWorkerHandler handlers.py:1254)."""

    def __init__(self, engine: Any, kv_client_factory=None) -> None:
        self._engine = engine
        # async () -> Client for the prefill component's "kv" endpoint
        self._kv_client_factory = kv_client_factory
        self._kv_client = None
        # Observability for the fallback path: a transfer failure silently
        # converting into a second full prefill is a 2× cost bug that MUST
        # be visible in metrics (r3 review finding).
        self.transfers = 0
        self.transfer_failures = 0
        self.blocks_pulled = 0
        self.bytes_pulled = 0
        self.transfer_seconds = 0.0  # summed per-pull elapsed (can overlap)
        # Window edges for aggregate-rate math: concurrent pulls overlap,
        # so bytes / (last_end - first_start) is the honest achieved rate
        # while summed per-pull seconds would understate it.
        self.transfer_first_start = 0.0
        self.transfer_last_end = 0.0
        self.metrics = DisaggMetrics()

    def register_metrics(self, server: Any) -> None:
        """Expose this handler's transfer families on a SystemStatusServer."""
        server.register_metrics(self.metrics.render)

    async def _pull_blocks(
        self, dp: DisaggregatedParams, trace_id: Optional[str] = None
    ) -> int:
        info = dp.kv_transfer or {}
        hashes = list(info.get("block_hashes") or [])
        if not hashes or self._kv_client_factory is None:
            return 0
        # Skip blocks already resident (earlier transfer or shared prefix).
        missing_from = 0
        pool = self._engine.pool
        for i, h in enumerate(hashes):
            if not pool.contains(h):
                missing_from = i
                break
        else:
            return 0
        want = hashes[missing_from:]
        if self._kv_client is None:
            self._kv_client = await self._kv_client_factory()
        self.transfers += 1
        self.metrics.transfers.inc()
        t0 = time.monotonic()
        if not self.transfer_first_start:
            self.transfer_first_start = t0
        imported = 0
        # The block every chunk chains from: the last resident block before
        # the missing run, then the tail of each imported chunk.
        anchor = hashes[missing_from - 1] if missing_from > 0 else None
        try:
            # Chunked pull: each reply is a bounded slice, imported as it
            # lands — device scatters and the decode loop's ticks interleave
            # with the next chunk's network read instead of waiting for one
            # monolithic payload.
            async for reply in self._kv_client.direct(
                {"op": "export", "block_hashes": want}, dp.worker_id
            ):
                found = reply.get("found") or []
                if not found:
                    break
                k = unpack_array(reply["k"])
                v = unpack_array(reply["v"])
                n = await self._engine.import_blocks_async(
                    found, k, v, anchor_parent=anchor
                )
                imported += n
                self.blocks_pulled += n
                chunk_bytes = len(reply["k"]["b"]) + len(reply["v"]["b"])
                self.bytes_pulled += chunk_bytes
                self.metrics.blocks_pulled.inc(n)
                self.metrics.bytes_pulled.inc(chunk_bytes)
                if n < len(found):
                    # Pool dry mid-chunk: anchoring later chunks on an
                    # uninstalled hash would commit children whose parent
                    # never committed (pool invariant) and every further
                    # chunk would transfer + scatter into a full pool.
                    logger.warning(
                        "KV pool dry after importing %d/%d blocks of a "
                        "chunk; stopping the pull early", n, len(found),
                    )
                    break
                anchor = found[-1]
                if reply.get("done", True):
                    break
        except Exception:
            self.transfer_failures += 1
            self.metrics.transfer_failures.inc()
            logger.exception(
                "KV pull from prefill worker %s failed after %d blocks; "
                "decoding with local prefill (fallback #%d — a recurring "
                "fallback means every request pays prefill TWICE)",
                dp.worker_id, imported, self.transfer_failures,
            )
        now = time.monotonic()
        self.transfer_seconds += now - t0
        self.transfer_last_end = now
        # Exemplar: a transfer-latency spike on a dashboard resolves to the
        # trace (and thus the /debug/requests timeline) that caused it.
        self.metrics.transfer_duration.observe(now - t0, trace_id=trace_id)
        return imported

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[BackendOutput]:
        req = (
            request
            if isinstance(request, PreprocessedRequest)
            else PreprocessedRequest.from_dict(dict(request))
        )
        if req.disaggregated_params is not None:
            t0 = time.monotonic()
            pulled = await self._pull_blocks(
                req.disaggregated_params,
                trace_id=lifecycle.trace_id_of(context),
            )
            lifecycle.record(
                req.request_id, "kv_transfer",
                context=context,
                blocks=pulled,
                worker=req.disaggregated_params.worker_id,
                duration_ms=round((time.monotonic() - t0) * 1000, 3),
            )
            if pulled:
                logger.info(
                    "imported %d KV blocks from prefill worker %s",
                    pulled, req.disaggregated_params.worker_id,
                )
        async for out in self._engine.generate(req, context):
            yield out
