"""Live-handoff tickets: zero-re-prefill migration of an in-flight decode.

The PR 7 migration path re-prefills the full prompt + carried tokens on the
new worker — correct, but it recomputes KV the old worker already holds.
FlowKV's observation (PAPERS.md) is that in-flight KV can ride a
low-latency transfer instead of being recomputed: a draining worker
detaches each live decode at a reconciled burst boundary, ships a
:class:`HandoffTicket` (prompt + generated tokens, position, sampling
params, arrival RNG salt, committed block chain) plus the sequence's KV
blocks in the wire-v2 pool-native form (disagg/wire.py — int8 pools ship
int8), and the peer installs the blocks VERBATIM and resumes decode at the
exact next token. Bit-identical continuation falls out of the PR 3
``fold_in(seed, salt, token_index)`` sampling keys: the ticket carries the
arrival salt and the position, so the adopted stream draws the same noise
the never-migrated stream would — with **zero re-prefilled tokens**.

The wire payload covers positions ``0..pos-1``: every committed
(complete, prefix-cached) block followed by the partially-filled tail
block. The peer installs committed blocks as shared cache content and the
tail rows as private blocks of the adopted sequence.

This module is numpy-only (no jax): the ticket + payload pack/unpack ride
the same msgpack-friendly dicts as the KV wire, so the recorder and
offline tooling can replay handoffs without a device runtime.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from typing import Any, AsyncIterator, Dict, List, Optional

from dynamo_tpu.disagg.wire import KvWireBlocks, pack_kv, unpack_kv
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

HANDOFF_ENDPOINT = "handoff"
HANDOFF_VERSION = 1


class HandoffRefused(Exception):
    """The peer cannot adopt this ticket (capacity, shape/seed mismatch,
    itself draining). NOT migratable by design: the source worker absorbs
    a refusal by trying the next peer or falling down the drain ladder —
    the client stream never sees it."""


@dataclass
class HandoffTicket:
    """Everything a peer needs to resume a live decode mid-token.

    ``pos`` is the number of positions whose KV is resident (the decode
    input token ``all_tokens[-1]`` has NOT written its KV yet — it is the
    next decode input, exactly as on the source). ``n_blocks`` counts the
    wire payload's rows: ``len(committed_hashes)`` shared-cache blocks
    followed by the private tail rows covering ``pos``."""

    request: Dict[str, Any]  # PreprocessedRequest.to_dict()
    generated: List[int]  # tokens already streamed to the client
    salt: int  # arrival-order sampling salt (RNG continuity)
    hash_salt: int  # adapter/mm prefix-cache salt
    pos: int
    committed_hashes: List[int] = field(default_factory=list)
    n_blocks: int = 0
    # Compatibility stamp: continuation is only bit-identical on an engine
    # with the same weights/layout/sampling seed. A mismatching peer
    # refuses and the source falls down the ladder.
    model: str = ""
    block_size: int = 0
    n_layers: int = 0
    n_kv_heads: int = 0
    head_dim: int = 0
    seed: int = 0
    version: int = HANDOFF_VERSION

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "HandoffTicket":
        return cls(**{
            k: v for k, v in d.items() if k in cls.__dataclass_fields__
        })


def pack_handoff(ticket: HandoffTicket, wire: Optional[KvWireBlocks]) -> Dict[str, Any]:
    """One handoff request message (msgpack/in-proc friendly)."""
    return {
        "handoff": ticket.to_dict(),
        "kv": pack_kv(wire) if wire is not None else None,
    }


def unpack_handoff(d: Dict[str, Any]):
    """Inverse of pack_handoff → (HandoffTicket, KvWireBlocks | None)."""
    ticket = HandoffTicket.from_dict(d["handoff"])
    kv = d.get("kv")
    return ticket, (unpack_kv(kv) if kv else None)


class HandoffHandler:
    """Peer side of a live handoff: serve the worker's ``handoff``
    endpoint. The reply stream is ``{"accepted": ...}`` first (the source's
    go/no-go for releasing its own copy), then the continuation's
    BackendOutput dicts — tokens generated AFTER the handoff point only
    (everything before it already reached the client through the source).
    """

    def __init__(self, engine: Any) -> None:
        self._engine = engine

    def _validate(self, ticket: HandoffTicket, wire) -> None:
        e = self._engine
        cfg = e.config
        for key, theirs, ours in (
            # A mismatched wire format must refuse, not install blocks
            # under stale semantics — from_dict drops unknown fields, so
            # without this row a future-version ticket could pass every
            # shape check and still resume a corrupted continuation.
            ("version", ticket.version, HANDOFF_VERSION),
            ("model", ticket.model, cfg.name),
            ("block_size", ticket.block_size, e.args.block_size),
            ("n_layers", ticket.n_layers, cfg.n_layers),
            ("n_kv_heads", ticket.n_kv_heads, cfg.n_kv_heads),
            ("head_dim", ticket.head_dim, cfg.head_dim_),
            # Same seed or the fold_in(seed, salt, pos) keys diverge and
            # the continuation stops being the stream the client was
            # already reading — refuse rather than silently fork it.
            ("seed", ticket.seed, e.args.seed),
        ):
            if theirs != ours:
                raise HandoffRefused(
                    f"ticket {key}={theirs!r} does not match engine {ours!r}"
                )
        prompt = list(ticket.request.get("token_ids") or [])
        if not prompt:
            raise HandoffRefused("ticket carries an empty prompt")
        n_tokens = len(prompt) + len(ticket.generated)
        if ticket.pos != n_tokens - 1:
            raise HandoffRefused(
                f"ticket pos {ticket.pos} inconsistent with "
                f"{n_tokens} prompt+generated tokens"
            )
        if n_tokens >= e.args.max_model_len:
            raise HandoffRefused(
                f"{n_tokens} tokens exceed max_model_len {e.args.max_model_len}"
            )
        need_blocks = -(-ticket.pos // e.args.block_size)  # ceil
        if ticket.n_blocks != need_blocks:
            raise HandoffRefused(
                f"ticket n_blocks {ticket.n_blocks} != ceil(pos/block_size) "
                f"{need_blocks}"
            )
        if wire is None or len(wire) != ticket.n_blocks:
            raise HandoffRefused(
                f"wire payload has {0 if wire is None else len(wire)} rows, "
                f"ticket names {ticket.n_blocks}"
            )
        if len(ticket.committed_hashes) > ticket.n_blocks:
            raise HandoffRefused("more committed hashes than wire rows")
        lora = ticket.request.get("lora_name")
        if lora and lora not in getattr(e, "_lora_index", {}):
            raise HandoffRefused(f"LoRA adapter {lora!r} not loaded here")

    async def generate(
        self, request: Any, context: Any
    ) -> AsyncIterator[dict]:
        try:
            ticket, wire = unpack_handoff(dict(request))
            self._validate(ticket, wire)
            seq = await self._engine.adopt_handoff(ticket, wire, context)
        except HandoffRefused as exc:
            logger.warning("handoff refused: %s", exc)
            yield {"accepted": False, "reason": str(exc)}
            return
        # The ack carries the adopter's incarnation: the source fences a
        # zombie peer's late ack (runtime/liveness.py) — releasing the
        # source KV copy on a dead incarnation's promise would lose the
        # stream.
        from dynamo_tpu.runtime.liveness import process_incarnation

        yield {"accepted": True, "inc": process_incarnation()}
        async for out in self._engine.stream_adopted(seq):
            yield out.to_dict()
