"""KV wire format v2: pool-native multi-tensor block transfer.

The v1 wire format was always DENSE: int8 pools were dequantized to bf16
before export, shipping 2x the bytes the pool actually holds — on the
transfer-bound disagg leg that IS the bottleneck (BENCH_r04: 92.8 vs 593.2
tok/s aggregated, TTFT +376 ms). v2 carries the pool-native form end to
end: a quantized pool ships ``{q8, scales}`` (≈ 0.53x the dense bf16 bytes
at head_dim 64), a dense pool ships its storage dtype, and the importer
installs whatever arrives into whatever pool it runs:

    exporter pool → importer pool   install path
    int8  → int8    verbatim q8/s scatter (bit-exact pool transfer)
    int8  → dense   device-side dequant at scatter (int8 rides H2D)
    dense → int8    device-side requant at scatter (unchanged from v1)
    dense → dense   unchanged

Schema (one streamed chunk's ``kv`` field; ``pack_array`` dicts are
msgpack/in-proc friendly):

    {"version": 2,
     "dtype": "int8" | "<dense dtype>",
     "k": pack_array, "v": pack_array,            # [n, L, BS, KH, D]
     "k_scale": pack_array, "v_scale": pack_array}  # [n, L, KH, BS] f32,
                                                    # quantized only

Negotiation: the importer's pull request carries
``{"wire": {"version": 2, "accept": [dtypes...]}}``. An exporter that sees
no ``wire`` key answers in the v1 shape (dense ``k``/``v`` fields); a v2
importer accepts both (``unpack_reply``). ``accept`` lets an importer veto
the quantized encoding (the exporter densifies before shipping).

This module is deliberately numpy-only (no jax): the recorder, the KVBM
tiers, and offline replay tooling all load it without touching a device
runtime.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

WIRE_VERSION = 2

# Wire dtype tag for quantized payloads (payload int8 + f32 scales).
WIRE_DTYPE_Q8 = "int8"


def _np_dtype(name) -> np.dtype:
    """Resolve a wire dtype (string or dtype-like), registering bfloat16
    with numpy when needed."""
    if isinstance(name, str) and "bfloat16" in name:
        import ml_dtypes  # noqa: F401  (registers bfloat16 with numpy)
    return np.dtype(name)


def pack_array(a) -> Dict[str, Any]:
    """Serialize an array zero-copy: ``b`` is a memoryview over the array's
    own buffer (cast to bytes through a uint8 view — the only layout the
    buffer protocol accepts for ml_dtypes like bfloat16). A copy happens
    ONLY when the input is not already C-contiguous."""
    arr = np.ascontiguousarray(a)
    return {
        "b": arr.view(np.uint8).reshape(-1).data,
        "shape": list(arr.shape),
        "dtype": str(arr.dtype),
    }


def unpack_array(d: Dict[str, Any]) -> np.ndarray:
    """Inverse of pack_array; zero-copy view over the received buffer."""
    return np.frombuffer(d["b"], dtype=_np_dtype(d["dtype"])).reshape(d["shape"])


def packed_nbytes(d: Optional[Dict[str, Any]]) -> int:
    """Serialized payload bytes of one pack_array dict."""
    if not d:
        return 0
    buf = d["b"]
    return buf.nbytes if isinstance(buf, memoryview) else len(buf)


@dataclass
class KvWireBlocks:
    """``n`` KV blocks in wire form (host numpy).

    Dense: ``k``/``v`` are [n, L, BS, KH, D] in ``dtype``; scales are None.
    Quantized (``dtype == "int8"``): ``k``/``v`` are int8 payloads of the
    same shape and ``k_scale``/``v_scale`` are [n, L, KH, BS] float32 —
    the pool's own per-(token, head) scales (ops/kv_quant.py layout with
    block_size on the lane axis), shipped verbatim so an int8→int8
    transfer is bit-exact."""

    dtype: str
    k: np.ndarray
    v: np.ndarray
    k_scale: Optional[np.ndarray] = None
    v_scale: Optional[np.ndarray] = None

    @classmethod
    def dense(cls, k, v) -> "KvWireBlocks":
        k, v = np.asarray(k), np.asarray(v)
        return cls(dtype=str(k.dtype), k=k, v=v)

    @property
    def quantized(self) -> bool:
        return self.dtype == WIRE_DTYPE_Q8

    def __len__(self) -> int:
        return int(self.k.shape[0])

    @property
    def nbytes(self) -> int:
        """Wire bytes: payloads + scales (what serialization actually ships)."""
        n = int(self.k.nbytes) + int(self.v.nbytes)
        if self.k_scale is not None:
            n += int(self.k_scale.nbytes)
        if self.v_scale is not None:
            n += int(self.v_scale.nbytes)
        return n

    def take(self, sel: Sequence[int]) -> "KvWireBlocks":
        """Row subset (an importer installing only the non-resident blocks).
        Returns self when ``sel`` is the identity — the common whole-chunk
        install stays copy-free."""
        if len(sel) == len(self) and list(sel) == list(range(len(self))):
            return self
        idx = np.asarray(sel, dtype=np.int64)
        return KvWireBlocks(
            dtype=self.dtype,
            k=self.k[idx],
            v=self.v[idx],
            k_scale=None if self.k_scale is None else self.k_scale[idx],
            v_scale=None if self.v_scale is None else self.v_scale[idx],
        )

    def _dequant(self, q8: np.ndarray, s: np.ndarray, dtype) -> np.ndarray:
        # [n, L, KH, BS] → [n, L, BS, KH, 1] against [n, L, BS, KH, D]
        s_t = np.swapaxes(s, -1, -2)[..., None]
        return (q8.astype(np.float32) * s_t).astype(dtype)

    def to_dense(self, dtype: Optional[str] = None) -> Tuple[np.ndarray, np.ndarray]:
        """Dense [n, L, BS, KH, D] (k, v). Quantized payloads dequantize
        host-side to ``dtype`` (default bfloat16 — the v1 wire dtype);
        dense payloads pass through untouched unless ``dtype`` asks for a
        cast (negotiated-down exports)."""
        if not self.quantized:
            if dtype is None or _np_dtype(dtype) == self.k.dtype:
                return self.k, self.v
            out = _np_dtype(dtype)
            return self.k.astype(out), self.v.astype(out)
        out_dtype = _np_dtype(dtype or "bfloat16")
        return (
            self._dequant(self.k, self.k_scale, out_dtype),
            self._dequant(self.v, self.v_scale, out_dtype),
        )


def wire_block_bytes(
    n_layers: int,
    block_size: int,
    n_kv_heads: int,
    head_dim: int,
    wire_dtype: str,
) -> int:
    """Exact wire bytes of ONE block (k + v, scales included) for chunk
    sizing and router transfer-cost estimates. Replaces the v1
    ``kv_wire_itemsize`` (which could only express dense encodings)."""
    elems = n_layers * block_size * n_kv_heads * head_dim
    if wire_dtype == WIRE_DTYPE_Q8:
        scale_bytes = n_layers * n_kv_heads * block_size * 4  # f32 scales
        return 2 * (elems + scale_bytes)
    return 2 * elems * _np_dtype(wire_dtype).itemsize


def pack_kv(wire: KvWireBlocks) -> Dict[str, Any]:
    """One chunk's ``kv`` field (schema v2)."""
    d: Dict[str, Any] = {
        "version": WIRE_VERSION,
        "dtype": wire.dtype,
        "k": pack_array(wire.k),
        "v": pack_array(wire.v),
    }
    if wire.quantized:
        d["k_scale"] = pack_array(wire.k_scale)
        d["v_scale"] = pack_array(wire.v_scale)
    return d


def unpack_kv(d: Dict[str, Any]) -> KvWireBlocks:
    return KvWireBlocks(
        dtype=str(d["dtype"]),
        k=unpack_array(d["k"]),
        v=unpack_array(d["v"]),
        k_scale=unpack_array(d["k_scale"]) if d.get("k_scale") else None,
        v_scale=unpack_array(d["v_scale"]) if d.get("v_scale") else None,
    )


def unpack_reply(reply: Dict[str, Any]) -> Optional[KvWireBlocks]:
    """Decode one streamed transfer reply — v2 (``kv`` field) or the v1
    dense shape (separate ``k``/``v`` pack_array fields)."""
    if reply.get("kv"):
        return unpack_kv(reply["kv"])
    if reply.get("k") is not None and reply.get("v") is not None:
        return KvWireBlocks.dense(
            unpack_array(reply["k"]), unpack_array(reply["v"])
        )
    return None


def reply_wire_nbytes(reply: Dict[str, Any]) -> int:
    """Serialized KV payload bytes of one reply message (either schema)."""
    kv = reply.get("kv")
    if kv:
        return sum(
            packed_nbytes(kv.get(f)) for f in ("k", "v", "k_scale", "v_scale")
        )
    return packed_nbytes(reply.get("k")) + packed_nbytes(reply.get("v"))


def dense_tier_block(blk: Tuple[np.ndarray, ...]) -> Tuple[np.ndarray, np.ndarray]:
    """Densify a KVBM tier block: tiers store either (k, v) dense pairs or
    (k_q8, v_q8, k_scale, v_scale) quantized 4-tuples (see kvbm/tiers.py).
    Consumers that need dense arrays (the external-engine connector, the
    G4 remote write-behind) funnel through here."""
    if len(blk) == 2:
        return blk[0], blk[1]
    k_q8, v_q8, k_s, v_s = blk
    wire = KvWireBlocks(
        dtype=WIRE_DTYPE_Q8,
        k=k_q8[None],
        v=v_q8[None],
        k_scale=k_s[None],
        v_scale=v_s[None],
    )
    k, v = wire.to_dense()
    return k[0], v[0]


def tier_block_wire(blocks: Sequence[Tuple[np.ndarray, ...]]) -> KvWireBlocks:
    """Stack a uniform-form run of tier blocks into one KvWireBlocks (the
    onboard path). All blocks must share one form — callers split runs at
    form changes."""
    first = blocks[0]
    if len(first) == 2:
        return KvWireBlocks.dense(
            np.stack([b[0] for b in blocks]), np.stack([b[1] for b in blocks])
        )
    return KvWireBlocks(
        dtype=WIRE_DTYPE_Q8,
        k=np.stack([b[0] for b in blocks]),
        v=np.stack([b[1] for b in blocks]),
        k_scale=np.stack([b[2] for b in blocks]),
        v_scale=np.stack([b[3] for b in blocks]),
    )
