"""PrefillRouter: frontend-side disaggregation operator.

Reference parity: lib/llm/src/kv_router/prefill_router.rs:102 —
activate (:182) watches discovery for prefill instances; execute_prefill
(:354) sends the request with max_tokens=1 to a prefill worker; the
bootstrap metadata (:267–318) travels to the decode worker as
``disaggregated_params``. Requests below the length threshold (or when no
prefill workers are live) fall through to the decode path's local prefill
(conditional disagg, docs/performance/tuning.md disagg-router section).

Stream shape: the prefill worker's first token is emitted immediately (good
TTFT), then the decode stream continues from token 2.
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Optional

from dynamo_tpu.llm.protocols.common import (
    BackendOutput,
    DisaggregatedParams,
    FinishReason,
    PreprocessedRequest,
)
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class PrefillRouter:
    def __init__(
        self,
        prefill_client_factory,
        *,
        threshold_tokens: int = 32,
    ) -> None:
        # async () -> Client for the prefill component's generate endpoint
        self._factory = prefill_client_factory
        self._client = None
        self.threshold_tokens = threshold_tokens

    async def _prefill_client(self):
        if self._client is None:
            self._client = await self._factory()
        return self._client

    async def generate(
        self, request: Any, context: Context, next: AsyncEngine
    ) -> AsyncIterator[Any]:
        req = (
            request
            if isinstance(request, PreprocessedRequest)
            else PreprocessedRequest.from_dict(dict(request))
        )
        client = None
        try:
            client = await self._prefill_client()
        except Exception:
            logger.debug("prefill client unavailable; serving aggregated")
        if (
            client is None
            or not client.instance_ids
            or len(req.token_ids) < self.threshold_tokens
        ):
            async for item in next.generate(request, context):
                yield item
            return

        first: Optional[BackendOutput] = None
        try:
            async for item in client.generate(req.to_dict(), context):
                out = (
                    item
                    if isinstance(item, BackendOutput)
                    else BackendOutput.from_dict(item)
                )
                if out.error:
                    raise RuntimeError(out.error)
                if out.token_ids:
                    first = out
                    break
        except Exception as exc:
            logger.warning("remote prefill failed (%r); serving aggregated", exc)
            async for item in next.generate(request, context):
                yield item
            return
        if first is None or first.disaggregated_params is None:
            logger.warning("prefill returned no bootstrap; serving aggregated")
            async for item in next.generate(request, context):
                yield item
            return

        token = first.token_ids[0]
        dp: DisaggregatedParams = first.disaggregated_params
        yield BackendOutput(
            token_ids=[token], cumulative_tokens=1, logprobs=first.logprobs
        )
        # Evaluate stop conditions for the first token with the same gating
        # as the engine's _emit_token (min_tokens gates eos/stop ids).
        max_tokens = req.stop.max_tokens
        min_ok = req.stop.min_tokens is None or 1 >= req.stop.min_tokens
        if not req.stop.ignore_eos and min_ok and token in (req.eos_token_ids or []):
            yield BackendOutput(finish_reason=FinishReason.EOS)
            return
        if min_ok and token in (req.stop.stop_token_ids or []):
            yield BackendOutput(finish_reason=FinishReason.STOP)
            return
        if max_tokens is not None and max_tokens <= 1:
            yield BackendOutput(finish_reason=FinishReason.LENGTH)
            return

        decode_req = PreprocessedRequest.from_dict(req.to_dict())
        decode_req.token_ids = list(req.token_ids) + [token]
        if decode_req.stop.max_tokens is not None:
            decode_req.stop.max_tokens -= 1
        if decode_req.stop.min_tokens:
            decode_req.stop.min_tokens = max(decode_req.stop.min_tokens - 1, 0)
        decode_req.disaggregated_params = dp
        async for item in next.generate(decode_req, context):
            out = (
                item
                if isinstance(item, BackendOutput)
                else BackendOutput.from_dict(item)
            )
            if out.cumulative_tokens is not None:
                out.cumulative_tokens += 1  # account the prefill token
            yield out
