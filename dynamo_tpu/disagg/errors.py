"""Disagg transfer failure taxonomy.

``classify_failure`` buckets a pull-path exception into the ``error_kind``
label of ``dynamo_tpu_disagg_transfer_failures_total`` — the difference
between "the link is down" (connection), "the link is slow" (timeout) and
"the payload is garbage" (decode) is the difference between opening a
circuit breaker, lengthening a deadline, and paging a human.

``DisaggTransferError`` is the terminal failure of a pull on a handler
configured WITHOUT local-prefill fallback (strict disagg: the decode
worker cannot afford a full prefill). It subclasses ConnectionError so the
frontend's Migration operator re-dispatches the stream to another worker.
"""

from __future__ import annotations

import asyncio


class DisaggTransferError(ConnectionError):
    """KV pull terminally failed and local re-prefill is disabled —
    migratable: the router should place the request elsewhere."""


# Exception classes per kind, most specific first. TimeoutError is checked
# before ConnectionError because builtin TimeoutError subclasses OSError
# (and asyncio.TimeoutError is a DISTINCT class until Python 3.11).
_TIMEOUT_TYPES = (TimeoutError, asyncio.TimeoutError)
_CONNECTION_TYPES = (ConnectionError, EOFError, OSError)
_DECODE_TYPES = (ValueError, KeyError, TypeError, IndexError)


def classify_failure(exc: BaseException) -> str:
    """→ ``timeout`` | ``connection`` | ``decode`` | ``other``."""
    if isinstance(exc, _TIMEOUT_TYPES):
        return "timeout"
    if isinstance(exc, _CONNECTION_TYPES):
        return "connection"
    if isinstance(exc, _DECODE_TYPES):
        return "decode"
    return "other"
