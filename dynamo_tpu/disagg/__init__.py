"""Disaggregated prefill/decode serving.

Reference parity: lib/llm/src/kv_router/prefill_router.rs (+ disagg_serving
design doc): a prefill worker computes the prompt KV + first token; the
decode worker receives the KV and continues generation. The reference moves
KV with NIXL GPUDirect RDMA; TPU-native equivalent (SURVEY §2.5 note) is
content-addressed block transfer: blocks are keyed by chained hash, exported
from the prefill engine's HBM, shipped host-staged over the request plane
(DCN path), and imported into the decode engine's pool as cached blocks —
after which ordinary prefix-cached admission reuses them, and the partial
tail block is recomputed locally (cheap).
"""

from dynamo_tpu.disagg.errors import DisaggTransferError, classify_failure
from dynamo_tpu.disagg.handoff import (
    HANDOFF_ENDPOINT,
    HandoffHandler,
    HandoffRefused,
    HandoffTicket,
    pack_handoff,
    unpack_handoff,
)
from dynamo_tpu.disagg.handlers import (
    CircuitBreaker,
    DecodeHandler,
    KvTransferHandler,
    PrefillHandler,
    pack_array,
    unpack_array,
)
from dynamo_tpu.disagg.wire import (
    WIRE_VERSION,
    KvWireBlocks,
    pack_kv,
    unpack_kv,
    unpack_reply,
    wire_block_bytes,
)
from dynamo_tpu.disagg.prefill_router import PrefillRouter

__all__ = [
    "CircuitBreaker",
    "DecodeHandler",
    "DisaggTransferError",
    "HANDOFF_ENDPOINT",
    "HandoffHandler",
    "HandoffRefused",
    "HandoffTicket",
    "KvTransferHandler",
    "PrefillHandler",
    "PrefillRouter",
    "classify_failure",
    "pack_array",
    "pack_handoff",
    "unpack_array",
    "unpack_handoff",
]
