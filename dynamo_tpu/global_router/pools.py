"""Pool specs + grid-based selection strategies.

Reference parity: global_router/pool_selection.py (PrefillPoolSelectionStrategy
/ DecodePoolSelectionStrategy — an (x, y) grid of pool indices with clamped
lookup). One generic GridStrategy covers both axes pairs here; the JSON
config shape mirrors the reference's global_router_config.json.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class PoolSpec:
    """One routable pool: a namespace (its own workers + optional local
    router), with the component/endpoint the pool serves on."""

    namespace: str
    component: str = "backend"
    endpoint: str = "generate"


@dataclass
class GridStrategy:
    """pool = grid[x_idx][y_idx], indices clamped to the grid bounds.

    x is the request property (ISL or context length), y the SLA target
    (TTFT or ITL); ``select`` falls back to the y-range midpoint when the
    request carries no target (ref: pool_selection.py select_pool)."""

    x_min: float
    x_max: float
    y_min: float
    y_max: float
    mapping: List[List[int]]  # [x_resolution][y_resolution] → pool index

    @property
    def x_resolution(self) -> int:
        return len(self.mapping)

    @property
    def y_resolution(self) -> int:
        return len(self.mapping[0]) if self.mapping else 0

    def _idx(self, value: float, lo: float, hi: float, resolution: int) -> int:
        if resolution <= 1 or hi <= lo:
            return 0
        step = (hi - lo) / resolution
        return max(0, min(int((value - lo) / step), resolution - 1))

    def select(self, x: float, y: Optional[float] = None) -> int:
        if y is None:
            y = (self.y_min + self.y_max) / 2
        xi = self._idx(x, self.x_min, self.x_max, self.x_resolution)
        yi = self._idx(y, self.y_min, self.y_max, self.y_resolution)
        return self.mapping[xi][yi]


@dataclass
class GlobalRouterConfig:
    pools: List[PoolSpec] = field(default_factory=list)
    # (ISL, TTFT target ms) → pool, used for new requests
    prefill_strategy: Optional[GridStrategy] = None
    # (context length, ITL target ms) → pool
    decode_strategy: Optional[GridStrategy] = None

    @classmethod
    def from_dict(cls, doc: Dict[str, Any]) -> "GlobalRouterConfig":
        pools = [
            PoolSpec(**p) if isinstance(p, dict) else PoolSpec(namespace=p)
            for p in doc.get("pools", [])
        ]

        def grid(key: str) -> Optional[GridStrategy]:
            g = doc.get(key)
            if not g:
                return None
            return GridStrategy(
                x_min=g["x_min"], x_max=g["x_max"],
                y_min=g.get("y_min", 0.0), y_max=g.get("y_max", 1.0),
                mapping=g["mapping"],
            )

        return cls(
            pools=pools,
            prefill_strategy=grid("prefill_strategy"),
            decode_strategy=grid("decode_strategy"),
        )

    @classmethod
    def from_file(cls, path: str) -> "GlobalRouterConfig":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def validate(self) -> None:
        n = len(self.pools)
        if n == 0:
            raise ValueError("global router needs at least one pool")
        for name, strat in (
            ("prefill_strategy", self.prefill_strategy),
            ("decode_strategy", self.decode_strategy),
        ):
            if strat is None:
                continue
            for row in strat.mapping:
                for idx in row:
                    if not 0 <= idx < n:
                        raise ValueError(
                            f"{name} maps to pool {idx}, but only {n} pools exist"
                        )
