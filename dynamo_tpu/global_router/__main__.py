"""Global router service entrypoint.

Reference parity: components/src/dynamo/global_router/__main__.py — register
as a worker for the model, forward into per-pool namespaces.

Usage:
  python -m dynamo_tpu.global_router --config pools.json --model-name m \
      --namespace edge
"""

from __future__ import annotations

import argparse
import asyncio
import random

from dynamo_tpu import config
from dynamo_tpu.global_router.handler import GlobalRouterHandler
from dynamo_tpu.global_router.pools import GlobalRouterConfig
from dynamo_tpu.llm.discovery import register_llm
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.utils.logging import configure_logging


async def main() -> None:
    parser = argparse.ArgumentParser("dynamo-tpu global router")
    parser.add_argument("--config", required=True, help="pool config JSON")
    parser.add_argument("--model-name", required=True)
    parser.add_argument("--namespace", default=config.NAMESPACE.get())
    parser.add_argument("--component", default="backend")
    parser.add_argument("--context-length", type=int, default=8192)
    args = parser.parse_args()

    configure_logging()
    runtime = DistributedRuntime.from_settings()
    handler = GlobalRouterHandler(runtime, GlobalRouterConfig.from_file(args.config))
    instance_id = random.getrandbits(63)
    endpoint = (
        runtime.namespace(args.namespace)
        .component(args.component)
        .endpoint("generate")
    )
    served = await endpoint.serve_endpoint(handler.generate, instance_id=instance_id)
    card = ModelDeploymentCard(
        name=args.model_name, context_length=args.context_length
    )
    await register_llm(runtime, card, endpoint, instance_id)
    print(f"global router serving {args.model_name} over "
          f"{len(handler.config.pools)} pools", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await handler.close()
        await served.shutdown(grace_period=config.GRACE_PERIOD.get())
        await runtime.shutdown(grace_period=config.GRACE_PERIOD.get())


if __name__ == "__main__":
    asyncio.run(main())
