"""Hierarchical (global) routing across worker pools.

Reference parity: components/src/dynamo/global_router — a service that
registers as a worker from the frontend's perspective but internally
forwards each request to one of several *pools* (namespaces with their own
workers/local routers), picked by a grid strategy over (ISL, TTFT target)
for prefill-bound traffic and (context length, ITL target) for decode.
Hierarchical routing is how deployments mix heterogeneous pools (different
slice sizes, different models-of-the-same-family, spot vs reserved).
"""

from dynamo_tpu.global_router.pools import (
    GlobalRouterConfig,
    GridStrategy,
    PoolSpec,
)
from dynamo_tpu.global_router.handler import GlobalRouterHandler

__all__ = [
    "GlobalRouterConfig",
    "GridStrategy",
    "PoolSpec",
    "GlobalRouterHandler",
]
