"""GlobalRouterHandler: a worker-shaped bridge into per-pool namespaces.

Reference parity: global_router/handler.py (GlobalRouterHandler — registers
via register_llm like any worker, then forwards each request to the local
router/workers of the selected pool's namespace). Pool clients are created
lazily and cached; a pool with no live instances falls through to the next
best pool instead of failing the request.
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Dict, Optional

from dynamo_tpu.global_router.pools import GlobalRouterConfig
from dynamo_tpu.runtime.component import NoInstancesError, RouterMode
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class GlobalRouterHandler:
    def __init__(
        self,
        runtime: Any,
        config: GlobalRouterConfig,
        *,
        router_mode: RouterMode = RouterMode.ROUND_ROBIN,
    ) -> None:
        config.validate()
        self.runtime = runtime
        self.config = config
        self.router_mode = router_mode
        self._clients: Dict[int, Any] = {}
        # observability: per-pool forwarded request counts
        self.pool_requests: Dict[int, int] = {}

    async def _client(self, pool_idx: int) -> Any:
        client = self._clients.get(pool_idx)
        if client is None:
            spec = self.config.pools[pool_idx]
            client = await (
                self.runtime.namespace(spec.namespace)
                .component(spec.component)
                .endpoint(spec.endpoint)
                .client(self.router_mode)
            )
            self._clients[pool_idx] = client
        return client

    def select_pool(self, request: Any) -> int:
        """(ISL, TTFT target) through the prefill grid; decode-only
        continuations (disaggregated_params present) use the decode grid
        keyed by context length."""
        token_ids = (
            request.get("token_ids")
            if isinstance(request, dict)
            else getattr(request, "token_ids", None)
        ) or []
        isl = len(token_ids)
        extra = (
            request.get("extra")
            if isinstance(request, dict)
            else getattr(request, "extra", None)
        ) or {}
        ttft_target = extra.get("ttft_target_ms")
        itl_target = extra.get("itl_target_ms")
        disagg = (
            request.get("disaggregated_params")
            if isinstance(request, dict)
            else getattr(request, "disaggregated_params", None)
        )
        if disagg is not None and self.config.decode_strategy is not None:
            return self.config.decode_strategy.select(isl, itl_target)
        if self.config.prefill_strategy is not None:
            return self.config.prefill_strategy.select(isl, ttft_target)
        return 0

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        pool_idx = self.select_pool(request)
        order = [pool_idx] + [
            i for i in range(len(self.config.pools)) if i != pool_idx
        ]
        last_error: Optional[Exception] = None
        for idx in order:
            client = await self._client(idx)
            try:
                child = Context(parent=context, baggage=dict(context.baggage))
                stream = client.generate(request, child)
                first = await stream.__anext__()
            except (NoInstancesError, StopAsyncIteration) as exc:
                # Pool empty/dead: fall through to the next (ref: the
                # global router's resilience goal — a drained pool must not
                # fail traffic that another pool can serve).
                logger.warning("pool %d unavailable (%s); trying next", idx, exc)
                last_error = exc if isinstance(exc, Exception) else None
                continue
            self.pool_requests[idx] = self.pool_requests.get(idx, 0) + 1
            if idx != pool_idx:
                logger.info("request diverted from pool %d to %d", pool_idx, idx)
            yield first
            async for item in stream:
                yield item
            return
        raise NoInstancesError(
            f"no pool could serve the request (last error: {last_error})"
        )

    def get_pool_info(self) -> Dict[str, Any]:
        return {
            "pools": [vars(p) for p in self.config.pools],
            "requests_per_pool": dict(self.pool_requests),
        }

    async def close(self) -> None:
        for client in self._clients.values():
            await client.close()
        self._clients.clear()
